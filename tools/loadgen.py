"""Synthetic traffic generator for overload benching and live drills.

Generates *arrival schedules* — (time-offset, namespace, priority,
size) tuples — deterministically from a seed, then replays them against
a submit function (in-process ``Server.submit_job`` for the bench
overload phase) or a live cluster over HTTP (``--address``; submissions
go through ``api/client.py`` and therefore honor 429 + Retry-After like
any well-behaved client).

Traffic shapes (``--shape``):

* ``poisson``      — homogeneous Poisson arrivals at ``--rate``/s.
* ``diurnal``      — nonhomogeneous Poisson: the rate ramps along a
  half-sine from 20% of ``--rate`` to the peak and back (a day
  compressed into ``--duration`` seconds), sampled by thinning.
* ``flash_crowd``  — baseline Poisson with a burst window in the middle
  (``burst_mult``× the rate for 20% of the duration) — the shape the
  flash-crowd chaos scenario and the controller's fast window exist for.

Job-size mix is Zipf over group counts (most jobs small, a heavy tail
of wide ones), tenancy is Zipf over ``--tenants`` namespaces (one hot
tenant, a long tail), and ~30% of arrivals are priority-10 batch work —
under the default shed floor (50) exactly the slice the broker defers
first.

Replays are wall-clock faithful: the runner sleeps to each arrival's
offset (``--time-scale`` compresses), so a 30s diurnal ramp takes 30s.
Every run returns admit/reject counts and completion stats per shape.
"""

from __future__ import annotations

import argparse
import json
import math
import random
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

SHAPES = ("poisson", "diurnal", "flash_crowd")


@dataclass
class Arrival:
    t: float            # seconds from schedule start
    namespace: str
    priority: int
    group_count: int    # job width (Zipf-distributed)


@dataclass
class LoadGenConfig:
    seed: int = 0
    rate: float = 50.0          # mean arrivals/s (shape modulates)
    duration: float = 10.0
    tenants: int = 4            # namespaces: default + tenant-1..n-1
    zipf_s: float = 1.5         # skew for both tenancy and job width
    max_group_count: int = 8
    batch_fraction: float = 0.3  # priority-10 arrivals (shed bait)
    burst_mult: float = 8.0     # flash_crowd burst amplification
    burst_window: float = 0.2   # fraction of duration the burst lasts


def _zipf_weights(n: int, s: float) -> List[float]:
    w = [1.0 / (k ** s) for k in range(1, n + 1)]
    total = sum(w)
    return [x / total for x in w]


class LoadGen:
    """Deterministic schedule builder + replayer."""

    def __init__(self, config: Optional[LoadGenConfig] = None):
        self.cfg = config or LoadGenConfig()
        c = self.cfg
        self.namespaces = ["default"] + [
            f"tenant-{i}" for i in range(1, max(c.tenants, 1))
        ]
        self._ns_weights = _zipf_weights(len(self.namespaces), c.zipf_s)
        self._size_weights = _zipf_weights(c.max_group_count, c.zipf_s)

    # -- schedule construction (pure function of seed + shape) ---------

    def _rate_at(self, shape: str, t: float) -> float:
        c = self.cfg
        if shape == "poisson":
            return c.rate
        if shape == "diurnal":
            # Half-sine day: trough 20% of peak at both ends.
            frac = max(0.0, min(t / c.duration, 1.0))
            return c.rate * (0.2 + 0.8 * math.sin(math.pi * frac))
        if shape == "flash_crowd":
            start = c.duration * 0.4
            end = start + c.duration * c.burst_window
            return c.rate * (c.burst_mult if start <= t < end else 1.0)
        raise ValueError(f"unknown shape {shape!r}")

    def _peak_rate(self, shape: str) -> float:
        c = self.cfg
        return c.rate * (c.burst_mult if shape == "flash_crowd" else 1.0)

    def schedule(self, shape: str) -> List[Arrival]:
        """Arrivals via Lewis-Shedler thinning against the peak rate —
        exact for the homogeneous case, standard for the shaped ones."""
        import zlib

        c = self.cfg
        # str hashes are salted per-process; crc32 keeps the schedule a
        # pure function of (seed, shape) across runs.
        rng = random.Random(c.seed * 1000003 + zlib.crc32(shape.encode()))
        peak = self._peak_rate(shape)
        out: List[Arrival] = []
        t = 0.0
        while True:
            t += rng.expovariate(peak)
            if t >= c.duration:
                break
            if rng.random() * peak > self._rate_at(shape, t):
                continue  # thinned
            ns = rng.choices(self.namespaces, weights=self._ns_weights)[0]
            priority = 10 if rng.random() < c.batch_fraction else 50
            width = rng.choices(
                range(1, c.max_group_count + 1),
                weights=self._size_weights,
            )[0]
            out.append(Arrival(
                t=t, namespace=ns, priority=priority, group_count=width,
            ))
        return out

    # -- replay --------------------------------------------------------

    def run(
        self,
        submit: Callable[[Arrival], object],
        shape: str,
        time_scale: float = 1.0,
        on_reject: Optional[Callable[[Arrival, Exception], None]] = None,
    ) -> Dict[str, object]:
        """Replay ``shape``'s schedule against ``submit``, sleeping to
        each arrival offset (scaled).  ``submit`` raising is counted as
        a rejection (RateLimitError / APIError 429); other exceptions
        propagate.  Returns per-run accounting."""
        from nomad_tpu.server.admission import RateLimitError

        arrivals = self.schedule(shape)
        t0 = time.time()
        admitted = rejected = 0
        per_ns: Dict[str, List[int]] = {}
        for a in arrivals:
            target = t0 + a.t * time_scale
            delay = target - time.time()
            if delay > 0:
                time.sleep(delay)
            counts = per_ns.setdefault(a.namespace, [0, 0])
            try:
                submit(a)
                admitted += 1
                counts[0] += 1
            except RateLimitError as exc:
                rejected += 1
                counts[1] += 1
                if on_reject is not None:
                    on_reject(a, exc)
            except Exception as exc:  # noqa: BLE001
                code = getattr(exc, "code", None)
                if code != 429:
                    raise
                rejected += 1
                counts[1] += 1
                if on_reject is not None:
                    on_reject(a, exc)
        elapsed = time.time() - t0
        return {
            "shape": shape,
            "offered": len(arrivals),
            "admitted": admitted,
            "rejected": rejected,
            "elapsed_s": round(elapsed, 3),
            "offered_rate": round(len(arrivals) / max(elapsed, 1e-6), 1),
            "per_namespace": {
                ns: {"admitted": a_, "rejected": r_}
                for ns, (a_, r_) in sorted(per_ns.items())
            },
        }


def make_job_factory(mock_module):
    """Arrival → Job using the repo's mock fixtures (in-process runs)."""

    def make(a: Arrival):
        job = mock_module.job()
        job.namespace = a.namespace
        job.priority = a.priority
        tg = job.task_groups[0]
        tg.count = a.group_count
        for t in tg.tasks:
            t.resources.cpu = 20
            t.resources.memory_mb = 32
            t.config = {"run_for": 0}
        return job

    return make


# ----------------------------------------------------------------------
# CLI: drive a live cluster over HTTP
# ----------------------------------------------------------------------

def _http_submit(client, counter: Dict[str, int]):
    """Arrival → register over the API client (retries 429 internally;
    exhausted retries surface as APIError and count as rejections)."""

    def submit(a: Arrival) -> None:
        payload = {
            "ID": f"loadgen-{counter['n']}",
            "Name": f"loadgen-{counter['n']}",
            "Namespace": a.namespace,
            "Priority": a.priority,
            "Datacenters": ["dc1"],
            "TaskGroups": [{
                "Name": "g",
                "Count": a.group_count,
                "Tasks": [{
                    "Name": "t", "Driver": "mock",
                    "Config": {"run_for": 0},
                    "Resources": {"CPU": 20, "MemoryMB": 32},
                }],
            }],
        }
        counter["n"] += 1
        client.register_job(payload)

    return submit


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="synthetic traffic against a nomad_tpu cluster"
    )
    ap.add_argument("--address", default="http://127.0.0.1:4646")
    ap.add_argument("--token", default="")
    ap.add_argument("--shape", choices=SHAPES, default="poisson")
    ap.add_argument("--rate", type=float, default=50.0)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--time-scale", type=float, default=1.0)
    ap.add_argument("--burst-mult", type=float, default=8.0)
    args = ap.parse_args(argv)

    from nomad_tpu.api.client import APIClient

    gen = LoadGen(LoadGenConfig(
        seed=args.seed, rate=args.rate, duration=args.duration,
        tenants=args.tenants, burst_mult=args.burst_mult,
    ))
    client = APIClient(address=args.address, token=args.token)
    stats = gen.run(
        _http_submit(client, {"n": 0}), args.shape,
        time_scale=args.time_scale,
    )
    stats["client_rate_limited"] = client.rate_limited
    print(json.dumps(stats, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
