#!/usr/bin/env python
"""Bench regression ledger — normalize, baseline, verdict.

Every ``bench.py`` run (and the committed ``BENCH_*.json`` snapshots from
earlier rounds) is normalized into one line of ``BENCH_LEDGER.jsonl``:

    {"ts": ..., "source": "...", "ok": true,
     "metrics": {"eval_throughput": 969.5, "p99_ms": 266.0, ...},
     "verdicts": {"eval_throughput": {"verdict": "flat", ...}, ...}}

Two input shapes are understood:

* the driver wrapper ``{"n", "cmd", "rc", "tail", "parsed"}`` — ``parsed``
  is the bench's JSON stdout line (None when the run crashed; the entry
  is kept with ``ok: false`` so the ledger records the failure, but it
  contributes nothing to baselines);
* a flat result dict straight from ``bench.py`` (numeric leaves become
  metrics; a ``{"metric": name, "value": v}`` pair is folded to
  ``name: v``).

The baseline for a metric is the trailing window (default 8) of prior
*successful* runs that carried it.  A new value's verdict:

    deviation = value - median(baseline)
    threshold = max(MAD_SIGMAS * 1.4826 * MAD, REL_FLOOR * |median|)
    |deviation| <= threshold        -> flat
    else (by the metric's direction) -> improve | regress

Median/MAD instead of mean/stddev because bench history is exactly the
distribution outliers ruin: one swapped-out run would widen a stddev
gate enough to wave real regressions through.  The 1.4826 factor scales
MAD to a normal-equivalent sigma; REL_FLOOR keeps near-constant metrics
(MAD ~ 0) from flagging on noise.  Direction is inferred from the name
(throughput-ish = higher-better, latency/duration-ish = lower-better);
metrics with no inferable direction (batch sizes, node counts) are
recorded but never judged.

CLI:

    python tools/bench_history.py ingest BENCH_*.json   # seed/extend ledger
    python tools/bench_history.py record result.json    # one run + verdicts
    python tools/bench_history.py report [--last N]     # recent verdicts

``bench.py`` calls :func:`record_run` at the end of ``main()`` so the
ledger and verdict lines ride along with every local run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_LEDGER = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "BENCH_LEDGER.jsonl",
)

BASELINE_RUNS = 8      # trailing successful runs per metric
MIN_BASELINE = 3       # fewer than this -> verdict "new"
MAD_SIGMAS = 3.0       # breadth of the MAD gate
REL_FLOOR = 0.05       # never flag a <5% move, however tight the MAD

VERDICT_IMPROVE = "improve"
VERDICT_FLAT = "flat"
VERDICT_REGRESS = "regress"
VERDICT_NEW = "new"    # not enough history to judge

# Direction inference: first match wins, higher-better checked first so
# "evals_per_sec" doesn't fall into the lower-better "_s" suffix rule.
_HIGHER_TOKENS = ("per_sec", "throughput", "per_second", "speedup",
                  "evals_sec", "ops_sec")
_LOWER_TOKENS = ("latency",)
_LOWER_SUFFIXES = ("_ms", "_s", "_ns", "_us")
_LOWER_PREFIX_TOKENS = ("p50", "p90", "p95", "p99", "max_ms", "mean_ms")


def direction(metric: str) -> Optional[int]:
    """+1 = higher is better, -1 = lower is better, None = don't judge."""
    m = metric.lower()
    if any(tok in m for tok in _HIGHER_TOKENS):
        return 1
    if any(tok in m for tok in _LOWER_TOKENS):
        return -1
    leaf = m.rsplit(".", 1)[-1]
    if any(tok in leaf for tok in _LOWER_PREFIX_TOKENS):
        return -1
    if leaf.endswith(_LOWER_SUFFIXES):
        return -1
    return None


# -- normalization -----------------------------------------------------


def _flatten(obj: Dict[str, Any], prefix: str = "",
             out: Optional[Dict[str, float]] = None) -> Dict[str, float]:
    out = out if out is not None else {}
    for k, v in obj.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            _flatten(v, key + ".", out)
        elif isinstance(v, bool):
            continue  # config flags, not metrics
        elif isinstance(v, (int, float)):
            out[key] = float(v)
    return out


def flatten_metrics(result: Dict[str, Any]) -> Dict[str, float]:
    """Numeric leaves of a bench result, dotted keys for nesting; a
    top-level ``{"metric": name, "value": v}`` pair folds to ``name``."""
    result = dict(result)
    name = result.pop("metric", None)
    value = result.get("value")
    if isinstance(name, str) and isinstance(value, (int, float)):
        result.pop("value")
        result[name] = value
    return _flatten(result)


def normalize(raw: Dict[str, Any], source: str = "") -> Dict[str, Any]:
    """One ledger entry from either input shape (see module docstring)."""
    if "tail" in raw and ("rc" in raw or "parsed" in raw):
        parsed = raw.get("parsed")
        ok = raw.get("rc", 1) == 0 and isinstance(parsed, dict)
        metrics = flatten_metrics(parsed) if isinstance(parsed, dict) else {}
        meta = {"rc": raw.get("rc"), "n": raw.get("n")}
    else:
        ok = True
        metrics = flatten_metrics(raw)
        meta = {}
        for k in ("platform", "unit", "note", "phase"):
            if isinstance(raw.get(k), str):
                meta[k] = raw[k]
    return {
        "ts": time.time(),
        "source": source,
        "ok": ok,
        "metrics": metrics,
        "meta": meta,
    }


# -- ledger I/O --------------------------------------------------------


def read_ledger(path: str) -> List[Dict[str, Any]]:
    entries: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return entries
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                entries.append(json.loads(line))
            except ValueError:
                continue  # a torn write must not poison the history
    return entries


def append_entry(path: str, entry: Dict[str, Any]) -> None:
    with open(path, "a") as fh:
        fh.write(json.dumps(entry, sort_keys=True) + "\n")


# -- baseline + verdicts -----------------------------------------------


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _mad(vals: List[float], med: float) -> float:
    return _median([abs(v - med) for v in vals])


def baseline_values(
    history: List[Dict[str, Any]], metric: str, runs: int = BASELINE_RUNS
) -> List[float]:
    vals: List[float] = []
    for entry in reversed(history):
        if not entry.get("ok"):
            continue
        v = entry.get("metrics", {}).get(metric)
        if isinstance(v, (int, float)):
            vals.append(float(v))
            if len(vals) >= runs:
                break
    vals.reverse()
    return vals


def judge(
    value: float, baseline: List[float], metric: str
) -> Dict[str, Any]:
    d = direction(metric)
    if d is None:
        return {}
    if len(baseline) < MIN_BASELINE:
        return {"verdict": VERDICT_NEW, "baseline_n": len(baseline)}
    med = _median(baseline)
    mad = _mad(baseline, med)
    threshold = max(MAD_SIGMAS * 1.4826 * mad, REL_FLOOR * abs(med))
    deviation = value - med
    if abs(deviation) <= threshold:
        verdict = VERDICT_FLAT
    elif (deviation > 0) == (d > 0):
        verdict = VERDICT_IMPROVE
    else:
        verdict = VERDICT_REGRESS
    return {
        "verdict": verdict,
        "baseline_median": round(med, 6),
        "baseline_mad": round(mad, 6),
        "baseline_n": len(baseline),
        "deviation": round(deviation, 6),
        "threshold": round(threshold, 6),
        "delta_pct": round(100.0 * deviation / med, 2) if med else None,
    }


def judge_entry(
    entry: Dict[str, Any], history: List[Dict[str, Any]]
) -> Dict[str, Dict[str, Any]]:
    verdicts: Dict[str, Dict[str, Any]] = {}
    for metric, value in sorted(entry.get("metrics", {}).items()):
        v = judge(value, baseline_values(history, metric), metric)
        if v:
            verdicts[metric] = v
    return verdicts


def format_verdicts(entry: Dict[str, Any]) -> List[str]:
    lines: List[str] = []
    order = {VERDICT_REGRESS: 0, VERDICT_IMPROVE: 1, VERDICT_FLAT: 2,
             VERDICT_NEW: 3}
    items = sorted(
        entry.get("verdicts", {}).items(),
        key=lambda kv: (order.get(kv[1]["verdict"], 9), kv[0]),
    )
    for metric, v in items:
        if v["verdict"] == VERDICT_NEW:
            lines.append(f"bench[{metric}]: new (baseline "
                         f"{v['baseline_n']}/{MIN_BASELINE} runs)")
            continue
        pct = v.get("delta_pct")
        pct_s = f"{pct:+.1f}%" if pct is not None else "n/a"
        lines.append(
            f"bench[{metric}]: {v['verdict']} "
            f"({entry['metrics'][metric]:g} vs median "
            f"{v['baseline_median']:g}, {pct_s}, "
            f"gate ±{v['threshold']:g}, n={v['baseline_n']})"
        )
    return lines


def record_run(
    result: Dict[str, Any],
    source: str = "bench.py",
    ledger: str = DEFAULT_LEDGER,
) -> Dict[str, Any]:
    """Normalize one run, judge it against the ledger, append, return
    the entry (with ``verdicts``).  The hook ``bench.py`` calls."""
    history = read_ledger(ledger)
    entry = normalize(result, source=source)
    entry["verdicts"] = judge_entry(entry, history)
    append_entry(ledger, entry)
    return entry


# -- CLI ---------------------------------------------------------------


def cmd_ingest(args) -> int:
    history = read_ledger(args.ledger)
    added = 0
    for path in args.files:
        with open(path) as fh:
            raw = json.load(fh)
        entry = normalize(raw, source=os.path.basename(path))
        entry["verdicts"] = judge_entry(entry, history)
        append_entry(args.ledger, entry)
        history.append(entry)
        added += 1
        status = "ok" if entry["ok"] else "failed-run"
        print(f"ingested {path} ({status}, "
              f"{len(entry['metrics'])} metrics)")
    print(f"{added} entries -> {args.ledger}")
    return 0


def cmd_record(args) -> int:
    if args.file == "-":
        raw = json.load(sys.stdin)
        source = "stdin"
    else:
        with open(args.file) as fh:
            raw = json.load(fh)
        source = os.path.basename(args.file)
    entry = record_run(raw, source=source, ledger=args.ledger)
    for line in format_verdicts(entry):
        print(line)
    if not entry["verdicts"]:
        print("no judged metrics (failed run or no directional metrics)")
    return 1 if any(
        v["verdict"] == VERDICT_REGRESS for v in entry["verdicts"].values()
    ) else 0


def cmd_report(args) -> int:
    history = read_ledger(args.ledger)
    if not history:
        print(f"empty ledger: {args.ledger}")
        return 0
    recent = history[-args.last:]
    for entry in recent:
        stamp = time.strftime("%Y-%m-%d %H:%M:%S",
                              time.localtime(entry.get("ts", 0)))
        ok = "ok" if entry.get("ok") else "FAILED"
        print(f"--- {stamp}  {entry.get('source', '?')}  [{ok}]")
        lines = format_verdicts(entry)
        for line in lines:
            print(f"  {line}")
        if not lines and entry.get("ok"):
            print(f"  {len(entry.get('metrics', {}))} metrics, none judged")
    regress = sum(
        1 for e in recent
        for v in e.get("verdicts", {}).values()
        if v["verdict"] == VERDICT_REGRESS
    )
    print(f"{len(recent)} runs shown, {regress} regressions flagged")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ledger", default=DEFAULT_LEDGER)
    sub = ap.add_subparsers(dest="cmd", required=True)

    ing = sub.add_parser("ingest", help="normalize BENCH_*.json into the ledger")
    ing.add_argument("files", nargs="+")
    ing.set_defaults(fn=cmd_ingest)

    rec = sub.add_parser("record", help="append one run and print verdicts")
    rec.add_argument("file", help="result JSON path, or - for stdin")
    rec.set_defaults(fn=cmd_record)

    rep = sub.add_parser("report", help="show recent verdicts")
    rep.add_argument("--last", type=int, default=10)
    rep.set_defaults(fn=cmd_report)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
