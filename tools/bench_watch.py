"""Probe-then-bench retry loop: land the TPU evidence artifact.

The TPU tunnel wedges for long stretches (VERDICT rounds 2/4/5): a bench
started while it is wedged burns its whole probe budget and falls back to
CPU, so no TPU-platform artifact has ever been committed.  This watcher
inverts the loop — probe CHEAPLY first (one disposable subprocess, hard
timeout), and only when a probe comes back healthy pay for the full bench
run.  On the first bench that reports ``platform != cpu`` the raw JSON is
written to ``BENCH_tpu_evidence.json`` at the repo root — the artifact
PARITY.md's ≥50K claim is waiting on.

The bench it launches runs every phase of ``bench.py`` main(), which
since round 6 includes the ``live_pipeline`` depth sweep (pipelined
coalescer under synthetic fetch latency, ``BENCH_LIVE_*`` knobs) — a
TPU evidence artifact therefore also carries the live-path pipelining
numbers alongside the kernel throughput.

Usage:
    python tools/bench_watch.py [--attempts N] [--interval S] [--once]

Exit codes: 0 = evidence written (or already present), 1 = budget
exhausted without a TPU bench, 2 = bad invocation.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from nomad_tpu.retry import (  # noqa: E402
    RetryBudgetExceeded,
    RetryPolicy,
    env_int,
    retry_call,
)

EVIDENCE = os.path.join(REPO, "BENCH_tpu_evidence.json")
PROBE_TIMEOUT = env_int("BENCH_PROBE_TIMEOUT", 150)
# The bench itself retries internally; this bound only reaps a run that
# wedges mid-flight AFTER a healthy probe (observed failure mode: tunnel
# dies between probe and pipelined phase).
BENCH_TIMEOUT = env_int("BENCH_WATCH_BENCH_TIMEOUT", 1800)


def probe() -> str:
    """One disposable-subprocess backend probe; returns the platform name
    ('tpu', 'cpu', ...) or an error string prefixed with 'err:'."""
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT,
            cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return f"err:hung >{PROBE_TIMEOUT}s (wedged tunnel?)"
    if p.returncode != 0:
        return f"err:rc={p.returncode}: {p.stderr.strip()[-200:]}"
    return p.stdout.strip()


def run_bench() -> dict | None:
    """One full bench run; returns the parsed result JSON or None."""
    env = dict(os.environ)
    # The probe already succeeded — skip the bench's own 4-attempt probe
    # ladder so a mid-run wedge fails fast into THIS loop's next attempt.
    env.setdefault("BENCH_PROBE_ATTEMPTS", "1")
    try:
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=BENCH_TIMEOUT,
            cwd=REPO, env=env,
        )
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"bench_watch: bench hung >{BENCH_TIMEOUT}s\n")
        return None
    # The result is the LAST json line on stdout (breadcrumbs go to stderr).
    for line in reversed(p.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    sys.stderr.write(
        f"bench_watch: no JSON in bench output (rc={p.returncode}); "
        f"stderr tail: {p.stderr.strip()[-300:]}\n"
    )
    return None


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--attempts", type=int, default=12,
                    help="max probe attempts (default 12)")
    ap.add_argument("--interval", type=float, default=300.0,
                    help="seconds between failed probes (default 300)")
    ap.add_argument("--once", action="store_true",
                    help="single probe+bench attempt, no retry loop")
    args = ap.parse_args()

    if os.path.exists(EVIDENCE):
        sys.stderr.write(f"bench_watch: {EVIDENCE} already present\n")
        return 0

    attempts = 1 if args.once else args.attempts
    seen = {"n": 0}

    class _NoEvidence(Exception):
        """This attempt produced no TPU artifact — retry on schedule."""

    def attempt_once() -> dict:
        seen["n"] += 1
        plat = probe()
        sys.stderr.write(
            f"bench_watch: probe {seen['n']}/{attempts}: {plat}\n"
        )
        if not plat or plat.startswith("err:") or plat == "cpu":
            raise _NoEvidence(f"probe: {plat}")
        result = run_bench()
        if result is None or result.get("platform") == "cpu":
            sys.stderr.write(
                "bench_watch: probe was healthy but the bench run "
                "fell back / died; retrying\n"
            )
            raise _NoEvidence("bench fell back / died")
        return result

    # Flat (multiplier=1, no jitter) schedule: probing a wedged tunnel
    # faster doesn't unwedge it, and the operator asked for --interval.
    policy = RetryPolicy(
        base_delay=args.interval, multiplier=1.0, jitter=0.0,
        max_attempts=attempts,
    )
    try:
        result = retry_call(
            attempt_once, policy, retry_on=(_NoEvidence,),
            description="tpu evidence probe",
        )
    except RetryBudgetExceeded:
        sys.stderr.write("bench_watch: budget exhausted, no TPU evidence\n")
        return 1

    result["captured_by"] = "tools/bench_watch.py"
    result["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    tmp = EVIDENCE + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(result, fh, indent=2)
        fh.write("\n")
    os.replace(tmp, EVIDENCE)
    sys.stderr.write(
        f"bench_watch: evidence written -> {EVIDENCE} "
        f"(value={result.get('value')})\n"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
