"""Probe-then-bench retry loop: land the TPU evidence artifact.

The TPU tunnel wedges for long stretches (VERDICT rounds 2/4/5): a bench
started while it is wedged burns its whole probe budget and falls back to
CPU, so no TPU-platform artifact has ever been committed.  This watcher
inverts the loop — probe CHEAPLY first (one disposable subprocess, hard
timeout), and only when a probe comes back healthy pay for the full bench
run.  On the first bench that reports ``platform != cpu`` the raw JSON is
written to ``BENCH_tpu_evidence.json`` at the repo root — the artifact
PARITY.md's ≥50K claim is waiting on.

The bench it launches runs every phase of ``bench.py`` main(), which
since round 6 includes the ``live_pipeline`` depth sweep (pipelined
coalescer under synthetic fetch latency, ``BENCH_LIVE_*`` knobs) — a
TPU evidence artifact therefore also carries the live-path pipelining
numbers alongside the kernel throughput.

Usage:
    python tools/bench_watch.py [--attempts N] [--interval S] [--once]

Exit codes: 0 = evidence written (or already present), 1 = budget
exhausted without a TPU bench, 2 = bad invocation.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from nomad_tpu.retry import (  # noqa: E402
    RetryBudgetExceeded,
    RetryPolicy,
    env_int,
    retry_call,
)

EVIDENCE = os.path.join(REPO, "BENCH_tpu_evidence.json")
PROBE_TIMEOUT = env_int("BENCH_PROBE_TIMEOUT", 150)
# The bench itself retries internally; this bound only reaps a run that
# wedges mid-flight AFTER a healthy probe (observed failure mode: tunnel
# dies between probe and pipelined phase).
BENCH_TIMEOUT = env_int("BENCH_WATCH_BENCH_TIMEOUT", 1800)

# Probes/benches that had to be SIGKILLed (wedged tunnel analog).  The
# count rides into the ledger entry (``probe_wedged``) so wedge frequency
# is trendable next to the numbers it delayed.
WEDGED = {"probe": 0, "bench": 0}


def _run_reaped(cmd: list, timeout: int, env: dict | None = None):
    """Run ``cmd`` in its own process group; on timeout SIGKILL the whole
    group.  ``subprocess.run``'s timeout kill only signals the direct
    child — a wedged tunnel helper (grandchild holding the pipe open)
    leaves ``communicate()`` hanging forever, which is exactly the state
    this watcher exists to escape.  Returns (rc, stdout, stderr); rc is
    None when the group had to be killed."""
    p = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env=env, start_new_session=True,
    )
    try:
        out, err = p.communicate(timeout=timeout)
        return p.returncode, out, err
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            p.kill()
        try:  # bounded reap: a truly stuck group must not hang US
            p.communicate(timeout=10)
        except (subprocess.TimeoutExpired, ValueError):
            pass
        return None, "", ""


def probe() -> str:
    """One disposable-subprocess backend probe; returns the platform name
    ('tpu', 'cpu', ...) or an error string prefixed with 'err:'."""
    rc, out, err = _run_reaped(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        timeout=PROBE_TIMEOUT,
    )
    if rc is None:
        WEDGED["probe"] += 1
        return f"err:hung >{PROBE_TIMEOUT}s (wedged tunnel?); killed group"
    if rc != 0:
        return f"err:rc={rc}: {err.strip()[-200:]}"
    return out.strip()


def run_bench() -> dict | None:
    """One full bench run; returns the parsed result JSON or None."""
    env = dict(os.environ)
    # The probe already succeeded — skip the bench's own 4-attempt probe
    # ladder so a mid-run wedge fails fast into THIS loop's next attempt.
    env.setdefault("BENCH_PROBE_ATTEMPTS", "1")
    # The watcher records the ledger entry itself (with the wedge counts
    # merged in) — the child recording too would double-count the run.
    env["NOMAD_TPU_BENCH_LEDGER"] = "off"
    rc, out, err = _run_reaped(
        [sys.executable, os.path.join(REPO, "bench.py")],
        timeout=BENCH_TIMEOUT, env=env,
    )
    if rc is None:
        WEDGED["bench"] += 1
        sys.stderr.write(
            f"bench_watch: bench hung >{BENCH_TIMEOUT}s; killed group\n"
        )
        return None
    # The result is the LAST json line on stdout (breadcrumbs go to stderr).
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    sys.stderr.write(
        f"bench_watch: no JSON in bench output (rc={rc}); "
        f"stderr tail: {err.strip()[-300:]}\n"
    )
    return None


def _record_ledger(result: dict) -> None:
    """One ledger entry for this watch (child bench recording is off),
    with the SIGKILL tallies merged in as ``probe_wedged`` counts."""
    result = dict(result)
    result["probe_wedged"] = WEDGED["probe"]
    result["bench_wedged"] = WEDGED["bench"]
    ledger_env = os.environ.get("NOMAD_TPU_BENCH_LEDGER", "")
    if ledger_env.lower() in ("0", "off", "no"):
        return
    try:
        import bench_history

        kw = {"ledger": ledger_env} if ledger_env else {}
        entry = bench_history.record_run(
            result, source="bench_watch.py", **kw
        )
        for line in bench_history.format_verdicts(entry):
            sys.stderr.write(line + "\n")
    except Exception as e:  # noqa: BLE001 — the ledger must never cost a run
        sys.stderr.write(
            f"bench_watch ledger skipped: {type(e).__name__}: {e}\n"
        )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--attempts", type=int, default=12,
                    help="max probe attempts (default 12)")
    ap.add_argument("--interval", type=float, default=300.0,
                    help="seconds between failed probes (default 300)")
    ap.add_argument("--once", action="store_true",
                    help="single probe+bench attempt, no retry loop")
    args = ap.parse_args()

    if os.path.exists(EVIDENCE):
        sys.stderr.write(f"bench_watch: {EVIDENCE} already present\n")
        return 0

    attempts = 1 if args.once else args.attempts
    seen = {"n": 0}

    class _NoEvidence(Exception):
        """This attempt produced no TPU artifact — retry on schedule."""

    def attempt_once() -> dict:
        seen["n"] += 1
        plat = probe()
        sys.stderr.write(
            f"bench_watch: probe {seen['n']}/{attempts}: {plat}\n"
        )
        if not plat or plat.startswith("err:") or plat == "cpu":
            raise _NoEvidence(f"probe: {plat}")
        result = run_bench()
        if result is None or result.get("platform") == "cpu":
            sys.stderr.write(
                "bench_watch: probe was healthy but the bench run "
                "fell back / died; retrying\n"
            )
            raise _NoEvidence("bench fell back / died")
        return result

    # Flat (multiplier=1, no jitter) schedule: probing a wedged tunnel
    # faster doesn't unwedge it, and the operator asked for --interval.
    policy = RetryPolicy(
        base_delay=args.interval, multiplier=1.0, jitter=0.0,
        max_attempts=attempts,
    )
    try:
        result = retry_call(
            attempt_once, policy, retry_on=(_NoEvidence,),
            description="tpu evidence probe",
        )
    except RetryBudgetExceeded:
        sys.stderr.write("bench_watch: budget exhausted, no TPU evidence\n")
        # Even a fruitless watch leaves its wedge tally in the ledger —
        # "the tunnel was dead all night" is itself trend data.
        _record_ledger({
            "probe_attempts_made": seen["n"],
        })
        return 1

    result["captured_by"] = "tools/bench_watch.py"
    result["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    _record_ledger(result)
    tmp = EVIDENCE + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(result, fh, indent=2)
        fh.write("\n")
    os.replace(tmp, EVIDENCE)
    sys.stderr.write(
        f"bench_watch: evidence written -> {EVIDENCE} "
        f"(value={result.get('value')})\n"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
