"""Standing probe-then-bench watch: land the TPU evidence artifact.

The TPU tunnel wedges for long stretches (VERDICT rounds 2/4/5): a bench
started while it is wedged burns its whole probe budget and falls back to
CPU, so no TPU-platform artifact has ever been committed.  This watcher
inverts the loop — probe CHEAPLY first (one disposable subprocess, hard
timeout), and only when a probe comes back healthy pay for the full bench
run.  On the first bench that reports ``platform != cpu`` the raw JSON is
written to ``BENCH_tpu_evidence.json`` at the repo root — the artifact
PARITY.md's ≥50K claim is waiting on.

This is a STANDING watch, not a fixed-cadence poll:

* failed probes back off exponentially through the shared
  ``nomad_tpu.retry`` policy (base ``--interval``, capped at
  ``--max-interval``, jittered) — probing a wedged tunnel faster does not
  unwedge it, and an overnight watch shouldn't hammer the rig;
* EVERY wedged or failed probe (and every bench that died or fell back
  after a healthy probe) is recorded to ``BENCH_LEDGER.jsonl`` as a
  failed-run entry at the moment it happens — "the tunnel was dead from
  02:10 to 05:40" is readable from the ledger afterwards, not just a
  terminal tally;
* ``--max-hours`` bounds the whole watch in wall-clock time regardless of
  how many attempts the backoff schedule would still allow.

The bench it launches runs every phase of ``bench.py`` main(), including
the fused-megakernel phase (one launch per batched eval pipeline) — a TPU
evidence artifact therefore carries the fused and staged numbers side by
side.

Usage:
    python tools/bench_watch.py [--attempts N] [--interval S]
                                [--max-interval S] [--max-hours H] [--once]

Exit codes: 0 = evidence written (or already present), 1 = budget
exhausted without a TPU bench, 2 = bad invocation.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from nomad_tpu.obs.breaker import (  # noqa: E402
    STALL_SLOW,
    STALL_WEDGED,
    BreakerConfig,
    DeviceBreaker,
    classify_stall,
)
from nomad_tpu.retry import (  # noqa: E402
    RetryBudgetExceeded,
    RetryPolicy,
    env_int,
    retry_call,
)

EVIDENCE = os.path.join(REPO, "BENCH_tpu_evidence.json")
PROBE_TIMEOUT = env_int("BENCH_PROBE_TIMEOUT", 150)
# A probe that answers but takes longer than this is "slow" — the tunnel
# is alive but degrading, the same verdict band the coalescer's watchdog
# uses (see nomad_tpu/obs/breaker.py).
PROBE_SLOW = env_int("BENCH_PROBE_SLOW", 30)
# The bench itself retries internally; this bound only reaps a run that
# wedges mid-flight AFTER a healthy probe (observed failure mode: tunnel
# dies between probe and pipelined phase).
BENCH_TIMEOUT = env_int("BENCH_WATCH_BENCH_TIMEOUT", 1800)

# Probes/benches that had to be SIGKILLed (wedged tunnel analog).  Each is
# ALSO recorded to the ledger as it happens (_record_failure); the tally
# additionally rides into the final evidence entry so wedge frequency is
# trendable next to the numbers it delayed.
WEDGED = {"probe": 0, "bench": 0}

# Probe outcomes feed the SAME breaker state machine the coalescer runs
# on its device fetches — the slow band is [PROBE_SLOW, PROBE_TIMEOUT],
# a SIGKILLed probe is a wedge.  The breaker's trip count rides into
# every ledger entry so "the tunnel tripped 3 times overnight" is
# trendable next to the numbers it delayed.  cold_scale=1: the probe's
# kill bound already absorbs first-import cost.
PROBE_BREAKER = DeviceBreaker(config=BreakerConfig(
    deadline_ms=PROBE_SLOW * 1000,
    cold_scale=1.0,
    wedge_factor=max(float(PROBE_TIMEOUT) / max(PROBE_SLOW, 1), 1.0),
))


def _breaker_tallies() -> dict:
    b = PROBE_BREAKER.brief()
    return {
        "probe_breaker": b["breaker"],
        "probe_breaker_trips": b["trips"],
        "probe_breaker_wedged": b["wedged"],
        "probe_breaker_slow": b["slow"],
    }


def _run_reaped(cmd: list, timeout: int, env: dict | None = None):
    """Run ``cmd`` in its own process group; on timeout SIGKILL the whole
    group.  ``subprocess.run``'s timeout kill only signals the direct
    child — a wedged tunnel helper (grandchild holding the pipe open)
    leaves ``communicate()`` hanging forever, which is exactly the state
    this watcher exists to escape.  Returns (rc, stdout, stderr); rc is
    None when the group had to be killed."""
    p = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env=env, start_new_session=True,
    )
    try:
        out, err = p.communicate(timeout=timeout)
        return p.returncode, out, err
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            p.kill()
        try:  # bounded reap: a truly stuck group must not hang US
            p.communicate(timeout=10)
        except (subprocess.TimeoutExpired, ValueError):
            pass
        return None, "", ""


def probe() -> str:
    """One disposable-subprocess backend probe; returns the platform name
    ('tpu', 'cpu', ...) or an error string prefixed with 'err:'.

    The verdict reuses the coalescer watchdog's wedged-vs-slow
    classification (:func:`classify_stall`) and feeds ``PROBE_BREAKER``,
    so the watch and the live dispatch path judge the tunnel with one
    rulebook: killed-at-timeout is a wedge, answered-late is slow."""
    t0 = time.monotonic()
    rc, out, err = _run_reaped(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        timeout=PROBE_TIMEOUT,
    )
    elapsed = time.monotonic() - t0
    if rc is None:
        WEDGED["probe"] += 1
        PROBE_BREAKER.record_wedge(elapsed)
        return f"err:hung >{PROBE_TIMEOUT}s (wedged tunnel?); killed group"
    verdict = classify_stall(
        elapsed, PROBE_BREAKER.deadline_s(), PROBE_BREAKER.cfg.wedge_factor
    )
    if verdict == STALL_WEDGED:
        # The subprocess answered but only past the wedge bound (group
        # kill raced the reply) — trust the classification, not the rc.
        PROBE_BREAKER.record_wedge(elapsed)
    elif verdict == STALL_SLOW:
        PROBE_BREAKER.record_slow(elapsed)
        sys.stderr.write(
            f"bench_watch: probe answered late ({elapsed:.1f}s > "
            f"{PROBE_SLOW}s) — tunnel degrading\n"
        )
    else:
        PROBE_BREAKER.record_ok(elapsed)
    if rc != 0:
        return f"err:rc={rc}: {err.strip()[-200:]}"
    return out.strip()


def run_bench() -> dict | None:
    """One full bench run; returns the parsed result JSON or None."""
    env = dict(os.environ)
    # The probe already succeeded — skip the bench's own 4-attempt probe
    # ladder so a mid-run wedge fails fast into THIS loop's next attempt.
    env.setdefault("BENCH_PROBE_ATTEMPTS", "1")
    # The watcher records the ledger entries itself (per-failure records +
    # the final evidence entry) — the child recording too would
    # double-count the run.
    env["NOMAD_TPU_BENCH_LEDGER"] = "off"
    rc, out, err = _run_reaped(
        [sys.executable, os.path.join(REPO, "bench.py")],
        timeout=BENCH_TIMEOUT, env=env,
    )
    if rc is None:
        WEDGED["bench"] += 1
        sys.stderr.write(
            f"bench_watch: bench hung >{BENCH_TIMEOUT}s; killed group\n"
        )
        return None
    # The result is the LAST json line on stdout (breadcrumbs go to stderr).
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    sys.stderr.write(
        f"bench_watch: no JSON in bench output (rc={rc}); "
        f"stderr tail: {err.strip()[-300:]}\n"
    )
    return None


def _ledger_kwargs() -> dict | None:
    """Ledger destination from the env; None = recording disabled."""
    ledger_env = os.environ.get("NOMAD_TPU_BENCH_LEDGER", "")
    if ledger_env.lower() in ("0", "off", "no"):
        return None
    return {"ledger": ledger_env} if ledger_env else {}


def _record_failure(attempt: int, reason: str) -> None:
    """One failed-run ledger entry PER wedged/failed probe or bench, at
    the moment it happens — the driver-wrapper input shape (rc/parsed/
    tail) normalizes to ``ok: false``, so failures are visible in the
    history without ever contributing to a metric baseline."""
    kw = _ledger_kwargs()
    if kw is None:
        return
    try:
        import bench_history

        tallies = _breaker_tallies()
        bench_history.record_run(
            {
                "n": attempt,
                "cmd": "bench_watch probe",
                "rc": 1,
                "parsed": None,
                "tail": (
                    f"{reason} [breaker={tallies['probe_breaker']} "
                    f"trips={tallies['probe_breaker_trips']}]"
                ),
            },
            source="bench_watch.py",
            **kw,
        )
    except Exception as e:  # noqa: BLE001 — the ledger must never cost a run
        sys.stderr.write(
            f"bench_watch ledger skipped: {type(e).__name__}: {e}\n"
        )


def _record_ledger(result: dict) -> None:
    """The successful-run ledger entry, with the SIGKILL tallies merged in
    as ``probe_wedged``/``bench_wedged`` counts."""
    kw = _ledger_kwargs()
    if kw is None:
        return
    result = dict(result)
    result["probe_wedged"] = WEDGED["probe"]
    result["bench_wedged"] = WEDGED["bench"]
    result.update(_breaker_tallies())
    try:
        import bench_history

        entry = bench_history.record_run(
            result, source="bench_watch.py", **kw
        )
        for line in bench_history.format_verdicts(entry):
            sys.stderr.write(line + "\n")
    except Exception as e:  # noqa: BLE001 — the ledger must never cost a run
        sys.stderr.write(
            f"bench_watch ledger skipped: {type(e).__name__}: {e}\n"
        )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--attempts", type=int, default=48,
                    help="max probe attempts (default 48)")
    ap.add_argument("--interval", type=float, default=60.0,
                    help="base seconds between failed probes (default 60; "
                         "backs off exponentially from here)")
    ap.add_argument("--max-interval", type=float, default=900.0,
                    help="backoff ceiling in seconds (default 900)")
    ap.add_argument("--max-hours", type=float, default=12.0,
                    help="hard wall-clock bound on the whole watch "
                         "(default 12h)")
    ap.add_argument("--once", action="store_true",
                    help="single probe+bench attempt, no retry loop")
    args = ap.parse_args()
    if args.interval <= 0 or args.max_hours <= 0 or args.attempts <= 0:
        ap.error("--interval/--max-hours/--attempts must be positive")

    if os.path.exists(EVIDENCE):
        sys.stderr.write(f"bench_watch: {EVIDENCE} already present\n")
        return 0

    attempts = 1 if args.once else args.attempts
    seen = {"n": 0}

    class _NoEvidence(Exception):
        """This attempt produced no TPU artifact — retry on schedule."""

    def attempt_once() -> dict:
        seen["n"] += 1
        plat = probe()
        sys.stderr.write(
            f"bench_watch: probe {seen['n']}/{attempts}: {plat}\n"
        )
        if not plat or plat.startswith("err:") or plat == "cpu":
            _record_failure(seen["n"], f"probe: {plat}")
            raise _NoEvidence(f"probe: {plat}")
        result = run_bench()
        if result is None or result.get("platform") == "cpu":
            sys.stderr.write(
                "bench_watch: probe was healthy but the bench run "
                "fell back / died; retrying\n"
            )
            _record_failure(
                seen["n"],
                "bench fell back / died after healthy probe "
                f"(platform={None if result is None else result.get('platform')})",
            )
            raise _NoEvidence("bench fell back / died")
        return result

    # Exponential backoff (shared retry.py policy): a wedged tunnel isn't
    # unwedged by probing harder, so the schedule stretches from
    # --interval toward --max-interval, jittered to decorrelate from any
    # rig-side periodicity.  --max-hours is the deadline backstop — the
    # watch ends on whichever budget (attempts or wall clock) runs out
    # first.
    policy = RetryPolicy(
        base_delay=args.interval,
        max_delay=max(args.interval, args.max_interval),
        multiplier=2.0,
        jitter=0.25,
        max_attempts=attempts,
        deadline=args.max_hours * 3600.0,
    )

    def on_retry(attempt: int, exc: BaseException, delay: float) -> None:
        sys.stderr.write(
            f"bench_watch: attempt {attempt} failed ({exc}); "
            f"next probe in {delay:.0f}s\n"
        )

    try:
        result = retry_call(
            attempt_once, policy, retry_on=(_NoEvidence,),
            on_retry=on_retry, description="tpu evidence probe",
        )
    except RetryBudgetExceeded as e:
        sys.stderr.write(f"bench_watch: {e}; no TPU evidence\n")
        return 1

    result["captured_by"] = "tools/bench_watch.py"
    result["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    _record_ledger(result)
    tmp = EVIDENCE + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(result, fh, indent=2)
        fh.write("\n")
    os.replace(tmp, EVIDENCE)
    sys.stderr.write(
        f"bench_watch: evidence written -> {EVIDENCE} "
        f"(value={result.get('value')})\n"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
