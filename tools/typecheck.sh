#!/usr/bin/env bash
# Typing gate: mypy (non-strict, --check-untyped-defs) over the
# declarative layers — nomad_tpu/structs/ (wire/serde contracts) and
# nomad_tpu/lint/ (the analyzer itself) — and the device hot path —
# nomad_tpu/ops/ (kernels, request encoding, numpy twin) and
# nomad_tpu/parallel/ (mesh sharding), where a drifted NamedTuple field
# or Optional default becomes a silent recompile or a wrong-dtype
# transfer.  Config: mypy.ini.
#
# Exits 0 with a notice when mypy is not installed (the CI image may not
# ship it; the gate must not invent a dependency) — run
#   pip install mypy && tools/typecheck.sh
# locally for the real check.
set -euo pipefail
cd "$(dirname "$0")/.."

if ! python -m mypy --version >/dev/null 2>&1; then
    echo "typecheck: mypy not installed — skipping (pip install mypy to enable)"
    exit 0
fi

exec python -m mypy --config-file mypy.ini \
    nomad_tpu/structs/ nomad_tpu/lint/ nomad_tpu/ops/ nomad_tpu/parallel/
