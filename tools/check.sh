#!/usr/bin/env bash
# One-shot local gate: everything a PR must survive, in dependency order,
# with a per-stage summary at the end.  Runs ALL stages even when an
# early one fails (you want the whole damage report, not the first
# casualty); exits nonzero if ANY stage failed.
#
#   stage 1  lint (ast)     python -m nomad_tpu.lint          — syntactic rules
#   stage 2  lint (jaxpr)   python -m nomad_tpu.lint --jaxpr  — semantic device contracts
#   stage 3  typecheck      tools/typecheck.sh                — mypy (skips if not installed)
#   stage 4  tier-1         the ROADMAP.md pytest command     — the real test gate
#
# Usage: tools/check.sh [--fast]   (--fast skips stage 4)
set -u
cd "$(dirname "$0")/.."

FAST=0
[ "${1:-}" = "--fast" ] && FAST=1

names=()
rcs=()

stage() {
    local name="$1"
    shift
    echo
    echo "=== ${name} ==="
    "$@"
    local rc=$?
    names+=("$name")
    rcs+=("$rc")
    return 0
}

stage "lint (ast)" env JAX_PLATFORMS=cpu python -m nomad_tpu.lint
stage "lint (jaxpr)" env JAX_PLATFORMS=cpu python -m nomad_tpu.lint --jaxpr
stage "typecheck" bash tools/typecheck.sh
if [ "$FAST" -eq 0 ]; then
    # Tier-1, verbatim from ROADMAP.md (minus the log tee — this is the
    # local loop, not the driver).
    stage "tier-1" env JAX_PLATFORMS=cpu timeout -k 10 870 \
        python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider \
        -p no:xdist -p no:randomly
fi

echo
echo "=== summary ==="
fail=0
for i in "${!names[@]}"; do
    if [ "${rcs[$i]}" -eq 0 ]; then
        echo "  PASS  ${names[$i]}"
    else
        echo "  FAIL  ${names[$i]} (rc=${rcs[$i]})"
        fail=1
    fi
done
exit "$fail"
