#!/usr/bin/env bash
# One-shot local gate: everything a PR must survive, in dependency order,
# with a per-stage summary at the end.  Runs ALL stages even when an
# early one fails (you want the whole damage report, not the first
# casualty); exits nonzero if ANY stage failed.
#
#   stage 1  lint (ast)     python -m nomad_tpu.lint          — syntactic rules
#   stage 2  lint (jaxpr)   python -m nomad_tpu.lint --jaxpr  — semantic device contracts
#   stage 3  typecheck      tools/typecheck.sh                — mypy (skips if not installed)
#   stage 4  tier-1         the ROADMAP.md pytest command     — the real test gate
#   stage 5  chaos          (--chaos only) the device fault-domain scenarios
#                           via tools/chaos_repro.py — wedge recovery,
#                           slow-flap flip budget, shard-loss evacuation
#
# Usage: tools/check.sh [--fast] [--chaos]
#   --fast   skips stage 4
#   --chaos  adds stage 5 (seeded device-fault scenario replays)
set -u
cd "$(dirname "$0")/.."

FAST=0
CHAOS=0
for arg in "$@"; do
    case "$arg" in
        --fast) FAST=1 ;;
        --chaos) CHAOS=1 ;;
    esac
done

names=()
rcs=()

stage() {
    local name="$1"
    shift
    echo
    echo "=== ${name} ==="
    "$@"
    local rc=$?
    names+=("$name")
    rcs+=("$rc")
    return 0
}

stage "lint (ast)" env JAX_PLATFORMS=cpu python -m nomad_tpu.lint
stage "lint (jaxpr)" env JAX_PLATFORMS=cpu python -m nomad_tpu.lint --jaxpr
stage "typecheck" bash tools/typecheck.sh
if [ "$FAST" -eq 0 ]; then
    # Tier-1, verbatim from ROADMAP.md (minus the log tee — this is the
    # local loop, not the driver).
    stage "tier-1" env JAX_PLATFORMS=cpu timeout -k 10 870 \
        python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider \
        -p no:xdist -p no:randomly
fi
if [ "$CHAOS" -eq 1 ]; then
    # The seeded device fault-domain replays (same seeds as tier-1's
    # TestScenarios — rc 1 on any invariant violation).
    stage "chaos (wedge)" env JAX_PLATFORMS=cpu \
        python tools/chaos_repro.py wedged_dispatch_recovers 11
    stage "chaos (slow-flap)" env JAX_PLATFORMS=cpu \
        python tools/chaos_repro.py device_slow_flapping 7
    stage "chaos (shard-loss)" env JAX_PLATFORMS=cpu \
        python tools/chaos_repro.py shard_loss_evacuation 5
fi

echo
echo "=== summary ==="
fail=0
for i in "${!names[@]}"; do
    if [ "${rcs[$i]}" -eq 0 ]; then
        echo "  PASS  ${names[$i]}"
    else
        echo "  FAIL  ${names[$i]} (rc=${rcs[$i]})"
        fail=1
    fi
done
exit "$fail"
