"""Replay one chaos scenario by name + seed, for debugging a failure.

A failing ``tests/test_chaos.py`` scenario prints its report (name, seed,
fired faults, violations).  This tool re-runs that exact schedule outside
pytest so it can be iterated on quickly, with the full report dumped as
JSON — including the fired-fault rows, which ARE the schedule to compare
across replays.

Usage:
    JAX_PLATFORMS=cpu python tools/chaos_repro.py <scenario> <seed>
        [--stride N] [--workdir DIR]

    python tools/chaos_repro.py --list
    python tools/chaos_repro.py wal_truncation_sweep 7 --stride 1
    python tools/chaos_repro.py partition_then_heal 3

Exit status: 0 when every invariant held, 1 on violations (the report is
printed either way).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from nomad_tpu.retry import env_defaults  # noqa: E402

# Pin the rig BEFORE any jax-adjacent import: cpu backend, and the same
# doubled raft timeouts tests/conftest.py uses, so a replay sees the
# exact timing regime the failing test did.
env_defaults(JAX_PLATFORMS="cpu", NOMAD_TPU_RAFT_TIMEOUT_SCALE="2.0")


def main(argv=None) -> int:
    from nomad_tpu.chaos.scenarios import SCENARIOS

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("scenario", nargs="?", help="scenario name")
    ap.add_argument("seed", nargs="?", type=int, help="schedule seed")
    ap.add_argument(
        "--stride", type=int, default=0,
        help="WAL sweep cut stride (1 = every byte offset; "
             "0 = the seeded tier-1 stride)",
    )
    ap.add_argument(
        "--workdir", default="",
        help="scratch dir (default: a fresh temp dir)",
    )
    ap.add_argument(
        "--list", action="store_true", help="list scenarios and exit"
    )
    args = ap.parse_args(argv)

    if args.list or not args.scenario:
        for name in sorted(SCENARIOS):
            print(name)
        return 0
    if args.scenario not in SCENARIOS:
        ap.error(
            f"unknown scenario {args.scenario!r} "
            f"(choices: {', '.join(sorted(SCENARIOS))})"
        )
    if args.seed is None:
        ap.error("seed is required")

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos-repro-")
    kwargs = {}
    if args.scenario == "wal_truncation_sweep" and args.stride:
        kwargs["stride"] = args.stride
    report = SCENARIOS[args.scenario](args.seed, workdir, **kwargs)
    print(json.dumps(report, indent=2, default=str))
    if report.get("violations"):
        print(
            f"\n{len(report['violations'])} invariant violation(s)",
            file=sys.stderr,
        )
        return 1
    print("\nall invariants held", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
