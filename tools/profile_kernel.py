"""Profile one score_batch dispatch: cost analysis + component ablation.

Usage: python tools/profile_kernel.py [--hlo] [--ablate]
Writes nothing; prints findings. Round-4 perf investigation (VERDICT item 1).
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_NODES = int(os.environ.get("BENCH_NODES", "10000"))
BATCH = int(os.environ.get("BENCH_BATCH", "256"))


def main() -> None:
    import jax

    import bench
    from nomad_tpu.ops.kernels import score_batch
    from nomad_tpu.parallel import build_batch_inputs

    m = bench.build_cluster()
    shapes = bench.build_requests(m)
    arrays = m.sync()
    inp = build_batch_inputs(m, [shapes[i % len(shapes)] for i in range(BATCH)])
    args = (
        arrays, arrays.used, inp["tg_counts"], inp["spread_counts"],
        inp["penalties"], inp["reqs"], inp["class_eligs"], inp["host_masks"],
    )

    lowered = jax.jit(score_batch).lower(*args)
    compiled = lowered.compile()
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        print("== cost_analysis ==")
        for k in sorted(ca):
            v = ca[k]
            if isinstance(v, float) and v > 1e6:
                print(f"  {k}: {v:.3e}")
    except Exception as e:  # noqa: BLE001
        print("cost_analysis failed:", e)

    # Timed dispatch
    out = score_batch(*args)
    out.rows.block_until_ready()
    ts = []
    for _ in range(10):
        t = time.time()
        score_batch(*args).rows.block_until_ready()
        ts.append(time.time() - t)
    print(f"dispatch median: {np.median(ts)*1000:.2f} ms  "
          f"({BATCH/np.median(ts):.0f} evals/s)")

    if "--hlo" in sys.argv:
        txt = compiled.as_text()
        path = "/tmp/score_batch_hlo.txt"
        with open(path, "w") as f:
            f.write(txt)
        print("HLO written to", path, f"({len(txt)} bytes)")


if __name__ == "__main__":
    main()
