#!/usr/bin/env python
"""Summarize a nomad-tpu trace dump (Chrome trace-event JSON) in the
terminal — the quick look before loading it into Perfetto.

Usage:
    nomad-tpu trace dump -o trace.json      # or any flight-*.json dump
    python tools/trace_view.py trace.json
    python tools/trace_view.py trace.json --trace eval-abc123
    python tools/trace_view.py trace.json --phase plan.apply --slowest 10

Per-phase table: span count, total/mean/max duration, share of the
summed root-span time.  With ``--trace ID`` prints that eval's span
tree with per-span durations instead.  ``--phase NAME`` narrows any
view to spans whose phase name contains NAME (so ``--phase plan``
matches plan.queue_wait + plan.apply); ``--slowest N`` lists the N
longest individual spans — the first question a flight record gets
("which eval blew the p99?") answered without Perfetto.

For the full timeline, load the same file in https://ui.perfetto.dev
(drag the file into the page) — spans are grouped per thread with
trace/span/parent ids in the args pane.

Stdlib-only on purpose: works on any host that can scp the dump over.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, List


def load_events(path: str) -> List[Dict[str, Any]]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if isinstance(doc, dict):
        events = doc.get("traceEvents", [])
    else:
        events = doc  # bare-array variant is also legal Chrome format
    return [e for e in events if e.get("ph") == "X"]


def summarize(events: List[Dict[str, Any]]) -> None:
    by_name: Dict[str, List[float]] = defaultdict(list)
    roots = 0.0
    for e in events:
        dur_ms = e.get("dur", 0) / 1000.0
        by_name[e["name"]].append(dur_ms)
        if not e.get("args", {}).get("parent"):
            roots += dur_ms
    if not by_name:
        print("no complete spans in file")
        return
    rows = []
    for name, durs in sorted(by_name.items()):
        total = sum(durs)
        rows.append((
            name, len(durs), total, total / len(durs), max(durs),
            100.0 * total / roots if roots else 0.0,
        ))
    rows.sort(key=lambda r: -r[2])
    hdr = f"{'phase':<28}{'count':>7}{'total ms':>11}{'mean ms':>10}" \
          f"{'max ms':>10}{'% root':>8}"
    print(hdr)
    print("-" * len(hdr))
    for name, n, total, mean, mx, pct in rows:
        print(f"{name:<28}{n:>7}{total:>11.2f}{mean:>10.3f}"
              f"{mx:>10.3f}{pct:>8.1f}")
    print(f"\n{len(events)} spans; summed root-span time {roots:.2f} ms")
    print("full timeline: load this file in https://ui.perfetto.dev")


def filter_phase(
    events: List[Dict[str, Any]], phase: str
) -> List[Dict[str, Any]]:
    """Spans whose name contains ``phase`` (substring, so a family
    prefix like ``plan`` selects the whole plan.* group)."""
    return [e for e in events if phase in e.get("name", "")]


def show_slowest(events: List[Dict[str, Any]], n: int) -> None:
    """The N longest individual spans, slowest first."""
    ranked = sorted(events, key=lambda e: -e.get("dur", 0))[:n]
    if not ranked:
        print("no complete spans in file")
        return
    hdr = f"{'phase':<28}{'dur ms':>10}  {'trace':<38}{'ts us':>16}"
    print(hdr)
    print("-" * len(hdr))
    for e in ranked:
        args = e.get("args", {})
        print(f"{e['name']:<28}{e.get('dur', 0) / 1000.0:>10.3f}  "
              f"{str(args.get('trace', '-')):<38}{e.get('ts', 0):>16}")
    print(f"\ntop {len(ranked)} of {len(events)} spans by duration")


def show_trace(events: List[Dict[str, Any]], trace_id: str) -> None:
    mine = [e for e in events
            if e.get("args", {}).get("trace") == trace_id]
    if not mine:
        print(f"no spans for trace {trace_id!r}", file=sys.stderr)
        sys.exit(1)
    by_parent: Dict[Any, List[Dict[str, Any]]] = defaultdict(list)
    for e in mine:
        by_parent[e["args"].get("parent") or 0].append(e)
    for kids in by_parent.values():
        kids.sort(key=lambda e: e.get("ts", 0))
    t0 = min(e["ts"] for e in mine)

    def walk(parent: Any, depth: int) -> None:
        for e in by_parent.get(parent, ()):
            off = (e["ts"] - t0) / 1000.0
            dur = e.get("dur", 0) / 1000.0
            print(f"{'  ' * depth}{e['name']:<{30 - 2 * depth}}"
                  f" +{off:8.3f} ms  {dur:8.3f} ms")
            walk(e["args"].get("span"), depth + 1)

    print(f"trace {trace_id} ({len(mine)} spans)")
    walk(0, 0)


def main(argv: List[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="Chrome trace JSON (trace dump or "
                                 "flight-*.json)")
    ap.add_argument("--trace", default="",
                    help="print one trace's span tree instead")
    ap.add_argument("--phase", default="",
                    help="only spans whose phase name contains this")
    ap.add_argument("--slowest", type=int, default=0, metavar="N",
                    help="list the N longest spans instead of the table")
    args = ap.parse_args(argv)
    events = load_events(args.path)
    if args.phase:
        events = filter_phase(events, args.phase)
        if not events:
            print(f"no spans matching phase {args.phase!r}",
                  file=sys.stderr)
            return 1
    if args.trace:
        show_trace(events, args.trace)
    elif args.slowest > 0:
        show_slowest(events, args.slowest)
    else:
        summarize(events)
    return 0


if __name__ == "__main__":
    sys.exit(main())
