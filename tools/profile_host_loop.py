"""Profile the e2e server loop's HOST side (VERDICT r4 weak #3).

Runs bench.py's e2e phase shape — N nodes, a burst of jobs through
broker → worker → stack → coalescer → applier — under a SAMPLING
profiler that captures every thread's stack (the py-spy approach;
cProfile only sees the calling thread, and the server's work happens in
worker/applier/coalescer threads).  On the CPU backend: the question is
where HOST time goes, not device time.

Usage: JAX_PLATFORMS=cpu python tools/profile_host_loop.py [jobs] [nodes]
           [--latency-ms MS] [--out PATH]
Writes tools/host_loop_profile.txt (override with --out).

``--latency-ms`` turns on the fake-device backend with a synthetic
device→host fetch latency (NOMAD_TPU_FAKE_DEVICE_LATENCY_MS) — the knob
that makes the coalescer's dispatch/resolve overlap visible on a CPU-only
box: with the latency charged at resolve time, a profile shows exactly
which thread eats the tunnel RTT.
"""

from __future__ import annotations

import argparse
import collections
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# A registered TPU-tunnel plugin backend initializes (and, when the tunnel
# is wedged, hangs) even under JAX_PLATFORMS=cpu — drop it before any
# backend init (same guard as tests/conftest.py).
from __graft_entry__ import _scrub_non_cpu_backends  # noqa: E402

_scrub_non_cpu_backends()

import numpy as np  # noqa: E402

_ap = argparse.ArgumentParser(description="host-loop sampling profiler")
_ap.add_argument("jobs", nargs="?", type=int, default=256)
_ap.add_argument("nodes", nargs="?", type=int, default=2000)
_ap.add_argument(
    "--latency-ms", type=float, default=None,
    help="fake-device synthetic fetch latency; implies NOMAD_TPU_FAKE_DEVICE=1",
)
_ap.add_argument(
    "--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "host_loop_profile.txt"
    ),
    help="report path (default tools/host_loop_profile.txt)",
)
_ARGS = _ap.parse_args()

N_JOBS = _ARGS.jobs
N_NODES = _ARGS.nodes
if _ARGS.latency_ms is not None:
    os.environ["NOMAD_TPU_FAKE_DEVICE"] = "1"
    os.environ["NOMAD_TPU_FAKE_DEVICE_LATENCY_MS"] = str(_ARGS.latency_ms)
WORKERS = int(os.environ.get("PROFILE_WORKERS", "8"))
# Modest rate + raw-frame walking: traceback.extract_stack at high Hz
# reads source through linecache and hogs the GIL hard enough to starve
# the system under test to ~zero throughput (observed; self-poisoning).
SAMPLE_HZ = 25.0

_IDLE_LEAVES = ("wait", "_wait_for_tstate_lock", "select", "poll",
                "accept", "read", "recv_into")


class Sampler(threading.Thread):
    """Stack sampler over every live thread (sys._current_frames)."""

    def __init__(self):
        super().__init__(name="stack-sampler", daemon=True)
        self._halt = threading.Event()
        # (thread_name_prefix, leaf frame) -> samples
        self.leaf: collections.Counter = collections.Counter()
        # full-stack flame lines -> samples (for the report tail)
        self.stacks: collections.Counter = collections.Counter()
        self.samples = 0

    def run(self) -> None:
        me = threading.get_ident()
        interval = 1.0 / SAMPLE_HZ
        while not self._halt.wait(interval):
            frames = sys._current_frames()
            names = {
                t.ident: t.name for t in threading.enumerate()
            }
            self.samples += 1
            for tid, frame in frames.items():
                if tid == me:
                    continue
                name = names.get(tid, "?").split("-")[0]
                # Raw frame walk — no FrameSummary, no linecache.
                code = frame.f_code
                if code.co_name in _IDLE_LEAVES:
                    continue
                self.leaf[
                    f"{name}: {os.path.basename(code.co_filename)}:"
                    f"{frame.f_lineno} {code.co_name}"
                ] += 1
                sig = []
                f = frame
                depth = 0
                while f is not None and depth < 10:
                    sig.append(
                        f"{os.path.basename(f.f_code.co_filename)}:"
                        f"{f.f_code.co_name}"
                    )
                    f = f.f_back
                    depth += 1
                self.stacks[f"{name}: " + ";".join(reversed(sig))] += 1

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=5)


def main() -> None:
    from nomad_tpu import mock
    from nomad_tpu.server.server import Server, ServerConfig

    srv = Server(ServerConfig(
        num_workers=WORKERS,
        node_capacity=max(256, 1 << (N_NODES - 1).bit_length()),
        heartbeat_min_ttl=3600.0,
        heartbeat_max_ttl=7200.0,
    ))
    srv.start()
    rng = np.random.default_rng(7)
    for i in range(N_NODES):
        node = mock.node()
        node.node_class = f"class-{i % 6}"
        srv.register_node(node)
    with srv.matrix._host_lock:
        host = srv.matrix.snapshot_host()
        host["used"][:N_NODES] = (
            rng.uniform(0.1, 0.6, (N_NODES, 3)) * host["totals"][:N_NODES]
        )
        srv.matrix._dirty.update(range(N_NODES))

    def make_job(i: int):
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 2
        tg.tasks[0].resources.cpu = 50 + 25 * (i % 4)
        tg.tasks[0].resources.memory_mb = 64 + 32 * (i % 3)
        return job

    # Warm compiles outside the profile.
    ev = srv.submit_job(make_job(0))
    srv.wait_for_eval(ev.id, timeout=600.0)

    sampler = Sampler()
    sampler.start()
    t0 = time.time()
    evals = [srv.submit_job(make_job(i)) for i in range(N_JOBS)]
    pending = {e.id for e in evals}
    deadline = time.time() + 300.0
    last_index = 0
    while pending and time.time() < deadline:
        done = {
            eid for eid in pending
            if (e := srv.store.eval_by_id(eid)) is not None
            and e.terminal_status()
        }
        pending -= done
        if not pending:
            break
        # Condvar wait on the evals table instead of a 10ms sleep-poll:
        # wakes on the next eval write, so completion latency isn't
        # quantized to the poll period.
        last_index = srv.store.wait_for_table(
            "evals", last_index, timeout=0.25
        )
    wall = time.time() - t0
    sampler.stop()
    rate = (N_JOBS - len(pending)) / wall

    lat = os.environ.get("NOMAD_TPU_FAKE_DEVICE_LATENCY_MS", "0")
    lines = [
        f"e2e host profile: {N_JOBS} jobs, {N_NODES} nodes, "
        f"{WORKERS} workers, latency={lat}ms -> {rate:.1f} evals/s "
        f"wall={wall:.1f}s (pending={len(pending)})",
        f"coalescer: dispatches={srv.coalescer.dispatches} "
        f"coalesced={srv.coalescer.coalesced_requests}",
        f"samples: {sampler.samples} @ {SAMPLE_HZ:.0f}Hz "
        f"(busy-leaf samples below; idle waits dropped)",
        "",
        "==== top 40 busy leaf frames (thread: file:line fn  samples) ====",
    ]
    for key, n in sampler.leaf.most_common(40):
        lines.append(f"{n:6d}  {key}")
    lines.append("")
    lines.append("==== top 25 stacks ====")
    for key, n in sampler.stacks.most_common(25):
        lines.append(f"{n:6d}  {key}")
    srv.shutdown()

    report = "\n".join(lines) + "\n"
    path = _ARGS.out
    with open(path, "w") as fh:
        fh.write(report)
    print(report[:3000])
    print(f"... full profile -> {path}")


if __name__ == "__main__":
    main()
