"""Observability pass (rules O001–O004).

The flight recorder is only as good as its coverage: a chaos seam that
fires without leaving a trace event is invisible in the post-mortem
dump, so a fault-triggered failure can't be lined up against the spans
it perturbed.  The contract is simple — **every injector call site must
emit a trace event on the same path** — and this pass enforces it:

* **O001 seam without trace emission** — an ``inject(...)``/``_chaos(...)``
  call site with a literal seam string whose enclosing function never
  calls ``trace.event``/``trace.span``/``trace.record_span``, and whose
  injector function is not a module-local wrapper that emits the event
  itself (driver.py's ``_chaos`` pattern).

* **O002 SLO objective is not a registered metric** — an
  ``SLOSpec(...)`` call site whose literal ``objective=`` string does
  not resolve to any metric name the codebase registers.  A renamed
  timer would otherwise silently turn the SLO into a constant (never
  sampled, never breached, forever ``pending``).  "Registered" means
  any of: a literal first argument to ``timer(...)``/``incr(...)``/
  ``gauge_fn(...)``/``set_gauge(...)``; ``nomad.phase.<name>`` for a
  literal ``span(...)``/``record_span(...)`` name (trace spans feed
  phase timers); or a literal ``nomad.*`` string used as a dict-store
  key (the agent/observatory hand-rolled snapshot pattern).  The name
  set is collected from the whole tree, so the check is a ``run``-level
  pass; :func:`collect_metric_names` + :func:`analyze_slo_objectives`
  expose the two halves for fixtures.

* **O003 silent actuator decision** — a call site of an overload
  actuator (``set_gate_level(...)`` / ``set_shedding(...)``) whose
  enclosing function does not BOTH emit a trace event and increment a
  literal ``nomad.*`` counter (``.incr("nomad....")``).  The control
  loop's whole defense against oscillation arguments is an audit trail:
  a gate level or shed toggle that moves without a trace event and a
  counter can't be correlated with the 429s/deferrals it caused, and
  "why did throughput halve at 14:03" becomes unanswerable.
  :func:`analyze_actuators` is the per-module fixture API.

* **O004 silent breaker transition** — a call site of the device
  breaker's state mutator (``_apply_transition(...)``,
  ``obs/breaker.py``) whose enclosing function does not BOTH emit a
  trace event and increment a literal ``nomad.*`` counter.  Same
  argument as O003 for the device fault domain: a breaker that flips
  between the device path and the degraded host path without a trace
  event and a counter makes "why did placement latency triple at
  14:03" unanswerable.  :func:`analyze_breaker_transitions` is the
  per-module fixture API.

Shares the seam-site discovery with :mod:`.chaospass` (same
``INJECT_FUNC_NAMES``, same tree walk) so the two passes can't drift
apart on what counts as a seam.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set, Tuple

from . import Finding
from .chaospass import INJECT_FUNC_NAMES

# The trace-emission surface: any of these reached from a seam's
# enclosing function satisfies O001.
TRACE_EMIT_NAMES = frozenset({"event", "span", "record_span"})

_SKIP_FILES = ("chaos/injector.py", "chaos/scenarios.py")


def _call_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _emits_trace(node: ast.AST) -> bool:
    """Does this subtree contain a trace-emission call?  Nested function
    definitions are NOT descended into — a trace call in an inner
    closure is its own path, not this one's."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(child, ast.Call) and _call_name(child) in TRACE_EMIT_NAMES:
            return True
        if _emits_trace(child):
            return True
    return False


def _literal_seam_calls(
    body: ast.AST,
) -> List[Tuple[str, str, int]]:
    """(injector func name, seam string, line) for literal calls directly
    inside ``body`` (not inside nested defs)."""
    out: List[Tuple[str, str, int]] = []
    for child in ast.iter_child_nodes(body):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(child, ast.Call):
            fname = _call_name(child)
            if fname in INJECT_FUNC_NAMES and child.args:
                first = child.args[0]
                if isinstance(first, ast.Constant) and isinstance(
                    first.value, str
                ):
                    out.append((fname, first.value, child.lineno))
        out.extend(_literal_seam_calls(child))
    return out


def analyze_module(rel: str, src: str) -> List[Finding]:
    """Pure per-module check — the test fixture API."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []

    # Module-local injector wrappers that emit the event themselves
    # (driver.py's ``def _chaos(point, ...): ... trace.event(...)``):
    # calls THROUGH them are covered regardless of the caller's body.
    covered_wrappers: Set[str] = set()
    funcs: List[Tuple[str, ast.AST]] = []

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                funcs.append((qual, child))
                if child.name in INJECT_FUNC_NAMES and _emits_trace(child):
                    covered_wrappers.add(child.name)
                visit(child, qual)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}.{child.name}" if prefix else child.name)
            else:
                visit(child, prefix)

    visit(tree, "")

    findings: List[Finding] = []
    scopes: List[Tuple[str, ast.AST]] = [("<module>", tree)] + funcs
    for qual, scope in scopes:
        seam_calls = _literal_seam_calls(scope)
        if not seam_calls:
            continue
        emits = _emits_trace(scope)
        for fname, seam, line in seam_calls:
            if fname in covered_wrappers:
                continue  # the wrapper emits the event for every caller
            if emits:
                continue
            findings.append(Finding(
                "O001", rel, line, qual,
                f"chaos seam `{seam}` fires here but `{qual}` never emits "
                f"a trace event (trace.event/span/record_span) — the fault "
                f"is invisible in flight-recorder dumps",
            ))
    return findings


# -- O002: SLO objectives must resolve to registered metrics -----------

# Calls whose literal first string argument registers a metric name.
METRIC_REG_NAMES = frozenset({"timer", "incr", "gauge_fn", "set_gauge"})
# Calls whose literal first string argument names a trace span — spans
# feed `nomad.phase.<name>` timers via trace.record_span.
SPAN_REG_NAMES = frozenset({"span", "record_span"})


def _first_str_arg(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
        call.args[0].value, str
    ):
        return call.args[0].value
    return None


def collect_metric_names(src: str) -> Set[str]:
    """Every metric name this module registers (see O002 docstring)."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return set()
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fname = _call_name(node)
            first = _first_str_arg(node)
            if first is None:
                continue
            if fname in METRIC_REG_NAMES:
                names.add(first)
            elif fname in SPAN_REG_NAMES:
                names.add("nomad.phase." + first)
        elif isinstance(node, ast.Assign):
            # snap["nomad.broker.total_ready"] = ... — the hand-rolled
            # snapshot keys in api/agent.py and obs/evaluator.py.
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.slice, ast.Constant)
                    and isinstance(tgt.slice.value, str)
                    and tgt.slice.value.startswith("nomad.")
                ):
                    names.add(tgt.slice.value)
    return names


def _slo_objectives(src: str) -> List[Tuple[str, str, int]]:
    """(slo name, literal objective, line) for every SLOSpec(...) call
    whose objective is a string literal (keyword or 2nd positional)."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    out: List[Tuple[str, str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _call_name(node) == "SLOSpec"):
            continue
        objective = None
        slo_name = "?"
        for kw in node.keywords:
            if kw.arg == "objective" and isinstance(
                kw.value, ast.Constant
            ) and isinstance(kw.value.value, str):
                objective = kw.value.value
            if kw.arg == "name" and isinstance(
                kw.value, ast.Constant
            ) and isinstance(kw.value.value, str):
                slo_name = kw.value.value
        if objective is None and len(node.args) >= 2:
            a = node.args[1]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                objective = a.value
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                slo_name = a0.value
        if objective is not None:
            out.append((slo_name, objective, node.lineno))
    return out


def analyze_slo_objectives(
    rel: str, src: str, registered: Set[str]
) -> List[Finding]:
    """Pure O002 check of one module against a known name set."""
    findings: List[Finding] = []
    for slo_name, objective, line in _slo_objectives(src):
        if objective in registered:
            continue
        findings.append(Finding(
            "O002", rel, line, slo_name,
            f"SLO `{slo_name}` objective `{objective}` does not resolve "
            f"to any registered metric (timer/incr/gauge_fn/set_gauge, "
            f"trace span, or snapshot key) — the SLO would never sample",
        ))
    return findings


# -- O003: actuator decisions must trace + count ------------------------

# The overload actuator surface: any attribute/name call of these is a
# control decision taking effect (obs/controller.py's _actuate_* sites).
ACTUATOR_CALL_NAMES = frozenset({"set_gate_level", "set_shedding"})


def _actuator_calls(body: ast.AST) -> List[Tuple[str, int]]:
    """(actuator name, line) for calls directly inside ``body`` (nested
    defs excluded — same scoping discipline as the seam walk)."""
    out: List[Tuple[str, int]] = []
    for child in ast.iter_child_nodes(body):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(child, ast.Call):
            fname = _call_name(child)
            if fname in ACTUATOR_CALL_NAMES:
                out.append((fname, child.lineno))
        out.extend(_actuator_calls(child))
    return out


def _incrs_registered_counter(node: ast.AST) -> bool:
    """Does this subtree call ``.incr`` with a literal ``nomad.*`` name?
    Nested defs are not descended into."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if (
            isinstance(child, ast.Call)
            and _call_name(child) == "incr"
            and (first := _first_str_arg(child)) is not None
            and first.startswith("nomad.")
        ):
            return True
        if _incrs_registered_counter(child):
            return True
    return False


def analyze_actuators(rel: str, src: str) -> List[Finding]:
    """Pure per-module O003 check — the test fixture API."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []

    funcs: List[Tuple[str, ast.AST]] = []

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                funcs.append((qual, child))
                visit(child, qual)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}.{child.name}" if prefix else child.name)
            else:
                visit(child, prefix)

    visit(tree, "")

    findings: List[Finding] = []
    for qual, scope in [("<module>", tree)] + funcs:
        calls = _actuator_calls(scope)
        if not calls:
            continue
        missing = []
        if not _emits_trace(scope):
            missing.append("a trace event")
        if not _incrs_registered_counter(scope):
            missing.append('a literal `nomad.*` counter incr')
        if not missing:
            continue
        for fname, line in calls:
            findings.append(Finding(
                "O003", rel, line, qual,
                f"overload actuator `{fname}` moves here but `{qual}` "
                f"never emits {' or '.join(missing)} — the control "
                f"decision is unauditable (no way to line the flip up "
                f"with the 429s/sheds it caused)",
            ))
    return findings


# -- O004: breaker state transitions must trace + count -----------------

# The device-breaker mutation surface: _apply_transition is the only
# place the breaker's state actually moves (obs/breaker.py); every scope
# calling it owns the trace event + counter emission.
BREAKER_CALL_NAMES = frozenset({"_apply_transition"})


def _breaker_calls(body: ast.AST) -> List[Tuple[str, int]]:
    """(mutator name, line) for calls directly inside ``body`` (nested
    defs excluded — same scoping discipline as the actuator walk)."""
    out: List[Tuple[str, int]] = []
    for child in ast.iter_child_nodes(body):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(child, ast.Call):
            fname = _call_name(child)
            if fname in BREAKER_CALL_NAMES:
                out.append((fname, child.lineno))
        out.extend(_breaker_calls(child))
    return out


def analyze_breaker_transitions(rel: str, src: str) -> List[Finding]:
    """Pure per-module O004 check — the test fixture API."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []

    funcs: List[Tuple[str, ast.AST]] = []

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                funcs.append((qual, child))
                visit(child, qual)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}.{child.name}" if prefix else child.name)
            else:
                visit(child, prefix)

    visit(tree, "")

    findings: List[Finding] = []
    for qual, scope in [("<module>", tree)] + funcs:
        calls = _breaker_calls(scope)
        if not calls:
            continue
        # The mutator's own definition is not a call site of itself.
        if qual.endswith("_apply_transition"):
            continue
        missing = []
        if not _emits_trace(scope):
            missing.append("a trace event")
        if not _incrs_registered_counter(scope):
            missing.append('a literal `nomad.*` counter incr')
        if not missing:
            continue
        for fname, line in calls:
            findings.append(Finding(
                "O004", rel, line, qual,
                f"breaker transition `{fname}` moves here but `{qual}` "
                f"never emits {' or '.join(missing)} — the device path "
                f"flipped (device ↔ degraded host twin) with no way to "
                f"line it up with the latency it caused",
            ))
    return findings


def _walk_sources(root: str):
    pkg = os.path.join(root, "nomad_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d not in ("__pycache__", "lint")]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            p = os.path.join(dirpath, fn)
            rel = os.path.relpath(p, root).replace(os.sep, "/")
            with open(p) as fh:
                src = fh.read()
            yield rel, src


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []
    # Phase 1: collect the registered-metric universe (all modules,
    # including the O001-skipped ones — they still register metrics).
    registered: Set[str] = set()
    sources = list(_walk_sources(root))
    for _rel, src in sources:
        registered |= collect_metric_names(src)
    # Phase 2: per-module rules.
    for rel, src in sources:
        if not rel.endswith(_SKIP_FILES):
            findings.extend(analyze_module(rel, src))
            findings.extend(analyze_actuators(rel, src))
            findings.extend(analyze_breaker_transitions(rel, src))
        findings.extend(analyze_slo_objectives(rel, src, registered))
    return findings
