"""Observability pass (rule O001).

The flight recorder is only as good as its coverage: a chaos seam that
fires without leaving a trace event is invisible in the post-mortem
dump, so a fault-triggered failure can't be lined up against the spans
it perturbed.  The contract is simple — **every injector call site must
emit a trace event on the same path** — and this pass enforces it:

* **O001 seam without trace emission** — an ``inject(...)``/``_chaos(...)``
  call site with a literal seam string whose enclosing function never
  calls ``trace.event``/``trace.span``/``trace.record_span``, and whose
  injector function is not a module-local wrapper that emits the event
  itself (driver.py's ``_chaos`` pattern).

Shares the seam-site discovery with :mod:`.chaospass` (same
``INJECT_FUNC_NAMES``, same tree walk) so the two passes can't drift
apart on what counts as a seam.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set, Tuple

from . import Finding
from .chaospass import INJECT_FUNC_NAMES

# The trace-emission surface: any of these reached from a seam's
# enclosing function satisfies O001.
TRACE_EMIT_NAMES = frozenset({"event", "span", "record_span"})

_SKIP_FILES = ("chaos/injector.py", "chaos/scenarios.py")


def _call_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _emits_trace(node: ast.AST) -> bool:
    """Does this subtree contain a trace-emission call?  Nested function
    definitions are NOT descended into — a trace call in an inner
    closure is its own path, not this one's."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(child, ast.Call) and _call_name(child) in TRACE_EMIT_NAMES:
            return True
        if _emits_trace(child):
            return True
    return False


def _literal_seam_calls(
    body: ast.AST,
) -> List[Tuple[str, str, int]]:
    """(injector func name, seam string, line) for literal calls directly
    inside ``body`` (not inside nested defs)."""
    out: List[Tuple[str, str, int]] = []
    for child in ast.iter_child_nodes(body):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(child, ast.Call):
            fname = _call_name(child)
            if fname in INJECT_FUNC_NAMES and child.args:
                first = child.args[0]
                if isinstance(first, ast.Constant) and isinstance(
                    first.value, str
                ):
                    out.append((fname, first.value, child.lineno))
        out.extend(_literal_seam_calls(child))
    return out


def analyze_module(rel: str, src: str) -> List[Finding]:
    """Pure per-module check — the test fixture API."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []

    # Module-local injector wrappers that emit the event themselves
    # (driver.py's ``def _chaos(point, ...): ... trace.event(...)``):
    # calls THROUGH them are covered regardless of the caller's body.
    covered_wrappers: Set[str] = set()
    funcs: List[Tuple[str, ast.AST]] = []

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                funcs.append((qual, child))
                if child.name in INJECT_FUNC_NAMES and _emits_trace(child):
                    covered_wrappers.add(child.name)
                visit(child, qual)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}.{child.name}" if prefix else child.name)
            else:
                visit(child, prefix)

    visit(tree, "")

    findings: List[Finding] = []
    scopes: List[Tuple[str, ast.AST]] = [("<module>", tree)] + funcs
    for qual, scope in scopes:
        seam_calls = _literal_seam_calls(scope)
        if not seam_calls:
            continue
        emits = _emits_trace(scope)
        for fname, seam, line in seam_calls:
            if fname in covered_wrappers:
                continue  # the wrapper emits the event for every caller
            if emits:
                continue
            findings.append(Finding(
                "O001", rel, line, qual,
                f"chaos seam `{seam}` fires here but `{qual}` never emits "
                f"a trace event (trace.event/span/record_span) — the fault "
                f"is invisible in flight-recorder dumps",
            ))
    return findings


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []
    pkg = os.path.join(root, "nomad_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d not in ("__pycache__", "lint")]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            p = os.path.join(dirpath, fn)
            rel = os.path.relpath(p, root).replace(os.sep, "/")
            if rel.endswith(_SKIP_FILES):
                continue
            with open(p) as fh:
                src = fh.read()
            findings.extend(analyze_module(rel, src))
    return findings
