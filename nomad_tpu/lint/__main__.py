"""``python -m nomad_tpu.lint`` — run all passes, apply the baseline,
exit 0 only when every finding is allowlisted.

Output contract (STATIC_ANALYSIS.md):

* new findings print one-per-line as ``path:line: RULE [symbol] msg``;
* stale baseline entries (matched nothing this run) are reported so the
  allowlist ratchets down — stale entries alone do not fail the run;
* ``--verbose`` also prints what the baseline suppressed.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from . import load_baseline, repo_root, run_all, split_baselined


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="nomad lint",
        description="lock-discipline + JAX hot-path + chaos-seam static analysis",
    )
    ap.add_argument("--root", default=None, help="repo root (default: auto-detect)")
    ap.add_argument(
        "--baseline", default=None, help="baseline.json path (default: committed)"
    )
    ap.add_argument(
        "-v", "--verbose", action="store_true",
        help="also list findings the baseline suppressed",
    )
    ap.add_argument(
        "--jaxpr", action="store_true",
        help="also run the semantic device-contract pass (J100-J105): "
        "trace the registered fused/sharded entry points and check the "
        "declared budgets, donation sets and compile-cache ratchets "
        "(needs an importable JAX backend; skipped with a notice if "
        "none is present)",
    )
    args = ap.parse_args(argv)

    if args.jaxpr:
        from . import jaxprpass

        if not jaxprpass.available():
            print(
                "nomad lint: --jaxpr requested but no JAX backend is "
                "importable — semantic pass skipped",
                file=sys.stderr,
            )

    root = args.root or repo_root()
    findings = run_all(root, jaxpr=args.jaxpr)
    baseline = load_baseline(args.baseline)
    new, suppressed, stale = split_baselined(findings, baseline)

    for f in new:
        print(f.render())
    if args.verbose and suppressed:
        print(f"-- baseline suppressed {len(suppressed)} finding(s):")
        for f in suppressed:
            print(f"   {f.render()}")
    for e in stale:
        print(
            "-- stale baseline entry (matched nothing — delete it): "
            f"{e.get('rule')} {e.get('path')} [{e.get('symbol')}]"
        )

    if new:
        print(
            f"nomad lint: {len(new)} new finding(s) "
            f"({len(suppressed)} baselined, {len(stale)} stale entries)"
        )
        return 1
    print(
        f"nomad lint: clean ({len(suppressed)} baselined, "
        f"{len(stale)} stale entries)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
