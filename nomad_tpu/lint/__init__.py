"""``nomad lint`` — static analysis for the concurrency + JAX hot paths.

Three AST passes over the production tree, one runtime sanitizer:

* **lock discipline** (:mod:`.lockpass`, rules ``L001``–``L004``) —
  per-function lock-acquisition graphs across ``server/``,
  ``scheduler/``, ``state/``, ``client/``, ``stream/``, checked against
  the declared hierarchy in :mod:`.lock_order`.
* **JAX hot path** (:mod:`.jaxpass`, rules ``J001``–``J005``) — implicit
  host syncs on device values, jit-captured mutable globals,
  non-hashable static args, fused-path recompile triggers, and
  node-axis-shaped host fetches at fused/sharded call sites in
  ``ops/``, ``parallel/``, ``scheduler/coalescer.py``,
  ``state/matrix.py``.
* **chaos seams** (:mod:`.chaospass`, rules ``C001``–``C004``) — the
  CHAOS.md seam catalog and retry surface cross-checked against the
  injector call sites and the tests that exercise them.
* **observability** (:mod:`.obspass`, rules ``O001``–``O004``) — every
  injector call site must emit a trace event on the same path, so chaos
  faults are visible in flight-recorder dumps; every ``SLOSpec``'s
  literal objective must resolve to a metric the code actually
  registers, so a renamed timer can't silently disarm an SLO; every
  overload-actuator decision site (``set_gate_level``/``set_shedding``)
  must emit a trace event AND increment a ``nomad.*`` counter, so
  control-loop flips stay auditable against the 429s/sheds they cause;
  and every device-breaker transition site (``_apply_transition``,
  ``obs/breaker.py``) must do the same, so device↔degraded-path flips
  stay auditable against the latency they cause.
* **TSan-lite** (:mod:`.tsan`) — the runtime half: lockset-checked
  shared-state wrappers enabled under the seeded chaos scenarios.
* **jaxpr contracts** (:mod:`.jaxprpass` + :mod:`.contracts`, rules
  ``J100``–``J105``, opt-in via ``--jaxpr`` / ``run_all(jaxpr=True)``)
  — the semantic half of the JAX gate: every registered device entry
  point is traced to a ClosedJaxpr under a declared configuration grid
  and checked against its contract row (no host callbacks, output-byte
  budget + node-count independence, nothing node-axis-shaped across the
  mesh boundary, donation actually reaching XLA, measured compile-cache
  cardinality).  Requires an importable JAX backend; skipped otherwise.

Findings carry ``rule``, ``path:line`` and the enclosing ``symbol``;
``baseline.json`` allowlists deliberate exemptions by
``(rule, path, symbol)`` so the gate starts green and ratchets — see
STATIC_ANALYSIS.md for the workflow.  The loader enforces baseline
hygiene: duplicate keys and unsorted entries are load errors, so the
committed file stays canonical and ``git diff`` stays reviewable.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "repo_root",
    "run_all",
    "load_baseline",
    "split_baselined",
]


@dataclass(frozen=True)
class Finding:
    """One analyzer hit.  ``symbol`` is the enclosing function/method
    qualname (``Class.method`` or ``<module>``) — baseline matching keys
    on it instead of the line number so ordinary edits don't churn the
    allowlist."""

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    symbol: str
    message: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} [{self.symbol}] {self.message}"


def repo_root(start: Optional[str] = None) -> str:
    """The repository root: the nearest ancestor of this package that
    contains the ``nomad_tpu`` directory itself."""
    here = start or os.path.dirname(os.path.abspath(__file__))
    d = here
    while True:
        if os.path.isdir(os.path.join(d, "nomad_tpu")):
            return d
        parent = os.path.dirname(d)
        if parent == d:  # filesystem root — fall back to cwd
            return os.getcwd()
        d = parent


def run_all(root: Optional[str] = None, jaxpr: bool = False) -> List[Finding]:
    """Run every pass over the repo; returns findings sorted by path/line.

    ``jaxpr=True`` additionally runs the semantic contract pass
    (:mod:`.jaxprpass`), which traces the registered device entry points
    and therefore needs an importable JAX backend — when none is
    present the pass contributes nothing rather than failing.
    """
    from . import chaospass, jaxpass, lockpass, obspass

    root = root or repo_root()
    findings: List[Finding] = []
    findings += lockpass.run(root)
    findings += jaxpass.run(root)
    findings += chaospass.run(root)
    findings += obspass.run(root)
    if jaxpr:
        from . import jaxprpass

        findings += jaxprpass.run(root)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# ----------------------------------------------------------------------
# Baseline (the ratchet)
# ----------------------------------------------------------------------

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")


@dataclass
class Baseline:
    """The committed allowlist: entries are ``{rule, path, symbol, why}``.
    ``used`` tracks which entries matched this run so ``--prune`` can
    report stale ones."""

    entries: List[Dict[str, str]] = field(default_factory=list)

    def match(self, f: Finding) -> Optional[Dict[str, str]]:
        for e in self.entries:
            if (
                e.get("rule") == f.rule
                and e.get("path") == f.path
                and e.get("symbol") == f.symbol
            ):
                return e
        return None


def load_baseline(path: Optional[str] = None) -> Baseline:
    """Load and validate the allowlist.

    Two hygiene invariants are enforced at load time (both are
    :class:`ValueError`):

    * no duplicate ``(rule, path, symbol)`` keys — ``match()`` returns
      the first hit, so a duplicate silently decides which ``why``
      applies;
    * entries sorted by ``(rule, path, symbol)`` — the committed file
      has exactly one canonical form, so baseline diffs are
      append/delete only.
    """
    p = path or BASELINE_PATH
    if not os.path.exists(p):
        return Baseline()
    with open(p) as fh:
        data = json.load(fh)
    entries = list(data.get("exemptions", []))
    keys = [
        (e.get("rule", ""), e.get("path", ""), e.get("symbol", ""))
        for e in entries
    ]
    dups = sorted({k for k in keys if keys.count(k) > 1})
    if dups:
        raise ValueError(
            f"baseline {p}: duplicate (rule, path, symbol) entries {dups} — "
            "the first match wins silently, so one 'why' is dead text; "
            "delete the duplicates"
        )
    if keys != sorted(keys):
        raise ValueError(
            f"baseline {p}: entries must be sorted by (rule, path, symbol) "
            "so the committed file has one canonical form; re-sort it"
        )
    return Baseline(entries=entries)


def split_baselined(
    findings: Sequence[Finding], baseline: Baseline
) -> Tuple[List[Finding], List[Finding], List[Dict[str, str]]]:
    """Partition findings into (new, suppressed) and report baseline
    entries that matched nothing (stale — candidates for deletion)."""
    new: List[Finding] = []
    suppressed: List[Finding] = []
    matched: List[Dict[str, str]] = []
    for f in findings:
        e = baseline.match(f)
        if e is None:
            new.append(f)
        else:
            suppressed.append(f)
            if e not in matched:
                matched.append(e)
    stale = [e for e in baseline.entries if e not in matched]
    return new, suppressed, stale
