"""Chaos-seam pass (rules C001–C004).

CHAOS.md is the contract for the fault-injection surface: the seam
catalog says where faults can land, and the retry-surface section says
which modules recover through ``nomad_tpu/retry.py``.  Both rot
silently — a refactor renames a seam string, a doc row outlives its
call site, a module quietly regrows a hand-rolled sleep loop — and a
stale catalog means chaos runs exercise less than everyone believes.
This pass cross-checks the document against the tree:

* **C001 documented seam missing from code** — a catalog row's seam
  string has no ``inject(...)``/``_chaos(...)`` call site anywhere in
  ``nomad_tpu/``.
* **C002 undocumented code seam** — an injector call site uses a seam
  string with no catalog row.
* **C003 seam not exercised** — a documented seam never appears in
  ``tests/`` or ``chaos/scenarios.py`` (no schedule can have covered
  it).
* **C004 retry-surface drift** — a module the retry-surface section
  names no longer references the shared retry helpers (or no longer
  exists).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from . import Finding

DOC_NAME = "CHAOS.md"

# Functions whose first string argument names a seam.  `inject` is the
# production entry point; `_chaos` is driver.py's local guard wrapper.
INJECT_FUNC_NAMES = frozenset({"inject", "_chaos"})

_RETRY_REF = re.compile(
    r"retry_call|RetryPolicy|Backoff|RetryBudgetExceeded"
    r"|from\s+(?:nomad_tpu|\.\.?)\s*(?:\.\s*)?(?:import\s+retry|retry\s+import)"
)
_DOC_PATH = re.compile(r"`([\w./]+\.py)`")
_SEAM_ROW = re.compile(r"^\|\s*`([\w.]+)`\s*\|")


def parse_doc(doc: str) -> Tuple[Dict[str, int], Dict[str, int]]:
    """Extract (seam -> doc line) from the seam catalog and
    (module path -> doc line) from the retry-surface section."""
    seams: Dict[str, int] = {}
    retry_mods: Dict[str, int] = {}
    section = None
    for i, raw in enumerate(doc.splitlines(), start=1):
        line = raw.strip()
        if line.startswith("## "):
            title = line[3:].lower()
            if title.startswith("seam catalog"):
                section = "seams"
            elif title.startswith("retry policy surface"):
                section = "retry"
            else:
                section = None
            continue
        if section == "seams":
            m = _SEAM_ROW.match(line)
            if m and m.group(1).lower() not in ("seam",):
                seams.setdefault(m.group(1), i)
        elif section == "retry":
            for m in _DOC_PATH.finditer(line):
                p = m.group(1)
                if p.endswith("retry.py"):
                    continue  # the helper itself, not a consumer
                retry_mods.setdefault(p, i)
    return seams, retry_mods


def collect_code_seams(root: str) -> Dict[str, List[Tuple[str, int]]]:
    """seam string -> [(repo-relative path, line)] for every
    inject()/_chaos() call with a literal first argument."""
    sites: Dict[str, List[Tuple[str, int]]] = {}
    pkg = os.path.join(root, "nomad_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d not in ("__pycache__", "lint")]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            p = os.path.join(dirpath, fn)
            rel = os.path.relpath(p, root).replace(os.sep, "/")
            # injector.py defines inject(); scenarios/tests only build
            # schedules (FaultSpec strings are coverage, not seams).
            if rel.endswith("chaos/injector.py") or rel.endswith("chaos/scenarios.py"):
                continue
            with open(p) as fh:
                src = fh.read()
            for name, line in _literal_inject_calls(src):
                sites.setdefault(name, []).append((rel, line))
    return sites


def _literal_inject_calls(src: str) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for node in ast.walk(ast.parse(src)):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fname: Optional[str] = None
        if isinstance(node.func, ast.Name):
            fname = node.func.id
        elif isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        if fname not in INJECT_FUNC_NAMES:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            out.append((first.value, node.lineno))
    return out


def collect_exercised_strings(root: str) -> Set[str]:
    """Every string literal in tests/ and chaos/scenarios.py — a seam
    is 'exercised' when some schedule or assertion names it."""
    strings: Set[str] = set()
    targets: List[str] = []
    tests = os.path.join(root, "tests")
    if os.path.isdir(tests):
        for fn in sorted(os.listdir(tests)):
            if fn.endswith(".py"):
                targets.append(os.path.join(tests, fn))
    scen = os.path.join(root, "nomad_tpu", "chaos", "scenarios.py")
    if os.path.exists(scen):
        targets.append(scen)
    for p in targets:
        with open(p) as fh:
            src = fh.read()
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                strings.add(node.value)
    return strings


def analyze(
    doc: str,
    code_seams: Dict[str, List[Tuple[str, int]]],
    exercised: Set[str],
    retry_sources: Dict[str, Optional[str]],
) -> List[Finding]:
    """Pure cross-check — the test fixture API.  ``retry_sources`` maps
    each doc-named retry-surface path to its source text (None when the
    file is gone)."""
    doc_seams, retry_mods = parse_doc(doc)
    findings: List[Finding] = []

    for seam, doc_line in sorted(doc_seams.items()):
        if seam not in code_seams:
            findings.append(Finding(
                "C001", DOC_NAME, doc_line, seam,
                f"seam `{seam}` is documented in the catalog but has no "
                f"inject() call site in nomad_tpu/ — the row is stale or "
                f"the seam was renamed",
            ))
        elif seam not in exercised:
            findings.append(Finding(
                "C003", DOC_NAME, doc_line, seam,
                f"seam `{seam}` has a code site but never appears in "
                f"tests/ or chaos/scenarios.py — no schedule exercises it",
            ))

    for seam, sites in sorted(code_seams.items()):
        if seam not in doc_seams:
            path, line = sites[0]
            findings.append(Finding(
                "C002", path, line, seam,
                f"inject() seam `{seam}` is not documented in CHAOS.md's "
                f"seam catalog",
            ))

    for mod, doc_line in sorted(retry_mods.items()):
        src = retry_sources.get(mod)
        if src is None:
            findings.append(Finding(
                "C004", DOC_NAME, doc_line, mod,
                f"retry-surface module `{mod}` named in CHAOS.md does not "
                f"exist",
            ))
        elif not _RETRY_REF.search(src):
            findings.append(Finding(
                "C004", DOC_NAME, doc_line, mod,
                f"retry-surface module `{mod}` no longer references the "
                f"shared retry helpers (retry_call/RetryPolicy/Backoff)",
            ))
    return findings


def run(root: str) -> List[Finding]:
    doc_path = os.path.join(root, DOC_NAME)
    if not os.path.exists(doc_path):
        return [Finding("C001", DOC_NAME, 1, "<doc>", "CHAOS.md is missing")]
    with open(doc_path) as fh:
        doc = fh.read()

    _seams, retry_mods = parse_doc(doc)
    retry_sources: Dict[str, Optional[str]] = {}
    for mod in retry_mods:
        p = os.path.join(root, "nomad_tpu", mod)
        if not os.path.exists(p):
            p = os.path.join(root, mod)
        if os.path.exists(p):
            with open(p) as fh:
                retry_sources[mod] = fh.read()
        else:
            retry_sources[mod] = None

    return analyze(
        doc,
        collect_code_seams(root),
        collect_exercised_strings(root),
        retry_sources,
    )
