"""JAX hot-path pass (rules J001–J005).

The live dispatch path stays fast only while two disciplines hold: no
implicit device→host sync outside the resolver thread (each one stalls
for a full tunnel RTT and collapses the pipeline overlap), and no
recompilation surprises (jit tracing captures, static-arg hashing).
This pass enforces both lexically over ``ops/``, ``parallel/``,
``scheduler/coalescer.py`` and ``state/matrix.py``:

* **J001 host sync on a device value** — a name assigned from a
  device-producing call (``kernels.*``, ``jnp.*``, ``jax.jit``-wrapped
  fns, the sharded dispatch) later hits ``np.asarray``/``float``/
  ``int``/``.item()``/``.tolist()``/``.block_until_ready()`` — or a
  device-producing call is fed to one directly.  The designated
  resolver-thread fetch is a baseline exemption, not a rule carve-out,
  so moving it shows up in review.
* **J002 jit-captured mutable global** — a ``@jax.jit`` function reads a
  module-level name bound to a list/dict/set: tracing freezes its value
  at first call, so later mutation silently diverges (and a rebind
  retriggers a trace per identity).
* **J003 non-hashable static arg** — a call to a jit-with-
  ``static_argnames`` function passes a list/dict/set display (directly
  or via a local) to a static parameter, or the jitted function declares
  a mutable default for one: static args key the compile cache by
  hash/eq, so each call raises or recompiles.
* **J004 per-eval recompile trigger on the fused path** — a call to the
  mega-batched fused entry points (``fused_place_batch`` /
  ``fused_place_batch_live``) feeds them a shape-polymorphic operand
  (``np.stack``/``jnp.asarray`` over a comprehension, or a
  ``tree_map``-stacked pytree, whose leading dim tracks the batch
  occupancy) or derives a static arg from the batch (``len(batch)``,
  ``x.shape[...]``).  Either way the "one compile serves every
  occupancy" contract breaks and each distinct batch size pays a full
  XLA compile mid-dispatch.  Preallocate a ``(B, ...)`` operand slab
  (``ops.encode.RequestSlab``), mask dead lanes with ``lane_mask``, and
  keep static args bound to configuration constants.
* **J005 node-axis fetch at a fused/sharded call site** — a function that
  drives the fused or node-sharded dispatch entry points
  (``fused_place_batch[_live]`` / ``sharded_[fused_]place_batch``) also
  fetches a node-axis-shaped value to host: a sync sink
  (``np.asarray``/``.block_until_ready()``/…) applied to a
  ``DeviceArrays`` leaf (``arrays.used``, ``.totals``, ``.attr_hash``,
  …) or a node-shaped ``PlacementResult`` field (``used_after``,
  ``tg_count_after``).  The sharded megabatch contract
  (parallel/sharding.py) is that only the packed (B, P, 8) winner block
  ever crosses the device→host boundary; an (…, N) fetch reintroduces
  O(nodes) host traffic per dispatch and scales with cluster size —
  exactly what hierarchical top-k exists to prevent.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from . import Finding

SCAN_DIRS = ("ops", "parallel")
SCAN_FILES = (
    os.path.join("scheduler", "coalescer.py"),
    os.path.join("state", "matrix.py"),
)

# Dotted-prefix patterns whose call results live on device.
DEVICE_PRODUCER_PREFIXES = ("kernels.", "jnp.", "jax.numpy.")
DEVICE_PRODUCER_EXACT = {"jax.device_put"}
DEVICE_PRODUCER_NAMES = {"place_batch_live", "sharded_place_batch"}

# Sinks that force a device→host sync.
SYNC_CALL_NAMES = {"float", "int", "bool"}
SYNC_DOTTED = {"np.asarray", "numpy.asarray", "np.array", "numpy.array", "jax.device_get"}
SYNC_METHODS = {"item", "tolist", "block_until_ready"}

# J004: the mega-batched fused entry points whose one-compile-per-shape
# contract the rule protects.
FUSED_ENTRY_NAMES = {"fused_place_batch", "fused_place_batch_live"}
# Array constructors that stack per-dispatch Python sequences into a new
# leading dim — shape-polymorphic when fed a comprehension/starred seq.
STACKING_CALL_NAMES = {
    "stack", "vstack", "hstack", "concatenate", "asarray", "array",
}
# Static params of the fused entry points (mirrors ops/kernels.py); a
# batch-derived value here keys a fresh compile per occupancy.
FUSED_STATIC_PARAMS = ("n_placements", "features")

# J005: the node-sharded dispatch builders — a function calling any of
# these (or the fused entries above) is "on the fused/sharded path" and
# must never fetch node-axis-shaped arrays to host.  ``_sharded_fused_fn``
# is the coalescer's bound callable built by ``sharded_fused_place_batch``
# — the production dispatch site invokes the entry through it, so the
# bound name counts as an entry too.
SHARDED_ENTRY_NAMES = {
    "sharded_place_batch",
    "sharded_fused_place_batch",
    "_sharded_fused_fn",
}
# Node-axis-shaped leaves: every DeviceArrays field (state/matrix.py) plus
# the node-shaped PlacementResult fields (ops/kernels.py).  An attribute
# access with one of these names is treated as (…, N)-shaped.
NODE_AXIS_ATTRS = {
    "totals", "used", "eligible", "attr_hash", "attr_num", "attr_ver",
    "class_id", "dev_total", "dev_used", "prio_used", "port_words",
    "dyn_used",
    "used_after", "tg_count_after",
}


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_device_call(node: ast.AST, jitted_names: Set[str]) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    if d is None:
        return False
    short = d.rsplit(".", 1)[-1]
    if d in DEVICE_PRODUCER_EXACT or short in DEVICE_PRODUCER_NAMES:
        return True
    if d in jitted_names or short in jitted_names:
        return True
    return any(d.startswith(p) for p in DEVICE_PRODUCER_PREFIXES)


def _mutable_display(node: ast.AST) -> bool:
    return isinstance(
        node, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)
    )


def _varlen_stack_call(node: ast.AST) -> bool:
    """``np.stack([... for ...])`` / ``jnp.asarray(x for ...)`` /
    ``tree_map(...)``: a call that materializes a per-dispatch Python
    sequence into a new leading dim, so the result's shape tracks the
    live batch occupancy instead of a preallocated (B, ...) slab."""
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    short = (d or "").rsplit(".", 1)[-1]
    if short == "tree_map":
        return True
    if short not in STACKING_CALL_NAMES:
        return False
    for a in node.args:
        if isinstance(a, (ast.ListComp, ast.GeneratorExp)):
            return True
        if isinstance(a, (ast.List, ast.Tuple)) and any(
            isinstance(e, ast.Starred) for e in a.elts
        ):
            return True
    return False


def _batch_derived(node: ast.AST) -> bool:
    """True when the expression reads ``len(...)`` or ``.shape`` — a value
    that varies with the live batch rather than configuration."""
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Name)
            and sub.func.id == "len"
        ):
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "shape":
            return True
    return False


class _ModuleInfo:
    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.tree = tree
        # module-level names bound to mutable containers
        self.mutable_globals: Dict[str, int] = {}
        # jit-wrapped callables visible in this module: name -> static params
        self.jitted: Dict[str, Tuple[str, ...]] = {}
        self._scan_module_scope()

    def _scan_module_scope(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    if _mutable_display(node.value):
                        self.mutable_globals[t.id] = node.lineno
                    jc = _jit_call_info(node.value)
                    if jc is not None:
                        self.jitted[t.id] = jc
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                statics = _jit_decorator_statics(node)
                if statics is not None:
                    self.jitted[node.name] = statics


def _jit_call_info(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """`jax.jit(f, static_argnames=(...))` -> static names ('' if none)."""
    if not isinstance(node, ast.Call):
        return None
    if _dotted(node.func) not in ("jax.jit", "jit"):
        return None
    return _static_names(node)


def _jit_decorator_statics(fn: ast.AST) -> Optional[Tuple[str, ...]]:
    """Static argnames for @jax.jit / @partial(jax.jit, ...) decorated
    functions; None when the function isn't jitted at all."""
    for dec in getattr(fn, "decorator_list", []):
        d = _dotted(dec) or (_dotted(dec.func) if isinstance(dec, ast.Call) else None)
        if d in ("jax.jit", "jit"):
            return _static_names(dec) if isinstance(dec, ast.Call) else ()
        if isinstance(dec, ast.Call) and _dotted(dec.func) in (
            "functools.partial", "partial",
        ):
            if dec.args and _dotted(dec.args[0]) in ("jax.jit", "jit"):
                return _static_names(dec)
    return None


def _static_names(call: ast.Call) -> Tuple[str, ...]:
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
    return ()


# ----------------------------------------------------------------------


def _check_function(
    info: _ModuleInfo,
    fn: ast.AST,
    symbol: str,
    findings: List[Finding],
) -> None:
    jitted_names = set(info.jitted)
    device_vars: Set[str] = set()
    # locals bound to mutable displays (for J003 via a hop)
    mutable_locals: Dict[str, int] = {}
    # locals bound to per-dispatch stacked arrays (for J004 via a hop)
    stacked_locals: Dict[str, int] = {}
    # locals bound to node-axis-shaped attributes (for J005 via a hop)
    node_axis_vars: Dict[str, int] = {}

    # J005 scopes to functions that drive the fused/sharded dispatch path.
    fused_caller = any(
        isinstance(n, ast.Call)
        and (_dotted(n.func) or "").rsplit(".", 1)[-1]
        in (FUSED_ENTRY_NAMES | SHARDED_ENTRY_NAMES)
        and not (_dotted(n.func) or "").startswith("fake_device.")
        for n in ast.walk(fn)
    )

    def _node_axis_expr(expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and expr.attr in NODE_AXIS_ATTRS:
            return _dotted(expr) or f".{expr.attr}"
        if isinstance(expr, ast.Name) and expr.id in node_axis_vars:
            return expr.id
        return None

    statics = _jit_decorator_statics(fn)
    if statics:
        # J003: mutable default on a static parameter.
        args = fn.args
        defaults = args.defaults
        params = [a.arg for a in args.args]
        for param, default in zip(params[len(params) - len(defaults):], defaults):
            if param in statics and _mutable_display(default):
                findings.append(Finding(
                    "J003", info.path, fn.lineno, symbol,
                    f"static arg '{param}' has a non-hashable (mutable) "
                    f"default — jit static args are cache keys and must "
                    f"hash",
                ))

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                if _varlen_stack_call(node.value):
                    stacked_locals[t.id] = node.lineno
                if (
                    isinstance(node.value, ast.Attribute)
                    and node.value.attr in NODE_AXIS_ATTRS
                ):
                    node_axis_vars[t.id] = node.lineno
                if _is_device_call(node.value, jitted_names):
                    device_vars.add(t.id)
                elif _mutable_display(node.value):
                    mutable_locals[t.id] = node.lineno
                elif isinstance(node.value, ast.Name):
                    if node.value.id in device_vars:
                        device_vars.add(t.id)
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)

        def _arg_is_device(c: ast.Call) -> Optional[str]:
            for a in c.args:
                if isinstance(a, ast.Name) and a.id in device_vars:
                    return a.id
                if _is_device_call(a, jitted_names):
                    return _dotted(a.func) or "<device call>"
            return None

        # J001 sinks.
        hit: Optional[str] = None
        if d in SYNC_DOTTED:
            hit = _arg_is_device(node)
        elif isinstance(node.func, ast.Name) and node.func.id in SYNC_CALL_NAMES:
            hit = _arg_is_device(node)
        elif isinstance(node.func, ast.Attribute) and node.func.attr in SYNC_METHODS:
            recv = node.func.value
            if isinstance(recv, ast.Name) and recv.id in device_vars:
                hit = recv.id
            elif _is_device_call(recv, jitted_names):
                hit = _dotted(recv.func) or "<device call>"
        if hit is not None:
            sink = d or (
                f".{node.func.attr}()" if isinstance(node.func, ast.Attribute)
                else getattr(node.func, "id", "?")
            )
            findings.append(Finding(
                "J001", info.path, node.lineno, symbol,
                f"implicit device->host sync: {sink} on device value "
                f"'{hit}' — each sync stalls a full tunnel RTT; route "
                f"fetches through the resolver thread",
            ))
            continue

        # J005: node-axis-shaped operand fetched to host in a function
        # that drives the fused/sharded dispatch path.
        if fused_caller:
            tgt: Optional[str] = None
            if d in SYNC_DOTTED or (
                isinstance(node.func, ast.Name)
                and node.func.id in SYNC_CALL_NAMES
            ):
                for a in node.args:
                    tgt = _node_axis_expr(a)
                    if tgt:
                        break
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_METHODS
            ):
                tgt = _node_axis_expr(node.func.value)
            if tgt is not None:
                findings.append(Finding(
                    "J005", info.path, node.lineno, symbol,
                    f"node-axis-shaped value '{tgt}' fetched to host at a "
                    f"fused/sharded call site — only the packed "
                    f"(B, P, 8) winner block may cross the device->host "
                    f"boundary; an (..., N) fetch is O(nodes) host "
                    f"traffic per dispatch (see parallel/sharding.py "
                    f"hierarchical top-k)",
                ))
                continue

        # J004: per-eval recompile triggers at fused-megakernel call
        # sites. The fake-device twin has no compile cache, so its calls
        # are exempt.
        short_callee = d.rsplit(".", 1)[-1] if d else None
        if (
            short_callee in FUSED_ENTRY_NAMES
            and not (d or "").startswith("fake_device.")
        ):
            for a in node.args:
                if _varlen_stack_call(a) or (
                    isinstance(a, ast.Name) and a.id in stacked_locals
                ):
                    src = (
                        a.id if isinstance(a, ast.Name)
                        else _dotted(a.func) or "<stack call>"
                    )
                    findings.append(Finding(
                        "J004", info.path, node.lineno, symbol,
                        f"shape-polymorphic operand '{src}' fed to "
                        f"{short_callee}() — its leading dim tracks the "
                        f"batch occupancy, so every distinct batch size "
                        f"recompiles; preallocate a (B, ...) slab "
                        f"(ops.encode.RequestSlab) and mask dead lanes",
                    ))
            for kw in node.keywords:
                if kw.arg in FUSED_STATIC_PARAMS and _batch_derived(kw.value):
                    findings.append(Finding(
                        "J004", info.path, node.lineno, symbol,
                        f"static arg '{kw.arg}' of {short_callee}() is "
                        f"derived from the live batch (len()/.shape) — "
                        f"each occupancy keys a fresh XLA compile; bind "
                        f"static args to configuration constants and let "
                        f"lane_mask absorb occupancy",
                    ))

        # J003: mutable value into a static param of a known jitted fn.
        callee = d.rsplit(".", 1)[-1] if d else None
        if callee in info.jitted and info.jitted[callee]:
            statics_set = set(info.jitted[callee])
            for kw in node.keywords:
                if kw.arg in statics_set and (
                    _mutable_display(kw.value)
                    or (isinstance(kw.value, ast.Name) and kw.value.id in mutable_locals)
                ):
                    findings.append(Finding(
                        "J003", info.path, node.lineno, symbol,
                        f"non-hashable value passed to static arg "
                        f"'{kw.arg}' of jitted {callee}() — raises or "
                        f"poisons the compile cache",
                    ))

    # J002: jitted function reading a mutable module-level global.
    if statics is not None and info.mutable_globals:
        params = {a.arg for a in fn.args.args}
        assigned = {
            t.id
            for n in ast.walk(fn)
            if isinstance(n, ast.Assign)
            for t in n.targets
            if isinstance(t, ast.Name)
        }
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in info.mutable_globals
                and node.id not in params
                and node.id not in assigned
            ):
                findings.append(Finding(
                    "J002", info.path, node.lineno, symbol,
                    f"jit-traced function captures mutable global "
                    f"'{node.id}' — tracing freezes its value; pass it as "
                    f"an argument or make it immutable",
                ))
                break


# ----------------------------------------------------------------------


def analyze_sources(sources: Dict[str, str]) -> List[Finding]:
    """Analyze {repo-relative path: source text} — the test fixture API."""
    findings: List[Finding] = []
    for path, src in sources.items():
        info = _ModuleInfo(path, ast.parse(src))
        _walk(info, findings)
    return findings


def _walk(info: _ModuleInfo, findings: List[Finding]) -> None:
    def walk_body(body, prefix: str) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                walk_body(node.body, f"{node.name}.")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _check_function(info, node, f"{prefix}{node.name}", findings)

    walk_body(info.tree.body, "")


def run(root: str) -> List[Finding]:
    pkg = os.path.join(root, "nomad_tpu")
    paths: List[str] = []
    for d in SCAN_DIRS:
        base = os.path.join(pkg, d)
        if not os.path.isdir(base):
            continue
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    paths.append(os.path.join(dirpath, fn))
    for f in SCAN_FILES:
        p = os.path.join(pkg, f)
        if os.path.exists(p):
            paths.append(p)

    findings: List[Finding] = []
    for p in sorted(paths):
        with open(p) as fh:
            src = fh.read()
        rel = os.path.relpath(p, root).replace(os.sep, "/")
        info = _ModuleInfo(rel, ast.parse(src))
        _walk(info, findings)
    return findings
