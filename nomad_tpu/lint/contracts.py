"""The device-contract table for the jaxpr-level semantic gate.

Each :class:`DeviceContract` row declares, for one registered device
entry point, the properties :mod:`nomad_tpu.lint.jaxprpass` proves from
the *traced program* (not the source text):

* which abstract configuration grid to trace under (two node counts so
  J102 can assert node-count independence of the device→host tunnel);
* the device→host output-byte budget per launch (``None`` exempts an
  entry whose outputs are deliberately device-resident, e.g. the matrix
  scatter);
* the donation set — which positional operands the entry declares
  donated, checked against what actually survives ``lower()`` /
  ``compile()``;
* the compile-cache ratchet — a concrete sweep (occupancy fills,
  pow2-padded dirty-row counts) plus the max number of distinct cache
  entries it may cost.

New policy heads (ROADMAP item 4) register a row here instead of a new
lint rule: add the entry to :func:`table` with its budget/donation/sweep
declaration and the J101–J105 checks apply unchanged.  STATIC_ANALYSIS.md
("Semantic passes") documents the schema and the rule catalog.

Everything in this module is import-gated on JAX: importing
:mod:`nomad_tpu.lint` stays backend-free, and :func:`table` is only
called from :func:`jaxprpass.run` after an availability check.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import numpy as np


class Grid(NamedTuple):
    """One point of the trace/compile configuration grid.

    ``live`` is the occupancy (how many of the ``batch`` lanes carry a
    real eval — the lane-mask fill); ``deltas`` is the in-flight
    delta-row count K.  ``features`` is the static
    :class:`nomad_tpu.ops.kernels.Features` bucket (``None`` for entry
    points that take no feature switch, e.g. the row scatter).
    """

    nodes: int
    batch: int
    placements: int
    deltas: int
    live: int
    features: Any = None


@dataclass(frozen=True)
class DeviceContract:
    """One registered device entry point and its proven properties.

    ``build(grid)`` returns the jitted entry (factories like
    ``sharded_fused_place_batch`` are rebuilt per grid; module-level
    jitted functions just get returned).  ``operands(grid)`` returns a
    FRESH tuple of concrete numpy operands every call — freshness
    matters because donated entries consume their buffers during the
    J105 sweep.  ``static_kwargs(grid)`` is the static keyword set
    (``n_placements``/``features``) for entries that take one.
    """

    name: str
    path: str  # repo-relative, forward slashes — Finding's path
    build: Callable[[Grid], Callable[..., Any]]
    operands: Callable[[Grid], Tuple[Any, ...]]
    static_kwargs: Callable[[Grid], Dict[str, Any]]
    trace_grids: Tuple[Grid, ...]
    # J102: device→host bytes per launch; None = outputs are
    # device-resident by design (budget and node-independence both skipped).
    out_budget: Optional[Callable[[Grid], int]] = None
    # J104: positional argnums declared donated. Checked BOTH ways — a
    # declared-donated operand lowered undonated fires, and so does an
    # undeclared donation.
    donated_args: Tuple[int, ...] = ()
    # J103: entry is ALLOWED to emit node-axis-shaped outputs across the
    # mesh boundary (the scatter returns the resident matrix itself).
    node_axis_outputs_ok: bool = False
    # J103: shapes exempt from the boundary check — the declared
    # (shards, k) candidate table of a hierarchical top-k, if a node
    # count ever collides with it.
    boundary_exempt_shapes: Tuple[Tuple[int, ...], ...] = ()
    # J104: require an explicit input_output_alias in the compiled HLO.
    # Off for the current entries: on CPU the fused kernel's donated
    # lane operands are scratch-reusable but never output-ALIASED,
    # because no donated aval matches the packed (B, P, 8) output.
    expect_alias: bool = False
    # J104/J105 run at this (small) grid; None skips both.
    compile_grid: Optional[Grid] = None
    # J105: concrete sweep returning the measured compile count.
    sweep: Optional[Callable[[Callable[..., Any], "DeviceContract"], int]] = None
    max_compiles: Optional[int] = None


# ---------------------------------------------------------------------------
# Concrete operand builders (numpy; make_jaxpr abstracts them, calls use them)
# ---------------------------------------------------------------------------


def _concrete_arrays(n: int) -> Any:
    from ..state.matrix import (
        ATTR_SLOTS,
        DEVICE_SLOTS,
        PORT_WORDS,
        PRIORITY_BUCKETS,
        DeviceArrays,
    )

    return DeviceArrays(
        totals=np.full((n, 3), 100.0, np.float32),
        used=np.zeros((n, 3), np.float32),
        eligible=np.ones((n,), bool),
        attr_hash=np.zeros((n, ATTR_SLOTS), np.int32),
        attr_num=np.zeros((n, ATTR_SLOTS), np.float32),
        attr_ver=np.zeros((n, ATTR_SLOTS), np.float32),
        class_id=np.zeros((n,), np.int32),
        dev_total=np.zeros((n, DEVICE_SLOTS), np.int32),
        dev_used=np.zeros((n, DEVICE_SLOTS), np.int32),
        prio_used=np.zeros((n, PRIORITY_BUCKETS, 3), np.float32),
        port_words=np.zeros((n, PORT_WORDS), np.uint32),
        dyn_used=np.zeros((n,), np.int32),
    )


def _concrete_reqs(b: int) -> Any:
    from ..ops.encode import (
        MAX_AFFINITIES,
        MAX_CONSTRAINTS,
        MAX_DATACENTERS,
        MAX_SPREAD_VALUES,
        MAX_SPREADS,
        MAX_STATIC_PORTS,
        SchedRequest,
    )
    from ..state.matrix import DEVICE_SLOTS

    f32, i32 = np.float32, np.int32
    return SchedRequest(
        ask=np.ones((b, 3), f32),
        c_slot=np.full((b, MAX_CONSTRAINTS), -1, i32),
        c_op=np.zeros((b, MAX_CONSTRAINTS), i32),
        c_hash=np.zeros((b, MAX_CONSTRAINTS), i32),
        c_num=np.zeros((b, MAX_CONSTRAINTS), f32),
        dc_hash=np.full((b, MAX_DATACENTERS), -1, i32),
        dev_ask=np.zeros((b, DEVICE_SLOTS), i32),
        algorithm=np.zeros((b,), i32),
        desired_count=np.ones((b,), f32),
        a_slot=np.full((b, MAX_AFFINITIES), -1, i32),
        a_op=np.zeros((b, MAX_AFFINITIES), i32),
        a_hash=np.zeros((b, MAX_AFFINITIES), i32),
        a_num=np.zeros((b, MAX_AFFINITIES), f32),
        a_weight=np.zeros((b, MAX_AFFINITIES), f32),
        s_slot=np.full((b, MAX_SPREADS), -1, i32),
        s_weight=np.zeros((b, MAX_SPREADS), f32),
        s_even=np.zeros((b, MAX_SPREADS), bool),
        s_value_hash=np.zeros((b, MAX_SPREADS, MAX_SPREAD_VALUES), i32),
        s_desired=np.zeros((b, MAX_SPREADS, MAX_SPREAD_VALUES), f32),
        s_implicit=np.zeros((b, MAX_SPREADS), f32),
        s_sum_weights=np.zeros((b,), f32),
        preempt_bucket=np.full((b,), -1, i32),
        distinct_hosts=np.zeros((b,), bool),
        p_static=np.full((b, MAX_STATIC_PORTS), -1, i32),
        p_dyn=np.zeros((b,), i32),
    )


def fused_operands(g: Grid) -> Tuple[Any, ...]:
    """The 11-operand tuple shared by every fused_place_batch variant."""
    from ..ops.encode import MAX_SPREAD_VALUES, MAX_SPREADS

    n, b, k = g.nodes, g.batch, g.deltas
    lane_mask = np.zeros((b,), bool)
    lane_mask[: g.live] = True
    return (
        _concrete_arrays(n),
        np.zeros((n, 3), np.float32),  # used
        np.full((b, k), -1, np.int32),  # delta_rows (-1 = no delta)
        np.zeros((b, k, 3), np.float32),  # delta_vals
        np.zeros((b, n), np.int32),  # tg_counts
        np.zeros((b, MAX_SPREADS, MAX_SPREAD_VALUES), np.float32),
        np.zeros((b, n), bool),  # penalties
        _concrete_reqs(b),
        np.ones((b, 1), bool),  # class_eligs
        np.ones((b, n), bool),  # host_masks
        lane_mask,
    )


def scatter_operands(g: Grid) -> Tuple[Any, ...]:
    """(device, idx, *row_data) for the dirty-row scatter; ``g.deltas``
    is the (already pow2-padded) dirty-row count."""
    arrays = _concrete_arrays(g.nodes)
    k = g.deltas
    idx = np.arange(k, dtype=np.int32) % g.nodes
    row_data = tuple(np.asarray(f)[:k] for f in arrays)
    return (arrays, idx) + row_data


# ---------------------------------------------------------------------------
# J105 sweeps — concrete call sequences whose compile cost is ratcheted
# ---------------------------------------------------------------------------


def _cache_size(entry: Callable[..., Any]) -> int:
    size = getattr(entry, "_cache_size", None)
    return int(size()) if callable(size) else 0


def occupancy_sweep(entry: Callable[..., Any], c: DeviceContract) -> int:
    """Call the entry at every lane-mask fill 1..batch (fresh operands
    per call — donated buffers are consumed) and return how many NEW
    compile-cache entries the sweep cost.  The contract: occupancy is a
    runtime value, so ONE compile serves all fills."""
    import jax

    g = c.compile_grid
    assert g is not None
    before = _cache_size(entry)
    for k in range(1, g.batch + 1):
        gk = g._replace(live=k)
        out = entry(*c.operands(gk), **c.static_kwargs(gk))
        jax.block_until_ready(out)  # the compile must have really happened
    return _cache_size(entry) - before


def pow2_rows_sweep(entry: Callable[..., Any], c: DeviceContract) -> int:
    """Scatter sweep: dirty-row counts 1..batch, pow2-padded the way
    ``NodeMatrix._sync_locked`` pads them, so the distinct idx shapes —
    and therefore compiles — stay logarithmic in the row count."""
    import jax

    g = c.compile_grid
    assert g is not None
    before = _cache_size(entry)
    for k in range(1, g.batch + 1):
        padded = 1 << (k - 1).bit_length()
        gk = g._replace(deltas=padded)
        out = entry(*c.operands(gk), **c.static_kwargs(gk))
        jax.block_until_ready(out)
    return _cache_size(entry) - before


# ---------------------------------------------------------------------------
# The table
# ---------------------------------------------------------------------------

# Trace grids: two node counts (prime-ish, colliding with no slot width,
# batch, placement, or delta dimension) prove node-count independence;
# the third point swaps the static Features bucket.  Kept moderate —
# tracing cost is per-equation, not per-element.
_N_A, _N_B = 97, 159


def _fused_trace_grids() -> Tuple[Grid, ...]:
    from ..ops.kernels import FULL_FEATURES, Features

    narrow = Features(c_width=0, a_width=0, s_width=0, preempt=False, ports=False)
    base = Grid(nodes=_N_A, batch=6, placements=3, deltas=5, live=6,
                features=FULL_FEATURES)
    return (base, base._replace(nodes=_N_B), base._replace(features=narrow))


def _fused_compile_grid() -> Grid:
    from ..ops.kernels import Features

    narrow = Features(c_width=0, a_width=0, s_width=0, preempt=False, ports=False)
    return Grid(nodes=32, batch=4, placements=2, deltas=4, live=4, features=narrow)


def _fused_budget(g: Grid) -> int:
    # One packed (B, P, FUSED_PACKED_WIDTH) f32 fetch: 32 B per
    # placement-row per eval, whatever the node count.
    from ..ops.kernels import FUSED_PACKED_WIDTH

    return g.batch * g.placements * FUSED_PACKED_WIDTH * 4


def table() -> Tuple[DeviceContract, ...]:
    """The registered device entry points.  Built lazily (imports jax)."""
    from ..ops import kernels
    from ..parallel import sharding
    from ..state import matrix

    fused_kwargs = lambda g: {"n_placements": g.placements, "features": g.features}
    trace_grids = _fused_trace_grids()
    compile_grid = _fused_compile_grid()

    def build_sharded(g: Grid) -> Callable[..., Any]:
        # Deterministic 1-device (1, 1) mesh: collectives and the
        # shard_map boundary are present in the trace regardless of the
        # physical shard count, so the contract holds wherever it runs.
        mesh = sharding.make_mesh(1, batch=1)
        return sharding.sharded_fused_place_batch(mesh, g.placements)

    scatter_grid = Grid(nodes=_N_A, batch=4, placements=1, deltas=4, live=4)
    return (
        DeviceContract(
            name="fused_place_batch",
            path="nomad_tpu/ops/kernels.py",
            build=lambda g: kernels.fused_place_batch,
            operands=fused_operands,
            static_kwargs=fused_kwargs,
            trace_grids=trace_grids,
            out_budget=_fused_budget,
            donated_args=(),  # the un-donated entry: tests/tools reuse inputs
            compile_grid=compile_grid,
        ),
        DeviceContract(
            name="fused_place_batch_live",
            path="nomad_tpu/ops/kernels.py",
            build=lambda g: kernels.fused_place_batch_live,
            operands=fused_operands,
            static_kwargs=fused_kwargs,
            trace_grids=trace_grids,
            out_budget=_fused_budget,
            donated_args=tuple(range(2, 11)),  # per-dispatch lane operands
            compile_grid=compile_grid,
            sweep=occupancy_sweep,
            max_compiles=1,  # occupancy is runtime data: ONE compile, all fills
        ),
        DeviceContract(
            name="sharded_fused_place_batch",
            path="nomad_tpu/parallel/sharding.py",
            build=build_sharded,
            operands=fused_operands,
            static_kwargs=lambda g: {"features": g.features},
            trace_grids=trace_grids,
            out_budget=_fused_budget,
            donated_args=(),  # matrix stays shared with in-flight dispatches
        ),
        DeviceContract(
            name="make_row_scatter",
            path="nomad_tpu/state/matrix.py",
            build=lambda g: matrix.make_row_scatter(),
            operands=scatter_operands,
            static_kwargs=lambda g: {},
            trace_grids=(scatter_grid, scatter_grid._replace(nodes=_N_B)),
            out_budget=None,  # outputs ARE the device-resident matrix
            node_axis_outputs_ok=True,
            donated_args=(),  # in-flight dispatches still read the old snapshot
            compile_grid=scatter_grid._replace(nodes=32),
            sweep=pow2_rows_sweep,
            max_compiles=3,  # pow2 buckets of 1..4 dirty rows: {1, 2, 4}
        ),
    )


def get(name: str) -> DeviceContract:
    for c in table():
        if c.name == name:
            return c
    raise KeyError(name)
