"""Lock-discipline pass (rules L001–L004).

Builds a per-function summary of lock activity from the AST — which
canonical locks each ``with`` block acquires, what the function calls
while holding them, where it waits and where it blocks — then checks the
graph against the declared hierarchy in :mod:`.lock_order`:

* **L001 lock-order inversion** — acquiring a ranked lock while holding
  a ranked lock of higher (inner) rank, directly or through a resolvable
  call (one-level interprocedural: ``self.method()`` and
  ``self.<attr>.method()`` via ``lock_order.ATTR_TYPES``, closed under a
  fixpoint so chains resolve).
* **L002 wait holding a foreign lock** — ``Condition.wait``/``wait_for``
  releases only its own lock; waiting while holding a *different* ranked
  lock parks that lock for the whole wait (the deadlock shape).
* **L003 blocking call in a critical section** — ``time.sleep``, RPC
  verbs (``_call``/``_post``/``replicate``), subprocess/urlopen, file
  I/O through the WAL, ``Event.wait``, and device→host fetches
  (``np.asarray``/``device_get``/``block_until_ready``) while holding a
  lock.  Holding only ``device`` exempts device *launch* verbs
  (``sync``/``device_put``) — serializing those is that lock's job.
* **L004 literal-bounded condvar wait** — ``cond.wait(timeout=<literal>)``
  on the condvar's *own* lock: a numeric-literal timeout papers over a
  lost notify with polling.  Timeouts that flow from parameters or
  computed deadlines (real timers) are not flagged.

The pass is lexical about lock identity (attribute aliases declared in
``lock_order.ALIASES``) — it never imports the code under analysis.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import Finding
from . import lock_order as lo

# Directories (repo-relative, under nomad_tpu/) the pass covers.
SCAN_DIRS = ("server", "scheduler", "state", "client", "stream")
SCAN_FILES = ("metrics.py", os.path.join("chaos", "injector.py"))

FuncKey = Tuple[str, Optional[str], str]  # (modpath, class, func)


@dataclass
class _Event:
    kind: str  # acquire | call | block | wait
    line: int
    held: Tuple[str, ...]  # canonical/unknown lock names held at the event
    lock: Optional[str] = None  # acquire: the lock; wait: the receiver
    callee: Optional[FuncKey] = None
    desc: str = ""
    timed_literal: bool = False  # wait: a numeric-literal timeout flowed in


@dataclass
class _FuncSummary:
    key: FuncKey
    symbol: str
    events: List[_Event] = field(default_factory=list)
    direct_acquires: Set[str] = field(default_factory=set)
    direct_blocking: List[Tuple[int, str]] = field(default_factory=list)


def _modkey(root: str, path: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


def _is_self(node: ast.AST) -> bool:
    return isinstance(node, ast.Name) and node.id == "self"


def _dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` -> "a.b.c" for pure Name/Attribute chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _FuncVisitor(ast.NodeVisitor):
    """Walks ONE function body, tracking the with-held lock stack."""

    def __init__(self, modpath: str, cls: Optional[str], summary: _FuncSummary):
        self.modpath = modpath
        self.cls = cls
        self.s = summary
        self.held: List[str] = []
        # name -> "self.<attr>" aliases (replicator = self.replicator)
        self.aliases: Dict[str, str] = {}

    # -- lock identity -------------------------------------------------

    def _lock_name(self, node: ast.AST) -> Optional[str]:
        """Canonical (or synthetic-unknown) lock name of an expression
        used as a lock, or None if it doesn't look like one."""
        if isinstance(node, ast.Name):
            if node.id in lo.GLOBAL_NAME_ALIASES:
                return lo.GLOBAL_NAME_ALIASES[node.id]
            target = self.aliases.get(node.id)
            if target:
                return self._attr_lock(target.split(".", 1)[1])
            return None
        if isinstance(node, ast.Attribute) and _is_self(node.value):
            return self._attr_lock(node.attr)
        return None

    def _attr_lock(self, attr: str) -> Optional[str]:
        canon = lo.resolve(self.modpath, self.cls, attr)
        if canon:
            return canon
        if attr.rstrip("_").endswith(("lock", "cond")) or attr in ("_cv",):
            return f"{self.modpath}:{self.cls or '<module>'}.{attr}"
        return None

    # -- traversal -----------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass  # nested defs get their own summary

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass

    def visit_Assign(self, node: ast.Assign) -> None:
        if (
            len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Attribute)
            and _is_self(node.value.value)
        ):
            self.aliases[node.targets[0].id] = f"self.{node.value.attr}"
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        acquired: List[str] = []
        for item in node.items:
            name = self._lock_name(item.context_expr)
            if name is not None:
                self.s.events.append(_Event(
                    "acquire", item.context_expr.lineno,
                    tuple(self.held), lock=name,
                ))
                self.s.direct_acquires.add(name)
                self.held.append(name)
                acquired.append(name)
            else:
                self.generic_visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    def visit_Call(self, node: ast.Call) -> None:
        self._classify_call(node)
        self.generic_visit(node)

    # -- call classification -------------------------------------------

    def _timeout_is_literal(self, node: ast.Call) -> bool:
        """True when a numeric literal flows into the wait's timeout
        (positionally or by keyword, directly or through an IfExp arm)."""
        args: List[ast.AST] = []
        # wait(timeout) / wait_for(pred, timeout)
        fname = node.func.attr if isinstance(node.func, ast.Attribute) else ""
        pos = 0 if fname == "wait" else 1
        if len(node.args) > pos:
            args.append(node.args[pos])
        for kw in node.keywords:
            if kw.arg == "timeout":
                args.append(kw.value)

        def literal(e: ast.AST) -> bool:
            if isinstance(e, ast.Constant):
                return isinstance(e.value, (int, float)) and not isinstance(
                    e.value, bool
                )
            if isinstance(e, ast.IfExp):
                return literal(e.body) or literal(e.orelse)
            if isinstance(e, ast.Name):
                tl = self._literal_names.get(e.id)
                return bool(tl)
            return False

        return any(literal(a) for a in args)

    _literal_names: Dict[str, bool] = {}

    def _classify_call(self, node: ast.Call) -> None:
        held = tuple(self.held)
        func = node.func
        dotted = _dotted(func)

        # Condition/Event waits.
        if isinstance(func, ast.Attribute) and func.attr in ("wait", "wait_for"):
            recv_lock = self._lock_name(func.value)
            if recv_lock is not None:
                self.s.events.append(_Event(
                    "wait", node.lineno, held, lock=recv_lock,
                    timed_literal=self._timeout_is_literal(node),
                ))
                return
            if held and func.attr == "wait":
                # Event.wait (or an un-aliased latch) inside a section.
                self.s.events.append(_Event(
                    "block", node.lineno, held,
                    desc=f"{_dotted(func) or func.attr}() wait",
                ))
                return

        desc: Optional[str] = None
        if dotted in lo.BLOCKING_DOTTED:
            desc = f"{dotted}()"
        elif dotted in lo.DEVICE_FETCH_DOTTED:
            desc = f"{dotted}() device fetch"
        elif isinstance(func, ast.Name) and func.id == "open":
            desc = "open() file I/O"
        elif isinstance(func, ast.Attribute):
            if func.attr in lo.BLOCKING_ATTR_NAMES:
                desc = f".{func.attr}() network call"
            elif func.attr in lo.DEVICE_FETCH_ATTR_NAMES:
                desc = f".{func.attr}() device fetch"
            else:
                recv = func.value
                recv_attr = None
                if isinstance(recv, ast.Attribute) and _is_self(recv.value):
                    recv_attr = recv.attr
                elif isinstance(recv, ast.Name):
                    tgt = self.aliases.get(recv.id)
                    if tgt:
                        recv_attr = tgt.split(".", 1)[1]
                if recv_attr in lo.BLOCKING_RECEIVER_ATTRS:
                    desc = f"self.{recv_attr}.{func.attr}() file I/O"
                elif (
                    held == ("device",)
                    and func.attr in lo.DEVICE_OP_ATTR_NAMES
                ):
                    desc = None  # launching under the device lock is its job
        if desc is not None:
            self.s.direct_blocking.append((node.lineno, desc))
            if held:
                self.s.events.append(_Event("block", node.lineno, held, desc=desc))
            return

        # Resolvable calls for the interprocedural walk.
        callee = self._callee_key(func)
        if callee is not None:
            self.s.events.append(_Event(
                "call", node.lineno, held, callee=callee,
            ))

    def _callee_key(self, func: ast.AST) -> Optional[FuncKey]:
        if isinstance(func, ast.Name):
            return (self.modpath, None, func.id)
        if isinstance(func, ast.Attribute):
            recv = func.value
            if _is_self(recv):
                return (self.modpath, self.cls, func.attr)
            if isinstance(recv, ast.Attribute) and _is_self(recv.value):
                typed = lo.ATTR_TYPES.get(recv.attr)
                if typed:
                    return (typed[0], typed[1], func.attr)
        return None


def _collect_literal_timeout_names(fn: ast.AST) -> Dict[str, bool]:
    """Names in this function assigned a numeric literal (or an IfExp of
    literals) — feeds the L004 'literal-bounded wait' detection through
    one assignment hop (``timeout = 0.2 if busy else None``)."""
    out: Dict[str, bool] = {}

    def literal(e: ast.AST) -> bool:
        if isinstance(e, ast.Constant):
            return isinstance(e.value, (int, float)) and not isinstance(e.value, bool)
        if isinstance(e, ast.IfExp):
            return literal(e.body) or literal(e.orelse)
        return False

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                out[t.id] = literal(node.value)
    return out


# ----------------------------------------------------------------------
# Module walk
# ----------------------------------------------------------------------


def summarize_module(modpath: str, tree: ast.Module) -> List[_FuncSummary]:
    out: List[_FuncSummary] = []

    def walk_body(body: Sequence[ast.stmt], cls: Optional[str], prefix: str) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                walk_body(node.body, node.name, f"{node.name}.")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key: FuncKey = (modpath, cls, node.name)
                s = _FuncSummary(key=key, symbol=f"{prefix}{node.name}")
                v = _FuncVisitor(modpath, cls, s)
                v._literal_names = _collect_literal_timeout_names(node)
                for stmt in node.body:
                    v.visit(stmt)
                out.append(s)
                # Nested defs (decorator wrappers like @journaled's
                # `wrapper`) are real lock scopes — summarize them too.
                for stmt in node.body:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            k2: FuncKey = (modpath, cls, sub.name)
                            s2 = _FuncSummary(
                                key=k2, symbol=f"{prefix}{node.name}.{sub.name}"
                            )
                            v2 = _FuncVisitor(modpath, cls, s2)
                            v2._literal_names = _collect_literal_timeout_names(sub)
                            for st in sub.body:
                                v2.visit(st)
                            out.append(s2)

    walk_body(tree.body, None, "")
    return out


# ----------------------------------------------------------------------
# Checking
# ----------------------------------------------------------------------


def _transitive_acquires(
    summaries: Dict[FuncKey, _FuncSummary]
) -> Dict[FuncKey, Set[str]]:
    acq = {k: set(s.direct_acquires) for k, s in summaries.items()}
    changed = True
    while changed:
        changed = False
        for k, s in summaries.items():
            for ev in s.events:
                if ev.kind == "call" and ev.callee in acq:
                    before = len(acq[k])
                    acq[k] |= acq[ev.callee]
                    if len(acq[k]) != before:
                        changed = True
    return acq


def check_summaries(summaries: List[_FuncSummary]) -> List[Finding]:
    by_key = {s.key: s for s in summaries}
    trans = _transitive_acquires(by_key)
    findings: List[Finding] = []

    def inversion(held: Tuple[str, ...], lock: str) -> Optional[str]:
        r = lo.rank(lock)
        if r is None:
            return None
        if lock in held:
            # Re-entrant re-acquisition (the store's RLocks; e.g.
            # install_snapshot -> restore -> @journaled taking
            # _write_lock again) adds no ordering edge.
            return None
        for h in held:
            hr = lo.rank(h)
            if hr is not None and h != lock and r < hr:
                return h
        return None

    for s in summaries:
        path = s.key[0]
        for ev in s.events:
            if ev.kind == "acquire":
                outer = inversion(ev.held, ev.lock or "")
                if outer:
                    findings.append(Finding(
                        "L001", path, ev.line, s.symbol,
                        f"lock-order inversion: acquires '{ev.lock}' while "
                        f"holding '{outer}' (declared order: "
                        f"{' -> '.join(lo.ORDER)})",
                    ))
            elif ev.kind == "call" and ev.callee in trans:
                for lock in sorted(trans[ev.callee]):
                    outer = inversion(ev.held, lock)
                    if outer:
                        callee = ev.callee[2]
                        findings.append(Finding(
                            "L001", path, ev.line, s.symbol,
                            f"lock-order inversion via call: {callee}() "
                            f"acquires '{lock}' while '{outer}' is held",
                        ))
                # One-level blocking propagation: a callee that blocks
                # directly blocks this critical section too.
                if ev.held:
                    callee_s = by_key.get(ev.callee)
                    if callee_s is not None and callee_s.direct_blocking:
                        _, desc = callee_s.direct_blocking[0]
                        findings.append(Finding(
                            "L003", path, ev.line, s.symbol,
                            f"blocking call in critical section (holding "
                            f"{list(ev.held)}): {ev.callee[2]}() -> {desc}",
                        ))
            elif ev.kind == "block":
                findings.append(Finding(
                    "L003", path, ev.line, s.symbol,
                    f"blocking call in critical section (holding "
                    f"{list(ev.held)}): {ev.desc}",
                ))
            elif ev.kind == "wait":
                foreign = [
                    h for h in ev.held
                    if h != ev.lock and lo.rank(h) is not None
                ]
                if foreign:
                    findings.append(Finding(
                        "L002", path, ev.line, s.symbol,
                        f"Condition.wait on '{ev.lock}' while holding "
                        f"foreign lock(s) {foreign} — the wait parks them "
                        f"for its whole duration",
                    ))
                elif ev.timed_literal:
                    findings.append(Finding(
                        "L004", path, ev.line, s.symbol,
                        f"literal-bounded wait on '{ev.lock}': a hardcoded "
                        f"timeout polls around a lost notify instead of "
                        f"fixing the notify discipline",
                    ))
    return findings


# ----------------------------------------------------------------------
# Entry points
# ----------------------------------------------------------------------


def analyze_sources(sources: Dict[str, str]) -> List[Finding]:
    """Analyze {repo-relative path: source text} — the test fixture API."""
    summaries: List[_FuncSummary] = []
    for path, src in sources.items():
        summaries += summarize_module(path, ast.parse(src))
    return check_summaries(summaries)


def run(root: str) -> List[Finding]:
    pkg = os.path.join(root, "nomad_tpu")
    paths: List[str] = []
    for d in SCAN_DIRS:
        base = os.path.join(pkg, d)
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    paths.append(os.path.join(dirpath, fn))
    for f in SCAN_FILES:
        p = os.path.join(pkg, f)
        if os.path.exists(p):
            paths.append(p)

    summaries: List[_FuncSummary] = []
    for p in sorted(paths):
        with open(p) as fh:
            src = fh.read()
        summaries += summarize_module(_modkey(root, p), ast.parse(src))
    return check_summaries(summaries)
