"""TSan-lite: a lockset-based runtime race sanitizer for the declared
shared state (the dynamic half of ``nomad lint``).

The static passes prove the *lexical* discipline; this module checks the
*runtime* one: every access to a declared shared object must happen with
that object's guard lock in the accessing thread's lockset.  It is the
eraser-style lockset algorithm stripped to what this codebase needs:

* ``TrackedLock`` wraps a real ``Lock``/``RLock`` and maintains a
  thread-local multiset of held guards.  It implements the full
  ``Condition`` protocol (``_release_save``/``_acquire_restore``/
  ``_is_owned``) so wrapped condvars keep working —
  ``threading.Condition`` binds those *at construction*, so
  :func:`wrap_condition` rebinds them on the instance.
* Monitored containers (dict/list/set/deque and an ``ndarray`` view
  subclass) call :meth:`_ObjInfo.check` on every mutation (and read,
  unless the object is registered ``writes_only``).
* Per-object EXCLUSIVE→SHARED state machine: an object owned by the
  thread that has touched it so far is never checked (single-threaded
  setup is free); the moment a second thread touches it, every further
  unguarded access reports.
* Reports carry (label, op, thread, held locksets, stack).  Stacks are
  captured only when a violation fires — the hot path is a set lookup.

Zero overhead when disabled: the product constructors call
:func:`maybe_instrument`, which returns immediately unless a test called
:func:`enable` first.  Enable BEFORE constructing the objects under
test::

    from nomad_tpu.lint import tsan
    tsan.enable()
    try:
        ... run the chaos scenario ...
        assert tsan.reports() == []
    finally:
        tsan.disable()

Caveats (documented in STATIC_ANALYSIS.md): rebinding a monitored
attribute (e.g. matrix capacity growth swaps ``_alloc``) sheds the
monitor for the new object — the seeded scenarios don't grow capacity;
reads of ``writes_only`` tables are deliberately unchecked because the
store's read contract is immutable-replace under the GIL.
"""

from __future__ import annotations

import threading
import traceback
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, FrozenSet, List, Optional, Tuple

import numpy as np

_enabled = False
_report_lock = threading.Lock()
_reports: List[Dict[str, Any]] = []
_MAX_REPORTS = 100
_tls = threading.local()


def enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    with _report_lock:
        _reports.clear()
    _enabled = True


def disable() -> None:
    """Stop checking and drop accumulated reports — read
    :func:`reports` BEFORE disabling."""
    global _enabled
    _enabled = False
    with _report_lock:
        _reports.clear()


def reports() -> List[Dict[str, Any]]:
    with _report_lock:
        return list(_reports)


@contextmanager
def sanitized():
    """Enable for the block, disable on exit.  Construct the objects
    under test INSIDE the block — instrumentation happens at their
    constructors."""
    enable()
    try:
        yield
    finally:
        disable()


def _held() -> Dict[int, List]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = {}
    return h


def held_names() -> FrozenSet[str]:
    """The calling thread's current lockset (canonical guard names)."""
    return frozenset(name for name, c in _held().values() if c > 0)


# ----------------------------------------------------------------------
# TrackedLock
# ----------------------------------------------------------------------


class TrackedLock:
    """Wraps a ``Lock``/``RLock``; each acquire/release updates the
    calling thread's lockset.  Identity (``id(self)``) is the guard key,
    the ``name`` only labels reports."""

    def __init__(self, inner, name: str):
        self._inner = inner
        self._name = name

    # -- lockset bookkeeping ------------------------------------------

    def _count(self) -> int:
        e = _held().get(id(self))
        return e[1] if e is not None else 0

    def _add(self, n: int) -> None:
        h = _held()
        e = h.get(id(self))
        if e is None:
            h[id(self)] = [self._name, n]
        else:
            e[1] += n
            if e[1] <= 0:
                del h[id(self)]

    # -- Lock protocol -------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._add(1)
        return got

    def release(self) -> None:
        self._inner.release()
        self._add(-1)

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked() if hasattr(self._inner, "locked") else False

    # -- Condition protocol (bound onto wrapped Condition instances) ---

    def _is_owned(self) -> bool:
        return self._count() > 0

    def _release_save(self):
        count = self._count()
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        self._add(-count)
        return (state, count)

    def _acquire_restore(self, saved) -> None:
        state, count = saved
        if state is not None and hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._add(count)

    def __repr__(self) -> str:
        return f"<TrackedLock {self._name}>"


def wrap_condition(cond: threading.Condition, name: str) -> TrackedLock:
    """Route an existing ``Condition`` through a ``TrackedLock``.

    ``Condition.__init__`` snapshots ``acquire``/``release`` and (for
    RLocks) ``_release_save``/``_acquire_restore``/``_is_owned`` from the
    lock it was built on, so swapping ``_lock`` alone is not enough —
    every snapshotted method must be rebound on the instance."""
    tl = TrackedLock(cond._lock, name)
    _rebind_condition(cond, tl)
    return tl


def _rebind_condition(cond: threading.Condition, tl: TrackedLock) -> None:
    cond._lock = tl
    cond.acquire = tl.acquire
    cond.release = tl.release
    cond._is_owned = tl._is_owned
    cond._release_save = tl._release_save
    cond._acquire_restore = tl._acquire_restore


# ----------------------------------------------------------------------
# Object state + monitored containers
# ----------------------------------------------------------------------


class _ObjInfo:
    """Lockset state for one monitored object."""

    __slots__ = ("label", "guards", "writes_only", "owner", "shared")

    def __init__(self, label: str, guards: Tuple[TrackedLock, ...],
                 writes_only: bool = False):
        self.label = label
        self.guards = guards
        self.writes_only = writes_only
        self.owner: Optional[int] = None  # exclusive-owner thread id
        self.shared = False

    def check(self, op: str) -> None:
        if not _enabled:
            return
        tid = threading.get_ident()
        if not self.shared:
            if self.owner is None:
                self.owner = tid
                return
            if self.owner == tid:
                return
            self.shared = True  # second thread arrived — checks begin
        if self.writes_only and op == "read":
            return
        h = _held()
        for g in self.guards:
            e = h.get(id(g))
            if e is not None and e[1] > 0:
                return
        self._report(op)

    def _report(self, op: str) -> None:
        rec = {
            "label": self.label,
            "op": op,
            "thread": threading.current_thread().name,
            "held": sorted(held_names()),
            "required": sorted(g._name for g in self.guards),
            "stack": "".join(traceback.format_stack(limit=12)),
        }
        with _report_lock:
            if len(_reports) < _MAX_REPORTS:
                _reports.append(rec)


class MonitoredDict(dict):
    _tsan_info: _ObjInfo

    def __setitem__(self, k, v):
        self._tsan_info.check("write")
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._tsan_info.check("write")
        super().__delitem__(k)

    def pop(self, *a):
        self._tsan_info.check("write")
        return super().pop(*a)

    def popitem(self):
        self._tsan_info.check("write")
        return super().popitem()

    def clear(self):
        self._tsan_info.check("write")
        super().clear()

    def update(self, *a, **k):
        self._tsan_info.check("write")
        super().update(*a, **k)

    def setdefault(self, *a):
        self._tsan_info.check("write")
        return super().setdefault(*a)

    def __getitem__(self, k):
        self._tsan_info.check("read")
        return super().__getitem__(k)

    def get(self, *a):
        self._tsan_info.check("read")
        return super().get(*a)


class MonitoredList(list):
    _tsan_info: _ObjInfo

    def append(self, x):
        self._tsan_info.check("write")
        super().append(x)

    def extend(self, it):
        self._tsan_info.check("write")
        super().extend(it)

    def insert(self, i, x):
        self._tsan_info.check("write")
        super().insert(i, x)

    def pop(self, *a):
        self._tsan_info.check("write")
        return super().pop(*a)

    def remove(self, x):
        self._tsan_info.check("write")
        super().remove(x)

    def clear(self):
        self._tsan_info.check("write")
        super().clear()

    def __setitem__(self, i, v):
        self._tsan_info.check("write")
        super().__setitem__(i, v)

    def __delitem__(self, i):
        self._tsan_info.check("write")
        super().__delitem__(i)

    def __iadd__(self, other):
        self._tsan_info.check("write")
        return super().__iadd__(other)

    def __getitem__(self, i):
        self._tsan_info.check("read")
        return super().__getitem__(i)


class MonitoredSet(set):
    _tsan_info: _ObjInfo

    def add(self, x):
        self._tsan_info.check("write")
        super().add(x)

    def discard(self, x):
        self._tsan_info.check("write")
        super().discard(x)

    def remove(self, x):
        self._tsan_info.check("write")
        super().remove(x)

    def pop(self):
        self._tsan_info.check("write")
        return super().pop()

    def clear(self):
        self._tsan_info.check("write")
        super().clear()

    def update(self, *a):
        self._tsan_info.check("write")
        super().update(*a)

    def difference_update(self, *a):
        self._tsan_info.check("write")
        super().difference_update(*a)

    def __contains__(self, x):
        self._tsan_info.check("read")
        return super().__contains__(x)


class MonitoredDeque(deque):
    _tsan_info: _ObjInfo

    def append(self, x):
        self._tsan_info.check("write")
        super().append(x)

    def appendleft(self, x):
        self._tsan_info.check("write")
        super().appendleft(x)

    def extend(self, it):
        self._tsan_info.check("write")
        super().extend(it)

    def pop(self):
        self._tsan_info.check("write")
        return super().pop()

    def popleft(self):
        self._tsan_info.check("write")
        return super().popleft()

    def clear(self):
        self._tsan_info.check("write")
        super().clear()

    def __getitem__(self, i):
        self._tsan_info.check("read")
        return super().__getitem__(i)


class MonitoredArray(np.ndarray):
    """ndarray view that checks writes.  ``__array_finalize__`` carries
    the info onto every derived view, so ``alloc["used"][row] = x`` —
    which desugars through a view's ``__setitem__`` — is caught."""

    def __array_finalize__(self, obj):
        if obj is None:
            return
        info = getattr(obj, "_tsan_info", None)
        # Follow VIEWS only (slices, reshapes): ufunc results and copies
        # computed FROM the shared array are fresh private buffers, not
        # shared state — carrying the info onto them flags every scratch
        # write as a race.  may_share_memory is the cheap bounds check.
        if info is not None and np.may_share_memory(self, obj):
            self._tsan_info = info
        else:
            self._tsan_info = None

    def __setitem__(self, k, v):
        info = getattr(self, "_tsan_info", None)
        if info is not None:
            info.check("write")
        super().__setitem__(k, v)


_CONTAINER_TYPES = {
    dict: MonitoredDict,
    list: MonitoredList,
    set: MonitoredSet,
    deque: MonitoredDeque,
}


def _wrap_container(value, info: _ObjInfo):
    if isinstance(value, np.ndarray):
        view = value.view(MonitoredArray)
        view._tsan_info = info
        return view
    for base, mon in _CONTAINER_TYPES.items():
        if type(value) is base:
            if base is deque:
                out = mon(value, value.maxlen)
            else:
                out = mon(value)
            out._tsan_info = info
            return out
    raise TypeError(f"cannot monitor {type(value).__name__}")


def _monitor_attr(obj, attr: str, label: str,
                  guards: Tuple[TrackedLock, ...],
                  writes_only: bool = False) -> None:
    info = _ObjInfo(label, guards, writes_only)
    setattr(obj, attr, _wrap_container(getattr(obj, attr), info))


# ----------------------------------------------------------------------
# Registration (called from product constructors; no-ops when disabled)
# ----------------------------------------------------------------------

STORE_TABLES = ("nodes", "jobs", "evals", "allocs", "deployments")


def _register_store(store) -> None:
    # _lock and _cond share one underlying RLock — one TrackedLock for
    # both keeps the guard identity consistent.
    state_tl = TrackedLock(store._lock, "store.state")
    store._lock = state_tl
    _rebind_condition(store._cond, state_tl)
    store._write_lock = TrackedLock(store._write_lock, "store.write")
    wrap_condition(store._watch_cond, "store.watch")
    for t in STORE_TABLES:
        # writes_only: the read contract is immutable-replace under the
        # GIL (readers see either the old or the new object, never a
        # torn one) — only unlocked *writes* are races.
        _monitor_attr(store, t, f"store.{t}", (state_tl,), writes_only=True)


def _register_matrix(matrix) -> None:
    host_tl = TrackedLock(matrix._host_lock, "matrix.host")
    matrix._host_lock = host_tl
    _monitor_attr(matrix, "_dirty", "matrix._dirty", (host_tl,))
    _monitor_attr(matrix, "_sharded_dirty", "matrix._sharded_dirty", (host_tl,))
    # _alloc is a dict of named row arrays; writes land on the arrays
    # (alloc["used"][row] = x), so each value gets a monitored view.
    # The dict itself is never mutated in place (growth rebinds it).
    info = _ObjInfo("matrix._alloc", (host_tl,), writes_only=True)
    matrix._alloc = {
        k: _wrap_container(v, info) for k, v in matrix._alloc.items()
    }


def _register_broker(broker) -> None:
    tl = TrackedLock(broker._lock, "broker")
    broker._lock = tl
    _monitor_attr(broker, "_buffer", "broker._buffer", (tl,))
    _monitor_attr(broker, "_subs", "broker._subs", (tl,))


def _register_subscription(sub) -> None:
    tl = wrap_condition(sub._cond, "subscription")
    _monitor_attr(sub, "_queue", "subscription._queue", (tl,))


def _register_coalescer(co) -> None:
    tl = wrap_condition(co._cond, "coalescer")
    _monitor_attr(co, "_queue", "coalescer._queue", (tl,))
    _monitor_attr(co, "_ops", "coalescer._ops", (tl,))


_REGISTRARS = {
    "store": _register_store,
    "matrix": _register_matrix,
    "broker": _register_broker,
    "subscription": _register_subscription,
    "coalescer": _register_coalescer,
}


def maybe_instrument(kind: str, obj) -> None:
    """Product-side hook: wraps ``obj``'s declared shared state when the
    sanitizer is enabled; a single global-flag test otherwise."""
    if not _enabled:
        return
    _REGISTRARS[kind](obj)
