"""The declared lock hierarchy — the contract the lock-discipline pass
enforces (STATIC_ANALYSIS.md documents it with examples).

Locks are named canonically; :data:`ORDER` lists them outermost-first.
While holding a lock of rank *r*, only locks of rank > *r* may be
acquired.  Locks not named here are *unranked*: each is an island the
orderer cannot compare, so L001 never fires on them (L002/L003/L004
still apply).  Rank a lock by adding it to :data:`ORDER` and mapping its
attribute in :data:`ALIASES` — the analyzer picks it up with no other
change.

The hierarchy mirrors how the system actually nests today:

* ``store.write``   — ``StateStore._write_lock``: the journaled-writer
  gate; held across the replicate→apply sequence (reads proceed).
* ``replication``   — ``Replicator`` peer state; taken under the writer
  gate while an entry streams to peers.
* ``store.state``   — ``StateStore._lock``/``_cond``: the read lock;
  held only for in-memory applies and snapshots.
* ``device``        — ``state.matrix.DEVICE_LOCK``: serializes every
  device interaction (the single-chip tunnel wedges under concurrent
  host threads).
* ``matrix.host``   — ``NodeMatrix._host_lock``: guards the host mirror
  rows + dirty sets against the sync drain.
* ``broker``        — ``EventBroker._lock``: ring buffer + subscriber
  list; publish snapshots subscribers under it, then offers outside.
* ``subscription``  — per-``Subscription`` condvar (leaf of the event
  fan-out).
* ``store.watch``   — ``StateStore._watch_cond``: the dedicated
  index-watcher leaf; ``_bump`` notifies it while holding the state
  lock, so it must stay strictly innermost of the store family.
* ``metrics`` / ``injector`` — leaf bookkeeping locks; anything may
  record a metric or consult the fault injector while holding anything.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

ORDER: Tuple[str, ...] = (
    "store.write",
    "replication",
    "store.state",
    "device",
    "matrix.host",
    "broker",
    "subscription",
    "store.watch",
    "metrics",
    "injector",
)

RANK: Dict[str, int] = {name: i for i, name in enumerate(ORDER)}

# (module-path suffix, class name or "*", attribute) -> canonical name.
# A condition variable built on a lock maps to the SAME canonical name as
# the lock (waiting on it releases that lock, not a new one).
ALIASES: Dict[Tuple[str, str, str], str] = {
    ("state/store.py", "StateStore", "_write_lock"): "store.write",
    ("state/store.py", "StateStore", "_lock"): "store.state",
    ("state/store.py", "StateStore", "_cond"): "store.state",
    ("state/store.py", "StateStore", "_watch_cond"): "store.watch",
    ("server/replication.py", "*", "_lock"): "replication",
    ("state/matrix.py", "*", "DEVICE_LOCK"): "device",
    ("state/matrix.py", "NodeMatrix", "_host_lock"): "matrix.host",
    ("stream/broker.py", "EventBroker", "_lock"): "broker",
    ("stream/broker.py", "Subscription", "_cond"): "subscription",
    ("metrics.py", "*", "_lock"): "metrics",
    ("chaos/injector.py", "*", "_lock"): "injector",
}

# Canonical names that are condition variables (their .wait releases the
# underlying lock — waiting on one while holding a DIFFERENT ranked lock
# is the L002 deadlock shape).
CONDVARS = frozenset({"store.state", "store.watch", "subscription"})

# Bare names that always mean the device lock, wherever imported.
GLOBAL_NAME_ALIASES: Dict[str, str] = {"DEVICE_LOCK": "device"}

# `self.<attr>` -> the (module suffix, class) its methods resolve against,
# for the one-level interprocedural walk (self.matrix.upsert_node ->
# NodeMatrix.upsert_node's lock summary).
ATTR_TYPES: Dict[str, Tuple[str, str]] = {
    "store": ("state/store.py", "StateStore"),
    "matrix": ("state/matrix.py", "NodeMatrix"),
    "events": ("stream/broker.py", "EventBroker"),
    "broker": ("stream/broker.py", "EventBroker"),
    "replicator": ("server/replication.py", "Replicator"),
    "metrics": ("metrics.py", "MetricsRegistry"),
}

# Dotted-call names that block (L003) when made inside a critical section.
BLOCKING_DOTTED = frozenset({
    "time.sleep",
    "subprocess.run",
    "subprocess.Popen",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "urllib.request.urlopen",
    "socket.create_connection",
})

# Method names that block regardless of receiver: RPC sends and the
# replication fan-out ( `_post`/`_call`/`replicate` are this codebase's
# network verbs).
BLOCKING_ATTR_NAMES = frozenset({"_post", "_call", "replicate", "urlopen"})

# `self.<attr>.<anything>()` receivers that mean file I/O.
BLOCKING_RECEIVER_ATTRS = frozenset({"wal"})

# Device→host fetches: block for a full tunnel round-trip.
DEVICE_FETCH_DOTTED = frozenset({"np.asarray", "numpy.asarray", "jax.device_get"})
DEVICE_FETCH_ATTR_NAMES = frozenset({"block_until_ready"})

# Calls that are DEVICE_LOCK's purpose — launching/uploading under the
# device lock is why it exists, so these are exempt from L003 while it
# (alone among ranked locks) is held.
DEVICE_OP_ATTR_NAMES = frozenset({"sync", "sync_sharded", "device_put"})


def resolve(modpath: str, cls: Optional[str], attr: str) -> Optional[str]:
    """Canonical lock name for attribute ``attr`` of class ``cls`` in
    ``modpath`` (repo-relative, forward slashes); None if unranked.

    Falls back to a module+attr match when the class doesn't line up —
    decorator-produced wrappers (``@journaled``'s ``wrapper``) live at
    module scope but close over the same ``self``."""
    fallback: Optional[str] = None
    for (suffix, alias_cls, alias_attr), name in ALIASES.items():
        if attr != alias_attr:
            continue
        if not modpath.endswith(suffix):
            continue
        if alias_cls == "*" or cls == alias_cls:
            return name
        if fallback is None:
            fallback = name
    return fallback


def rank(name: str) -> Optional[int]:
    return RANK.get(name)
