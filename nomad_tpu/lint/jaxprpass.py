"""Jaxpr-level semantic pass: prove the device-kernel contracts from the
traced program, not the source text.

The AST rules (J001–J005) pattern-match call sites, which a one-helper
refactor evades (tests/test_lint.py documents the known J005 miss).  This
pass closes that hole by tracing every registered entry point in
:mod:`.contracts` to a ClosedJaxpr under a declared configuration grid
and walking the result:

* **J101** — no host-callback primitive (``io_callback``,
  ``pure_callback``, ``debug_callback``) anywhere inside a fused
  program.  A callback re-introduces the per-eval host round trip the
  megakernel exists to amortize.
* **J102** — total device→host output bytes per launch within the
  declared budget, and *independent of the node count* (traced at two N
  values, byte counts must match): the O(B·P)-bytes tunnel contract.
* **J103** — no node-axis-sized value crossing a collective
  (``psum``/``pmax``/``pmin``/``all_gather``/…) or leaving the
  ``shard_map`` boundary, except declared exemptions: nothing
  N-shaped may be replicated, reduced, or fetched across the mesh.
* **J104** — the declared donation set actually reaches XLA: every
  operand declared donated is donated after ``lower()`` (and no operand
  is donated undeclared), and donation survives to the compiled
  executable.  ``expect_alias`` additionally requires an
  ``input_output_alias`` in the HLO — off for the current entries
  because on CPU no donated lane-operand aval matches the packed
  (B, P, 8) output, so XLA can reuse the buffers as scratch but never
  alias them.
* **J105** — compile-cache cardinality, measured from the real cache:
  the contract's concrete sweep (occupancy fills, pow2 dirty-row
  buckets) may cost at most ``max_compiles`` new cache entries.

A contract whose harness itself breaks (entry won't trace, operands
mismatch) surfaces as **J100** so the gate fails loudly instead of
silently skipping the entry.

Findings flow through the same ``(rule, path, symbol)`` baseline ratchet
as the AST passes; ``symbol`` is the contract name.  Everything is
gated on JAX importability — :func:`run` returns ``[]`` (with a stderr
notice under ``--jaxpr``) when no backend is present.
"""

from __future__ import annotations

import os
import re
import warnings
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from . import Finding, repo_root

__all__ = ["available", "check_contract", "run"]

# Primitive names that punch through to the host mid-program.
CALLBACK_PRIMS = frozenset(
    {"io_callback", "pure_callback", "debug_callback", "callback"}
)

# Cross-shard collectives (psum appears as psum2 under shard_map in this
# jax).  pbroadcast is deliberately absent: it is replication
# bookkeeping, not data movement.
COLLECTIVE_PRIMS = frozenset(
    {
        "psum",
        "psum2",
        "pmax",
        "pmin",
        "all_gather",
        "all_to_all",
        "reduce_scatter",
        "ppermute",
        "pgather",
    }
)


def available() -> bool:
    """True when JAX imports and a backend initializes."""
    try:
        import jax

        jax.devices()
        return True
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Jaxpr walking
# ---------------------------------------------------------------------------


def _subjaxprs(v: Any) -> Iterator[Any]:
    if hasattr(v, "jaxpr"):  # ClosedJaxpr
        yield v.jaxpr
    elif hasattr(v, "eqns"):  # raw Jaxpr (shard_map, custom_* params)
        yield v
    elif isinstance(v, (list, tuple)):
        for x in v:
            yield from _subjaxprs(x)


def iter_eqns(jaxpr: Any) -> Iterator[Any]:
    """Every equation, recursively through pjit/scan/cond/shard_map/… ."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _subjaxprs(v):
                yield from iter_eqns(sub)


def _aval_bytes(aval: Any) -> int:
    n = 1
    for d in aval.shape:
        n *= int(d)
    return n * aval.dtype.itemsize


def _shapes(eqn: Any) -> List[Tuple[int, ...]]:
    out = []
    for var in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(var, "aval", None)
        shape = getattr(aval, "shape", None)
        if shape is not None:
            out.append(tuple(int(d) for d in shape))
    return out


# ---------------------------------------------------------------------------
# The checks
# ---------------------------------------------------------------------------


def _def_line(root: str, relpath: str, name: str) -> int:
    """Line of ``def name`` / ``name =`` so findings are clickable."""
    try:
        with open(os.path.join(root, relpath)) as fh:
            src = fh.read()
    except OSError:
        return 1
    m = re.search(
        rf"^(?:def {re.escape(name)}\b|{re.escape(name)}\s*=)", src, re.M
    )
    return src[: m.start()].count("\n") + 1 if m else 1


def _trace(entry: Callable[..., Any], args: Tuple[Any, ...],
           kwargs: Dict[str, Any]) -> Any:
    import functools

    import jax

    return jax.make_jaxpr(functools.partial(entry, **kwargs))(*args)


def _positional_args_info(lowered: Any, n_args: int) -> Sequence[Any]:
    """``lowered.args_info`` subtree per positional arg (statics are
    keyword-only for every registered entry, so positions line up)."""
    info = lowered.args_info
    if (
        isinstance(info, tuple)
        and len(info) == 2
        and isinstance(info[1], dict)
        and len(info[0]) == n_args
    ):
        return info[0]
    return info


def _check_traced(c: Any, g: Any, closed: Any, emit: Callable[[str, str], None]) -> int:
    """J101 + J103 on one traced grid point; returns the output bytes
    (J102 budget/independence is judged across grid points by the
    caller)."""
    callbacks = sorted(
        {e.primitive.name for e in iter_eqns(closed.jaxpr)
         if e.primitive.name in CALLBACK_PRIMS}
    )
    if callbacks:
        emit(
            "J101",
            f"host callback primitive(s) {callbacks} inside the fused "
            f"program at grid {g!r} — every launch would round-trip to "
            "the host",
        )

    marker = int(g.nodes)
    flagged: set = set()
    for eqn in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            for shape in _shapes(eqn):
                if marker in shape and shape not in c.boundary_exempt_shapes:
                    key = (name, shape)
                    if key not in flagged:
                        flagged.add(key)
                        emit(
                            "J103",
                            f"collective '{name}' moves a node-axis value "
                            f"of shape {shape} (N={marker}) across the mesh "
                            f"at grid {g!r} — only the declared (shards, k) "
                            "candidate table may cross",
                        )
        elif name == "shard_map" and not c.node_axis_outputs_ok:
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                shape = tuple(int(d) for d in getattr(aval, "shape", ()))
                if marker in shape and shape not in c.boundary_exempt_shapes:
                    emit(
                        "J103",
                        f"shard_map output of shape {shape} (N={marker}) "
                        f"escapes the mesh boundary at grid {g!r}",
                    )
    return sum(_aval_bytes(a) for a in closed.out_avals)


def _check_donation(c: Any, emit: Callable[[str, str], None]) -> None:
    import jax

    g = c.compile_grid
    entry = c.build(g)
    args = c.operands(g)
    lowered = entry.lower(*args, **c.static_kwargs(g))
    declared = set(c.donated_args)
    pos_info = _positional_args_info(lowered, len(args))
    for i in range(len(args)):
        leaves = jax.tree_util.tree_leaves(pos_info[i])
        donated = [bool(getattr(leaf, "donated", False)) for leaf in leaves]
        if i in declared and not all(donated):
            emit(
                "J104",
                f"operand {i} is declared donated but lowered with "
                f"{donated.count(False)}/{len(donated)} leaves undonated — "
                "the donation was dropped before reaching XLA",
            )
        if i not in declared and any(donated):
            emit(
                "J104",
                f"operand {i} is donated but not declared in the contract "
                "— in-flight dispatches sharing that buffer would read "
                "freed memory",
            )
    if not declared:
        return
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        compiled = lowered.compile()
    compiled_donated = tuple(getattr(compiled, "donate_argnums", ()) or ())
    if not compiled_donated:
        emit(
            "J104",
            "declared donation set vanished between lower() and compile() "
            "— XLA sees no donated operands",
        )
    if c.expect_alias and "input_output_alias" not in compiled.as_text():
        emit(
            "J104",
            "contract requires input_output_alias but the compiled HLO has "
            "none — every donated buffer fell back to copy",
        )


def check_contract(c: Any, root: Optional[str] = None) -> List[Finding]:
    """Run J101–J105 for one :class:`.contracts.DeviceContract` row."""
    root = root or repo_root()
    line = _def_line(root, c.path, c.name)
    findings: List[Finding] = []

    def emit(rule: str, msg: str) -> None:
        f = Finding(rule=rule, path=c.path, line=line, symbol=c.name, message=msg)
        if f not in findings:
            findings.append(f)

    try:
        bytes_by_nodes: Dict[Tuple[Any, ...], Dict[int, int]] = {}
        for g in c.trace_grids:
            entry = c.build(g)
            closed = _trace(entry, c.operands(g), c.static_kwargs(g))
            out_bytes = _check_traced(c, g, closed, emit)
            if c.out_budget is None:
                continue
            budget = int(c.out_budget(g))
            if out_bytes > budget:
                emit(
                    "J102",
                    f"launch returns {out_bytes} B to the host at grid "
                    f"{g!r}, over the declared budget of {budget} B",
                )
            # Node-count independence: same grid modulo N must cost the
            # same bytes.
            key = (g.batch, g.placements, g.deltas, g.live, g.features)
            bytes_by_nodes.setdefault(key, {})[int(g.nodes)] = out_bytes
        for key, by_n in bytes_by_nodes.items():
            if len(set(by_n.values())) > 1:
                emit(
                    "J102",
                    "device→host bytes depend on the node count "
                    f"({ {n: b for n, b in sorted(by_n.items())} }) — an "
                    "O(N) value is crossing the tunnel",
                )

        if c.compile_grid is not None:
            _check_donation(c, emit)

        if c.sweep is not None and c.max_compiles is not None:
            entry = c.build(c.compile_grid)
            measured = int(c.sweep(entry, c))
            if measured > c.max_compiles:
                emit(
                    "J105",
                    f"configuration sweep cost {measured} compile-cache "
                    f"entries, over the declared max of {c.max_compiles} — "
                    "a runtime value leaked into the static key",
                )
    except Exception as exc:  # noqa: BLE001 — surface as a finding, loudly
        emit(
            "J100",
            f"contract harness failed: {type(exc).__name__}: {exc}",
        )
    return findings


def run(root: Optional[str] = None) -> List[Finding]:
    """All contracts; ``[]`` when no JAX backend is importable."""
    if not available():
        return []
    from . import contracts

    root = root or repo_root()
    findings: List[Finding] = []
    for c in contracts.table():
        findings += check_contract(c, root=root)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
