"""Latency timers + counters (reference: armon/go-metrics usage —
``nomad.worker.invoke_scheduler`` worker.go:245, ``nomad.plan.evaluate`` /
``nomad.plan.apply`` plan_apply.go:185,370, surfaced at ``/v1/metrics``).

A ``Timer`` keeps cheap streaming aggregates (count/sum/min/max) plus a
bounded reservoir for percentiles — enough for the p99-latency SLO the
BASELINE tracks, without a dependency.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List


class Timer:
    def __init__(self, reservoir: int = 1024):
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = 0.0
        self._samples: deque = deque(maxlen=reservoir)

    def observe(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.sum += seconds
            if seconds < self.min:
                self.min = seconds
            if seconds > self.max:
                self.max = seconds
            self._samples.append(seconds)

    @contextmanager
    def time(self):
        t0 = time.time()
        try:
            yield
        finally:
            self.observe(time.time() - t0)

    def _percentile(self, sorted_samples: List[float], q: float) -> float:
        if not sorted_samples:
            return 0.0
        idx = min(len(sorted_samples) - 1, int(q * len(sorted_samples)))
        return sorted_samples[idx]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            samples = sorted(self._samples)
            count, total = self.count, self.sum
            mn = self.min if self.count else 0.0
            mx = self.max
        return {
            "count": count,
            "mean_ms": round(total / count * 1000.0, 3) if count else 0.0,
            "min_ms": round(mn * 1000.0, 3),
            "max_ms": round(mx * 1000.0, 3),
            "p50_ms": round(self._percentile(samples, 0.50) * 1000.0, 3),
            "p95_ms": round(self._percentile(samples, 0.95) * 1000.0, 3),
            "p99_ms": round(self._percentile(samples, 0.99) * 1000.0, 3),
        }


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._timers: Dict[str, Timer] = {}
        self._counters: Dict[str, int] = {}

    def timer(self, name: str) -> Timer:
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = Timer()
                self._timers[name] = t
            return t

    def incr(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + by

    def snapshot(self) -> Dict:
        with self._lock:
            timers = dict(self._timers)
            counters = dict(self._counters)
        out: Dict = {}
        for name, value in counters.items():
            out[name] = value
        for name, t in timers.items():
            out[name] = t.snapshot()
        return out
