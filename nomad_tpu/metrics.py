"""Latency timers + counters (reference: armon/go-metrics usage —
``nomad.worker.invoke_scheduler`` worker.go:245, ``nomad.plan.evaluate`` /
``nomad.plan.apply`` plan_apply.go:185,370, surfaced at ``/v1/metrics``).

A ``Timer`` keeps cheap streaming aggregates (count/sum/min/max) plus a
bounded reservoir for percentiles — enough for the p99-latency SLO the
BASELINE tracks, without a dependency.

Counters take optional labels (``incr("nomad.kernel.launches",
path="solo")``), stored flat under ``name{k=v,...}`` keys so snapshots
stay JSON-plain. ``gauge_fn`` registers a callable polled at snapshot
time — how scattered object counters (matrix uploads, coalescer
dispatches) unify into the registry without double bookkeeping.
``to_prometheus`` renders any snapshot in the Prometheus text
exposition format for ``/v1/metrics?format=prometheus``.

``RollingWindow`` is the sliding-window primitive the SLO engine
(``nomad_tpu/obs/``) evaluates burn rates over: timestamped samples in a
bounded deque, with count/rate/percentile readable over any trailing
window.  ``Timer`` feeds one alongside its reservoir so windowed
percentiles (``windowed(60)["p99_ms"]``) are available without a second
observation on the hot path.
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Tuple


class RollingWindow:
    """Timestamped samples in a bounded deque, aggregated over any
    trailing window.  The write path is one deque append under a lock;
    reads walk backwards from the newest sample and stop at the window
    edge, so cost scales with the window's population, not the buffer.

    Two uses: value samples (``observe`` latencies → ``percentile``)
    and level samples of a monotonic counter (``observe`` the counter →
    ``rate_of_change`` = Δvalue/Δt over the window, the Prometheus
    ``rate()`` shape the SLO evaluator applies to throughput counters).
    """

    def __init__(self, maxlen: int = 4096):
        self._lock = threading.Lock()
        self._samples: deque = deque(maxlen=maxlen)  # (ts, value)

    def observe(self, value: float, ts: Optional[float] = None) -> None:
        with self._lock:
            self._samples.append((ts if ts is not None else time.time(), value))

    def _window(
        self, window_s: float, now: Optional[float] = None
    ) -> List[Tuple[float, float]]:
        cutoff = (now if now is not None else time.time()) - window_s
        with self._lock:
            out = []
            for ts, v in reversed(self._samples):
                if ts < cutoff:
                    break
                out.append((ts, v))
        out.reverse()
        return out

    def count(self, window_s: float, now: Optional[float] = None) -> int:
        return len(self._window(window_s, now))

    def rate(self, window_s: float, now: Optional[float] = None) -> float:
        """Samples per second over the trailing window."""
        if window_s <= 0:
            return 0.0
        return len(self._window(window_s, now)) / window_s

    def rate_of_change(
        self, window_s: float, now: Optional[float] = None
    ) -> float:
        """Δvalue/Δt across the window — ``rate()`` over level samples
        of a monotonic counter.  0.0 until two samples span the window."""
        win = self._window(window_s, now)
        if len(win) < 2:
            return 0.0
        (t0, v0), (t1, v1) = win[0], win[-1]
        if t1 <= t0:
            return 0.0
        return (v1 - v0) / (t1 - t0)

    def percentile(
        self, window_s: float, q: float, now: Optional[float] = None
    ) -> float:
        vals = sorted(v for _, v in self._window(window_s, now))
        if not vals:
            return 0.0
        rank = math.ceil(q * len(vals))
        return vals[min(len(vals) - 1, max(0, rank - 1))]

    def values(
        self, window_s: float, now: Optional[float] = None
    ) -> List[float]:
        return [v for _, v in self._window(window_s, now)]


class Timer:
    def __init__(self, reservoir: int = 1024):
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = 0.0
        self._samples: deque = deque(maxlen=reservoir)
        # Timestamped twin of the reservoir: windowed percentiles for
        # the SLO engine without a second observe on the hot path.
        self.window = RollingWindow(maxlen=reservoir)

    def observe(self, seconds: float) -> None:
        now = time.time()
        with self._lock:
            self.count += 1
            self.sum += seconds
            if seconds < self.min:
                self.min = seconds
            if seconds > self.max:
                self.max = seconds
            self._samples.append(seconds)
        self.window.observe(seconds, ts=now)

    @contextmanager
    def time(self):
        t0 = time.time()
        try:
            yield
        finally:
            self.observe(time.time() - t0)

    def _percentile(self, sorted_samples: List[float], q: float) -> float:
        # Ceil-rank (nearest-rank) definition: the smallest sample with
        # at least q of the distribution at or below it. The old
        # ``int(q * n)`` floor under-reported p99 for small reservoirs
        # (p99 of 100 samples indexed [99] only by the clamp; p99 of 10
        # picked the 10th-largest's neighbor at n=1000 boundaries).
        if not sorted_samples:
            return 0.0
        rank = math.ceil(q * len(sorted_samples))
        idx = min(len(sorted_samples) - 1, max(0, rank - 1))
        return sorted_samples[idx]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            samples = sorted(self._samples)
            count, total = self.count, self.sum
            mn = self.min if self.count else 0.0
            mx = self.max
        return {
            "count": count,
            "mean_ms": round(total / count * 1000.0, 3) if count else 0.0,
            "min_ms": round(mn * 1000.0, 3),
            "max_ms": round(mx * 1000.0, 3),
            "p50_ms": round(self._percentile(samples, 0.50) * 1000.0, 3),
            "p95_ms": round(self._percentile(samples, 0.95) * 1000.0, 3),
            "p99_ms": round(self._percentile(samples, 0.99) * 1000.0, 3),
        }

    def windowed(self, window_s: float) -> Dict[str, float]:
        """Percentiles over the trailing ``window_s`` seconds only —
        the sliding-window view the SLO burn-rate math evaluates (the
        plain reservoir never forgets a quiet period's samples)."""
        vals = sorted(self.window.values(window_s))
        n = len(vals)

        def pct(q: float) -> float:
            if not vals:
                return 0.0
            rank = math.ceil(q * n)
            return vals[min(n - 1, max(0, rank - 1))]

        return {
            "count": n,
            "mean_ms": round(sum(vals) / n * 1000.0, 3) if n else 0.0,
            "p50_ms": round(pct(0.50) * 1000.0, 3),
            "p95_ms": round(pct(0.95) * 1000.0, 3),
            "p99_ms": round(pct(0.99) * 1000.0, 3),
        }


def labeled(name: str, **labels) -> str:
    """Flatten ``name`` + labels into the canonical ``name{k=v,...}``
    snapshot key (labels sorted, so the key is stable)."""
    if not labels:
        return name
    inner = ",".join("%s=%s" % (k, labels[k]) for k in sorted(labels))
    return "%s{%s}" % (name, inner)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._timers: Dict[str, Timer] = {}
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}

    def timer(self, name: str) -> Timer:
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = Timer()
                self._timers[name] = t
            return t

    def incr(self, name: str, by: int = 1, **labels) -> None:
        key = labeled(name, **labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + by

    def gauge_fn(self, name: str, fn: Callable[[], float], **labels) -> None:
        """Register a pull gauge: ``fn`` is polled at snapshot time.
        Lets object-owned counters (matrix.scatter_syncs, coalescer
        dispatch tallies) surface in the registry without a second
        write on every hot-path increment."""
        with self._lock:
            self._gauges[labeled(name, **labels)] = fn

    def snapshot(self) -> Dict:
        with self._lock:
            timers = dict(self._timers)
            counters = dict(self._counters)
            gauges = dict(self._gauges)
        out: Dict = {}
        for name, value in counters.items():
            out[name] = value
        for name, fn in gauges.items():
            try:
                out[name] = fn()
            except Exception:
                # A gauge over a torn-down object must not break /v1/metrics.
                out[name] = 0
        for name, t in timers.items():
            out[name] = t.snapshot()
        return out


# ----------------------------------------------------------------------
# Prometheus text exposition (https://prometheus.io/docs/instrumenting/exposition_formats/)

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")
# DOTALL: label values may legally contain newlines — the exposition
# layer escapes them, but the key regex must not refuse to parse them.
_LABELED = re.compile(r"^(?P<name>[^{]+)\{(?P<labels>.*)\}$", re.DOTALL)


def _prom_name(name: str) -> str:
    name = _PROM_BAD.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _split_key(key: str) -> "tuple[str, Dict[str, str]]":
    """``name{k=v,...}`` snapshot key → (base name, label dict)."""
    m = _LABELED.match(key)
    if not m:
        return key, {}
    labels: Dict[str, str] = {}
    for part in m.group("labels").split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            labels[k.strip()] = v.strip()
    return m.group("name"), labels


def _escape_label_value(value: str) -> str:
    """Escape a label value per the exposition-format spec: backslash,
    double-quote, and line-feed must be escaped inside the quotes
    (backslash first, or the other escapes get double-escaped)."""
    return (
        value.replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _prom_series(base: str, labels: Dict[str, str]) -> str:
    name = _prom_name(base)
    if not labels:
        return name
    inner = ",".join(
        '%s="%s"' % (_prom_name(k), _escape_label_value(str(labels[k])))
        for k in sorted(labels)
    )
    return "%s{%s}" % (name, inner)


def _help_text(base: str, kind: str) -> str:
    """One-line HELP: the registry's dotted metric name is the most
    useful thing to echo — it is the key to grep for in the code."""
    if kind == "summary":
        return "latency summary of registry timer %s (milliseconds)" % base
    return "registry metric %s" % base


def to_prometheus(snapshot: Dict) -> str:
    """Render a flat snapshot (counters/gauges as numbers, timers as
    their summary dicts) in the Prometheus text exposition format.
    Timer summaries become ``<name>_ms{quantile=..}`` series plus
    ``<name>_count`` / ``<name>_sum_ms``.  Every metric family gets
    ``# HELP`` and ``# TYPE`` header lines, emitted once per family
    (labeled series of the same base share one header block)."""
    lines: List[str] = []
    headered: set = set()

    def _header(stem: str, base: str, kind: str) -> None:
        if stem in headered:
            return
        headered.add(stem)
        lines.append("# HELP %s %s" % (stem, _help_text(base, kind)))
        lines.append("# TYPE %s %s" % (stem, kind))

    for key in sorted(snapshot):
        value = snapshot[key]
        base, labels = _split_key(key)
        if isinstance(value, dict):
            stem = _prom_name(base) + "_ms"
            _header(stem, base, "summary")
            for q, field in (("0.5", "p50_ms"), ("0.95", "p95_ms"), ("0.99", "p99_ms")):
                ql = dict(labels)
                ql["quantile"] = q
                lines.append(
                    "%s %s" % (_prom_series(base + "_ms", ql), value.get(field, 0.0))
                )
            lines.append(
                "%s %s" % (_prom_series(base + "_count", labels), value.get("count", 0))
            )
            lines.append(
                "%s %s" % (
                    _prom_series(base + "_sum_ms", labels),
                    round(value.get("mean_ms", 0.0) * value.get("count", 0), 3),
                )
            )
        elif isinstance(value, bool):
            _header(_prom_name(base), base, "gauge")
            lines.append("%s %d" % (_prom_series(base, labels), int(value)))
        elif isinstance(value, (int, float)):
            _header(_prom_name(base), base, "gauge")
            lines.append("%s %s" % (_prom_series(base, labels), value))
        # non-numeric snapshot entries (strings) are skipped
    return "\n".join(lines) + "\n"

