"""Latency timers + counters (reference: armon/go-metrics usage —
``nomad.worker.invoke_scheduler`` worker.go:245, ``nomad.plan.evaluate`` /
``nomad.plan.apply`` plan_apply.go:185,370, surfaced at ``/v1/metrics``).

A ``Timer`` keeps cheap streaming aggregates (count/sum/min/max) plus a
bounded reservoir for percentiles — enough for the p99-latency SLO the
BASELINE tracks, without a dependency.

Counters take optional labels (``incr("nomad.kernel.launches",
path="solo")``), stored flat under ``name{k=v,...}`` keys so snapshots
stay JSON-plain. ``gauge_fn`` registers a callable polled at snapshot
time — how scattered object counters (matrix uploads, coalescer
dispatches) unify into the registry without double bookkeeping.
``to_prometheus`` renders any snapshot in the Prometheus text
exposition format for ``/v1/metrics?format=prometheus``.
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List


class Timer:
    def __init__(self, reservoir: int = 1024):
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = 0.0
        self._samples: deque = deque(maxlen=reservoir)

    def observe(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.sum += seconds
            if seconds < self.min:
                self.min = seconds
            if seconds > self.max:
                self.max = seconds
            self._samples.append(seconds)

    @contextmanager
    def time(self):
        t0 = time.time()
        try:
            yield
        finally:
            self.observe(time.time() - t0)

    def _percentile(self, sorted_samples: List[float], q: float) -> float:
        # Ceil-rank (nearest-rank) definition: the smallest sample with
        # at least q of the distribution at or below it. The old
        # ``int(q * n)`` floor under-reported p99 for small reservoirs
        # (p99 of 100 samples indexed [99] only by the clamp; p99 of 10
        # picked the 10th-largest's neighbor at n=1000 boundaries).
        if not sorted_samples:
            return 0.0
        rank = math.ceil(q * len(sorted_samples))
        idx = min(len(sorted_samples) - 1, max(0, rank - 1))
        return sorted_samples[idx]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            samples = sorted(self._samples)
            count, total = self.count, self.sum
            mn = self.min if self.count else 0.0
            mx = self.max
        return {
            "count": count,
            "mean_ms": round(total / count * 1000.0, 3) if count else 0.0,
            "min_ms": round(mn * 1000.0, 3),
            "max_ms": round(mx * 1000.0, 3),
            "p50_ms": round(self._percentile(samples, 0.50) * 1000.0, 3),
            "p95_ms": round(self._percentile(samples, 0.95) * 1000.0, 3),
            "p99_ms": round(self._percentile(samples, 0.99) * 1000.0, 3),
        }


def labeled(name: str, **labels) -> str:
    """Flatten ``name`` + labels into the canonical ``name{k=v,...}``
    snapshot key (labels sorted, so the key is stable)."""
    if not labels:
        return name
    inner = ",".join("%s=%s" % (k, labels[k]) for k in sorted(labels))
    return "%s{%s}" % (name, inner)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._timers: Dict[str, Timer] = {}
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}

    def timer(self, name: str) -> Timer:
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = Timer()
                self._timers[name] = t
            return t

    def incr(self, name: str, by: int = 1, **labels) -> None:
        key = labeled(name, **labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + by

    def gauge_fn(self, name: str, fn: Callable[[], float], **labels) -> None:
        """Register a pull gauge: ``fn`` is polled at snapshot time.
        Lets object-owned counters (matrix.scatter_syncs, coalescer
        dispatch tallies) surface in the registry without a second
        write on every hot-path increment."""
        with self._lock:
            self._gauges[labeled(name, **labels)] = fn

    def snapshot(self) -> Dict:
        with self._lock:
            timers = dict(self._timers)
            counters = dict(self._counters)
            gauges = dict(self._gauges)
        out: Dict = {}
        for name, value in counters.items():
            out[name] = value
        for name, fn in gauges.items():
            try:
                out[name] = fn()
            except Exception:
                # A gauge over a torn-down object must not break /v1/metrics.
                out[name] = 0
        for name, t in timers.items():
            out[name] = t.snapshot()
        return out


# ----------------------------------------------------------------------
# Prometheus text exposition (https://prometheus.io/docs/instrumenting/exposition_formats/)

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_LABELED = re.compile(r"^(?P<name>[^{]+)\{(?P<labels>.*)\}$")


def _prom_name(name: str) -> str:
    name = _PROM_BAD.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _split_key(key: str) -> "tuple[str, Dict[str, str]]":
    """``name{k=v,...}`` snapshot key → (base name, label dict)."""
    m = _LABELED.match(key)
    if not m:
        return key, {}
    labels: Dict[str, str] = {}
    for part in m.group("labels").split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            labels[k.strip()] = v.strip()
    return m.group("name"), labels


def _prom_series(base: str, labels: Dict[str, str]) -> str:
    name = _prom_name(base)
    if not labels:
        return name
    inner = ",".join(
        '%s="%s"' % (_prom_name(k), labels[k]) for k in sorted(labels)
    )
    return "%s{%s}" % (name, inner)


def to_prometheus(snapshot: Dict) -> str:
    """Render a flat snapshot (counters/gauges as numbers, timers as
    their summary dicts) in the Prometheus text exposition format.
    Timer summaries become ``<name>_ms{quantile=..}`` series plus
    ``<name>_count`` / ``<name>_sum_ms``."""
    lines: List[str] = []
    for key in sorted(snapshot):
        value = snapshot[key]
        base, labels = _split_key(key)
        if isinstance(value, dict):
            stem = _prom_name(base) + "_ms"
            lines.append("# TYPE %s summary" % stem)
            for q, field in (("0.5", "p50_ms"), ("0.95", "p95_ms"), ("0.99", "p99_ms")):
                ql = dict(labels)
                ql["quantile"] = q
                lines.append(
                    "%s %s" % (_prom_series(base + "_ms", ql), value.get(field, 0.0))
                )
            lines.append(
                "%s %s" % (_prom_series(base + "_count", labels), value.get("count", 0))
            )
            lines.append(
                "%s %s" % (
                    _prom_series(base + "_sum_ms", labels),
                    round(value.get("mean_ms", 0.0) * value.get("count", 0), 3),
                )
            )
        elif isinstance(value, bool):
            lines.append("%s %d" % (_prom_series(base, labels), int(value)))
        elif isinstance(value, (int, float)):
            lines.append("%s %s" % (_prom_series(base, labels), value))
        # non-numeric snapshot entries (strings) are skipped
    return "\n".join(lines) + "\n"

