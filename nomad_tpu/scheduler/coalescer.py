"""Dispatch coalescer — pipelined device dispatch for concurrent selects.

Round-3 diagnosis: every worker's ``select()`` held the global DEVICE_LOCK
across its own kernel dispatch, and fetched seven result buffers
individually — through the TPU tunnel each fetch costs a full sync
round-trip (bench.py ``rtt_floor_ms``, ~65ms observed), so four workers
serialized into ~1.5 evals/sec end-to-end while the batched kernel sat
unused outside the bench.

This module makes the batched kernel THE live path: workers enqueue
compiled placement requests and block on a future; a dispatch thread
drains the queue, stacks up to ``max_lanes`` requests, and issues ONE
``ops.kernels.place_batch`` dispatch whose packed result costs ONE fetch.

Round-6 diagnosis: the dispatch thread itself performed that fetch
(``np.asarray`` blocks for the tunnel RTT), so exactly one dispatch was
ever in flight and the live path could never reach the pipelined rate the
bench proves (depth 8 amortizes the RTT → 62K evals/s).  The loop is now
a producer/consumer pipeline:

* the **dispatch thread** only launches — it relies on JAX async dispatch
  and never calls ``np.asarray``.  Up to ``pipeline_depth`` launches
  (default 8, env ``NOMAD_TPU_PIPELINE_DEPTH``) overlap; the bounded
  ticket queue provides backpressure.
* a **resolver thread** performs the blocking device→host fetch for each
  in-flight ticket and completes the ``_Pending`` futures in launch order.

Because overlapped dispatches read a matrix that plans committed during
their flight may mutate, each ticket records ``matrix.version`` at launch;
a version mismatch at resolve time counts into ``stale_dispatches``.
Correctness does not depend on the count: stale-read placements are
re-checked by the serialized plan applier's authoritative re-verify
(server/plan_apply.py ``_evaluate``) exactly as optimistic-worker plans
already are.

Shape discipline (SURVEY.md §7 hard-part e — p99 means no recompiles):
every dispatch uses the SAME static shapes — ``max_lanes`` lanes (short
batches padded by memset of the preallocated staging buffers) and a
``PLACEMENT_CHUNK``-long scan (callers take the first rows they asked
for) — so exactly one executable serves every batch size. Wasted lanes
cost ~µs of MXU time; a recompile costs tens of seconds.

The reference's analog: many schedulers walk nodes concurrently and the
plan applier serializes commits (worker.go:49-53, plan_apply.go:49-69).
The optimistic-concurrency contract is unchanged — coalesced selects may
pick conflicting nodes; the applier's re-verify catches it.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from .. import trace
from ..obs.breaker import (
    STALL_SLOW,
    STALL_WEDGED,
    DeviceBreaker,
    DeviceWedgedError,
    watchdog_fetch,
)
from ..ops import kernels
from ..ops.encode import RequestSlab, SchedRequest
from ..retry import env_int
from ..state.matrix import DEVICE_LOCK

log = logging.getLogger(__name__)

# Sparse plan-delta capacity per request; selects with more touched rows
# fall back to the solo dispatch path.
MAX_DELTA_ROWS = 32

_DEPTH_ENV = "NOMAD_TPU_PIPELINE_DEPTH"
_MEGABATCH_ENV = "NOMAD_TPU_MEGABATCH"
_SHARDED_MEGABATCH_ENV = "NOMAD_TPU_SHARDED_MEGABATCH"


def default_pipeline_depth() -> int:
    """Overlapping dispatches kept in flight (env-tunable, default 8 — the
    depth bench.py's pipelined phase showed amortizing the tunnel RTT)."""
    return max(1, env_int(_DEPTH_ENV, 8))


def megabatch_enabled() -> bool:
    """The fused megakernel path (ops.kernels.fused_place_batch): explicit
    lane masks, occupancy-bucketed compiles, and the device-resident
    AllocsFit re-verify column. Default ON; ``NOMAD_TPU_MEGABATCH=0``
    falls back to the staged place_batch path."""
    return os.environ.get(_MEGABATCH_ENV, "1").lower() not in (
        "0", "off", "false",
    )


def sharded_megabatch_enabled() -> bool:
    """The node-sharded fused megakernel (parallel/sharding.py
    sharded_fused_place_batch): hierarchical top-k ranking plus the
    on-device cross-lane AllocsFit verify, with the node axis split over
    the mesh.  Default ON when a mesh is configured;
    ``NOMAD_TPU_SHARDED_MEGABATCH=0`` keeps multi-chip dispatches on the
    staged sharded_place_batch path (no verify column)."""
    return os.environ.get(_SHARDED_MEGABATCH_ENV, "1").lower() not in (
        "0", "off", "false",
    )


@dataclass
class PlaceOutcome:
    """Unpacked per-request result (numpy, host-side)."""

    rows: np.ndarray  # (P,) i32
    scores: np.ndarray  # (P,) f32
    binpack: np.ndarray  # (P,) f32
    preempted: np.ndarray  # (P,) bool
    nodes_evaluated: np.ndarray  # (P,) i32
    nodes_filtered: np.ndarray  # (P,) i32
    nodes_exhausted: np.ndarray  # (P,) i32
    # Fused-path extras: device-resident AllocsFit re-verify verdicts
    # ((P,) bool — True = placement survives the sequential cross-lane
    # re-check at `matrix_version`; None on the staged path) and the matrix
    # version the dispatch was scored against. At an unchanged version a
    # False verdict is a guaranteed plan-applier rejection; the applier
    # against live state stays authoritative either way.
    fit_verified: Optional[np.ndarray] = None
    matrix_version: int = -1


@dataclass
class _DeviceOp:
    fn: "callable"
    done: threading.Event = field(default_factory=threading.Event)
    result: object = None
    error: Optional[BaseException] = None


@dataclass
class _Pending:
    request: SchedRequest
    delta_rows: np.ndarray  # (MAX_DELTA_ROWS,) i32, -1 padded
    delta_vals: np.ndarray  # (MAX_DELTA_ROWS, 3) f32
    tg_count: np.ndarray  # (N,) i32
    spread_counts: np.ndarray  # (S, V) f32
    penalty: np.ndarray  # (N,) bool
    class_elig: np.ndarray  # (pad,) bool
    host_mask: np.ndarray  # (N,) bool
    # Placements the caller will actually consume (0 = all scan_length).
    # The jax kernel ignores it (static shapes); the fake-device twin stops
    # its scan after this many live steps.
    n_live: int = 0
    enqueued_at: float = 0.0
    done: threading.Event = field(default_factory=threading.Event)
    outcome: Optional[PlaceOutcome] = None
    error: Optional[BaseException] = None
    # Trace context captured on the submitting worker's thread (place());
    # the dispatch thread stitches coalescer.queue_wait onto it and the
    # resolver thread stitches coalescer.device — the launch→resolver hop.
    trace_ctx: Optional[trace.SpanContext] = None


@dataclass
class _Ticket:
    """One in-flight dispatch: the un-fetched packed result, its lanes, and
    the matrix version its inputs were synced at."""

    packed: object
    entries: List[_Pending]
    matrix_version: int
    launched_at: float = 0.0
    # True when this launch is the half-open breaker's single probe; its
    # fetch verdict decides whether the device path is re-admitted.
    canary: bool = False


class DeviceCoalescer:
    """The single dispatch port for the shared device matrix."""

    def __init__(
        self,
        matrix,
        max_lanes: int = 64,
        scan_length: Optional[int] = None,
        linger_s: float = 0.002,
        pipeline_depth: Optional[int] = None,
        n_device_shards: Optional[int] = None,
        metrics=None,
    ):
        from .stack import PLACEMENT_CHUNK

        self.matrix = matrix
        self.max_lanes = max_lanes
        self.scan_length = scan_length or PLACEMENT_CHUNK
        self.linger_s = linger_s
        self.pipeline_depth = (
            pipeline_depth if pipeline_depth else default_pipeline_depth()
        )
        # Multi-chip: when >1, dispatches go through the SPMD twin of
        # place_batch (parallel/sharding.py sharded_place_batch) over a
        # ('batch', 'node') mesh — the live server path the dryrun
        # certifies.  None = auto: all visible devices on real
        # accelerators, single-device on CPU (the virtual 8-CPU rig is a
        # test harness, not a deployment; tests opt in explicitly).
        self.n_device_shards = n_device_shards
        self.metrics = metrics  # optional MetricsRegistry (the server's)
        self._mesh = None
        self._sharded_fn = None
        self._sharded_fused_fn = None
        # Chaos shard.partition bookkeeping: shard -> node ids darkened by
        # the seam (heal_shard_partitions re-lights them).
        self._dark_shards: Dict[int, List[str]] = {}
        self._queue: List[_Pending] = []
        # Arbitrary device closures (system feasibility, bulk plan verify,
        # oversized-delta solo selects) executed on the dispatch thread so
        # the live server has exactly ONE device-LAUNCHING thread — the
        # single-chip tunnel client wedges under concurrent host threads
        # (state/matrix.py DEVICE_LOCK note).  The resolver thread only
        # fetches already-launched results, the same overlap bench.py's
        # pipelined phase exercises through the tunnel.
        self._ops: List["_DeviceOp"] = []
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._resolver: Optional[threading.Thread] = None
        self._tickets: Optional["queue.Queue"] = None
        self._depth_sem: Optional[threading.Semaphore] = None
        # Preallocated (max_lanes, N) host staging buffers the lanes write
        # into — per-dispatch np.stack allocations replaced by row writes,
        # lane padding by memset (see _staging).
        self._stage: Optional[Dict[str, np.ndarray]] = None
        # Preallocated (max_lanes, …) request operand slab: per-lane
        # SchedRequest pytrees write rows in place instead of the old
        # per-dispatch tree_map(np.stack) allocation storm.
        self._req_slab = RequestSlab(max_lanes)
        # Gauges/counters (ints under the GIL; exact enough for telemetry).
        self.dispatches = 0
        self.coalesced_requests = 0
        self.stale_dispatches = 0
        self.inflight = 0
        # Device cost attribution (surfaced as nomad.kernel.* gauges by
        # the server): solo escape-hatch launches and host→device operand
        # traffic staged per batched dispatch.
        self.solo_ops = 0
        self.operand_bytes_total = 0
        # Fused-megakernel accounting: launches and live lanes through the
        # fused path (launches-per-eval = fused_dispatches / fused_lanes),
        # verify-column conflicts (placements an earlier lane's plan will
        # make the applier reject), and the occupancy-features ratchet —
        # a monotone widening union, so each Features variant compiles at
        # most once per process instead of flapping per batch.
        self.megabatch = megabatch_enabled()
        if self.megabatch:
            kernels.pallas_requested()  # warn once if the reserved flag is set
        self.sharded_megabatch = sharded_megabatch_enabled()
        self.fused_dispatches = 0
        self.fused_lanes = 0
        self.verify_conflicts = 0
        self.feature_recompiles = 0
        self._features = None
        # Device→host result traffic for fused/sharded dispatches (the
        # packed (B, P, 8) fetch — O(lanes·placements), NEVER node-axis
        # shaped; exported as nomad.topk.host_bytes_total).  The parity
        # test pins it to the winner-row budget to prove no (N,)-shaped
        # array rides the fetch.
        self.topk_host_bytes_total = 0
        # Device fault domain (obs/breaker.py): the resolver classifies
        # every fetch ok/slow/wedged under the watchdog deadline; the
        # breaker gates _dispatch between the device path and the staged
        # host twin.  Wedged tickets count here (their futures raise
        # DeviceWedgedError); shard evacuations re-home the matrix onto
        # the surviving shards.
        self.breaker = DeviceBreaker(metrics=metrics)
        self.wedged_dispatches = 0
        self.shard_evacuations = 0
        # Shard count before the first evacuation (heal restores it);
        # None = no evacuation active.
        self._pre_evac_shards: Optional[int] = None
        self._pre_evac_device_shards: Optional[int] = None
        # TSan-lite (lint/tsan.py): lockset checking on the pending queue
        # and device-op list when a test enabled the sanitizer.
        from ..lint.tsan import maybe_instrument

        maybe_instrument("coalescer", self)

    # ------------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return  # leadership can cycle; one dispatch thread only
        self._stop.clear()
        # A fresh leadership term probes the device fresh — a breaker
        # left open by the previous term would silently pin the new one
        # to the degraded path.
        self.breaker.reset()
        # The pipeline bound: a launch consumes a permit, the resolver
        # returns it after the fetch, so exactly pipeline_depth dispatches
        # overlap (depth 1 = the old serial behavior).  The ticket queue
        # itself never blocks — its occupancy is bounded by the permits.
        self._depth_sem = threading.BoundedSemaphore(self.pipeline_depth)
        self._tickets = queue.Queue()
        self._resolver = threading.Thread(
            target=self._resolve_loop, name="resolver-coalescer", daemon=True
        )
        self._resolver.start()
        self._thread = threading.Thread(
            target=self._run, name="device-coalescer", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread:
            self._thread.join(timeout=10)

    def inflight_depth(self) -> int:
        """Dispatches launched but not yet resolved (pipeline occupancy)."""
        return self.inflight

    # ------------------------------------------------------------------

    def place(
        self,
        request: SchedRequest,
        delta_rows: np.ndarray,
        delta_vals: np.ndarray,
        tg_count: np.ndarray,
        spread_counts: np.ndarray,
        penalty: np.ndarray,
        class_elig: np.ndarray,
        host_mask: np.ndarray,
        timeout: float = 600.0,  # must cover a cold TPU jit compile
        n_live: int = 0,
    ) -> PlaceOutcome:
        """Submit one placement request; blocks until its batch lands.
        The scan always runs ``scan_length`` steps — take ``rows[:k]``."""
        p = _Pending(
            request=request,
            delta_rows=delta_rows,
            delta_vals=delta_vals,
            tg_count=tg_count,
            spread_counts=spread_counts,
            penalty=penalty,
            class_elig=class_elig,
            host_mask=host_mask,
            n_live=n_live,
            enqueued_at=time.time(),
            trace_ctx=trace.current(),
        )
        with self._cond:
            if self._stop.is_set():
                raise RuntimeError("coalescer stopped")
            self._queue.append(p)
            self._cond.notify()
        if not p.done.wait(timeout=timeout):
            raise TimeoutError("coalescer dispatch timed out")
        if p.error is not None:
            raise p.error
        assert p.outcome is not None
        return p.outcome

    def run_device_op(self, fn, timeout: float = 600.0):
        """Execute ``fn()`` on the dispatch thread and return its result.

        The escape hatch for device work that doesn't fit the batched
        placement shape (system feasibility sweeps, bulk plan verification,
        oversized-delta selects): they still run on the one device thread
        instead of racing it on the tunnel."""
        op = _DeviceOp(fn=fn)
        self.solo_ops += 1
        with self._cond:
            if self._stop.is_set():
                raise RuntimeError("coalescer stopped")
            self._ops.append(op)
            self._cond.notify()
        if not op.done.wait(timeout=timeout):
            raise TimeoutError("device op timed out")
        if op.error is not None:
            raise op.error
        return op.result

    # ------------------------------------------------------------------

    def _run(self) -> None:
        """Dispatch (producer) loop: build batches, launch, hand tickets to
        the resolver.  Never blocks on a device→host fetch."""
        from ..chaos import inject

        while True:
            self._drain_ops()
            batch = self._next_batch()
            if batch is None and self._stop.is_set():
                self._shutdown_pipeline()
                return
            if not batch:
                continue
            inject("coalescer.dispatch", lanes=len(batch))
            trace.event("seam.coalescer.dispatch", lanes=len(batch))
            # Wait for a pipeline slot BEFORE launching: the permit bounds
            # overlapping latency windows (and how stale an in-flight read
            # can get).  Requests arriving during the wait coalesce into
            # the NEXT batch — the batch itself is already sealed.
            self._depth_sem.acquire()
            waited = time.time()
            if self.metrics is not None:
                qw = self.metrics.timer("nomad.coalescer.queue_wait")
                for p in batch:
                    qw.observe(max(0.0, waited - p.enqueued_at))
            # Stitch each lane's enqueue→launch wait onto its eval trace
            # (carried here from the worker thread on _Pending.trace_ctx).
            for p in batch:
                if p.trace_ctx is not None:
                    trace.record_span(
                        "coalescer.queue_wait",
                        p.enqueued_at,
                        waited,
                        ctx=p.trace_ctx,
                        metrics=self.metrics,
                    )
            # Device fault domain: while the breaker is open, dispatches
            # degrade to the staged host twin (placements keep flowing at
            # reduced throughput); half-open admits exactly one canary
            # launch whose fetch verdict decides re-admission.
            allowed, canary = self.breaker.allow_device_dispatch()
            if not allowed:
                self.breaker.note_degraded()
            try:
                with trace.span("coalescer.launch", lanes=len(batch),
                                metrics=self.metrics):
                    packed, version = self._dispatch(
                        batch, degraded=not allowed
                    )
            except BaseException as exc:  # noqa: BLE001
                if canary:
                    # The probe died before producing a fetch verdict —
                    # release the slot so half-open can retry.
                    self.breaker.cancel_canary()
                self._depth_sem.release()
                for p in batch:
                    p.error = exc
                    p.done.set()
                continue
            self.dispatches += 1
            self.coalesced_requests += len(batch)
            self.inflight += 1
            self._tickets.put(
                _Ticket(
                    packed, batch, version, launched_at=waited,
                    canary=canary,
                )
            )

    def _shutdown_pipeline(self) -> None:
        """Stop path: fail queued work, let the resolver drain in-flight
        tickets (their callers are still blocked on real futures), then
        join it."""
        with self._cond:
            leftover_ops, self._ops = self._ops, []
            leftover_q, self._queue = self._queue, []
        err = RuntimeError("coalescer stopped")
        for op in leftover_ops:
            op.error = err
            op.done.set()
        for p in leftover_q:
            p.error = err
            p.done.set()
        self._tickets.put(None)  # sentinel after every real ticket
        self._resolver.join(timeout=10)
        if self._resolver.is_alive():
            # The resolver missed its join window (a fetch past every
            # watchdog bound, or the watchdog disabled): fail whatever is
            # still queued from here so no caller blocks past shutdown.
            self._fail_queued_tickets(err)

    def _fail_queued_tickets(self, err: BaseException) -> None:
        """Drain the ticket queue and fail every undone future — the
        no-caller-blocks-past-shutdown guarantee.  Pipeline accounting
        mirrors _resolve_loop's finally block so the dispatch loop never
        waits on a permit that will not come back."""
        while True:
            try:
                ticket = self._tickets.get_nowait()
            except queue.Empty:
                return
            if ticket is None:
                continue
            if ticket.canary:
                self.breaker.cancel_canary()
            for p in ticket.entries:
                if not p.done.is_set():
                    p.error = err
                    p.done.set()
            self.inflight -= 1
            try:
                self._depth_sem.release()
            except ValueError:
                pass  # bounded; resolver may have already released it
            with self._cond:
                self._cond.notify_all()

    def _resolve_loop(self) -> None:
        """Resolver (consumer) loop: the ONLY place the live path blocks on
        a device→host fetch.  Tickets complete in launch order."""
        try:
            while True:
                ticket = self._tickets.get()
                if ticket is None:
                    return
                try:
                    self._resolve(ticket)
                except BaseException as exc:  # noqa: BLE001
                    # _resolve guards the fetch itself; this catches
                    # anything after it (outcome unpack, metrics).  Fail
                    # the lanes and keep the resolver alive — pipeline
                    # accounting below must run no matter what, or the
                    # dispatch loop deadlocks on a permit that will never
                    # come back.
                    for p in ticket.entries:
                        if not p.done.is_set():
                            p.error = exc
                            p.done.set()
                finally:
                    self.inflight -= 1
                    self._depth_sem.release()
                    with self._cond:
                        # Wake an idle dispatch loop waiting to quiesce.
                        self._cond.notify_all()
        finally:
            # Resolver exit — clean (sentinel) or death: every in-flight
            # future must still complete, or its caller blocks forever.
            self._fail_queued_tickets(RuntimeError("coalescer stopped"))

    def _drain_ops(self) -> None:
        while True:
            with self._cond:
                if not self._ops:
                    return
                op = self._ops.pop(0)
            try:
                op.result = op.fn()
            except BaseException as exc:  # noqa: BLE001
                op.error = exc
            op.done.set()

    def _next_batch(self) -> Optional[List[_Pending]]:
        with self._cond:
            if not self._queue:
                # Untimed wait: every transition the predicate watches
                # notifies _cond — place()/run_device_op() on enqueue,
                # stop() on shutdown, and the resolver's try/finally
                # guarantees its wake-up even when _resolve raises, so
                # there is no lost-notify hole left to poll around
                # (lint rule L004).
                self._cond.wait_for(
                    lambda: bool(self._queue)
                    or bool(self._ops)
                    or self._stop.is_set(),
                )
            if not self._queue:
                return None
        # Linger briefly so concurrent workers land in one dispatch.  The
        # fake-device backend answers synchronously, so lingering would only
        # add serial latency on the one dispatch thread — requests still
        # coalesce while a dispatch is in progress.
        from ..ops import fake_device

        if self.linger_s and not fake_device.enabled():
            self._stop.wait(self.linger_s)
        with self._cond:
            batch = self._queue[: self.max_lanes]
            del self._queue[: len(batch)]
        return batch or None

    # ------------------------------------------------------------------

    def _resolve_sharding(self) -> int:
        """Decide (once) how many devices dispatches span."""
        if self.n_device_shards is None:
            import jax

            devs = jax.devices()
            self.n_device_shards = (
                len(devs) if devs[0].platform != "cpu" and len(devs) > 1
                else 1
            )
        if self.n_device_shards > 1 and self._sharded_fn is None:
            from ..parallel.sharding import (
                make_mesh,
                node_shard_count,
                sharded_fused_place_batch,
                sharded_place_batch,
            )

            self._mesh = make_mesh(self.n_device_shards)
            self._sharded_fn = sharded_place_batch(
                self._mesh, self.scan_length
            )
            node_shards = node_shard_count(self._mesh)
            if self.megabatch and self.sharded_megabatch:
                self._sharded_fused_fn = sharded_fused_place_batch(
                    self._mesh, self.scan_length
                )
            # Home rows to their mesh shard so claims balance across the
            # node axis and growth never migrates a row between shards.
            # (Skipped while an evacuation is active: the survivor layout
            # relayout_shards built IS the homing — re-partitioning here
            # would undo it.)
            if (
                node_shards > 1
                and self._pre_evac_shards is None
                and self.matrix.capacity % node_shards == 0
            ):
                self.matrix.set_shard_count(node_shards)
                if self.metrics is not None:
                    # The server registered shard_rows for the init-time
                    # partition; re-register for the homed mesh width.
                    for s in range(node_shards):
                        self.metrics.gauge_fn(
                            "nomad.matrix.shard_rows",
                            lambda s=s: (
                                self.matrix.shard_row_counts()[s]
                                if s < self.matrix.shard_count else 0
                            ),
                            shard=s,
                        )
            log.info(
                "coalescer: multi-chip dispatch over mesh %s (%s)",
                dict(zip(self._mesh.axis_names, self._mesh.devices.shape)),
                "fused" if self._sharded_fused_fn is not None else "staged",
            )
        return self.n_device_shards

    def _darken_shard(self) -> None:
        """Chaos ``shard.partition`` effect (kind 'dark'): mark every node
        homed on the most-populated shard ineligible — the authoritative-
        state analog of losing a whole mesh shard.  Deterministic target
        (highest claimed-row count, lowest index on ties) so seeded
        schedules replay identically."""
        counts = self.matrix.shard_row_counts()
        target = max(range(len(counts)), key=lambda s: (counts[s], -s))
        ids = self.matrix.shard_nodes(target)
        for nid in ids:
            self.matrix.set_eligibility(nid, False)
        self._dark_shards.setdefault(target, []).extend(ids)
        trace.event(
            "seam.shard.partition.dark", shard=target, nodes=len(ids)
        )

    def heal_shard_partitions(self) -> List[int]:
        """Re-light every shard darkened by the partition seam; returns the
        healed shard indices (chaos scenarios assert invariants after)."""
        healed = sorted(self._dark_shards)
        for _shard, ids in sorted(self._dark_shards.items()):
            for nid in ids:
                self.matrix.set_eligibility(nid, True)
        self._dark_shards.clear()
        return healed

    def _lose_shard(self) -> None:
        """Chaos ``shard.loss`` effect (kind 'lost'): evacuate the
        most-populated home shard — the same deterministic target rule as
        _darken_shard (highest claimed-row count, lowest index on ties)
        so seeded schedules replay identically."""
        if int(getattr(self.matrix, "shard_count", 1)) <= 1:
            return  # dense layout — nothing to evacuate
        counts = self.matrix.shard_row_counts()
        target = max(range(len(counts)), key=lambda s: (counts[s], -s))
        self.evacuate_shard(target)

    def evacuate_shard(self, shard: int) -> int:
        """Evacuate a lost shard: the node matrix re-lays-out across the
        survivors (state/matrix.py ``relayout_shards`` replays the claim
        policy over nodes in row order, so the result is bit-identical to
        a from-scratch layout on the surviving shards — the PARITY.md
        evacuation proof).  In-flight tickets that launched against the
        old layout invalidate through the matrix version bump + remap
        window, exactly like growth relocations; the compiled sharded
        entry points drop so the next dispatch re-resolves against the
        survivor mesh.  Returns the surviving shard count."""
        with DEVICE_LOCK:
            before = int(self.matrix.shard_count)
            if before <= 1:
                raise ValueError("evacuation requires shard_count > 1")
            if self._pre_evac_shards is None:
                self._pre_evac_shards = before
                self._pre_evac_device_shards = self.n_device_shards
            self.matrix.evacuate_shard(shard)
            survivors = int(self.matrix.shard_count)
            if self.n_device_shards is not None and self.n_device_shards > 1:
                self.n_device_shards -= 1
            self._mesh = None
            self._sharded_fn = None
            self._sharded_fused_fn = None
        self.shard_evacuations += 1
        self.breaker.note_evacuation()
        trace.event(
            "seam.shard.loss.evacuated", shard=shard, survivors=survivors
        )
        if self.metrics is not None:
            self.metrics.incr("nomad.coalescer.shard_evacuations")
        return survivors

    def heal_shard_evacuations(self) -> Optional[int]:
        """Re-admit evacuated shards (chaos ``heal``): a full re-layout
        back to the pre-evacuation shard count, through the same remap
        mechanism as the evacuation itself.  Returns the restored shard
        count, or None when no evacuation is active."""
        restored = self._pre_evac_shards
        if restored is None:
            return None
        with DEVICE_LOCK:
            self.matrix.relayout_shards(restored)
            self._pre_evac_shards = None
            self.n_device_shards = self._pre_evac_device_shards
            self._mesh = None
            self._sharded_fn = None
            self._sharded_fused_fn = None
        trace.event("seam.shard.loss.healed", restored=restored)
        return restored

    def _ratchet_features(self, k: int):
        """The occupancy-features ratchet: a monotone widening union, so
        each Features variant compiles at most once per process instead of
        flapping per batch — a narrow batch after a wide one reuses the
        wide executable."""
        feats = kernels.features_of(self._req_slab.live_view(k))
        widened = (
            feats if self._features is None else self._features.widen(feats)
        )
        if widened != self._features:
            self.feature_recompiles += 1
            self._features = widened
        return self._features

    def _staging(self, n: int, cw: int, sc_shape) -> Dict[str, np.ndarray]:
        """Preallocated (max_lanes, …) host staging buffers.  Lanes write
        rows in place; unused lanes are padded by memset — no per-dispatch
        np.stack allocations, no filler _Pending objects.  Rebuilt only
        when the matrix grows or the class-pad bucket shifts."""
        st = self._stage
        if (
            st is None
            or st["host_mask"].shape[1] != n
            or st["class_elig"].shape[1] != cw
            or st["spread_counts"].shape[1:] != sc_shape
        ):
            lanes = self.max_lanes
            st = self._stage = {
                "host_mask": np.zeros((lanes, n), bool),
                "tg_count": np.zeros((lanes, n), np.int32),
                "penalty": np.zeros((lanes, n), bool),
                "class_elig": np.ones((lanes, cw), bool),
                "spread_counts": np.zeros((lanes,) + sc_shape, np.float32),
                "delta_rows": np.full((lanes, MAX_DELTA_ROWS), -1, np.int32),
                "delta_vals": np.zeros(
                    (lanes, MAX_DELTA_ROWS, 3), np.float32
                ),
                "lane_mask": np.zeros((lanes,), bool),
            }
        return st

    def _dispatch(self, batch: List[_Pending], degraded: bool = False):
        """Launch one batched place_batch; returns (unfetched packed result,
        matrix version at launch).  ``degraded`` (breaker open) forces the
        staged host twin — the fake-device numpy path answers from the
        host mirror, so placements keep flowing while the device is out."""
        from ..chaos import inject
        from ..ops import fake_device

        fake = fake_device.enabled() or degraded
        if fake:
            n_shards = 1
        else:
            n_shards = self._resolve_sharding()

        sharded = None
        if n_shards > 1:
            # Multi-chip: the matrix stays RESIDENT across the mesh —
            # sync_sharded scatters only dirty rows to the owning shard
            # instead of re-laying the full matrix per dispatch.
            with DEVICE_LOCK:
                sharded = self.matrix.sync_sharded(self._mesh)
                version = self.matrix.version
            n = int(self.matrix.capacity)
            arrays = None
        elif degraded and not fake_device.enabled():
            # Breaker open on a real backend: feed the host twin from the
            # host mirror directly — sync() would build a device snapshot
            # through the very tunnel the breaker just declared wedged.
            arrays = self.matrix.sync_host()
            version = self.matrix.version
            n = int(arrays.used.shape[0])
        else:
            with DEVICE_LOCK:
                arrays = self.matrix.sync()
                version = self.matrix.version
            n = int(arrays.used.shape[0])

        # Chaos seam: partition an entire matrix shard MID-dispatch — the
        # snapshot above was synced pre-darkening, so this launch still
        # places onto the dark shard and the applier's authoritative
        # re-verify (eligibility-gated) must reject every one of them.
        fault = inject(
            "shard.partition",
            shards=int(getattr(self.matrix, "shard_count", 1)),
            lanes=len(batch),
        )
        trace.event("seam.shard.partition", lanes=len(batch))
        if fault is not None and fault.kind == "dark":
            self._darken_shard()

        # Chaos seam: lose an entire matrix shard (mesh-slice death, not
        # just ineligibility) — kind 'lost' evacuates it: the matrix
        # re-lays-out across the survivors, in-flight tickets invalidate
        # through the version/remap stale-dispatch mechanism, and this
        # launch proceeds against the post-evacuation layout.
        loss = inject(
            "shard.loss",
            shards=int(getattr(self.matrix, "shard_count", 1)),
            lanes=len(batch),
        )
        trace.event("seam.shard.loss", lanes=len(batch))
        if loss is not None and loss.kind == "lost":
            self._lose_shard()
            # The snapshot above was synced pre-evacuation; re-sync so
            # the launch scores the re-homed layout, not freed rows.
            if degraded and not fake_device.enabled():
                arrays = self.matrix.sync_host()
                version = self.matrix.version
                n = int(arrays.used.shape[0])
            elif fake:
                with DEVICE_LOCK:
                    arrays = self.matrix.sync()
                    version = self.matrix.version
                n = int(arrays.used.shape[0])
            else:
                # Evacuation dropped the compiled sharded entry points;
                # re-resolve so this launch runs on the survivor mesh
                # (or the single-device path when one shard remains).
                n_shards = self._resolve_sharding()
                if n_shards > 1:
                    with DEVICE_LOCK:
                        sharded = self.matrix.sync_sharded(self._mesh)
                        version = self.matrix.version
                    n = int(self.matrix.capacity)
                else:
                    with DEVICE_LOCK:
                        arrays = self.matrix.sync()
                        version = self.matrix.version
                    n = int(arrays.used.shape[0])

        if fake:
            # Fake-device backend: numpy twins answer synchronously from
            # the host snapshot.  No lane padding (shapes need not be
            # static for numpy) and no stacking — the twin takes lists.
            # Requests built just before a matrix growth carry narrower
            # arrays; pad each by its OWN width (new rows masked off —
            # they were not host-checked).
            for p in batch:
                if p.host_mask.shape[0] < n:
                    p.host_mask = np.concatenate([
                        p.host_mask,
                        np.zeros((n - p.host_mask.shape[0],), bool),
                    ])
                if p.tg_count.shape[0] < n:
                    p.tg_count = np.concatenate([
                        p.tg_count,
                        np.zeros((n - p.tg_count.shape[0],), np.int32),
                    ])
                if p.penalty.shape[0] < n:
                    p.penalty = np.concatenate([
                        p.penalty,
                        np.zeros((n - p.penalty.shape[0],), bool),
                    ])
            lane_lists = (
                [p.delta_rows for p in batch],
                [p.delta_vals for p in batch],
                [p.tg_count for p in batch],
                [p.spread_counts for p in batch],
                [p.penalty for p in batch],
                [p.request for p in batch],
                [p.class_elig for p in batch],
                [p.host_mask for p in batch],
            )
            if self.megabatch:
                packed = fake_device.fused_place_batch(
                    arrays,
                    arrays.used,
                    *lane_lists,
                    lane_mask=np.ones((len(batch),), bool),
                    n_placements=self.scan_length,
                    live_counts=[
                        p.n_live or self.scan_length for p in batch
                    ],
                )
                self.fused_dispatches += 1
                self.fused_lanes += len(batch)
            else:
                packed = fake_device.place_batch(
                    arrays,
                    arrays.used,
                    *lane_lists,
                    n_placements=self.scan_length,
                    live_counts=[
                        p.n_live or self.scan_length for p in batch
                    ],
                )
            self.operand_bytes_total += sum(
                p.host_mask.nbytes + p.tg_count.nbytes + p.penalty.nbytes
                + p.class_elig.nbytes + p.spread_counts.nbytes
                + p.delta_rows.nbytes + p.delta_vals.nbytes
                for p in batch
            )
            lat = fake_device.latency_s()
            if lat > 0:
                # Synthetic tunnel RTT: the fetch pays it, not the launch,
                # so overlapping dispatches overlap their latency windows.
                packed = fake_device.DeferredResult(packed, lat)
            return packed, version

        k = len(batch)
        cw = max(p.class_elig.shape[0] for p in batch)
        sc_shape = batch[0].spread_counts.shape
        st = self._staging(n, cw, sc_shape)
        hm, tg = st["host_mask"], st["tg_count"]
        pen, ce = st["penalty"], st["class_elig"]
        sc, dr, dv = st["spread_counts"], st["delta_rows"], st["delta_vals"]
        lm = st["lane_mask"]
        lm[:k] = True
        lm[k:] = False
        for i, p in enumerate(batch):
            # Requests built just before a matrix growth or a class-count
            # pow2 crossing carry narrower arrays; the staging row's tail
            # keeps the inert value (new rows masked off — they were not
            # host-checked; unknown classes eligible, matching
            # _class_eligibility's default).
            w = p.host_mask.shape[0]
            hm[i, :w] = p.host_mask
            hm[i, w:] = False
            w = p.tg_count.shape[0]
            tg[i, :w] = p.tg_count
            tg[i, w:] = 0
            w = p.penalty.shape[0]
            pen[i, :w] = p.penalty
            pen[i, w:] = False
            w = p.class_elig.shape[0]
            ce[i, :w] = p.class_elig
            ce[i, w:] = True
            sc[i] = p.spread_counts
            dr[i] = p.delta_rows
            dv[i] = p.delta_vals
        if k < self.max_lanes:
            # Pad lanes by memset: an all-False host mask makes every
            # placement in the lane fail cheaply; whatever the other
            # staging rows still hold from earlier dispatches only affects
            # the dead lane's own (discarded) scores.  Deltas are reset so
            # a stale row id can't scatter into the shared used base.
            hm[k:] = False
            dr[k:] = -1

        # Request operands write into the preallocated (max_lanes, …) slab;
        # dead-lane rows keep their previous valid contents (masked off by
        # lane_mask / the all-False host mask, never decoded into results).
        for i, p in enumerate(batch):
            self._req_slab.fill(i, p.request)
        reqs = self._req_slab.batch()
        # Host→device operand traffic for this launch: the staged lane
        # buffers plus the request slab (cost-attribution gauge; the
        # resident matrix itself transfers via scatter, counted by
        # matrix.upload_bytes_total).
        self.operand_bytes_total += (
            sum(a.nbytes for a in st.values()) + self._req_slab.nbytes()
        )
        if n_shards > 1:
            if self._sharded_fused_fn is not None:
                # Node-sharded fused megakernel: each mesh shard scores
                # only its local node slice, the winner comes from the
                # hierarchical top-k reduce, and the AllocsFit verify
                # column is computed on winner rows only — the packed
                # (B, P, 8) fetch is the sole device→host traffic.
                feats = self._ratchet_features(k)
                self.fused_dispatches += 1
                self.fused_lanes += k
                return self._sharded_fused_fn(
                    sharded, sharded.used, dr, dv, tg, sc, pen, reqs, ce,
                    hm, lm, features=feats,
                ), version
            # Staged sharded fallback (NOMAD_TPU_SHARDED_MEGABATCH=0):
            # packed result is PACKED_WIDTH wide and _resolve distinguishes
            # the two by the trailing dimension.
            return self._sharded_fn(
                sharded, sharded.used, dr, dv, tg, sc, pen, reqs, ce, hm
            ), version
        if self.megabatch:
            # Fused megakernel: one launch covers feasibility → binpack →
            # spread/affinity → evict-set → the cross-lane AllocsFit
            # re-verify column.
            feats = self._ratchet_features(k)
            self.fused_dispatches += 1
            self.fused_lanes += k
            return kernels.fused_place_batch_live(
                arrays, arrays.used, dr, dv, tg, sc, pen, reqs, ce, hm,
                lm, n_placements=self.scan_length,
                features=feats,
            ), version
        # place_batch_live donates the per-dispatch lane operands (their
        # device buffers become XLA scratch); `arrays`/`used` stay live —
        # they are matrix-resident and shared with in-flight dispatches.
        return kernels.place_batch_live(
            arrays, arrays.used, dr, dv, tg, sc, pen, reqs, ce, hm,
            n_placements=self.scan_length,
        ), version

    def _resolve(self, ticket: _Ticket) -> None:
        from ..chaos import inject
        from ..ops.fake_device import DeferredResult

        packed, entries = ticket.packed, ticket.entries
        brk = self.breaker

        # Chaos seams: a synthetic wedge (the fetch never returns inside
        # the watchdog bound) or a synthetic slowdown (returns inside the
        # slow band) on this ticket's device→host fetch.
        wedge = inject("device.wedge", lanes=len(entries))
        trace.event("seam.device.wedge", lanes=len(entries))
        slow = None
        if wedge is None or wedge.kind != "wedge":
            slow = inject("device.slow", lanes=len(entries))
        trace.event("seam.device.slow", lanes=len(entries))

        deadline = brk.deadline_s()
        factor = brk.cfg.wedge_factor
        seamed = (wedge is not None and wedge.kind == "wedge") or (
            slow is not None and slow.kind == "slow"
        )

        if not seamed and isinstance(packed, np.ndarray):
            # Fast path: the result is already host-resident (fake-device
            # twin, no synthetic latency) — no fetch to watchdog, and no
            # sacrificial thread on the 62K evals/s pipeline.
            arr = packed
            brk.record_ok(0.0, canary=ticket.canary)
        else:
            def _fetch():
                if wedge is not None and wedge.kind == "wedge":
                    # Synthetic wedge: hold the fetch past every watchdog
                    # bound (duration caps it so abandoned threads die).
                    time.sleep(
                        wedge.duration
                        if wedge.duration > 0
                        else max(deadline * factor * 4.0, 1.0)
                    )
                elif slow is not None and slow.kind == "slow":
                    # Synthetic slow band: past the deadline, inside the
                    # wedge bound — the result is late but usable.
                    time.sleep(
                        slow.duration
                        if slow.duration > 0
                        else deadline * (1.0 + factor) / 2.0
                    )
                pk = packed
                if isinstance(pk, DeferredResult):
                    pk = pk.result()
                return np.asarray(pk)  # ONE device→host fetch per dispatch

            try:
                verdict, arr, elapsed = watchdog_fetch(
                    _fetch, deadline, factor
                )
            except BaseException as exc:  # noqa: BLE001
                if ticket.canary:
                    brk.cancel_canary()
                for p in entries:
                    p.error = exc
                    p.done.set()
                return
            if verdict == STALL_WEDGED:
                # The fetch blew through the wedge bound: abandon it, trip
                # the breaker, and complete every lane with the typed
                # error — the worker's exception path nacks the eval back
                # to the broker for redelivery (via the degraded path once
                # the breaker opens).  Later tickets still resolve in
                # launch order; the pipeline permit is returned by
                # _resolve_loop's finally.
                brk.record_wedge(elapsed, canary=ticket.canary)
                self.wedged_dispatches += 1
                trace.event(
                    "coalescer.wedged_dispatch",
                    lanes=len(entries),
                    elapsed_ms=round(elapsed * 1e3, 1),
                )
                err = DeviceWedgedError(
                    f"device fetch wedged after {elapsed * 1e3:.0f}ms "
                    f"(deadline {deadline * 1e3:.0f}ms)",
                    elapsed_s=elapsed,
                    deadline_s=deadline,
                )
                for p in entries:
                    p.error = err
                    p.done.set()
                return
            if verdict == STALL_SLOW:
                brk.record_slow(elapsed, canary=ticket.canary)
            else:
                brk.record_ok(elapsed, canary=ticket.canary)
        resolved_at = time.time()
        # Result traffic: the packed (lanes, placements, width) fetch is
        # O(B·P) — winner rows only, never node-axis shaped (lint J005
        # guards the call sites; the parity test pins this counter).
        self.topk_host_bytes_total += arr.nbytes
        # The launch→resolver hop: each lane's device window (launch to
        # fetched-on-host) recorded here, on the resolver thread, against
        # the trace context the worker thread captured in place().
        for p in entries:
            if p.trace_ctx is not None:
                trace.record_span(
                    "coalescer.device",
                    ticket.launched_at or resolved_at,
                    resolved_at,
                    ctx=p.trace_ctx,
                    metrics=self.metrics,
                    lanes=len(entries),
                )
        if self.matrix.version != ticket.matrix_version:
            # The matrix moved while this dispatch was in flight: its
            # placements were scored against a stale snapshot.  They are
            # still safe to propose — the serialized applier re-verifies
            # every plan against authoritative state — but the count is
            # the pipelining tax worth watching (surfaced as a registry
            # gauge over this attribute by the server).
            self.stale_dispatches += 1
            trace.event("coalescer.stale_dispatch")
        fused = arr.shape[-1] == kernels.FUSED_PACKED_WIDTH
        for i, p in enumerate(entries):
            row = arr[i]
            # Shard-preserving capacity growth relocates rows; a dispatch
            # that launched pre-growth reports OLD global row ids.  Map
            # them through the matrix's remap window (no-op when nothing
            # grew; unmappably old rows become -1 = failed placement).
            rows_i = self.matrix.translate_rows(
                row[:, kernels.PACKED_ROW].astype(np.int32),
                ticket.matrix_version,
            )
            fit_verified = None
            if fused:
                # The device-resident AllocsFit column: a 0.0 on a real
                # placement means an earlier lane in THIS launch already
                # claimed the capacity — at an unchanged matrix version the
                # applier is guaranteed to reject it.  Advisory: the
                # serialized applier stays authoritative either way.
                vcol = row[:, kernels.FUSED_PACKED_VERIFIED]
                placed = rows_i >= 0
                fit_verified = ~(placed & (vcol == 0.0))
                self.verify_conflicts += int((~fit_verified).sum())
            p.outcome = PlaceOutcome(
                rows=rows_i,
                scores=row[:, kernels.PACKED_SCORE],
                binpack=row[:, kernels.PACKED_BINPACK],
                preempted=row[:, kernels.PACKED_PREEMPT] != 0.0,
                nodes_evaluated=row[:, kernels.PACKED_EVALUATED].astype(
                    np.int32
                ),
                nodes_filtered=row[:, kernels.PACKED_FILTERED].astype(
                    np.int32
                ),
                nodes_exhausted=row[:, kernels.PACKED_EXHAUSTED].astype(
                    np.int32
                ),
                fit_verified=fit_verified,
                matrix_version=ticket.matrix_version,
            )
            p.done.set()
