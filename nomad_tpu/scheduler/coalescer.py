"""Dispatch coalescer — one device thread batching concurrent selects.

Round-3 diagnosis: every worker's ``select()`` held the global DEVICE_LOCK
across its own kernel dispatch, and fetched seven result buffers
individually — through the TPU tunnel each fetch costs a full sync
round-trip (bench.py ``rtt_floor_ms``, ~65ms observed), so four workers
serialized into ~1.5 evals/sec end-to-end while the batched kernel sat
unused outside the bench.

This module makes the batched kernel THE live path: workers enqueue
compiled placement requests and block on a future; a single device thread
drains the queue, stacks up to ``max_lanes`` requests, and issues ONE
``ops.kernels.place_batch`` dispatch whose packed result costs ONE fetch.
Up to ``max_inflight`` dispatches are kept in flight so the tunnel
round-trip amortizes across batches (the same pipelining bench.py
measures).

Shape discipline (SURVEY.md §7 hard-part e — p99 means no recompiles):
every dispatch uses the SAME static shapes — ``max_lanes`` lanes (short
batches padded with inert requests) and a ``PLACEMENT_CHUNK``-long scan
(callers take the first rows they asked for) — so exactly one executable
serves every batch size. Wasted lanes cost ~µs of MXU time; a recompile
costs tens of seconds.

The reference's analog: many schedulers walk nodes concurrently and the
plan applier serializes commits (worker.go:49-53, plan_apply.go:49-69).
The optimistic-concurrency contract is unchanged — coalesced selects may
pick conflicting nodes; the applier's re-verify catches it.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..ops import kernels
from ..ops.encode import SchedRequest
from ..state.matrix import DEVICE_LOCK

log = logging.getLogger(__name__)

# Sparse plan-delta capacity per request; selects with more touched rows
# fall back to the solo dispatch path.
MAX_DELTA_ROWS = 32


@dataclass
class PlaceOutcome:
    """Unpacked per-request result (numpy, host-side)."""

    rows: np.ndarray  # (P,) i32
    scores: np.ndarray  # (P,) f32
    binpack: np.ndarray  # (P,) f32
    preempted: np.ndarray  # (P,) bool
    nodes_evaluated: np.ndarray  # (P,) i32
    nodes_filtered: np.ndarray  # (P,) i32
    nodes_exhausted: np.ndarray  # (P,) i32


@dataclass
class _DeviceOp:
    fn: "callable"
    done: threading.Event = field(default_factory=threading.Event)
    result: object = None
    error: Optional[BaseException] = None


@dataclass
class _Pending:
    request: SchedRequest
    delta_rows: np.ndarray  # (MAX_DELTA_ROWS,) i32, -1 padded
    delta_vals: np.ndarray  # (MAX_DELTA_ROWS, 3) f32
    tg_count: np.ndarray  # (N,) i32
    spread_counts: np.ndarray  # (S, V) f32
    penalty: np.ndarray  # (N,) bool
    class_elig: np.ndarray  # (pad,) bool
    host_mask: np.ndarray  # (N,) bool
    # Placements the caller will actually consume (0 = all scan_length).
    # The jax kernel ignores it (static shapes); the fake-device twin stops
    # its scan after this many live steps.
    n_live: int = 0
    done: threading.Event = field(default_factory=threading.Event)
    outcome: Optional[PlaceOutcome] = None
    error: Optional[BaseException] = None


class DeviceCoalescer:
    """The single dispatch port for the shared device matrix."""

    def __init__(
        self,
        matrix,
        max_lanes: int = 64,
        scan_length: Optional[int] = None,
        linger_s: float = 0.002,
        max_inflight: int = 4,
        n_device_shards: Optional[int] = None,
    ):
        from .stack import PLACEMENT_CHUNK

        self.matrix = matrix
        self.max_lanes = max_lanes
        self.scan_length = scan_length or PLACEMENT_CHUNK
        self.linger_s = linger_s
        self.max_inflight = max_inflight
        # Multi-chip: when >1, dispatches go through the SPMD twin of
        # place_batch (parallel/sharding.py sharded_place_batch) over a
        # ('batch', 'node') mesh — the live server path the dryrun
        # certifies.  None = auto: all visible devices on real
        # accelerators, single-device on CPU (the virtual 8-CPU rig is a
        # test harness, not a deployment; tests opt in explicitly).
        self.n_device_shards = n_device_shards
        self._mesh = None
        self._sharded_fn = None
        self._queue: List[_Pending] = []
        # Arbitrary device closures (system feasibility, bulk plan verify,
        # oversized-delta solo selects) executed on the dispatch thread so
        # the live server has exactly ONE device-touching thread — the
        # single-chip tunnel client wedges under concurrent host threads
        # (state/matrix.py DEVICE_LOCK note).
        self._ops: List["_DeviceOp"] = []
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.dispatches = 0
        self.coalesced_requests = 0

    # ------------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return  # leadership can cycle; one dispatch thread only
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="device-coalescer", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread:
            self._thread.join(timeout=10)

    # ------------------------------------------------------------------

    def place(
        self,
        request: SchedRequest,
        delta_rows: np.ndarray,
        delta_vals: np.ndarray,
        tg_count: np.ndarray,
        spread_counts: np.ndarray,
        penalty: np.ndarray,
        class_elig: np.ndarray,
        host_mask: np.ndarray,
        timeout: float = 600.0,  # must cover a cold TPU jit compile
        n_live: int = 0,
    ) -> PlaceOutcome:
        """Submit one placement request; blocks until its batch lands.
        The scan always runs ``scan_length`` steps — take ``rows[:k]``."""
        p = _Pending(
            request=request,
            delta_rows=delta_rows,
            delta_vals=delta_vals,
            tg_count=tg_count,
            spread_counts=spread_counts,
            penalty=penalty,
            class_elig=class_elig,
            host_mask=host_mask,
            n_live=n_live,
        )
        with self._cond:
            if self._stop.is_set():
                raise RuntimeError("coalescer stopped")
            self._queue.append(p)
            self._cond.notify()
        if not p.done.wait(timeout=timeout):
            raise TimeoutError("coalescer dispatch timed out")
        if p.error is not None:
            raise p.error
        assert p.outcome is not None
        return p.outcome

    def run_device_op(self, fn, timeout: float = 600.0):
        """Execute ``fn()`` on the dispatch thread and return its result.

        The escape hatch for device work that doesn't fit the batched
        placement shape (system feasibility sweeps, bulk plan verification,
        oversized-delta selects): they still run on the one device thread
        instead of racing it on the tunnel."""
        op = _DeviceOp(fn=fn)
        with self._cond:
            if self._stop.is_set():
                raise RuntimeError("coalescer stopped")
            self._ops.append(op)
            self._cond.notify()
        if not op.done.wait(timeout=timeout):
            raise TimeoutError("device op timed out")
        if op.error is not None:
            raise op.error
        return op.result

    # ------------------------------------------------------------------

    def _run(self) -> None:
        inflight: List[Tuple[object, List[_Pending]]] = []
        while True:
            self._drain_ops()
            batch = self._next_batch(block=not inflight)
            if batch is None and self._stop.is_set() and not inflight:
                with self._cond:
                    leftover_ops, self._ops = self._ops, []
                    leftover_q, self._queue = self._queue, []
                err = RuntimeError("coalescer stopped")
                for op in leftover_ops:
                    op.error = err
                    op.done.set()
                for p in leftover_q:
                    p.error = err
                    p.done.set()
                return
            if batch:
                try:
                    out = self._dispatch(batch)
                    inflight.append((out, batch))
                    self.dispatches += 1
                    self.coalesced_requests += len(batch)
                except BaseException as exc:  # noqa: BLE001
                    for p in batch:
                        p.error = exc
                        p.done.set()
            # Fetch the oldest dispatch when the pipe is full or there is
            # nothing new to issue — keeps up to max_inflight overlapping
            # the tunnel round-trip.
            if inflight and (len(inflight) >= self.max_inflight or not batch):
                out, entries = inflight.pop(0)
                self._resolve(out, entries)

    def _drain_ops(self) -> None:
        while True:
            with self._cond:
                if not self._ops:
                    return
                op = self._ops.pop(0)
            try:
                op.result = op.fn()
            except BaseException as exc:  # noqa: BLE001
                op.error = exc
            op.done.set()

    def _next_batch(self, block: bool) -> Optional[List[_Pending]]:
        with self._cond:
            if not self._queue and block:
                self._cond.wait_for(
                    lambda: self._queue or self._ops or self._stop.is_set(),
                    timeout=0.2,
                )
            if not self._queue:
                return None
        # Linger briefly so concurrent workers land in one dispatch.  The
        # fake-device backend answers synchronously, so lingering would only
        # add serial latency on the one dispatch thread — requests still
        # coalesce while a dispatch is in progress.
        from ..ops import fake_device

        if self.linger_s and not fake_device.enabled():
            self._stop.wait(self.linger_s)
        with self._cond:
            batch = self._queue[: self.max_lanes]
            del self._queue[: len(batch)]
        return batch or None

    # ------------------------------------------------------------------

    def _resolve_sharding(self) -> int:
        """Decide (once) how many devices dispatches span."""
        if self.n_device_shards is None:
            import jax

            devs = jax.devices()
            self.n_device_shards = (
                len(devs) if devs[0].platform != "cpu" and len(devs) > 1
                else 1
            )
        if self.n_device_shards > 1 and self._sharded_fn is None:
            from ..parallel.sharding import make_mesh, sharded_place_batch

            self._mesh = make_mesh(self.n_device_shards)
            self._sharded_fn = sharded_place_batch(
                self._mesh, self.scan_length
            )
            log.info(
                "coalescer: multi-chip dispatch over mesh %s",
                dict(zip(self._mesh.axis_names, self._mesh.devices.shape)),
            )
        return self.n_device_shards

    def _dispatch(self, batch: List[_Pending]):
        from ..ops import fake_device

        fake = fake_device.enabled()
        if fake:
            n_shards = 1
        else:
            n_shards = self._resolve_sharding()
        with DEVICE_LOCK:
            arrays = self.matrix.sync()
        n = int(arrays.used.shape[0])

        # Requests built just before a matrix growth or a class-count pow2
        # crossing carry narrower arrays; pad each by its OWN width
        # (new rows masked off — they were not host-checked; unknown
        # classes eligible, matching _class_eligibility's default).
        for p in batch:
            if p.host_mask.shape[0] < n:
                p.host_mask = np.concatenate([
                    p.host_mask,
                    np.zeros((n - p.host_mask.shape[0],), bool),
                ])
            if p.tg_count.shape[0] < n:
                p.tg_count = np.concatenate([
                    p.tg_count,
                    np.zeros((n - p.tg_count.shape[0],), np.int32),
                ])
            if p.penalty.shape[0] < n:
                p.penalty = np.concatenate([
                    p.penalty,
                    np.zeros((n - p.penalty.shape[0],), bool),
                ])
        cw = max(p.class_elig.shape[0] for p in batch)
        for p in batch:
            if p.class_elig.shape[0] < cw:
                p.class_elig = np.concatenate([
                    p.class_elig,
                    np.ones((cw - p.class_elig.shape[0],), bool),
                ])

        if fake:
            # Fake-device backend: numpy twins answer synchronously from
            # the host snapshot.  No lane padding (shapes need not be
            # static for numpy) and no stacking — the twin takes lists.
            return fake_device.place_batch(
                arrays,
                arrays.used,
                [p.delta_rows for p in batch],
                [p.delta_vals for p in batch],
                [p.tg_count for p in batch],
                [p.spread_counts for p in batch],
                [p.penalty for p in batch],
                [p.request for p in batch],
                [p.class_elig for p in batch],
                [p.host_mask for p in batch],
                n_placements=self.scan_length,
                live_counts=[p.n_live or self.scan_length for p in batch],
            )

        import jax

        # Pad to the fixed lane count with inert copies of the first
        # request (host_mask all-False → every placement fails cheaply).
        lanes: List[_Pending] = list(batch)
        if len(lanes) < self.max_lanes:
            inert = batch[0]
            dead_mask = np.zeros_like(inert.host_mask)
            filler = _Pending(
                request=inert.request,
                delta_rows=np.full_like(inert.delta_rows, -1),
                delta_vals=np.zeros_like(inert.delta_vals),
                tg_count=inert.tg_count,
                spread_counts=inert.spread_counts,
                penalty=inert.penalty,
                class_elig=inert.class_elig,
                host_mask=dead_mask,
            )
            lanes.extend([filler] * (self.max_lanes - len(lanes)))

        reqs = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *[p.request for p in lanes]
        )
        args = (
            arrays,
            arrays.used,
            np.stack([p.delta_rows for p in lanes]),
            np.stack([p.delta_vals for p in lanes]),
            np.stack([p.tg_count for p in lanes]),
            np.stack([p.spread_counts for p in lanes]),
            np.stack([p.penalty for p in lanes]),
            reqs,
            np.stack([p.class_elig for p in lanes]),
            np.stack([p.host_mask for p in lanes]),
        )
        if n_shards > 1:
            from ..parallel.sharding import shard_matrix_arrays

            # Lay the matrix across the mesh's node axis.  (Sharded-
            # resident incremental updates are a further optimization;
            # today the authoritative copy lives on device 0 and re-lays
            # per dispatch.)
            sharded = shard_matrix_arrays(self._mesh, arrays)
            return self._sharded_fn(
                sharded, sharded.used, *args[2:]
            )
        return kernels.place_batch(*args, n_placements=self.scan_length)

    def _resolve(self, packed, entries: List[_Pending]) -> None:
        try:
            arr = np.asarray(packed)  # ONE device→host fetch per dispatch
        except BaseException as exc:  # noqa: BLE001
            for p in entries:
                p.error = exc
                p.done.set()
            return
        for i, p in enumerate(entries):
            row = arr[i]
            p.outcome = PlaceOutcome(
                rows=row[:, kernels.PACKED_ROW].astype(np.int32),
                scores=row[:, kernels.PACKED_SCORE],
                binpack=row[:, kernels.PACKED_BINPACK],
                preempted=row[:, kernels.PACKED_PREEMPT] != 0.0,
                nodes_evaluated=row[:, kernels.PACKED_EVALUATED].astype(
                    np.int32
                ),
                nodes_filtered=row[:, kernels.PACKED_FILTERED].astype(
                    np.int32
                ),
                nodes_exhausted=row[:, kernels.PACKED_EXHAUSTED].astype(
                    np.int32
                ),
            )
            p.done.set()
