"""Allocation reconciler — diff (job spec, existing allocs, node taints) into
placement/stop/update sets.

Reference: scheduler/reconcile.go:39-983 + reconcile_util.go. This is
deliberately host Python: it is branchy, small-n (allocs of ONE job), and
runs once per eval — the per-node math it feeds lives in the kernels.

Covered here: terminal filtering by name (funcs.go:69-90), tainted-node
migration/lost handling, excess stop, in-place vs destructive updates with
rolling max_parallel pacing, failed-alloc rescheduling with
constant/exponential/fibonacci backoff and follow-up evals
(generic_sched.go:719-753), deployment creation/progress for jobs with an
update stanza, and canary placement/promotion bookkeeping.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..structs.types import (
    AllocClientStatus,
    AllocDesiredStatus,
    Allocation,
    Deployment,
    DeploymentState,
    DeploymentStatus,
    DeploymentStatusUpdate,
    DesiredTransition,
    EvalStatus,
    EvalTrigger,
    Evaluation,
    Job,
    JobType,
    Node,
    RescheduleEvent,
    RescheduleTracker,
    TaskGroup,
)

# Alloc stop descriptions (reference: scheduler/reconcile.go:26-37).
ALLOC_NOT_NEEDED = "alloc not needed due to job update"
ALLOC_MIGRATING = "alloc is being migrated"
ALLOC_UPDATING = "alloc is being updated due to job update"
ALLOC_LOST = "alloc is lost since its node is down"
ALLOC_IN_PLACE = "alloc updating in-place"
ALLOC_NODE_TAINTED = "alloc not needed as node is tainted"
ALLOC_RESCHEDULED = "alloc was rescheduled because it failed"


@dataclass
class PlaceRequest:
    name: str
    task_group: TaskGroup
    previous_alloc: Optional[Allocation] = None
    reschedule: bool = False
    canary: bool = False


@dataclass
class StopRequest:
    alloc: Allocation
    description: str
    client_status: str = ""


@dataclass
class UpdateRequest:
    alloc: Allocation
    new_job: Job


@dataclass
class TGReconcileResult:
    place: List[PlaceRequest] = field(default_factory=list)
    stop: List[StopRequest] = field(default_factory=list)
    inplace: List[UpdateRequest] = field(default_factory=list)
    destructive: List[UpdateRequest] = field(default_factory=list)
    ignore: int = 0
    placing_canaries: bool = False
    # desired annotation counts (reference: structs.DesiredUpdates)
    desired: Dict[str, int] = field(default_factory=dict)


@dataclass
class ReconcileResults:
    place: List[PlaceRequest] = field(default_factory=list)
    stop: List[StopRequest] = field(default_factory=list)
    inplace: List[UpdateRequest] = field(default_factory=list)
    destructive: List[UpdateRequest] = field(default_factory=list)
    # delayed-reschedule follow-up evals (eval_broker DelayHeap consumers)
    followup_evals: List[Evaluation] = field(default_factory=list)
    # metadata-only alloc updates stamping follow_up_eval_id onto failed
    # allocs awaiting a delayed reschedule (plan.alloc_updates)
    followup_updates: List[Allocation] = field(default_factory=list)
    deployment: Optional[Deployment] = None
    deployment_updates: List[DeploymentStatusUpdate] = field(default_factory=list)
    desired_tg_updates: Dict[str, Dict[str, int]] = field(default_factory=dict)


def tasks_updated(a: TaskGroup, b: TaskGroup) -> bool:
    """Whether a TG change is destructive (reference: tasksUpdated,
    scheduler/util.go). Count changes alone are NOT destructive."""
    ax = dataclasses.asdict(a)
    bx = dataclasses.asdict(b)
    for k in ("count",):
        ax.pop(k, None)
        bx.pop(k, None)
    return ax != bx


# tasks_updated runs two deep dataclasses.asdict walks per version-mismatched
# alloc; under a rolling update every alloc of the job hits the same
# (old version, new version) pair.  A registered (job id, version) names an
# immutable definition in the store, so the verdict is cacheable by version
# pair.  Bounded FIFO so long-lived servers don't grow without limit.
_tasks_updated_cache: Dict[Tuple[str, int, int, str], bool] = {}
_TASKS_UPDATED_CACHE_MAX = 4096


def tasks_updated_memo(old_job: Job, new_job: Job, tg_name: str) -> bool:
    key = (old_job.id, old_job.version, new_job.version, tg_name)
    hit = _tasks_updated_cache.get(key)
    if hit is None:
        old_tg = old_job.lookup_task_group(tg_name)
        new_tg = new_job.lookup_task_group(tg_name)
        hit = (
            True
            if old_tg is None or new_tg is None
            else tasks_updated(old_tg, new_tg)
        )
        if len(_tasks_updated_cache) >= _TASKS_UPDATED_CACHE_MAX:
            _tasks_updated_cache.pop(next(iter(_tasks_updated_cache)))
        _tasks_updated_cache[key] = hit
    return hit


def reschedule_delay(policy, attempt: int) -> float:
    """Backoff for the next reschedule (generic_sched.go:719-753)."""
    base = policy.delay
    if policy.delay_function == "constant":
        d = base
    elif policy.delay_function == "exponential":
        d = base * (2 ** max(0, attempt))
    else:  # fibonacci
        x, y = base, base
        for _ in range(max(0, attempt)):
            x, y = y, x + y
        d = x
    if policy.max_delay > 0:
        d = min(d, policy.max_delay)
    return d


def should_reschedule(
    alloc: Allocation, policy, now: float
) -> Tuple[bool, float]:
    """(eligible, wait_seconds). wait == 0 → reschedule immediately; wait > 0
    → schedule a follow-up eval at now+wait. The backoff is anchored at the
    alloc's failure time (NextRescheduleTime semantics: eligible when
    fail_time + delay(attempt) has passed)."""
    if policy is None or (policy.attempts == 0 and not policy.unlimited):
        return False, 0.0
    events = (
        alloc.reschedule_tracker.events if alloc.reschedule_tracker else []
    )
    attempt = len(events)
    if not policy.unlimited:
        window_start = now - policy.interval
        recent = [e for e in events if e.reschedule_time >= window_start]
        if len(recent) >= policy.attempts:
            return False, 0.0
        attempt = len(recent)
    next_time = alloc.fail_time() + reschedule_delay(policy, attempt)
    return True, max(0.0, next_time - now)


class AllocReconciler:
    """Reference: NewAllocReconciler (reconcile.go:90)."""

    def __init__(
        self,
        job_id: str,
        job: Optional[Job],
        existing: List[Allocation],
        tainted: Dict[str, Optional[Node]],
        eval_id: str,
        deployment: Optional[Deployment] = None,
        now: Optional[float] = None,
        batch: bool = False,
        supports_disconnected_clients: bool = False,
    ):
        self.job_id = job_id
        self.job = job
        self.existing = existing
        self.tainted = tainted
        self.eval_id = eval_id
        self.deployment = deployment
        self.now = now if now is not None else time.time()
        self.batch = batch
        self.job_stopped = job is None or job.stopped()

    # ------------------------------------------------------------------

    def compute(self) -> ReconcileResults:
        res = ReconcileResults()

        if self.job_stopped:
            for alloc in self.existing:
                if not alloc.terminal_status():
                    res.stop.append(
                        StopRequest(alloc, ALLOC_NOT_NEEDED)
                    )
            if self.deployment is not None and self.deployment.active():
                res.deployment_updates.append(
                    DeploymentStatusUpdate(
                        deployment_id=self.deployment.id,
                        status=DeploymentStatus.CANCELLED.value,
                        status_description="Cancelled because job is stopped",
                    )
                )
            return res

        job = self.job
        assert job is not None

        by_tg: Dict[str, List[Allocation]] = {}
        for alloc in self.existing:
            by_tg.setdefault(alloc.task_group, []).append(alloc)

        # Cancel deployments for older job versions (reconcile.go
        # cancelDeployments).
        deployment = self.deployment
        if deployment is not None and deployment.active():
            if deployment.job_version != job.version:
                res.deployment_updates.append(
                    DeploymentStatusUpdate(
                        deployment_id=deployment.id,
                        status=DeploymentStatus.CANCELLED.value,
                        status_description=(
                            "Cancelled due to newer version of job"
                        ),
                    )
                )
                deployment = None

        creating_deployment = False
        dstates: Dict[str, DeploymentState] = {}

        # A failed deployment for the CURRENT version blocks further updates
        # until a new version arrives (reconcile.go deploymentFailed); only
        # an active same-version deployment continues to be driven.
        self._deployment = (
            deployment
            if deployment is not None and deployment.job_version == job.version
            else None
        )
        self._deployment_failed = (
            self._deployment is not None
            and self._deployment.status == DeploymentStatus.FAILED.value
        )
        self._deployment_paused = (
            self._deployment is not None
            and self._deployment.status == DeploymentStatus.PAUSED.value
        )

        for tg in job.task_groups:
            allocs = by_tg.pop(tg.name, [])
            tg_res = self._compute_group(tg, allocs, res)
            res.place.extend(tg_res.place)
            res.stop.extend(tg_res.stop)
            res.inplace.extend(tg_res.inplace)
            res.destructive.extend(tg_res.destructive)
            res.desired_tg_updates[tg.name] = tg_res.desired

            # Deployment bookkeeping: a service job with an update stanza
            # gets a deployment tracking each changed TG when no deployment
            # exists yet for this job version
            # (reconcile.go computeDeploymentUpdates).
            if (
                job.type == JobType.SERVICE.value
                and tg.update is not None
                and tg.update.max_parallel > 0
                and (tg_res.place or tg_res.destructive
                     or tg_res.placing_canaries)
                and self._deployment is None
                and not self._deployment_failed
            ):
                creating_deployment = True
                dstates[tg.name] = DeploymentState(
                    auto_revert=tg.update.auto_revert,
                    auto_promote=tg.update.auto_promote,
                    desired_total=tg.count,
                    desired_canaries=tg.update.canary,
                    progress_deadline=tg.update.progress_deadline,
                    require_progress_by=self.now + tg.update.progress_deadline,
                )

        # Allocs of task groups no longer in the job: stop.
        for allocs in by_tg.values():
            for alloc in allocs:
                if not alloc.terminal_status():
                    res.stop.append(StopRequest(alloc, ALLOC_NOT_NEEDED))

        if creating_deployment:
            res.deployment = Deployment(
                namespace=job.namespace,
                job_id=job.id,
                job_version=job.version,
                job_modify_index=job.modify_index,
                job_create_index=job.create_index,
                task_groups=dstates,
                status=DeploymentStatus.RUNNING.value,
                status_description="Deployment is running",
            )
        return res

    # ------------------------------------------------------------------

    def _compute_group(
        self, tg: TaskGroup, allocs: List[Allocation], res: ReconcileResults
    ) -> TGReconcileResult:
        out = TGReconcileResult()
        job = self.job
        assert job is not None
        desired: Dict[str, int] = {
            "place": 0,
            "stop": 0,
            "migrate": 0,
            "in_place_update": 0,
            "destructive_update": 0,
            "ignore": 0,
        }
        out.desired = desired

        # -- partition: live / failed-retryable / terminal-by-name
        # (funcs.go:69-90). Failed allocs still desired to run are NOT plain
        # terminal: they hold their name and go through reschedule policy
        # (reconcile_util.go filterByRescheduleable).
        live: List[Allocation] = []
        failed: List[Allocation] = []
        waiting: List[Allocation] = []  # pending delayed reschedule elsewhere
        terminal_by_name: Dict[str, Allocation] = {}
        n_allocs = len(allocs)
        if n_allocs:
            # Mask combination instead of per-alloc branch chains: one
            # attribute sweep per predicate, then boolean algebra.  On jobs
            # with hundreds of allocs this replaces the interpreted if/elif
            # ladder with four numpy ops.
            run_v = AllocDesiredStatus.RUN.value
            fail_v = AllocClientStatus.FAILED.value
            is_failed = np.fromiter(
                (
                    a.desired_status == run_v
                    and a.client_status == fail_v
                    and not a.next_allocation
                    for a in allocs
                ),
                bool,
                n_allocs,
            )
            is_terminal = np.fromiter(
                (a.terminal_status() for a in allocs), bool, n_allocs
            )
            for i in np.flatnonzero(is_failed):
                a = allocs[i]
                # A follow-up eval owns this alloc until it fires; only the
                # owning eval may reschedule it (updateByReschedulable).
                if a.follow_up_eval_id and a.follow_up_eval_id != self.eval_id:
                    waiting.append(a)
                else:
                    failed.append(a)
            for i in np.flatnonzero(~is_failed & is_terminal):
                a = allocs[i]
                prev = terminal_by_name.get(a.name)
                if prev is None or prev.create_index < a.create_index:
                    terminal_by_name[a.name] = a
            live = [allocs[i] for i in np.flatnonzero(~is_failed & ~is_terminal)]

        # -- tainted-node handling: migrate (drain, drainer-paced) or lost
        # (down/gone).  Draining nodes migrate ONLY the allocs the drainer
        # has stamped with a migrate DesiredTransition — that is how drain
        # pacing works (reconcile_util.go filterByTainted +
        # nomad/drainer/watch_jobs.go batches).
        untainted: List[Allocation] = []
        migrate: List[Allocation] = []
        lost: List[Allocation] = []
        if not self.tainted:
            # Steady-state fast path: no tainted nodes — only the migrate
            # transition can reroute an alloc, and the drainer stamps it
            # rarely.  One mask sweep, no per-alloc dict probes.
            if live:
                wants_migrate = np.fromiter(
                    (a.desired_transition.should_migrate() for a in live),
                    bool,
                    len(live),
                )
                if wants_migrate.any():
                    migrate = [live[i] for i in np.flatnonzero(wants_migrate)]
                    untainted = [
                        live[i] for i in np.flatnonzero(~wants_migrate)
                    ]
                else:
                    untainted = live
        else:
            for a in live:
                if a.node_id not in self.tainted:
                    # Drainer-forced migration arrives as a DesiredTransition
                    # (nomad/drainer/drainer.go:357).
                    if a.desired_transition.should_migrate():
                        migrate.append(a)
                    else:
                        untainted.append(a)
                    continue
                node = self.tainted[a.node_id]
                if node is not None and node.drain:
                    if a.desired_transition.should_migrate():
                        migrate.append(a)
                    else:
                        untainted.append(a)
                else:
                    lost.append(a)

        # -- canaries of the current deployment are handled out-of-band of
        # the name bookkeeping below (reconcile.go cancelUnneededCanaries /
        # computeCanaries): they shadow existing names until promotion.
        deployment = self._deployment
        dstate = (
            deployment.task_groups.get(tg.name)
            if deployment is not None
            else None
        )
        promoted = dstate.promoted if dstate is not None else False
        canaries: List[Allocation] = []
        if deployment is not None:
            canaries = [
                a for a in untainted
                if a.deployment_id == deployment.id
                and a.deployment_status is not None
                and a.deployment_status.canary
            ]
            canary_ids = {a.id for a in canaries}
            untainted = [a for a in untainted if a.id not in canary_ids]
        if self._deployment_failed and canaries:
            # Failed deployment: its canaries are torn down (the old
            # version keeps running; auto-revert is the watcher's job).
            for a in canaries:
                out.stop.append(StopRequest(a, ALLOC_NOT_NEEDED))
                desired_canary_stops = desired.get("stop", 0) + 1
                desired["stop"] = desired_canary_stops
            canaries = []
        if promoted and canaries:
            # Promoted canaries are ordinary new-version allocs; they win
            # the name slots, pushing same-name old allocs into excess.
            untainted = canaries + untainted
            canaries = []

        # -- failed allocs through reschedule policy: now / later / never
        reschedule_now: List[Allocation] = []
        reschedule_later: List[Tuple[Allocation, float]] = []
        failed_holding_name: List[Allocation] = list(waiting)
        policy = tg.reschedule_policy
        for a in failed:
            force = a.desired_transition.should_force_reschedule()
            ok, delay = should_reschedule(a, policy, self.now)
            if force or (ok and delay <= 0):
                reschedule_now.append(a)
            elif ok:
                reschedule_later.append((a, delay))
            else:
                # Not reschedulable: the failed alloc keeps its name slot and
                # is left in place (job shows as degraded).
                failed_holding_name.append(a)

        # -- batch jobs keep successfully-completed allocs completed: the
        # terminal map prevents re-placement of the same name.
        count = 0 if job.stopped() else tg.count

        # -- name bookkeeping
        def name_of(i: int) -> str:
            return f"{job.id}.{tg.name}[{i}]"

        # -- excess: stop highest-index names beyond count
        keep: List[Allocation] = []
        excess: List[Allocation] = []
        by_index = sorted(untainted, key=lambda a: a.index)
        seen_names: set = set()
        for a in by_index:
            if a.index < count and a.name not in seen_names:
                keep.append(a)
                seen_names.add(a.name)
            else:
                excess.append(a)
        for a in excess:
            out.stop.append(StopRequest(a, ALLOC_NOT_NEEDED))
            desired["stop"] += 1

        # -- updates: in-place vs destructive, paced by update.max_parallel
        inplace: List[Allocation] = []
        destructive: List[Allocation] = []
        for a in keep:
            if a.job is not None and a.job.version == job.version:
                out.ignore += 1
                desired["ignore"] += 1
                continue
            if a.job is not None and not tasks_updated_memo(a.job, job, tg.name):
                inplace.append(a)
            else:
                destructive.append(a)

        for a in inplace:
            out.inplace.append(UpdateRequest(a, job))
            desired["in_place_update"] += 1

        # -- canary gate: destructive changes behind a canary stanza place
        # canaries first and defer the rolling update until the deployment
        # watcher promotes (reconcile.go computeCanaries).
        requires_canaries = (
            tg.update is not None
            and tg.update.canary > 0
            and destructive
            and not promoted
        )
        if requires_canaries:
            if not (self._deployment_failed or self._deployment_paused):
                missing = tg.update.canary - len(canaries)
                for i in range(max(0, missing)):
                    out.place.append(
                        PlaceRequest(
                            name=name_of(i),
                            task_group=tg,
                            canary=True,
                        )
                    )
                    desired["canary"] = desired.get("canary", 0) + 1
                    out.placing_canaries = True
            for a in destructive:
                out.ignore += 1
                desired["ignore"] += 1
            destructive = []

        # -- rolling-update pacing: max_parallel minus in-flight placements
        # of the new version that have not yet reported healthy — the
        # health gate that makes batches wait (reconcile.go
        # computeDestructiveUpdates + deploymentwatcher next-batch evals).
        if tg.update is not None and tg.update.max_parallel > 0:
            in_flight_unhealthy = 0
            if deployment is not None:
                in_flight_unhealthy = sum(
                    1
                    for a in keep
                    if a.deployment_id == deployment.id
                    and (
                        a.deployment_status is None
                        or a.deployment_status.healthy is not True
                    )
                )
            limit = max(0, tg.update.max_parallel - in_flight_unhealthy)
        else:
            limit = len(destructive)
        if self._deployment_failed or self._deployment_paused:
            limit = 0
        for a in destructive[:limit]:
            out.destructive.append(UpdateRequest(a, job))
            desired["destructive_update"] += 1
        for a in destructive[limit:]:
            out.ignore += 1
            desired["ignore"] += 1

        # -- migrations: stop + place elsewhere
        for a in migrate:
            out.stop.append(StopRequest(a, ALLOC_MIGRATING))
            desired["migrate"] += 1
            if a.index < count:
                out.place.append(
                    PlaceRequest(
                        name=a.name,
                        task_group=tg,
                        previous_alloc=a,
                    )
                )

        # -- lost: mark lost + replace
        for a in lost:
            out.stop.append(
                StopRequest(
                    a, ALLOC_LOST, client_status=AllocClientStatus.LOST.value
                )
            )
            desired["stop"] += 1
            if a.index < count:
                out.place.append(
                    PlaceRequest(name=a.name, task_group=tg, previous_alloc=a)
                )

        # -- reschedule now: stop-and-replace with penalty on prior node
        for a in reschedule_now:
            out.place.append(
                PlaceRequest(
                    name=a.name,
                    task_group=tg,
                    previous_alloc=a,
                    reschedule=True,
                )
            )
            desired["place"] += 1

        # -- reschedule later: follow-up eval at now+delay
        #    (generic_sched.go createRescheduleLaterEvals)
        delays = sorted(set(d for _, d in reschedule_later))
        eval_by_delay: Dict[float, Evaluation] = {}
        for d in delays:
            ev = Evaluation(
                namespace=job.namespace,
                priority=job.priority,
                type=job.type,
                triggered_by=EvalTrigger.RETRY_FAILED_ALLOC.value,
                job_id=job.id,
                status=EvalStatus.PENDING.value,
                wait_until=self.now + d,
            )
            eval_by_delay[d] = ev
            res.followup_evals.append(ev)
        for a, d in reschedule_later:
            upd = a.copy()
            upd.follow_up_eval_id = eval_by_delay[d].id
            res.followup_updates.append(upd)

        # -- place missing: every name index below count not already covered
        # by a kept alloc, an in-flight placement, a name-holding failed
        # alloc, a pending delayed reschedule, or (batch) a successful run.
        used_names = (
            {a.name for a in keep}
            | {p.name for p in out.place}
            | {a.name for a in failed_holding_name}
            | {a.name for a, _ in reschedule_later}
        )
        if self.batch:
            used_names |= {
                n for n, a in terminal_by_name.items() if a.ran_successfully()
            }
        for i in range(count):
            nm = name_of(i)
            if nm in used_names:
                continue
            prev = terminal_by_name.get(nm)
            out.place.append(
                PlaceRequest(name=nm, task_group=tg, previous_alloc=prev)
            )
            used_names.add(nm)
            desired["place"] += 1

        return out
