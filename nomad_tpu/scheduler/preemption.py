"""Preemption victim selection — host-side residue of the vectorized search.

The kernel identifies nodes where evicting lower-priority work would make the
ask fit (prio_used prefix-sum, ops/kernels.preemption_state). This module
picks the *actual* victim allocs on the single chosen node — the reference's
greedy search (scheduler/preemption.go:198-557) reduced to one node.

Victims must have priority < job.priority − 10 (preemption.go:663); chosen
greedily by (priority, resource distance) until the deficit is covered,
then filtered back (superset elimination, preemption.go:702).
"""

from __future__ import annotations

import math
from typing import List, Optional

from ..structs.types import (
    Allocation,
    Job,
    Node,
    PREEMPTION_PRIORITY_DELTA,
    Resources,
)


def resource_distance(delta: Resources, ask: Resources) -> float:
    """Euclidean distance between a victim's resources and the remaining
    deficit, normalized per-dimension (preemption.go basicResourceDistance
    :608)."""
    total = 0.0
    n = 0
    for d, a in (
        (delta.cpu, ask.cpu),
        (delta.memory_mb, ask.memory_mb),
        (delta.disk_mb, ask.disk_mb),
    ):
        if a > 0:
            total += ((d - a) / a) ** 2
            n += 1
    return math.sqrt(total / n) if n else 0.0


def select_victims(
    job: Job,
    node: Node,
    proposed: List[Allocation],
    ask: Resources,
    available: Resources,
) -> Optional[List[Allocation]]:
    """Pick allocs to evict so that ``ask`` fits in ``available`` + freed.

    Returns None when no admissible victim set covers the deficit.
    """
    deficit = Resources(
        cpu=max(0, ask.cpu - available.cpu),
        memory_mb=max(0, ask.memory_mb - available.memory_mb),
        disk_mb=max(0, ask.disk_mb - available.disk_mb),
    )
    if deficit.cpu == 0 and deficit.memory_mb == 0 and deficit.disk_mb == 0:
        return []

    threshold = job.priority - PREEMPTION_PRIORITY_DELTA
    candidates = [
        a
        for a in proposed
        if not a.terminal_status() and a.job_priority() < threshold
    ]
    # Lowest priority first, then best resource-distance match.
    candidates.sort(
        key=lambda a: (a.job_priority(), resource_distance(a.resources, deficit))
    )

    victims: List[Allocation] = []
    freed = Resources(cpu=0, memory_mb=0, disk_mb=0)
    for a in candidates:
        if (
            freed.cpu >= deficit.cpu
            and freed.memory_mb >= deficit.memory_mb
            and freed.disk_mb >= deficit.disk_mb
        ):
            break
        victims.append(a)
        freed.add(a.resources)

    if not (
        freed.cpu >= deficit.cpu
        and freed.memory_mb >= deficit.memory_mb
        and freed.disk_mb >= deficit.disk_mb
    ):
        return None

    # Superset elimination: drop victims whose removal still covers the
    # deficit (preemption.go filterSuperset :702).
    filtered: List[Allocation] = list(victims)
    for a in sorted(victims, key=lambda v: -v.job_priority()):
        without = Resources(
            cpu=freed.cpu - a.resources.cpu,
            memory_mb=freed.memory_mb - a.resources.memory_mb,
            disk_mb=freed.disk_mb - a.resources.disk_mb,
        )
        if (
            without.cpu >= deficit.cpu
            and without.memory_mb >= deficit.memory_mb
            and without.disk_mb >= deficit.disk_mb
        ):
            filtered.remove(a)
            freed = without
    return filtered
