"""Scheduler utilities (reference: scheduler/util.go).

``tainted_nodes`` mirrors util.go taintedNodes: the set of nodes whose allocs
must migrate (drain) or are lost (down/gone).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from ..structs.types import Allocation, Node, NodeStatus


def tainted_nodes(snapshot, allocs: Iterable[Allocation]) -> Dict[str, Optional[Node]]:
    """node_id -> Node (or None if the node no longer exists) for every node
    that is down, draining, or ineligible-due-to-drain, referenced by allocs."""
    out: Dict[str, Optional[Node]] = {}
    for alloc in allocs:
        if alloc.node_id in out:
            continue
        node = snapshot.node_by_id(alloc.node_id)
        if node is None:
            out[alloc.node_id] = None
            continue
        if node.status == NodeStatus.DOWN.value or node.drain:
            out[alloc.node_id] = node
    return out
