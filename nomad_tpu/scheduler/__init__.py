"""Scheduler package — pure logic over a state snapshot.

Mirrors the reference's ``scheduler/`` package boundary: a scheduler is a
pure function of (snapshot, eval) → plan submitted through a ``Planner``
(scheduler/scheduler.go:54-119). The ranking pipeline itself runs as
vectorized kernels on TPU (``nomad_tpu.ops.kernels``); this package is the
host orchestration around them.
"""

from .core import CoreScheduler
from .generic import GenericScheduler
from .system import SystemScheduler
from .stack import GenericStack, SystemStack

BUILTIN_SCHEDULERS = {
    "service": lambda *a, **kw: GenericScheduler("service", *a, **kw),
    "batch": lambda *a, **kw: GenericScheduler("batch", *a, **kw),
    "system": lambda *a, **kw: SystemScheduler(*a, **kw),
    "_core": lambda *a, **kw: CoreScheduler(*a, **kw),
}


def new_scheduler(sched_type: str, snapshot, planner, matrix=None):
    """Factory (reference: scheduler.NewScheduler, scheduler/scheduler.go:36)."""
    factory = BUILTIN_SCHEDULERS.get(sched_type)
    if factory is None:
        raise ValueError(f"unknown scheduler type {sched_type!r}")
    return factory(snapshot, planner, matrix)
