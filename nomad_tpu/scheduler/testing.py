"""Scheduler test harness — a real StateStore plus a fake Planner that
applies plans directly and records them.

Reference: scheduler/testing.go:40-279 (Harness, with RejectPlan at :17-38 to
force the stale-snapshot refresh path). This is tier 1 of the test strategy
(SURVEY.md §4): the kernels get golden-tested against real state here.
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Tuple

from ..state.store import StateSnapshot, StateStore
from ..structs.types import Evaluation, Plan, PlanResult


class Harness:
    def __init__(self, store: Optional[StateStore] = None):
        self.store = store if store is not None else StateStore()
        self.plans: List[Plan] = []
        self.evals: List[Evaluation] = []
        self.created_evals: List[Evaluation] = []
        self._index = itertools.count(1000)
        self.reject_plan = False  # RejectPlan (testing.go:17-38)
        self.partial_commit_nodes: set = set()  # nodes whose allocs drop

    def next_index(self) -> int:
        return next(self._index)

    # -- Planner interface ---------------------------------------------------

    def submit_plan(self, plan: Plan) -> Tuple[Optional[PlanResult], Optional[StateSnapshot]]:
        self.plans.append(plan)
        if self.reject_plan:
            # A rejected plan applies nothing; None forces the scheduler's
            # refresh-and-retry path regardless of plan contents.
            return None, self.store.snapshot()

        index = self.next_index()
        alloc_lists = {
            nid: [a for a in allocs]
            for nid, allocs in plan.node_allocation.items()
            if nid not in self.partial_commit_nodes
        }
        allocs = [a for lst in alloc_lists.values() for a in lst]
        allocs.extend(plan.alloc_updates)
        stops = [a for lst in plan.node_update.values() for a in lst]
        preempts = [a for lst in plan.node_preemptions.values() for a in lst]
        self.store.upsert_plan_results(
            index,
            allocs,
            stops,
            preempts,
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
        )
        result = PlanResult(
            node_allocation=alloc_lists,
            node_update=dict(plan.node_update),
            node_preemptions=dict(plan.node_preemptions),
            refresh_index=index,
            alloc_index=index,
        )
        snap = self.store.snapshot() if self.partial_commit_nodes else None
        return result, snap

    def update_eval(self, eval: Evaluation) -> None:
        self.evals.append(eval)
        self.store.upsert_evals(self.next_index(), [eval])

    def create_evals(self, evals: List[Evaluation]) -> None:
        self.created_evals.extend(evals)
        self.store.upsert_evals(self.next_index(), list(evals))

    def refresh_snapshot(self) -> StateSnapshot:
        return self.store.snapshot()

    def snapshot(self) -> StateSnapshot:
        return self.store.snapshot()

    def process(self, scheduler_factory, eval: Evaluation):
        """Run one scheduler invocation (testing.go Process)."""
        sched = scheduler_factory(self.snapshot(), self, self.store.matrix)
        sched.process(eval)
        return sched
