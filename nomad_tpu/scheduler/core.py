"""CoreScheduler — internal ``_core`` evals implementing garbage collection.

Reference: ``nomad/core_sched.go`` (``CoreScheduler.Process`` :44-67):
``_core`` evaluations are ordinary broker work items whose ``job_id``
selects the GC routine (eval-gc, job-gc, deployment-gc, node-gc, or the
force variants that ignore thresholds).  The reference converts GC
thresholds from raft indexes to wall-time with its ``timetable``; here
every object carries wall-clock timestamps/indexes directly, so the
thresholds are plain ages.

Deletions flow through the server's GC apply methods so they hit the WAL
and (later) the event stream like every other mutation.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional

from ..structs.types import EvalStatus, Evaluation, JobType

log = logging.getLogger(__name__)

# Job ids for core evals (core_sched.go job names).
CORE_JOB_EVAL_GC = "eval-gc"
CORE_JOB_JOB_GC = "job-gc"
CORE_JOB_DEPLOYMENT_GC = "deployment-gc"
CORE_JOB_NODE_GC = "node-gc"
CORE_JOB_FORCE_GC = "force-gc"

# Default thresholds (reference config defaults: EvalGCThreshold 1h,
# JobGCThreshold 4h, DeploymentGCThreshold 1h, NodeGCThreshold 24h).
EVAL_GC_THRESHOLD = 3600.0
JOB_GC_THRESHOLD = 4 * 3600.0
DEPLOYMENT_GC_THRESHOLD = 3600.0
NODE_GC_THRESHOLD = 24 * 3600.0


class CoreScheduler:
    """Processes ``_core`` evals (scheduler type ``_core``)."""

    def __init__(self, snapshot, planner, matrix=None):
        self.snapshot = snapshot
        self.planner = planner
        self.server = planner.server  # GC mutates through server applies

    # ------------------------------------------------------------------

    def process(self, ev: Evaluation) -> None:
        force = ev.job_id == CORE_JOB_FORCE_GC
        kind = ev.job_id
        # Job GC must precede eval GC in a forced sweep: eval GC deletes a
        # dead batch job's terminal evals+allocs, after which the job no
        # longer looks dead (batch-dead = "has allocs, all terminal") and
        # would survive every force-gc (the reference's forceGC runs jobGC
        # first for the same reason, core_sched.go).
        if force or kind == CORE_JOB_JOB_GC:
            self._job_gc(force)
        if force or kind == CORE_JOB_EVAL_GC:
            self._eval_gc(force)
        if force or kind == CORE_JOB_DEPLOYMENT_GC:
            self._deployment_gc(force)
        if force or kind == CORE_JOB_NODE_GC:
            self._node_gc(force)
        done = ev.copy()
        done.status = EvalStatus.COMPLETE.value
        self.planner.update_eval(done)

    # ------------------------------------------------------------------

    def _cutoff(self, threshold: float, force: bool) -> float:
        return time.time() if force else time.time() - threshold

    def _eval_gc(self, force: bool) -> None:
        """Terminal evals (and their terminal allocs) past the threshold
        (core_sched.go evalGC + gcEval)."""
        store = self.server.store
        cutoff = self._cutoff(EVAL_GC_THRESHOLD, force)
        gc_evals: List[str] = []
        gc_allocs: List[str] = []
        for ev in list(store.evals.values()):
            if not ev.terminal_status():
                continue
            if ev.create_time and ev.create_time > cutoff:
                continue
            allocs = store.allocs_by_eval(ev.id)
            # A batch job's evals/allocs are retained until the job is
            # GC'd (core_sched.go:139 batch carve-out).
            job = store.job_by_id(ev.namespace, ev.job_id)
            if (
                job is not None
                and job.type == JobType.BATCH.value
                and not job.stopped()
                and not force
            ):
                continue
            if any(not a.terminal_status() for a in allocs):
                continue
            gc_evals.append(ev.id)
            gc_allocs.extend(a.id for a in allocs)
        if gc_evals or gc_allocs:
            self.server.apply_gc(evals=gc_evals, allocs=gc_allocs)
            log.info("eval GC reaped %d evals / %d allocs",
                     len(gc_evals), len(gc_allocs))

    def _job_gc(self, force: bool) -> None:
        """Dead/stopped jobs with only terminal evals+allocs
        (core_sched.go jobGC)."""
        store = self.server.store
        cutoff = self._cutoff(JOB_GC_THRESHOLD, force)
        gc_jobs = []
        gc_evals: List[str] = []
        gc_allocs: List[str] = []
        for (ns, jid), job in list(store.jobs.items()):
            if job.is_periodic() and not job.stopped():
                continue
            if not (job.stopped() or self._job_dead(ns, jid, job)):
                continue
            if job.submit_time and job.submit_time > cutoff:
                continue
            evals = store.evals_by_job(ns, jid)
            allocs = store.allocs_by_job(ns, jid)
            if any(not e.terminal_status() for e in evals):
                continue
            if any(not a.terminal_status() for a in allocs):
                continue
            gc_jobs.append((ns, jid))
            gc_evals.extend(e.id for e in evals)
            gc_allocs.extend(a.id for a in allocs)
        if gc_jobs:
            self.server.apply_gc(
                jobs=gc_jobs, evals=gc_evals, allocs=gc_allocs
            )
            log.info("job GC reaped %d jobs", len(gc_jobs))

    def _job_dead(self, ns: str, jid: str, job) -> bool:
        if job.type == JobType.BATCH.value:
            allocs = self.server.store.allocs_by_job(ns, jid)
            return bool(allocs) and all(a.terminal_status() for a in allocs)
        return False

    def _deployment_gc(self, force: bool) -> None:
        store = self.server.store
        cutoff = self._cutoff(DEPLOYMENT_GC_THRESHOLD, force)
        gc = []
        for dep in list(store.deployments.values()):
            if dep.active():
                continue
            job = store.job_by_id(dep.namespace, dep.job_id)
            if (
                job is not None
                and not force
                and job.submit_time
                and job.submit_time > cutoff
            ):
                continue
            gc.append(dep.id)
        if gc:
            self.server.apply_gc(deployments=gc)
            log.info("deployment GC reaped %d deployments", len(gc))

    def _node_gc(self, force: bool) -> None:
        """Down nodes with no allocations (core_sched.go nodeGC)."""
        store = self.server.store
        cutoff = self._cutoff(NODE_GC_THRESHOLD, force)
        gc = []
        for node in list(store.nodes.values()):
            if not node.terminal():
                continue
            if not force and node.status_updated_at > cutoff:
                continue
            if store.allocs_by_node(node.id):
                continue
            gc.append(node.id)
        if gc:
            self.server.apply_gc(nodes=gc)
            log.info("node GC reaped %d nodes", len(gc))
