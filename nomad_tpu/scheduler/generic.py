"""Generic (service/batch) scheduler.

Reference: scheduler/generic_sched.go:125-328 — the retry loop around
(snapshot → reconcile → compute placements → submit plan), with blocked-eval
creation on placement failure (:193-212), partial-commit retry on a stale
snapshot, and follow-up evals for delayed reschedules.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..structs.types import (
    AllocClientStatus,
    AllocDeploymentStatus,
    AllocDesiredStatus,
    Allocation,
    AllocMetric,
    EvalStatus,
    EvalTrigger,
    Evaluation,
    Job,
    JobType,
    Plan,
    RescheduleEvent,
    RescheduleTracker,
    Resources,
)
from .context import EvalContext
from .preemption import select_victims
from .reconcile import (
    ALLOC_RESCHEDULED,
    ALLOC_UPDATING,
    AllocReconciler,
    PlaceRequest,
)
from .stack import GenericStack
from .util import tainted_nodes

# Retry bounds (reference: generic_sched.go:15-22).
MAX_SERVICE_SCHEDULE_ATTEMPTS = 5
MAX_BATCH_SCHEDULE_ATTEMPTS = 2

BLOCKED_EVAL_MAX_PLAN_DESC = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENTS = "created to place remaining allocations"


class SchedulerError(Exception):
    pass


class GenericScheduler:
    """One eval → one (or a few, on retry) plan submissions."""

    def __init__(self, sched_type: str, snapshot, planner, matrix=None):
        self.sched_type = sched_type
        self.batch = sched_type == JobType.BATCH.value
        self.snapshot = snapshot
        self.planner = planner
        self.matrix = matrix if matrix is not None else snapshot.store.matrix
        self.limit = (
            MAX_BATCH_SCHEDULE_ATTEMPTS
            if self.batch
            else MAX_SERVICE_SCHEDULE_ATTEMPTS
        )
        self.queued_allocs: Dict[str, int] = {}
        self.failed_tg_allocs: Dict[str, AllocMetric] = {}
        self.blocked: Optional[Evaluation] = None

    # ------------------------------------------------------------------

    def process(self, eval: Evaluation) -> None:
        ok = False
        for attempt in range(self.limit):
            ok, retry = self._attempt(eval)
            if ok or not retry:
                break
            # stale snapshot: refresh and try again (worker re-snapshot,
            # generic_sched.go:161-173)
            self.snapshot = self.planner.refresh_snapshot()
        if not ok and not self._no_work:
            self._fail_eval(eval, "maximum attempts reached")
            return
        self._finish_eval(eval)

    # ------------------------------------------------------------------

    _no_work = False

    def _attempt(self, eval: Evaluation):
        """Returns (success, retry)."""
        snap = self.snapshot
        job = snap.job_by_id(eval.namespace, eval.job_id)
        self.queued_allocs = {}
        self.failed_tg_allocs = {}

        plan = Plan(
            eval_id=eval.id,
            priority=eval.priority,
            job=job,
            snapshot_index=snap.snapshot_index,
            eval_token=eval.leader_ack,
        )
        ctx = EvalContext(snap, plan)

        allocs = snap.allocs_by_job(eval.namespace, eval.job_id)
        tainted = tainted_nodes(snap, allocs)
        deployment = snap.latest_deployment_by_job(eval.namespace, eval.job_id)

        reconciler = AllocReconciler(
            job_id=eval.job_id,
            job=job,
            existing=allocs,
            tainted=tainted,
            eval_id=eval.id,
            deployment=deployment,
            batch=self.batch,
        )
        results = reconciler.compute()
        # Annotations for `job plan` dry runs (scheduler/annotate.go:1-201
        # via structs.DesiredUpdates).
        self.last_desired_updates = dict(results.desired_tg_updates)
        # Placements made while an active same-version deployment is being
        # driven (next batches, canaries) attach to it (generic_sched.go
        # computePlacements deploymentID stamping).
        self._active_deployment = (
            deployment
            if deployment is not None
            and job is not None
            and deployment.job_version == job.version
            and deployment.active()
            else None
        )

        # Follow-up evals must exist before allocs reference them
        # (generic_sched.go createRescheduleLaterEvals ordering).
        if results.followup_evals:
            self.planner.create_evals(results.followup_evals)

        # Stops, delayed-reschedule stamps, and in-place updates.
        for stop in results.stop:
            plan.append_stopped_alloc(
                stop.alloc, stop.description, client_status=stop.client_status
            )
        plan.alloc_updates.extend(results.followup_updates)
        for upd in results.inplace:
            new = upd.alloc.copy()
            new.job = upd.new_job
            plan.append_alloc(new)
        for upd in results.destructive:
            plan.append_stopped_alloc(upd.alloc, ALLOC_UPDATING)
            results.place.append(
                PlaceRequest(
                    name=upd.alloc.name,
                    task_group=upd.new_job.lookup_task_group(
                        upd.alloc.task_group
                    ),
                    previous_alloc=upd.alloc,
                )
            )

        plan.deployment = results.deployment
        plan.deployment_updates = results.deployment_updates

        # Placements through the TPU stack.
        if job is not None and results.place:
            self._compute_placements(ctx, job, eval, results.place)

        if plan.is_no_op() and not self.failed_tg_allocs:
            self._no_work = True
            return True, False
        self._no_work = False

        result, new_snapshot = self.planner.submit_plan(plan)
        if result is None:
            return False, True

        # Update queued counts by what actually committed.
        full, expected, actual = result.full_commit(plan)
        if not full:
            # partial commit: retry against the refresh index snapshot
            if new_snapshot is not None:
                self.snapshot = new_snapshot
            return False, True
        return True, False

    # ------------------------------------------------------------------

    def _compute_placements(
        self,
        ctx: EvalContext,
        job: Job,
        eval: Evaluation,
        places: List[PlaceRequest],
    ) -> None:
        cfg = ctx.snapshot.scheduler_config()
        preemption_on = (
            cfg.preemption_config.batch_scheduler_enabled
            if self.batch
            else cfg.preemption_config.service_scheduler_enabled
        )
        stack = GenericStack(
            ctx,
            self.matrix,
            algorithm=cfg.scheduler_algorithm,
            preemption_enabled=preemption_on,
            batch=self.batch,
        )
        stack.set_job(job)
        replaced = {
            p.previous_alloc.id for p in places
            if p.previous_alloc is not None
        }
        for stops in ctx.plan.node_update.values():
            replaced.update(s.id for s in stops)
        stack.set_replaced(replaced)
        self._stack = stack

        # Group placement asks: requests with penalty nodes (reschedules)
        # place one-by-one; the rest batch through one kernel scan.
        by_tg: Dict[str, List[PlaceRequest]] = {}
        for p in places:
            if p.task_group is None:
                continue
            by_tg.setdefault(p.task_group.name, []).append(p)

        for tg_name, reqs in by_tg.items():
            tg = reqs[0].task_group
            sticky = (
                tg.ephemeral_disk.sticky if tg.ephemeral_disk else False
            )
            plain, penalized, preferred = [], [], []
            for p in reqs:
                if _penalty_nodes(p):
                    penalized.append(p)
                elif sticky and p.previous_alloc is not None:
                    preferred.append(p)
                else:
                    plain.append(p)

            if plain:
                options = stack.select(tg, n_placements=len(plain))
                for p, opt in zip(plain, options):
                    self._handle_option(ctx, job, eval, p, opt, tg)
            for p in preferred:
                # Sticky ephemeral disk: try the previous alloc's node
                # FIRST so local data survives the replacement; fall back
                # to a normal placement (findPreferredNode,
                # generic_sched.go:756-770).
                opts = stack.select(
                    tg, n_placements=1,
                    restrict_nodes=[p.previous_alloc.node_id],
                )
                if opts[0] is None:
                    opts = stack.select(tg, n_placements=1)
                self._handle_option(ctx, job, eval, p, opts[0], tg)
            for p in penalized:
                opts = stack.select(
                    tg, n_placements=1, penalty_nodes=_penalty_nodes(p)
                )
                self._handle_option(ctx, job, eval, p, opts[0], tg)

    def _handle_option(self, ctx, job, eval, place: PlaceRequest, opt, tg):
        if opt is None:
            # failed placement → blocked-eval accounting
            # (generic_sched.go:193-212)
            self.queued_allocs[tg.name] = self.queued_allocs.get(tg.name, 0) + 1
            metric = self.failed_tg_allocs.setdefault(tg.name, AllocMetric())
            metric.coalesced_failures += 1
            return

        resources = tg.combined_resources()
        alloc = Allocation(
            namespace=job.namespace,
            eval_id=eval.id,
            name=place.name,
            node_id=opt.node_id,
            node_name=opt.node.name,
            job_id=job.id,
            job=job,
            task_group=tg.name,
            resources=resources,
            desired_status=AllocDesiredStatus.RUN.value,
            client_status=AllocClientStatus.PENDING.value,
            metrics=opt.metric,
            assigned_ports=opt.assigned_ports,
            create_time=time.time(),
        )
        prev = place.previous_alloc
        if prev is not None:
            alloc.previous_allocation = prev.id
            if place.reschedule:
                tracker = (
                    prev.reschedule_tracker.events[:]
                    if prev.reschedule_tracker
                    else []
                )
                tracker.append(
                    RescheduleEvent(
                        reschedule_time=time.time(),
                        prev_alloc_id=prev.id,
                        prev_node_id=prev.node_id,
                    )
                )
                alloc.reschedule_tracker = RescheduleTracker(events=tracker)
                alloc.desired_description = ALLOC_RESCHEDULED
        deploy = ctx.plan.deployment or getattr(
            self, "_active_deployment", None
        )
        if deploy is not None:
            alloc.deployment_id = deploy.id
        if place.canary:
            alloc.deployment_status = AllocDeploymentStatus(canary=True)

        if opt.needs_preempt:
            node = opt.node
            proposed = ctx.proposed_allocs(node.id)
            avail = node.comparable_resources()
            used = Resources(cpu=0, memory_mb=0, disk_mb=0)
            for a in proposed:
                used.add(a.resources)
            remaining = Resources(
                cpu=avail.cpu - used.cpu,
                memory_mb=avail.memory_mb - used.memory_mb,
                disk_mb=avail.disk_mb - used.disk_mb,
            )
            victims = select_victims(job, node, proposed, resources, remaining)
            if victims is None:
                self.queued_allocs[tg.name] = (
                    self.queued_allocs.get(tg.name, 0) + 1
                )
                self.failed_tg_allocs.setdefault(tg.name, AllocMetric())
                return
            for v in victims:
                ctx.plan.append_preempted_alloc(v, alloc.id)

        ctx.plan.append_alloc(alloc)

    # ------------------------------------------------------------------

    def _finish_eval(self, eval: Evaluation) -> None:
        updated = eval.copy()
        updated.status = EvalStatus.COMPLETE.value
        updated.queued_allocations = dict(self.queued_allocs)
        updated.failed_tg_allocs = dict(self.failed_tg_allocs)

        # Blocked eval for failed placements (generic_sched.go:193-212).
        if self.failed_tg_allocs and eval.triggered_by != (
            EvalTrigger.MAX_PLAN_ATTEMPTS.value
        ):
            stack = getattr(self, "_stack", None)
            blocked = Evaluation(
                namespace=eval.namespace,
                priority=eval.priority,
                type=eval.type,
                triggered_by=EvalTrigger.QUEUED_ALLOCS.value,
                job_id=eval.job_id,
                status=EvalStatus.BLOCKED.value,
                status_description=BLOCKED_EVAL_FAILED_PLACEMENTS,
                previous_eval=eval.id,
                # Unblock keying (blocked_evals.go): which classes we saw
                # (in)eligible at this snapshot, and whether class caching
                # escaped to per-node checks.
                snapshot_index=self.snapshot.snapshot_index,
                class_eligibility=dict(stack.class_eligibility) if stack else {},
                escaped_computed_class=(
                    stack.escaped_computed_class if stack else True
                ),
            )
            updated.blocked_eval = blocked.id
            self.planner.create_evals([blocked])
        self.planner.update_eval(updated)

    def _fail_eval(self, eval: Evaluation, reason: str) -> None:
        updated = eval.copy()
        updated.status = EvalStatus.FAILED.value
        updated.status_description = reason
        self.planner.update_eval(updated)


def _penalty_nodes(place: PlaceRequest) -> List[str]:
    """Previous node ids penalized for a rescheduled placement
    (SelectOptions.PenaltyNodeIDs, generic_sched.go:694-716)."""
    if not place.reschedule or place.previous_alloc is None:
        return []
    prev = place.previous_alloc
    nodes = [prev.node_id]
    if prev.reschedule_tracker:
        nodes.extend(e.prev_node_id for e in prev.reschedule_tracker.events)
    return [n for n in dict.fromkeys(nodes) if n]
