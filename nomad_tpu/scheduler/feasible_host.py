"""Host-side constraint evaluation for non-vectorizable operators.

The kernels evaluate hash-equality, numeric and version predicates for every
node in one pass (ops/kernels.py). Operators that cannot vectorize — regexp,
set_contains, lexical ordering, multi-clause version ranges — escape here and
are evaluated **once per computed class** (the reference's own optimization:
ComputedClass feasibility cache, scheduler/feasible.go:1029,
nomad/structs/node_class.go:28-37), or per node for unique attributes.

Reference semantics: checkConstraint (feasible.go:793-858) and the operator
implementations at feasible.go:860-1020.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from ..state.matrix import node_attributes, version_value
from ..structs.types import Constraint, Node, Op

_regex_cache: Dict[str, Optional[re.Pattern]] = {}
_version_clause_re = re.compile(r"^\s*(>=|<=|>|<|=|!=|~>)?\s*v?([\d.]+)\s*$")


def _lookup_attr(node: Node, target: str) -> Optional[str]:
    """Resolve ``${attr.x}`` / ``${meta.y}`` / ``${node.class}`` to a value
    (reference: resolveTarget, feasible.go:748-790)."""
    name = target
    if name.startswith("${") and name.endswith("}"):
        name = name[2:-1]
    if name.startswith("attr."):
        name = name[len("attr.") :]
    attrs = node_attributes(node)
    return attrs.get(name) or None


def _check_regexp(value: str, pattern: str) -> bool:
    compiled = _regex_cache.get(pattern)
    if pattern not in _regex_cache:
        try:
            compiled = re.compile(pattern)
        except re.error:
            compiled = None
        _regex_cache[pattern] = compiled
    return compiled is not None and compiled.search(value) is not None


def _check_version(value: str, spec: str) -> bool:
    """Constraint-style version check supporting comma-separated clauses
    (e.g. ``>= 1.0, < 2.0``). ``~>`` is pessimistic (same major, >= given)."""
    packed = version_value(value)
    if packed != packed:  # NaN
        return False
    for clause in spec.split(","):
        m = _version_clause_re.match(clause)
        if not m:
            return False
        op = m.group(1) or "="
        want = version_value(m.group(2))
        if want != want:
            return False
        if op == "~>":
            parts = m.group(2).split(".")
            major = float(int(parts[0]))
            if not (packed >= want and (packed // 1e6) == major):
                return False
        elif op == ">=" and not packed >= want:
            return False
        elif op == "<=" and not packed <= want:
            return False
        elif op == ">" and not packed > want:
            return False
        elif op == "<" and not packed < want:
            return False
        elif op == "=" and not packed == want:
            return False
        elif op == "!=" and not packed != want:
            return False
    return True


def check_constraint_host(con: Constraint, node: Node) -> bool:
    """Evaluate one escaped constraint against one node."""
    operand = con.operand
    if operand == Op.IS_SET.value:
        return _lookup_attr(node, con.l_target) is not None
    if operand == Op.IS_NOT_SET.value:
        return _lookup_attr(node, con.l_target) is None

    value = _lookup_attr(node, con.l_target)
    if operand in (Op.NEQ.value, "not"):
        return value is None or value != con.r_target
    if value is None:
        return False

    if operand in (Op.EQ.value, "==", "is"):
        return value == con.r_target
    if operand == Op.REGEXP.value:
        return _check_regexp(value, con.r_target)
    if operand in (Op.VERSION.value, Op.SEMVER.value):
        return _check_version(value, con.r_target)
    if operand == Op.SET_CONTAINS.value:
        have = {p.strip() for p in value.split(",")}
        want = [p.strip() for p in con.r_target.split(",")]
        return all(w in have for w in want)
    if operand == Op.SET_CONTAINS_ANY.value:
        have = {p.strip() for p in value.split(",")}
        return any(p.strip() in have for p in con.r_target.split(","))
    # Lexical ordering fallback for non-numeric <, >, ... (feasible.go:918).
    if operand == Op.LT.value:
        return value < con.r_target
    if operand == Op.LTE.value:
        return value <= con.r_target
    if operand == Op.GT.value:
        return value > con.r_target
    if operand == Op.GTE.value:
        return value >= con.r_target
    return False


def check_host_volumes(node: Node, volumes: List[str]) -> bool:
    """HostVolumeChecker (feasible.go:132)."""
    return all(v in node.host_volumes for v in volumes)
