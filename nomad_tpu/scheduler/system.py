"""System scheduler — one alloc per feasible node.

Reference: scheduler/system_sched.go:22-54 (+ diffSystemAllocs in util.go).
Feasibility for the whole cluster is one kernel call
(SystemStack.feasible_nodes); the per-node diff stays host-side.
"""

from __future__ import annotations

import time
from typing import Dict, List

from ..structs.types import (
    AllocClientStatus,
    AllocDesiredStatus,
    Allocation,
    AllocMetric,
    EvalStatus,
    Evaluation,
    Plan,
)
from .context import EvalContext
from .reconcile import ALLOC_NOT_NEEDED, ALLOC_UPDATING, tasks_updated
from .stack import SystemStack
from .util import tainted_nodes

MAX_SYSTEM_SCHEDULE_ATTEMPTS = 5


class SystemScheduler:
    def __init__(self, snapshot, planner, matrix=None):
        self.snapshot = snapshot
        self.planner = planner
        self.matrix = matrix if matrix is not None else snapshot.store.matrix
        self.queued_allocs: Dict[str, int] = {}
        self.failed_tg_allocs: Dict[str, AllocMetric] = {}

    def process(self, eval: Evaluation) -> None:
        for _ in range(MAX_SYSTEM_SCHEDULE_ATTEMPTS):
            ok, retry = self._attempt(eval)
            if ok or not retry:
                break
            self.snapshot = self.planner.refresh_snapshot()
        self._finish_eval(eval)

    def _attempt(self, eval: Evaluation):
        snap = self.snapshot
        job = snap.job_by_id(eval.namespace, eval.job_id)
        self.queued_allocs = {}
        self.failed_tg_allocs = {}

        plan = Plan(
            eval_id=eval.id,
            priority=eval.priority,
            job=job,
            snapshot_index=snap.snapshot_index,
            eval_token=eval.leader_ack,
        )
        ctx = EvalContext(snap, plan)
        allocs = snap.allocs_by_job(eval.namespace, eval.job_id)
        tainted = tainted_nodes(snap, allocs)

        if job is None or job.stopped():
            for a in allocs:
                if not a.terminal_status():
                    plan.append_stopped_alloc(a, ALLOC_NOT_NEEDED)
            if not plan.is_no_op():
                self.planner.submit_plan(plan)
            return True, False

        stack = SystemStack(ctx, self.matrix)
        stack.set_job(job)
        # Allocs on tainted nodes are stopped below; only THEIR volume
        # claims may be looked through when re-placing (see set_replaced).
        stack.set_replaced({
            a.id for a in allocs
            if not a.terminal_status() and a.node_id in tainted
        })
        self._stack = stack  # eligibility telemetry for blocked-eval keying

        live_by_node_tg: Dict[tuple, List[Allocation]] = {}
        for a in allocs:
            if not a.terminal_status():
                live_by_node_tg.setdefault((a.node_id, a.task_group), []).append(a)

        for tg in job.task_groups:
            feasible, metric = stack.feasible_nodes(tg)
            feasible_set = set(feasible)

            # Feasible-but-exhausted nodes are reported as failures so the
            # shortfall is visible (placed + failed = eligible nodes) and a
            # blocked eval can retry when capacity frees (system_sched.go
            # failedTGAllocs + queuedAllocs accounting).
            if metric.nodes_exhausted > 0:
                m = metric.copy()
                m.coalesced_failures = metric.nodes_exhausted
                self.failed_tg_allocs[tg.name] = m
                self.queued_allocs[tg.name] = (
                    self.queued_allocs.get(tg.name, 0) + metric.nodes_exhausted
                )

            # Stop allocs on nodes no longer feasible / tainted.
            for (node_id, tg_name), node_allocs in list(live_by_node_tg.items()):
                if tg_name != tg.name:
                    continue
                node = snap.node_by_id(node_id)
                lost = node_id in tainted and (node is None or not node.drain)
                if node_id not in feasible_set or node_id in tainted:
                    for a in node_allocs:
                        plan.append_stopped_alloc(
                            a,
                            ALLOC_NOT_NEEDED,
                            client_status=(
                                AllocClientStatus.LOST.value if lost else ""
                            ),
                        )
                    del live_by_node_tg[(node_id, tg_name)]

            # Place/refresh one alloc per feasible node.
            for node_id in feasible:
                existing = live_by_node_tg.get((node_id, tg.name), [])
                if existing:
                    a = existing[0]
                    if a.job is not None and a.job.version == job.version:
                        continue
                    old_tg = a.job.lookup_task_group(tg.name) if a.job else None
                    if old_tg is not None and not tasks_updated(old_tg, tg):
                        new = a.copy()
                        new.job = job
                        plan.append_alloc(new)
                        continue
                    plan.append_stopped_alloc(a, ALLOC_UPDATING)
                node = snap.node_by_id(node_id)
                if node is None:
                    continue
                ports = stack._assign_ports(node, tg)
                if ports is None:
                    # Port shortfall is a failed placement too: it must
                    # reach failed_tg_allocs so a blocked eval parks and
                    # retries when the conflicting alloc frees the port.
                    self.queued_allocs[tg.name] = (
                        self.queued_allocs.get(tg.name, 0) + 1
                    )
                    m = self.failed_tg_allocs.get(tg.name)
                    if m is None:
                        m = metric.copy()
                        self.failed_tg_allocs[tg.name] = m
                    m.coalesced_failures += 1
                    continue
                alloc = Allocation(
                    namespace=job.namespace,
                    eval_id=eval.id,
                    name=f"{job.id}.{tg.name}[0]",
                    node_id=node_id,
                    node_name=node.name,
                    job_id=job.id,
                    job=job,
                    task_group=tg.name,
                    resources=tg.combined_resources(),
                    desired_status=AllocDesiredStatus.RUN.value,
                    client_status=AllocClientStatus.PENDING.value,
                    metrics=metric.copy(),
                    assigned_ports=ports,
                    create_time=time.time(),
                )
                plan.append_alloc(alloc)

        # Allocs of task groups removed from the job: stop (the generic
        # path's by_tg.pop leftover loop; reconcile.py).
        tg_names = {tg.name for tg in job.task_groups}
        for (node_id, tg_name), node_allocs in live_by_node_tg.items():
            if tg_name not in tg_names:
                for a in node_allocs:
                    plan.append_stopped_alloc(a, ALLOC_NOT_NEEDED)

        if plan.is_no_op():
            return True, False
        result, new_snapshot = self.planner.submit_plan(plan)
        if result is None:
            return False, True
        full, _, _ = result.full_commit(plan)
        if not full:
            if new_snapshot is not None:
                self.snapshot = new_snapshot
            return False, True
        return True, False

    def _finish_eval(self, eval: Evaluation) -> None:
        updated = eval.copy()
        updated.status = EvalStatus.COMPLETE.value
        updated.queued_allocations = dict(self.queued_allocs)
        updated.failed_tg_allocs = dict(self.failed_tg_allocs)

        # Exhausted/failed nodes park a blocked eval so the system job
        # retries when capacity frees (system_sched.go:142-152; unblocked
        # via BlockedEvals.unblock_node / class capacity events).
        if self.failed_tg_allocs:
            stack = getattr(self, "_stack", None)
            blocked = Evaluation(
                namespace=eval.namespace,
                priority=eval.priority,
                type=eval.type,
                triggered_by="queued-allocs",
                job_id=eval.job_id,
                status=EvalStatus.BLOCKED.value,
                status_description="created to place remaining system allocs",
                previous_eval=eval.id,
                snapshot_index=self.snapshot.snapshot_index,
                class_eligibility=(
                    dict(stack.class_eligibility) if stack else {}
                ),
                escaped_computed_class=(
                    stack.escaped_computed_class if stack else True
                ),
            )
            updated.blocked_eval = blocked.id
            self.planner.create_evals([blocked])
        self.planner.update_eval(updated)
