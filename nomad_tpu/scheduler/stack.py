"""Selection stacks — host orchestration of the vectorized ranking pipeline.

The reference's GenericStack is a 14-iterator pull chain walking sampled
nodes one at a time (scheduler/stack.go:324-417, sampling at :78-91). Here a
``select`` call compiles the task group once (ops/encode.py), builds the
plan-adjusted proposed usage, and invokes one fused kernel
(ops/kernels.place_task_group) that scores **all** nodes and places N allocs
in a lax.scan — the sampling trade-off disappears because scoring the full
cluster is one matrix pass on the MXU.

Host-side residue (SURVEY.md §7 hard-part b): combinatorial port/device
*assignment* happens only for the chosen node; non-vectorizable constraints
are evaluated per computed class (feasible_host.py); a rare post-check
failure masks the node and re-runs the kernel.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import trace
from ..ops.encode import (
    CompiledTaskGroup,
    MAX_SPREAD_VALUES,
    RequestEncoder,
    pow2_bucket as _pow2_bucket,
)
from ..ops import fake_device, kernels
from ..state.matrix import DEVICE_LOCK, NodeMatrix, node_attributes, stable_hash
from ..structs.types import (
    Allocation,
    AllocMetric,
    Job,
    Node,
    Op,
    TaskGroup,
)
from .context import EvalContext
from .feasible_host import check_constraint_host, check_host_volumes

# Dynamic port range (reference: structs/network.go MinDynamicPort/MaxDynamicPort).
from ..state.matrix import MAX_DYNAMIC_PORT, MIN_DYNAMIC_PORT  # noqa: E402
# (canonical port-range constants live beside the port bitmap encoding)

# Placement chunk ceiling: bounds the set of lax.scan lengths the jit cache
# ever sees to {1, 2, 4, 8, 16} (SURVEY.md §7 hard-part e).
PLACEMENT_CHUNK = 16
# Bound on kernel re-entries after host-side rejections (gone node, port
# conflict) or preemption-assisted picks.
MAX_SELECT_RETRIES = 8

# Solo-path occupancy ratchet (mirrors DeviceCoalescer._features): the
# Features bucket widens monotonically across the process, so the jit cache
# sees a short chain of variants instead of flapping per request.  Mutated
# only on the device thread (dev_op closures run serialized).
_solo_features: Optional[kernels.Features] = None


def _ratchet_features(request) -> kernels.Features:
    global _solo_features
    feats = kernels.features_of(request)
    _solo_features = (
        feats if _solo_features is None else _solo_features.widen(feats)
    )
    return _solo_features


def _dense_used0(arrays, deltas: Dict[int, np.ndarray]):
    """Proposed base usage: matrix usage + sparse per-row plan deltas.
    Device code — call on the device thread (dev_op closures)."""
    import jax.numpy as jnp

    used0 = arrays.used
    if deltas:
        rows = np.fromiter(deltas.keys(), np.int32)
        dvals = np.stack([deltas[r] for r in rows])
        used0 = used0.at[jnp.asarray(rows)].add(jnp.asarray(dvals))
    return used0


def _full_mask(n: int, host_mask: Optional[np.ndarray]) -> np.ndarray:
    """host_mask with the all-pass default materialized."""
    return host_mask if host_mask is not None else np.ones((n,), bool)


def _pad_width(arr: np.ndarray, n: int, fill) -> np.ndarray:
    """Pad a node-axis array to width ``n`` — the matrix can grow between
    building host inputs and the dev_op running on the device thread; new
    rows get the conservative fill (False/0: not host-checked this round)."""
    if arr.shape[0] >= n:
        return arr
    out = np.full((n,) + arr.shape[1:], fill, arr.dtype)
    out[: arr.shape[0]] = arr
    return out


@dataclass
class SelectionOption:
    """One placement decision (reference: rank.RankedNode)."""

    node_id: str
    node: Node
    row: int
    final_score: float
    binpack_score: float
    needs_preempt: bool
    metric: AllocMetric = field(default_factory=AllocMetric)
    # task -> {label: port} assigned host-side for the chosen node
    assigned_ports: Dict[str, Dict[str, int]] = field(default_factory=dict)
    # Fused-path advisory: the device's sequential cross-lane AllocsFit
    # verdict for this placement (False = an earlier lane in the same
    # launch claimed the capacity — the applier will reject this plan at an
    # unchanged matrix version).  None on the staged/solo paths.
    fit_verified: Optional[bool] = None




class GenericStack:
    """Service/batch ranking stack (reference: stack.go:324-417)."""

    def __init__(
        self,
        ctx: EvalContext,
        matrix: NodeMatrix,
        algorithm: str = "binpack",
        preemption_enabled: bool = False,
        batch: bool = False,
    ):
        self.ctx = ctx
        self.matrix = matrix
        self.algorithm = algorithm
        self.preemption_enabled = preemption_enabled
        self.batch = batch
        # Shared, matrix-lifetime encoder: stacks are rebuilt per eval, so a
        # per-stack encoder would discard the compile cache every eval.
        self.encoder: RequestEncoder = matrix.shared_encoder()
        self.job: Optional[Job] = None
        # Eligibility telemetry consumed by blocked-eval creation
        # (reference: EvalEligibility, context.go:190; fills the eval's
        # ClassEligibility / EscapedComputedClass fields).
        self.class_eligibility: Dict[str, bool] = {}
        self.escaped_computed_class = False
        # Alloc ids this pass is replacing or stopping — the ONLY live
        # volume claims a new placement may look through (set_replaced).
        self.replaced_allocs: set = set()

    def set_job(self, job: Job) -> None:
        self.job = job

    def set_replaced(self, alloc_ids) -> None:
        """Declare the allocs this scheduling pass replaces/stops; their
        volume claims don't block placement (the reconciler releases them
        in the same plan)."""
        self.replaced_allocs = set(alloc_ids)

    def _record_eligibility(self, class_elig: np.ndarray, host_mask) -> None:
        for key, cid in self.matrix.class_ids.items():
            if cid < len(class_elig):
                self.class_eligibility[key] = bool(class_elig[cid])
        if host_mask is not None:
            # Per-node (class-unhashable) checks were in play — the eval
            # escapes class caching and must retry on any capacity change.
            self.escaped_computed_class = True

    # -- proposed-state assembly -------------------------------------------

    def _plan_usage_deltas(self) -> Dict[int, np.ndarray]:
        """Net (cpu, mem, disk) the in-flight plan adds per node row."""
        deltas: Dict[int, np.ndarray] = {}
        plan = self.ctx.plan

        def add(node_id: str, res, sign: float) -> None:
            row = self.matrix.row_of.get(node_id)
            if row is None:
                return
            d = deltas.setdefault(row, np.zeros(3, np.float32))
            d += sign * np.array([res.cpu, res.memory_mb, res.disk_mb], np.float32)

        for node_id, allocs in plan.node_allocation.items():
            for a in allocs:
                add(node_id, a.resources, 1.0)
        for node_id, allocs in plan.node_update.items():
            for a in allocs:
                add(node_id, a.resources, -1.0)
        for node_id, allocs in plan.node_preemptions.items():
            for a in allocs:
                add(node_id, a.resources, -1.0)
        return deltas

    def _tg_counts(self, job: Job, tg: TaskGroup) -> Dict[int, int]:
        """Proposed allocs of this job+TG per node row (JobAntiAffinity and
        distinct_hosts inputs)."""
        counts: Dict[int, int] = {}
        plan = self.ctx.plan
        removed = self.ctx.plan_removed_ids()
        for a in self.ctx.snapshot.allocs_by_job(job.namespace, job.id):
            if a.terminal_status() or a.id in removed or a.task_group != tg.name:
                continue
            row = self.matrix.row_of.get(a.node_id)
            if row is not None:
                counts[row] = counts.get(row, 0) + 1
        for node_id, allocs in plan.node_allocation.items():
            n = sum(1 for a in allocs if a.task_group == tg.name)
            if n:
                row = self.matrix.row_of.get(node_id)
                if row is not None:
                    counts[row] = counts.get(row, 0) + n
        return counts

    def _spread_counts(
        self, job: Job, tg: TaskGroup, compiled: CompiledTaskGroup
    ) -> np.ndarray:
        """(S, V) usage counts per attribute value, aligned/extended against
        the compiled s_value_hash table (propertyset.go usage tracking)."""
        req = compiled.request
        s_hash = req.s_value_hash.copy()
        counts = np.zeros_like(s_hash, np.float32)
        if not compiled.spreads:
            return counts
        removed = self.ctx.plan_removed_ids()
        live = [
            a
            for a in self.ctx.snapshot.allocs_by_job(job.namespace, job.id)
            if not a.terminal_status() and a.id not in removed
            and a.task_group == tg.name
        ]
        for allocs in self.ctx.plan.node_allocation.values():
            live.extend(a for a in allocs if a.task_group == tg.name)
        for si, sp in enumerate(compiled.spreads[: s_hash.shape[0]]):
            if req.s_slot[si] < 0:
                continue
            name = sp.attribute
            if name.startswith("${") and name.endswith("}"):
                name = name[2:-1]
            if name.startswith("attr."):
                name = name[len("attr.") :]
            for a in live:
                node = self.ctx.snapshot.node_by_id(a.node_id)
                if node is None:
                    continue
                value = node_attributes(node).get(name)
                if not value:
                    continue
                h = stable_hash(value)
                idx = np.where(s_hash[si] == h)[0]
                if idx.size:
                    counts[si, idx[0]] += 1.0
                else:
                    free = np.where(s_hash[si] == 0)[0]
                    if free.size:
                        s_hash[si, free[0]] = h
                        counts[si, free[0]] = 1.0
        # persist discovered values into the request copy used by the kernel
        compiled.request = req._replace(s_value_hash=s_hash)
        return counts

    def _class_eligibility(self, compiled: CompiledTaskGroup) -> np.ndarray:
        """Evaluate escaped non-unique constraints once per computed class
        (the ComputedClass cache, feasible.go:1029). Returns a padded bool
        vector indexed by class id."""
        n_classes = max(1, len(self.matrix.class_ids))
        pad = _pow2_bucket(n_classes)
        elig = np.ones((pad,), bool)
        escaped = [
            e.constraint
            for e in compiled.escaped
            if not e.unique
            and e.constraint.operand
            not in (Op.DISTINCT_HOSTS.value, Op.DISTINCT_PROPERTY.value)
        ]
        if not escaped:
            return elig
        for cid, rep_node_id in self.matrix.class_repr.items():
            node = self.ctx.snapshot.node_by_id(rep_node_id)
            if node is None:
                continue
            ok = all(check_constraint_host(c, node) for c in escaped)
            if cid < pad:
                elig[cid] = ok
        return elig

    def _volume_claimable(self, vol, vreq, job: Job) -> bool:
        """Do the volume's live claims admit this request?  Claims from
        terminal (or vanished) allocs don't count — the volume watcher
        releases them lazily; claims from allocs this pass replaces/stops
        (set_replaced) don't block their own replacement.  A blanket
        same-job exemption would let two LIVE allocs of one job
        double-claim a single-node-writer volume."""
        if vreq.read_only or vol.access_mode == "multi-node-multi-writer":
            return True
        if vol.access_mode != "single-node-writer":
            return False  # reader-only volume cannot take a writer
        snap = self.ctx.snapshot
        for alloc_id in vol.write_claims:
            a = snap.alloc_by_id(alloc_id) if hasattr(
                snap, "alloc_by_id"
            ) else None
            if a is None or a.terminal_status():
                continue
            if alloc_id in self.replaced_allocs:
                continue
            return False
        return True

    def _host_mask(
        self, job: Job, tg: TaskGroup, compiled: CompiledTaskGroup
    ) -> Optional[np.ndarray]:
        """Per-node mask for unique-attr escapes, distinct_hosts,
        distinct_property, host volumes, and escaped device asks. None when
        nothing applies (the common case — no O(N) host walk)."""
        n = self.matrix.capacity
        mask: Optional[np.ndarray] = None

        def ensure() -> np.ndarray:
            nonlocal mask
            if mask is None:
                mask = np.ones((n,), bool)
            return mask

        unique = [e.constraint for e in compiled.escaped if e.unique]
        distinct_hosts = any(
            e.constraint.operand == Op.DISTINCT_HOSTS.value for e in compiled.escaped
        )
        distinct_props = [
            e.constraint
            for e in compiled.escaped
            if e.constraint.operand == Op.DISTINCT_PROPERTY.value
        ]

        # Registered-volume feasibility (CSIVolumeChecker, feasible.go:209):
        # the volume must exist, its claims must admit this request, and
        # only nodes exposing its backing host volume qualify.
        csi_sources: List[str] = []
        for vreq in compiled.csi_volumes:
            vol = self.ctx.snapshot.volume_by_id(job.namespace, vreq.source)
            if vol is None or not self._volume_claimable(vol, vreq, job):
                return np.zeros((n,), bool)  # nothing feasible → blocked
            csi_sources.append(vol.source)

        if (
            unique or compiled.host_volumes or csi_sources
            or compiled.escaped_devices or compiled.dc_escaped
        ):
            m = ensure()
            dcs = set(job.datacenters)
            for node_id, row in self.matrix.row_of.items():
                node = self.ctx.snapshot.node_by_id(node_id)
                if node is None:
                    m[row] = False
                    continue
                if compiled.dc_escaped and node.datacenter not in dcs:
                    m[row] = False
                    continue
                if unique and not all(
                    check_constraint_host(c, node) for c in unique
                ):
                    m[row] = False
                    continue
                if compiled.host_volumes and not check_host_volumes(
                    node, compiled.host_volumes
                ):
                    m[row] = False
                    continue
                if csi_sources and not check_host_volumes(
                    node, csi_sources
                ):
                    m[row] = False
                    continue
                for name, count in compiled.escaped_devices:
                    if len(node.resources.devices.get(name, [])) < count:
                        m[row] = False
                        break

        if distinct_hosts:
            # Mask nodes already holding a proposed alloc of this job
            # (DistinctHostsIterator, feasible.go:505).
            m = ensure()
            removed = self.ctx.plan_removed_ids()
            for a in self.ctx.snapshot.allocs_by_job(job.namespace, job.id):
                if a.terminal_status() or a.id in removed:
                    continue
                row = self.matrix.row_of.get(a.node_id)
                if row is not None:
                    m[row] = False
            for node_id, allocs in self.ctx.plan.node_allocation.items():
                if allocs:
                    row = self.matrix.row_of.get(node_id)
                    if row is not None:
                        m[row] = False

        for con in distinct_props:
            # DistinctPropertyIterator (feasible.go:604): limit allocs of the
            # job per distinct value of the property.
            m = ensure()
            limit = int(con.r_target) if str(con.r_target).isdigit() else 1
            name = con.l_target
            if name.startswith("${") and name.endswith("}"):
                name = name[2:-1]
            if name.startswith("attr."):
                name = name[len("attr.") :]
            counts: Dict[str, int] = {}
            removed = self.ctx.plan_removed_ids()
            live = [
                a
                for a in self.ctx.snapshot.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status() and a.id not in removed
            ]
            for allocs in self.ctx.plan.node_allocation.values():
                live.extend(allocs)
            for a in live:
                anode = self.ctx.snapshot.node_by_id(a.node_id)
                if anode is None:
                    continue
                v = node_attributes(anode).get(name)
                if v:
                    counts[v] = counts.get(v, 0) + 1
            for node_id, row in self.matrix.row_of.items():
                node = self.ctx.snapshot.node_by_id(node_id)
                if node is None:
                    continue
                v = node_attributes(node).get(name)
                if v is not None and counts.get(v, 0) >= limit:
                    m[row] = False
        return mask

    # -- port assignment (host-side, chosen node only) ----------------------

    def _assign_ports(
        self, node: Node, tg: TaskGroup, extra_used: Optional[set] = None
    ) -> Optional[Dict[str, Dict[str, int]]]:
        """Assign reserved + dynamic ports on the chosen node; None on
        conflict (NetworkIndex equivalent, nomad/structs/network.go:35).
        ``extra_used``: ports handed out earlier in the same select batch,
        before the plan reflects them."""
        if not tg.networks and not any(
            t.resources.networks for t in tg.tasks
        ):
            # No port asks — skip the proposed-allocs walk entirely.  That
            # walk (every live alloc on the node, through the MVCC snapshot
            # wrapper) was the single hottest worker frame for port-less
            # jobs, which place on every node the kernel picks.
            return {}
        used = set(node.reserved.reserved_ports)
        if extra_used:
            used |= extra_used
        for a in self.ctx.proposed_allocs(node.id):
            for nets in a.assigned_ports.values():
                used.update(nets.values())
            for net in a.resources.networks:
                used.update(net.reserved_ports)

        result: Dict[str, Dict[str, int]] = {}
        nets = list(tg.networks) + [
            n for t in tg.tasks for n in t.resources.networks
        ]
        owners = ["group"] * len(tg.networks) + [
            t.name for t in tg.tasks for _ in t.resources.networks
        ]
        cursor = MIN_DYNAMIC_PORT
        for net, owner in zip(nets, owners):
            ports: Dict[str, int] = {}
            for port in net.reserved_ports:
                if port in used:
                    return None
                used.add(port)
                ports[str(port)] = port
            for label in net.dynamic_ports:
                while cursor in used and cursor <= MAX_DYNAMIC_PORT:
                    cursor += 1
                if cursor > MAX_DYNAMIC_PORT:
                    return None
                used.add(cursor)
                ports[label] = cursor
            if ports:
                result.setdefault(owner, {}).update(ports)
        return result

    # -- the main entry ------------------------------------------------------

    def select(
        self,
        tg: TaskGroup,
        n_placements: int = 1,
        penalty_nodes: Optional[Sequence[str]] = None,
        restrict_nodes: Optional[Sequence[str]] = None,
    ) -> List[Optional[SelectionOption]]:
        """Place ``n_placements`` allocs of ``tg``; one option (or None) per
        requested placement (reference: stack.go:117-179 Select, called per
        missing alloc from generic_sched.go:472).  ``restrict_nodes`` limits
        candidates to the given set (sticky ephemeral-disk preference,
        generic_sched.go:756-770 findPreferredNode).

        With a coalescer attached to the matrix (the live server), the
        kernel call is batched with other workers' selects and this method
        never touches the device directly; otherwise the whole selection
        holds DEVICE_LOCK (tests, solo tools)."""
        if getattr(self.matrix, "coalescer", None) is not None:
            return self._select_locked(
                tg, n_placements, penalty_nodes, restrict_nodes
            )
        with DEVICE_LOCK:
            return self._select_locked(
                tg, n_placements, penalty_nodes, restrict_nodes
            )

    # -- kernel dispatch (coalesced or solo) --------------------------------

    def _dispatch_place(
        self,
        compiled: CompiledTaskGroup,
        deltas: Dict[int, np.ndarray],
        tg_count: np.ndarray,
        spread_counts: np.ndarray,
        penalty: np.ndarray,
        class_elig: np.ndarray,
        host_mask: Optional[np.ndarray],
        remaining: int,
    ):
        """Run one placement scan; returns host-side arrays (rows, scores,
        binpack, preempted, n_eval, n_filt, n_exh, fit_verified) of scan
        length ≥ the bucket for ``remaining``.  fit_verified is None unless
        the fused megakernel path supplied its cross-lane verify column.

        With a mesh configured the coalescer routes the batch through the
        node-sharded fused entry (parallel/sharding.py, hierarchical
        top-k); either way the rows returned here are GLOBAL and already
        translated through any shard-preserving capacity growth that
        happened while the dispatch was in flight (matrix.translate_rows),
        so the node_of lookup below never sees a pre-relocation id."""
        from .coalescer import MAX_DELTA_ROWS, megabatch_enabled

        # One consistent width for every per-node array in this request:
        # re-reading matrix.capacity here could disagree with the shapes the
        # caller built if a node registration grew the matrix mid-select.
        n = tg_count.shape[0]
        coal = getattr(self.matrix, "coalescer", None)
        if coal is not None and len(deltas) <= MAX_DELTA_ROWS:
            drows = np.full((MAX_DELTA_ROWS,), -1, np.int32)
            dvals = np.zeros((MAX_DELTA_ROWS, 3), np.float32)
            for i, (row, d) in enumerate(deltas.items()):
                drows[i] = row
                dvals[i] = d
            out = coal.place(
                compiled.request,
                drows,
                dvals,
                tg_count,
                spread_counts,
                penalty,
                class_elig,
                host_mask if host_mask is not None
                else self.matrix.shared_masks()[1],
                n_live=remaining,
            )
            return (
                out.rows, out.scores, out.binpack, out.preempted,
                out.nodes_evaluated, out.nodes_filtered, out.nodes_exhausted,
                out.fit_verified,
            )

        # Solo path: dense proposed usage, one direct dispatch.  With a
        # coalescer present (live server) the closure still executes on ITS
        # thread — the tunnel client wedges under concurrent device use.
        def dev_op():
            arrays = self.matrix.sync()
            n_dev = int(arrays.used.shape[0])
            bucket = min(_pow2_bucket(remaining), PLACEMENT_CHUNK)
            if fake_device.enabled():
                result = fake_device.place_task_group(
                    arrays,
                    compiled.request,
                    fake_device.dense_used0(arrays, deltas),
                    _pad_width(tg_count, n_dev, 0),
                    spread_counts,
                    _pad_width(penalty, n_dev, False),
                    class_elig,
                    _pad_width(_full_mask(n, host_mask), n_dev, False),
                    n_placements=bucket,
                )
                return (
                    result.rows, result.scores, result.binpack,
                    result.preempted, result.nodes_evaluated,
                    result.nodes_filtered, result.nodes_exhausted,
                    None,
                )

            import jax.numpy as jnp

            feats = (
                _ratchet_features(compiled.request)
                if megabatch_enabled() else kernels.FULL_FEATURES
            )
            result = kernels.place_task_group(
                arrays,
                compiled.request,
                _dense_used0(arrays, deltas),
                jnp.asarray(_pad_width(tg_count, n_dev, 0)),
                jnp.asarray(spread_counts),
                jnp.asarray(_pad_width(penalty, n_dev, False)),
                jnp.asarray(class_elig),
                jnp.asarray(_pad_width(_full_mask(n, host_mask), n_dev, False)),
                n_placements=bucket,
                features=feats,
            )
            return (
                np.asarray(result.rows),
                np.asarray(result.scores),
                np.asarray(result.binpack),
                np.asarray(result.preempted),
                np.asarray(result.nodes_evaluated),
                np.asarray(result.nodes_filtered),
                np.asarray(result.nodes_exhausted),
                None,
            )

        return self.matrix.run_on_device(dev_op)

    def _select_locked(
        self,
        tg: TaskGroup,
        n_placements: int = 1,
        penalty_nodes: Optional[Sequence[str]] = None,
        restrict_nodes: Optional[Sequence[str]] = None,
    ) -> List[Optional[SelectionOption]]:
        assert self.job is not None, "set_job first"
        job = self.job
        start = time.monotonic()

        sched_cfg = self.ctx.snapshot.scheduler_config()
        with trace.span("sched.encode"):
            compiled = self.encoder.compile(
                job,
                tg,
                algorithm=self.algorithm,
                preemption_enabled=self.preemption_enabled,
            )

        n = self.matrix.capacity

        if penalty_nodes:
            penalty = np.zeros((n,), bool)
            for node_id in penalty_nodes:
                row = self.matrix.row_of.get(node_id)
                if row is not None:
                    penalty[row] = True
        else:
            # Steady state: no penalized nodes — reuse the matrix-wide
            # read-only all-False mask instead of allocating per eval.
            penalty = self.matrix.shared_masks()[0]

        with trace.span("sched.feasibility"):
            class_elig = self._class_eligibility(compiled)
            base_host_mask = self._host_mask(job, tg, compiled)
        self._record_eligibility(class_elig, base_host_mask)
        if restrict_nodes is not None:
            allowed = np.zeros((n,), bool)
            for node_id in restrict_nodes:
                row = self.matrix.row_of.get(node_id)
                if row is not None:
                    allowed[row] = True
            base_host_mask = (
                allowed if base_host_mask is None
                else (base_host_mask & allowed)
            )

        options: List[Optional[SelectionOption]] = []
        banned_rows: List[int] = []
        # Accounting for selections made in *earlier kernel calls of this
        # select()*: the plan only learns about them after select returns, so
        # later chunks/retries must fold them in here to avoid over-commit.
        chosen_rows: List[int] = []
        chosen_ports: Dict[str, set] = {}
        remaining = n_placements
        retries = 0
        while remaining > 0 and retries <= MAX_SELECT_RETRIES:
            host_mask = base_host_mask
            if banned_rows:
                host_mask = (
                    np.ones((n,), bool) if host_mask is None else host_mask.copy()
                )
                host_mask[banned_rows] = False

            deltas = self._plan_usage_deltas()
            for row in chosen_rows:
                d = deltas.setdefault(row, np.zeros(3, np.float32))
                d += np.asarray(compiled.request.ask, np.float32)

            tg_counts = self._tg_counts(job, tg)
            for row in chosen_rows:
                tg_counts[row] = tg_counts.get(row, 0) + 1
            if tg_counts:
                tg_count = np.zeros((n,), np.int32)
                for row, c in tg_counts.items():
                    tg_count[row] = c
            else:
                # First placement pass of a fresh job: no proposed allocs
                # anywhere — reuse the matrix-wide read-only zero vector.
                tg_count = self.matrix.shared_zero_i32()

            spread_counts = self._spread_counts(job, tg, compiled)

            # Binpack + score are fused into the placement kernel, so one
            # span covers the whole device dispatch (launch + result wait).
            with trace.span("sched.dispatch", lanes=remaining):
                (rows_all, scores_all, binpack_all, preempted_all, n_eval_all,
                 n_filt_all, n_exh_all, verified_all) = self._dispatch_place(
                    compiled, deltas, tg_count, spread_counts, penalty,
                    class_elig, host_mask, remaining,
                )
            take = min(len(rows_all), remaining)
            rows_out = rows_all[:take]
            scores = scores_all[:take]
            binpack = binpack_all[:take]
            preempted = preempted_all[:take]
            n_eval = n_eval_all[:take]
            n_filt = n_filt_all[:take]
            n_exh = n_exh_all[:take]

            retry = False
            for i, row in enumerate(rows_out):
                metric = AllocMetric(
                    nodes_evaluated=int(n_eval[i]),
                    nodes_filtered=int(n_filt[i]),
                    nodes_exhausted=int(n_exh[i]),
                )
                metric.allocation_time = time.monotonic() - start
                if row < 0:
                    options.append(None)
                    remaining -= 1
                    continue
                node_id = self.matrix.node_of.get(int(row))
                node = (
                    self.ctx.snapshot.node_by_id(node_id) if node_id else None
                )
                if node is None:
                    banned_rows.append(int(row))
                    retries += 1
                    retry = True
                    break
                # Host-side combinatorial residue: port assignment, aware of
                # ports handed out earlier in this same batch.
                ports = self._assign_ports(
                    node, tg, extra_used=chosen_ports.get(node_id)
                )
                if ports is None:
                    banned_rows.append(int(row))
                    retries += 1
                    retry = True
                    break
                metric.score_node(node_id, "binpack", float(binpack[i]))
                metric.score_node(node_id, "final", float(scores[i]))
                opt = SelectionOption(
                    node_id=node_id,
                    node=node,
                    row=int(row),
                    final_score=float(scores[i]),
                    binpack_score=float(binpack[i]),
                    needs_preempt=bool(preempted[i]),
                    metric=metric,
                    assigned_ports=ports,
                    fit_verified=(
                        bool(verified_all[i])
                        if verified_all is not None else None
                    ),
                )
                options.append(opt)
                chosen_rows.append(int(row))
                if ports:
                    bag = chosen_ports.setdefault(node_id, set())
                    for per_task in ports.values():
                        bag.update(per_task.values())
                remaining -= 1
                if bool(preempted[i]):
                    # A preemption-assisted pick changes proposed state in a
                    # way the in-scan accounting can't see (victims are chosen
                    # host-side afterwards); re-enter conservatively — the
                    # chosen_rows delta keeps this node's ask accounted.
                    retries += 1
                    retry = True
                    break
            if not retry:
                # Results beyond `take` from this chunk are discarded;
                # remaining placements loop around with updated accounting.
                continue

        while len(options) < n_placements:
            options.append(None)
        return options


class SystemStack(GenericStack):
    """System-job stack: feasibility for every node at once
    (reference: stack.go:183-321; the system scheduler places one alloc per
    feasible node, system_sched.go:22-54)."""

    def feasible_nodes(self, tg: TaskGroup) -> Tuple[List[str], AllocMetric]:
        assert self.job is not None
        job = self.job
        with trace.span("sched.encode"):
            compiled = self.encoder.compile(
                job, tg, algorithm=self.algorithm, preemption_enabled=False
            )
        with trace.span("sched.feasibility"):
            class_elig = self._class_eligibility(compiled)
            host_mask = self._host_mask(job, tg, compiled)
        self._record_eligibility(class_elig, host_mask)
        n = self.matrix.capacity

        # Fit must judge the node *without* this job's own TG alloc — a
        # re-evaluation replaces it, it doesn't stack a second copy — and
        # with the in-flight plan's stops/placements folded in.
        deltas = self._plan_usage_deltas()
        for a in self.ctx.snapshot.allocs_by_job(job.namespace, job.id):
            if a.terminal_status() or a.task_group != tg.name:
                continue
            row = self.matrix.row_of.get(a.node_id)
            if row is None:
                continue
            d = deltas.setdefault(row, np.zeros(3, np.float32))
            r = a.resources
            d -= np.array([r.cpu, r.memory_mb, r.disk_mb], np.float32)

        def dev_op():
            arrays = self.matrix.sync()
            n_dev = int(arrays.used.shape[0])
            if fake_device.enabled():
                return fake_device.system_feasible(
                    arrays,
                    fake_device.dense_used0(arrays, deltas),
                    compiled.request,
                    class_elig,
                    _pad_width(_full_mask(n, host_mask), n_dev, False),
                )

            import jax.numpy as jnp

            # One stacked (2, N) result = one device→host fetch (each
            # separate fetch costs a tunnel round-trip).
            return np.asarray(kernels.system_feasible(
                arrays,
                _dense_used0(arrays, deltas),
                compiled.request,
                jnp.asarray(class_elig),
                jnp.asarray(
                    _pad_width(_full_mask(n, host_mask), n_dev, False)
                ),
            ))

        with trace.span("sched.dispatch"):
            mf = self.matrix.run_on_device(dev_op)
        mask, fits = mf[0], mf[1]
        ok = mask & fits
        metric = AllocMetric(
            nodes_evaluated=int(mask.sum()),
            nodes_filtered=int((~mask).sum()),
            nodes_exhausted=int((mask & ~fits).sum()),
        )
        out = []
        for row in np.nonzero(ok)[0]:
            node_id = self.matrix.node_of.get(int(row))
            if node_id is not None:
                out.append(node_id)
        return out, metric
