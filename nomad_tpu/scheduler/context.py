"""Evaluation context — the plan under construction plus scoring telemetry.

Reference: scheduler/context.go:12-211 (EvalContext holds the state snapshot,
the Plan being built, per-placement AllocMetrics, and the ProposedAllocs
cache that lets later placements in the same plan see earlier ones).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..structs.types import Allocation, AllocMetric, Job, Plan


class EvalContext:
    def __init__(self, snapshot, plan: Plan):
        self.snapshot = snapshot
        self.plan = plan
        self.metrics: Dict[str, AllocMetric] = {}  # per-TG last metric

    def plan_removed_ids(self) -> set:
        """Ids of allocs the in-flight plan stops, evicts, or preempts —
        excluded from every proposed-usage computation."""
        removed = set()
        for allocs in self.plan.node_update.values():
            removed.update(a.id for a in allocs)
        for allocs in self.plan.node_preemptions.values():
            removed.update(a.id for a in allocs)
        return removed

    def proposed_allocs(self, node_id: str) -> List[Allocation]:
        """Allocs a node would have if the plan applied: existing non-terminal
        − plan stops/evictions/preemptions + in-plan placements
        (reference: context.go ProposedAllocs)."""
        existing = [
            a
            for a in self.snapshot.allocs_by_node(node_id)
            if not a.terminal_status()
        ]
        removed = {
            a.id
            for a in self.plan.node_update.get(node_id, [])
        } | {a.id for a in self.plan.node_preemptions.get(node_id, [])}
        proposed = [a for a in existing if a.id not in removed]
        proposed.extend(self.plan.node_allocation.get(node_id, []))
        return proposed
