"""nomad_tpu — a TPU-native workload-orchestration framework.

A brand-new framework with the capabilities of HashiCorp Nomad (studied at
/root/reference, surveyed in SURVEY.md), re-designed TPU-first: the host runs
a conventional control plane (state store, eval broker, plan applier, node
agents), while the scheduling math — constraint feasibility, bin-pack fit and
scoring, spread/affinity, preemption search, and plan-commit re-verification —
runs as batched JAX/XLA kernels over a device-resident cluster matrix.
"""

__version__ = "0.1.0"


def enable_compilation_cache(path: str = "/tmp/nomad_tpu_jax_cache") -> None:
    """Opt into JAX's persistent compilation cache.

    The scheduler's p99 budget assumes warm jit caches; the persistent cache
    makes that true across *processes* too (server restarts, test runs,
    bench warmup). Call before the first kernel invocation.
    """
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
