"""nomad_tpu — a TPU-native workload-orchestration framework.

A brand-new framework with the capabilities of HashiCorp Nomad (studied at
/root/reference, surveyed in SURVEY.md), re-designed TPU-first: the host runs
a conventional control plane (state store, eval broker, plan applier, node
agents), while the scheduling math — constraint feasibility, bin-pack fit and
scoring, spread/affinity, preemption search, and plan-commit re-verification —
runs as batched JAX/XLA kernels over a device-resident cluster matrix.
"""

__version__ = "0.1.0"
