"""Mock fixtures for tests and simulation (reference: nomad/mock/mock.go —
mock.Node(), mock.Job(), mock.Alloc(), mock.SystemJob(), mock.Eval())."""

from __future__ import annotations

from typing import Optional

from .structs.types import (
    AllocClientStatus,
    generate_uuid,
    AllocDesiredStatus,
    Allocation,
    DriverInfo,
    Evaluation,
    EvalTrigger,
    Job,
    JobType,
    Node,
    NodeResources,
    NodeReservedResources,
    Resources,
    Task,
    TaskGroup,
)


def node(**overrides) -> Node:
    n = Node(
        datacenter="dc1",
        node_class="linux-medium-pci",
        attributes={
            "kernel.name": "linux",
            "cpu.arch": "amd64",
            "os.name": "ubuntu",
            "os.version": "22.04",
            "driver.mock": "1",
        },
        resources=NodeResources(cpu=4000, memory_mb=8192, disk_mb=100 * 1024),
        reserved=NodeReservedResources(cpu=100, memory_mb=256),
        drivers={"mock": DriverInfo(detected=True, healthy=True)},
    )
    for k, v in overrides.items():
        setattr(n, k, v)
    return n


def job(**overrides) -> Job:
    j = Job(
        id=f"mock-service-{generate_uuid()[:8]}",
        name="my-job",
        type=JobType.SERVICE.value,
        priority=50,
        datacenters=["dc1"],
        task_groups=[
            TaskGroup(
                name="web",
                count=10,
                tasks=[
                    Task(
                        name="web",
                        driver="mock",
                        resources=Resources(cpu=500, memory_mb=256),
                    )
                ],
            )
        ],
    )
    for k, v in overrides.items():
        setattr(j, k, v)
    return j


def batch_job(**overrides) -> Job:
    j = job(**overrides)
    j.type = JobType.BATCH.value
    j.id = f"mock-batch-{generate_uuid()[:8]}"
    return j


def system_job(**overrides) -> Job:
    j = Job(
        id=f"mock-system-{generate_uuid()[:8]}",
        name="my-system-job",
        type=JobType.SYSTEM.value,
        priority=100,
        datacenters=["dc1"],
        task_groups=[
            TaskGroup(
                name="system",
                count=0,
                tasks=[
                    Task(
                        name="sys",
                        driver="mock",
                        resources=Resources(cpu=100, memory_mb=64),
                    )
                ],
            )
        ],
    )
    for k, v in overrides.items():
        setattr(j, k, v)
    return j


def eval_for(j: Job, **overrides) -> Evaluation:
    e = Evaluation(
        namespace=j.namespace,
        priority=j.priority,
        type=j.type,
        triggered_by=EvalTrigger.JOB_REGISTER.value,
        job_id=j.id,
    )
    for k, v in overrides.items():
        setattr(e, k, v)
    return e


def alloc(j: Optional[Job] = None, n: Optional[Node] = None, **overrides) -> Allocation:
    j = j if j is not None else job()
    tg = j.task_groups[0]
    a = Allocation(
        namespace=j.namespace,
        name=f"{j.id}.{tg.name}[0]",
        node_id=n.id if n else "",
        job_id=j.id,
        job=j,
        task_group=tg.name,
        resources=tg.combined_resources(),
        desired_status=AllocDesiredStatus.RUN.value,
        client_status=AllocClientStatus.RUNNING.value,
    )
    for k, v in overrides.items():
        setattr(a, k, v)
    return a
