"""Agent configuration files — HCL load + merge.

Reference: ``command/agent/config.go`` + ``config_parse.go``: agents load
one or more HCL/JSON config files (or directories of them), merge them in
order (later wins), and CLI flags override the result.  This build reuses
the jobspec HCL dialect for the same shape:

    name       = "server-1"
    datacenter = "dc1"
    bind_addr  = "127.0.0.1"
    http_port  = 4646
    data_dir   = "/var/lib/nomad_tpu"

    server {
      enabled        = true
      workers        = 4
      acl_enabled    = true
      peers          = ["http://10.0.0.1:4646", "http://10.0.0.2:4646"]
      node_capacity  = 2048
    }

    client {
      enabled = true
      servers = "http://10.0.0.1:4646"
      token   = "<node acl secret>"
      meta { rack = "r1" }
    }
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..jobspec.hcl import parse_hcl


def load_config_files(paths: List[str]) -> Dict:
    """Parse and merge config files/directories in order (later wins —
    command/agent/config.go Merge)."""
    merged: Dict = {}
    for path in paths:
        if os.path.isdir(path):
            for name in sorted(os.listdir(path)):
                if name.endswith((".hcl", ".json")):
                    _merge(merged, _load_one(os.path.join(path, name)))
        else:
            _merge(merged, _load_one(path))
    return merged


def _load_one(path: str) -> Dict:
    with open(path) as fh:
        text = fh.read()
    if path.endswith(".json"):
        import json

        return json.loads(text)
    return parse_hcl(text)


def _merge(base: Dict, extra: Dict) -> Dict:
    for k, v in extra.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            _merge(base[k], v)
        else:
            base[k] = v
    return base


def apply_config(doc: Dict, agent_config) -> None:
    """Fold a merged config document into an AgentConfig (CLI flags are
    applied afterwards by the caller and win)."""
    ac = agent_config
    ac.name = doc.get("name", ac.name)
    ac.datacenter = doc.get("datacenter", ac.datacenter)
    ac.region = doc.get("region", ac.region)
    ac.http_host = doc.get("bind_addr", ac.http_host)
    ac.http_port = int(doc.get("http_port", ac.http_port))

    srv = doc.get("server") or {}
    if srv:
        ac.server_enabled = bool(srv.get("enabled", ac.server_enabled))
        sc = ac.server_config
        sc.num_workers = int(srv.get("workers", sc.num_workers))
        sc.node_capacity = int(srv.get("node_capacity", sc.node_capacity))
        sc.acl_enabled = bool(srv.get("acl_enabled", sc.acl_enabled))
        # No name fallback here: CLI flags apply AFTER this, and a shared
        # config file must not stamp every server with the same
        # replication identity (Server falls back to its unique address).
        sc.server_id = srv.get("server_id", sc.server_id)
        peers = srv.get("peers")
        if peers:
            sc.peers = list(peers)
        sc.raft_enabled = bool(srv.get("raft_enabled", sc.raft_enabled))
        if srv.get("cluster_secret"):
            sc.cluster_secret = str(srv["cluster_secret"])
        if srv.get("heartbeat_min_ttl"):
            sc.heartbeat_min_ttl = float(srv["heartbeat_min_ttl"])
        if srv.get("heartbeat_max_ttl"):
            sc.heartbeat_max_ttl = float(srv["heartbeat_max_ttl"])
    if doc.get("data_dir"):
        ac.server_config.data_dir = os.path.join(doc["data_dir"], "server")
        ac.client_config.data_dir = os.path.join(doc["data_dir"], "client")

    cli = doc.get("client") or {}
    if cli:
        ac.client_enabled = bool(cli.get("enabled", ac.client_enabled))
        cc = ac.client_config
        if cli.get("servers"):
            ac.server_addr = str(cli["servers"])
        if cli.get("token"):
            ac.client_token = str(cli["token"])
        cc.node_class = cli.get("node_class", cc.node_class)
        meta = cli.get("meta")
        if isinstance(meta, dict):
            cc.meta.update({k: str(v) for k, v in meta.items()})
        if cli.get("artifact_root"):
            cc.artifact_root = str(cli["artifact_root"])
        # host_volume "name" { path = "/export/x" } blocks.
        hv = cli.get("host_volume")
        if isinstance(hv, dict):
            for name, body in hv.items():
                bodies = body if isinstance(body, list) else [body]
                for b in bodies:
                    if isinstance(b, dict) and b.get("path"):
                        cc.host_volumes[name] = str(b["path"])
        # plugin "name" { binary = "/path" } blocks (external drivers).
        pl = cli.get("plugin")
        if isinstance(pl, dict):
            for name, body in pl.items():
                bodies = body if isinstance(body, list) else [body]
                for b in bodies:
                    if isinstance(b, dict) and b.get("binary"):
                        cc.plugins[name] = {"binary": str(b["binary"])}
