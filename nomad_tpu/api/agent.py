"""Agent — server and/or client plus the HTTP API in one process.

Reference: ``command/agent/agent.go`` (NewAgent boots nomad.NewServer and/or
client.NewClient in-process) + ``command/agent/http.go`` (HTTPServer).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..client import Client, ClientConfig
from ..server import Server, ServerConfig


@dataclass
class AgentConfig:
    name: str = "agent-1"
    region: str = "global"
    datacenter: str = "dc1"
    server_enabled: bool = True
    client_enabled: bool = True
    # Remote server agent address for client-only agents (the wire seam:
    # client/client.go dials servers; here HTTP at /v1/internal/*).
    server_addr: str = ""
    # Node ACL secret attached to every server RPC (client acl.token).
    client_token: str = ""
    http_host: str = "127.0.0.1"
    http_port: int = 0  # 0 = ephemeral
    server_config: ServerConfig = field(default_factory=ServerConfig)
    client_config: ClientConfig = field(default_factory=ClientConfig)


class Agent:
    def __init__(self, config: Optional[AgentConfig] = None):
        self.config = config or AgentConfig()
        self.started_at = 0.0
        self.server: Optional[Server] = None
        self.client: Optional[Client] = None
        if self.config.server_enabled:
            self.server = Server(self.config.server_config)
        if self.config.client_enabled:
            if self.server is not None:
                server_handle = self.server
            elif self.config.server_addr:
                from .rpc import FailoverRPC, HTTPServerRPC

                addrs = [
                    a.strip()
                    for a in self.config.server_addr.split(",")
                    if a.strip()
                ]
                server_handle = (
                    FailoverRPC(addrs, token=self.config.client_token)
                    if len(addrs) > 1
                    else HTTPServerRPC(
                        addrs[0], token=self.config.client_token
                    )
                )
            else:
                raise ValueError(
                    "client-only agents need --servers <addr> of a server agent"
                )
            self.config.client_config.datacenter = self.config.datacenter
            self.client = Client(server_handle, self.config.client_config)

        from .http_server import HTTPAPIServer

        self.http = HTTPAPIServer(
            self, host=self.config.http_host, port=self.config.http_port
        )
        self.rpc_addr = self.http.addr
        if self.server is not None and (
            self.config.server_config.peers
            or self.config.server_config.raft_enabled
        ):
            # Multi-server: join the peer set as a follower; the election
            # promotes one leader (server/replication.py).  raft_enabled
            # covers the single-server-that-grows case (`server join`).
            self.server.setup_replication(self.rpc_addr)

    def start(self) -> None:
        self.started_at = time.time()
        if self.server is not None:
            self.server.start()
            rep = self.server.replicator
            if rep is not None and self.client is not None:
                # The in-process client registers through direct server
                # calls (no leader-redirect retry on that seam): wait out
                # the first election so its boot writes don't race it.
                deadline = time.time() + 10.0
                while time.time() < deadline and not rep.leader_addr:
                    time.sleep(0.05)
        if self.client is not None:
            # Advertise this agent's HTTP address on the node so servers
            # can forward task-fs/log requests to it (the reference
            # advertises client HTTP addrs the same way).
            self.client.node.attributes = dict(self.client.node.attributes)
            self.client.node.attributes["nomad.advertise.address"] = (
                self.rpc_addr
            )
            self.client.start()
        self.http.start()

    def shutdown(self) -> None:
        if self.client is not None:
            self.client.shutdown()
        if self.server is not None:
            self.server.shutdown()
        self.http.shutdown()

    # ------------------------------------------------------------------

    def member_info(self) -> Dict:
        return {
            "Name": self.config.name,
            "Region": self.config.region,
            "Datacenter": self.config.datacenter,
            "Server": self.server is not None,
            "Client": self.client is not None,
            "Addr": self.rpc_addr,
            "Status": "alive",
        }

    def metrics(self) -> Dict:
        out: Dict = {"uptime_s": round(time.time() - self.started_at, 1)}
        if self.server is not None:
            s = self.server
            out.update(
                {
                    "nomad.broker.total_ready": s.eval_broker.ready_count(),
                    "nomad.broker.total_unacked": s.eval_broker.unacked_count(),
                    "nomad.broker.total_pending": s.eval_broker.pending_count(),
                    "nomad.blocked_evals.total_blocked":
                        s.blocked_evals.blocked_count(),
                    "nomad.plan.queue_depth": s.plan_queue.depth(),
                    "nomad.plan.applied": s.plan_applier.plans_applied,
                    "nomad.plan.partial": s.plan_applier.plans_partial,
                    "nomad.state.nodes": len(s.store.nodes),
                    "nomad.state.jobs": len(s.store.jobs),
                    "nomad.state.allocs": len(s.store.allocs),
                    "nomad.state.evals": len(s.store.evals),
                    "nomad.worker.evals_processed": sum(
                        w.evals_processed for w in s.workers
                    ),
                    "nomad.heartbeat.active": s.heartbeater.tracked(),
                    "nomad.stream.subscribers":
                        s.store.events.subscriber_count(),
                }
            )
            # Coalescer pipeline + matrix transfer + per-kernel cost
            # attribution now ride in as registry pull gauges (registered
            # by Server._register_telemetry_gauges, same key names), and
            # the latency timers (worker.go:245, plan_apply.go:185,370
            # analogs) plus nomad.phase.* trace histograms alongside them.
            out.update(s.metrics.snapshot())
        if self.client is not None:
            out["client.allocs_running"] = self.client.num_allocs()
        return out
