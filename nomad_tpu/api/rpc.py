"""Client→server node RPCs over the wire.

Reference: the client dials servers over yamux-multiplexed msgpack RPC
(``client/client.go:1997`` watchAllocations → ``Node.GetClientAllocs``
``nomad/node_endpoint.go:915``; ``registerAndHeartbeat`` :1550 →
Node.Register/UpdateStatus; batched ``Node.UpdateAlloc`` :1054).

This build's wire is HTTP+JSON (serde full-fidelity encoding, NOT the
human-facing ``/v1`` JSON) on the server agent's existing listener, under
``/v1/internal/``.  ``HTTPServerRPC`` implements the exact five-method
surface the in-process ``Server`` object exposes to ``Client``, so a
client agent runs unchanged against either — the same seam the reference
has between ``client.RPC`` and in-process test servers.

Blocking queries carry their wait budget in the request and hold the HTTP
response open server-side (the memdb WatchSet discipline over the wire).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import List, Tuple

from ..structs import serde
from ..structs.types import Allocation, Node


class RPCError(Exception):
    pass


class HTTPServerRPC:
    """The client's handle to a remote server agent."""

    def __init__(self, addr: str, timeout: float = 10.0):
        self.addr = addr.rstrip("/")
        self.timeout = timeout

    # ------------------------------------------------------------------

    def _call(self, path: str, payload=None, timeout=None):
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            self.addr + path,
            data=data,
            method="POST" if data is not None else "GET",
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(
                req, timeout=timeout or self.timeout
            ) as resp:
                return json.loads(resp.read() or b"null")
        except urllib.error.HTTPError as exc:
            raise RPCError(
                f"{path}: {exc.code} {exc.read().decode(errors='replace')}"
            ) from exc
        except urllib.error.URLError as exc:
            raise RPCError(f"{path}: {exc.reason}") from exc

    # ------------------------------------------------------------------
    # The five-method client↔server surface
    # ------------------------------------------------------------------

    def register_node(self, node: Node) -> float:
        out = self._call(
            "/v1/internal/node/register", {"Node": serde.to_wire(node)}
        )
        return float(out["TTL"])

    def heartbeat_node(self, node_id: str) -> float:
        out = self._call(
            "/v1/internal/node/heartbeat", {"NodeID": node_id}
        )
        return float(out["TTL"])

    def update_node_status(self, node_id: str, status: str) -> None:
        self._call(
            "/v1/internal/node/status",
            {"NodeID": node_id, "Status": status},
        )

    def get_client_allocs(
        self, node_id: str, min_index: int = 0, timeout: float = 30.0
    ) -> Tuple[List[Allocation], int]:
        out = self._call(
            "/v1/internal/node/client-allocs",
            {"NodeID": node_id, "MinIndex": min_index, "Wait": timeout},
            # The HTTP timeout must outlast the server-side blocking wait.
            timeout=timeout + self.timeout,
        )
        allocs = [serde.from_wire(w) for w in out["Allocs"]]
        return allocs, int(out["Index"])

    def update_allocs_from_client(self, updates: List[Allocation]) -> None:
        self._call(
            "/v1/internal/node/update-allocs",
            {"Allocs": [serde.to_wire(a) for a in updates]},
        )
