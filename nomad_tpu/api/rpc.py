"""Client→server node RPCs over the wire.

Reference: the client dials servers over yamux-multiplexed msgpack RPC
(``client/client.go:1997`` watchAllocations → ``Node.GetClientAllocs``
``nomad/node_endpoint.go:915``; ``registerAndHeartbeat`` :1550 →
Node.Register/UpdateStatus; batched ``Node.UpdateAlloc`` :1054).

This build's wire is HTTP+JSON (serde full-fidelity encoding, NOT the
human-facing ``/v1`` JSON) on the server agent's existing listener, under
``/v1/internal/``.  ``HTTPServerRPC`` implements the exact five-method
surface the in-process ``Server`` object exposes to ``Client``, so a
client agent runs unchanged against either — the same seam the reference
has between ``client.RPC`` and in-process test servers.

Blocking queries carry their wait budget in the request and hold the HTTP
response open server-side (the memdb WatchSet discipline over the wire).
"""

from __future__ import annotations

import json
import re
import urllib.error
import urllib.request
from typing import List, Tuple

from .. import trace
from ..chaos import inject
from ..retry import RetryBudgetExceeded, RetryPolicy, retry_call
from ..structs import serde
from ..structs.types import Allocation, Node


class RPCError(Exception):
    pass


class HTTPServerRPC:
    """The client's handle to a remote server agent.

    ``token`` is the node's ACL secret (the reference's client
    ``acl.token`` config), attached to every RPC so ACL-enabled servers
    authorize the node endpoints.
    """

    def __init__(self, addr: str, timeout: float = 10.0, token: str = ""):
        self.addr = addr.rstrip("/")
        self.timeout = timeout
        self.token = token

    # ------------------------------------------------------------------

    def _call(self, path: str, payload=None, timeout=None):
        # Chaos seam: a request can be lost, erred, delayed (handled inside
        # inject), or duplicated before it ever reaches the wire.
        fault = inject("rpc.call", path=path, addr=self.addr)
        trace.event("seam.rpc.call", path=path)
        if fault is not None:
            if fault.kind == "drop":
                raise RPCError(f"{path}: injected connection drop")
            if fault.kind == "error":
                raise RPCError(f"{path}: 500 injected server error")
        data = json.dumps(payload).encode() if payload is not None else None
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["X-Nomad-Token"] = self.token

        def post_once():
            req = urllib.request.Request(
                self.addr + path,
                data=data,
                method="POST" if data is not None else "GET",
                headers=headers,
            )
            try:
                with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout
                ) as resp:
                    return json.loads(resp.read() or b"null")
            except urllib.error.HTTPError as exc:
                raise RPCError(
                    f"{path}: {exc.code} {exc.read().decode(errors='replace')}"
                ) from exc
            except urllib.error.URLError as exc:
                raise RPCError(f"{path}: {exc.reason}") from exc

        if fault is not None and fault.kind == "dup":
            # A retransmitted request (lost ack): the server must treat the
            # second copy idempotently; callers see the second response.
            post_once()
        return post_once()

    # ------------------------------------------------------------------
    # The five-method client↔server surface
    # ------------------------------------------------------------------

    def register_node(self, node: Node) -> float:
        out = self._call(
            "/v1/internal/node/register", {"Node": serde.to_wire(node)}
        )
        return float(out["TTL"])

    def heartbeat_node(self, node_id: str) -> float:
        out = self._call(
            "/v1/internal/node/heartbeat", {"NodeID": node_id}
        )
        return float(out["TTL"])

    def update_node_status(self, node_id: str, status: str) -> None:
        self._call(
            "/v1/internal/node/status",
            {"NodeID": node_id, "Status": status},
        )

    def get_client_allocs(
        self, node_id: str, min_index: int = 0, timeout: float = 30.0
    ) -> Tuple[List[Allocation], int]:
        out = self._call(
            "/v1/internal/node/client-allocs",
            {"NodeID": node_id, "MinIndex": min_index, "Wait": timeout},
            # The HTTP timeout must outlast the server-side blocking wait.
            timeout=timeout + self.timeout,
        )
        allocs = [serde.from_wire(w) for w in out["Allocs"]]
        return allocs, int(out["Index"])

    def update_allocs_from_client(self, updates: List[Allocation]) -> None:
        self._call(
            "/v1/internal/node/update-allocs",
            {"Allocs": [serde.to_wire(a) for a in updates]},
        )

    def check_acl_capability(
        self, token: str, kind: str, capability: str,
        namespace: str = "default",
    ) -> bool:
        out = self._call("/v1/internal/acl/check", {
            "Token": token, "Kind": kind, "Capability": capability,
            "Namespace": namespace,
        })
        return bool(out.get("Allowed"))

    def get_volume_source(self, namespace: str, volume_id: str):
        out = self._call("/v1/internal/node/volume-source", {
            "Namespace": namespace, "VolumeID": volume_id,
        })
        return out.get("Source")

    def get_alloc_fs_origin(self, alloc_id: str):
        return self._call("/v1/internal/node/alloc-fs-origin", {
            "AllocID": alloc_id,
        })


# The hint travels inside a JSON error body — stop before quote/brace.
_LEADER_HINT = re.compile(r"leader=([^\s\"'}]+)")


class FailoverRPC:
    """The client's handle to a multi-server control plane.

    Wraps one :class:`HTTPServerRPC` per server address; every call tries
    the current target and, on connection errors or a ``not leader``
    redirect (409 with a ``leader=<addr>`` hint), retargets and retries —
    the client-side half of failover (the reference's client tracks a
    server list from heartbeats and rotates on RPC errors,
    client/servers/manager.go).
    """

    def __init__(
        self,
        addrs: List[str],
        timeout: float = 10.0,
        token: str = "",
        retry_policy: "RetryPolicy | None" = None,
    ):
        assert addrs, "need at least one server address"
        self.token = token
        self.rpcs = {
            a: HTTPServerRPC(a, timeout=timeout, token=token) for a in addrs
        }
        self.addrs = list(addrs)
        self.current = self.addrs[0]
        # Failover budget: enough attempts to visit every server twice
        # (one full rotation may land mid-election), jittered so a fleet
        # of clients doesn't hammer the new leader in lockstep, with a
        # hard deadline so a fully-partitioned client surfaces an error
        # instead of spinning forever.
        self.retry_policy = retry_policy or RetryPolicy(
            base_delay=0.05,
            max_delay=1.0,
            max_attempts=2 * len(self.addrs),
            deadline=max(15.0, 2 * len(self.addrs) * timeout),
            attempt_timeout=timeout,
        )

    def _retarget(self, err: RPCError) -> None:
        hint = _LEADER_HINT.search(str(err))
        if hint and hint.group(1) in self.rpcs:
            self.current = hint.group(1)
            return
        idx = self.addrs.index(self.current)
        self.current = self.addrs[(idx + 1) % len(self.addrs)]

    def _with_failover(self, fn_name: str, *args, **kwargs):
        def attempt():
            return getattr(self.rpcs[self.current], fn_name)(*args, **kwargs)

        def on_retry(n, exc, delay):
            self._retarget(exc)

        try:
            return retry_call(
                attempt,
                policy=self.retry_policy,
                retry_on=(RPCError,),
                on_retry=on_retry,
                description=f"rpc failover {fn_name}",
            )
        except RetryBudgetExceeded as exc:
            # Callers (and tests) match on RPCError; surface the last
            # underlying RPC failure, not the budget wrapper.
            raise exc.__cause__  # type: ignore[misc]

    def register_node(self, node: Node) -> float:
        return self._with_failover("register_node", node)

    def heartbeat_node(self, node_id: str) -> float:
        return self._with_failover("heartbeat_node", node_id)

    def update_node_status(self, node_id: str, status: str) -> None:
        return self._with_failover("update_node_status", node_id, status)

    def get_client_allocs(
        self, node_id: str, min_index: int = 0, timeout: float = 30.0
    ) -> Tuple[List[Allocation], int]:
        return self._with_failover(
            "get_client_allocs", node_id, min_index=min_index, timeout=timeout
        )

    def update_allocs_from_client(self, updates: List[Allocation]) -> None:
        return self._with_failover("update_allocs_from_client", updates)

    def check_acl_capability(self, *args, **kwargs) -> bool:
        return self._with_failover("check_acl_capability", *args, **kwargs)

    def get_volume_source(self, *args, **kwargs):
        return self._with_failover("get_volume_source", *args, **kwargs)

    def get_alloc_fs_origin(self, *args, **kwargs):
        return self._with_failover("get_alloc_fs_origin", *args, **kwargs)
