"""HTTP API + agent (reference: command/agent/ — http.go:252-324 routes)."""

from .agent import Agent, AgentConfig
from .http_server import HTTPAPIServer
from .client import APIClient

__all__ = ["Agent", "AgentConfig", "HTTPAPIServer", "APIClient"]
