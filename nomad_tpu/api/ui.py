"""Minimal operator web UI, served at ``/ui``.

The reference ships a full Ember SPA (``ui/``, reference repo); this is a
deliberately small, dependency-free single page over the same ``/v1``
APIs — jobs, allocations, nodes, deployments, evaluations, volumes,
members — with auto-refresh.  It exists so the HTTP surface has a human
face, not to replicate the Ember app.
"""

UI_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>nomad_tpu</title>
<style>
  :root { color-scheme: light dark; }
  body { font: 13px/1.5 -apple-system, system-ui, sans-serif; margin: 0;
         background: Canvas; color: CanvasText; }
  header { display: flex; align-items: baseline; gap: 1rem;
           padding: .6rem 1rem; border-bottom: 1px solid color-mix(in srgb, CanvasText 18%, Canvas); }
  header h1 { font-size: 1rem; margin: 0; }
  header span { opacity: .65; font-size: .8rem; }
  nav button { margin-right: .4rem; padding: .25rem .7rem; cursor: pointer;
               border: 1px solid color-mix(in srgb, CanvasText 25%, Canvas);
               background: transparent; color: inherit; border-radius: 4px; }
  nav button.on { background: color-mix(in srgb, CanvasText 12%, Canvas); font-weight: 600; }
  main { padding: .8rem 1rem; }
  table { border-collapse: collapse; width: 100%; }
  th, td { text-align: left; padding: .25rem .6rem;
           border-bottom: 1px solid color-mix(in srgb, CanvasText 12%, Canvas); }
  th { opacity: .7; font-weight: 600; }
  tr:hover td { background: color-mix(in srgb, CanvasText 6%, Canvas); }
  .mono { font-family: ui-monospace, monospace; font-size: 12px; }
  .ok { color: #2e9e44; } .bad { color: #d43d2a; } .warn { color: #c98a00; }
  #err { color: #d43d2a; padding: .3rem 1rem; }
</style>
</head>
<body>
<header>
  <h1>nomad_tpu</h1>
  <nav id="tabs"></nav>
  <input id="token" type="password" placeholder="ACL token"
         style="margin-left:auto; padding:.2rem .4rem; font-size:.8rem;">
  <span id="meta"></span>
</header>
<div id="err"></div>
<main id="main">loading…</main>
<script>
const TABS = ["jobs", "allocations", "nodes", "deployments",
              "evaluations", "volumes", "members"];
let tab = location.hash.slice(1) || "jobs";

async function j(path) {
  const token = localStorage.getItem("nomad_tpu_token") || "";
  const r = await fetch(path, token ? {
    headers: {"X-Nomad-Token": token}
  } : {});
  if (!r.ok) throw new Error(path + " -> " + r.status);
  return r.json();
}
function h(s) {
  return String(s ?? "").replace(/[&<>"]/g,
    c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));
}
function cls(s) {
  if (["running","ready","complete","successful","alive"].includes(s)) return "ok";
  if (["failed","lost","down","dead"].includes(s)) return "bad";
  return "warn";
}
function table(cols, rows) {
  return "<table><tr>" + cols.map(c => `<th>${h(c)}</th>`).join("") +
    "</tr>" + rows.map(r => "<tr>" + r.map(c => `<td>${c}</td>`).join("") +
    "</tr>").join("") + "</table>";
}
const short = id => `<span class=mono title="${h(id)}">${h(String(id).slice(0, 8))}</span>`;
const st = s => `<span class="${cls(s)}">${h(s)}</span>`;

const RENDER = {
  async jobs() {
    const jobs = await j("/v1/jobs");
    return table(["ID", "Type", "Priority", "Status", "Version"],
      jobs.map(x => [h(x.id), h(x.type), x.priority,
                     st(x.status) + (x.stop ? " (stopped)" : ""), x.version]));
  },
  async allocations() {
    const allocs = await j("/v1/allocations");
    return table(["ID", "Job", "Group", "Node", "Desired", "Status"],
      allocs.map(a => [short(a.id), h(a.job_id), h(a.task_group),
                       short(a.node_id), h(a.desired_status),
                       st(a.client_status)]));
  },
  async nodes() {
    const nodes = await j("/v1/nodes");
    return table(["ID", "Name", "DC", "Class", "Status", "Eligibility"],
      nodes.map(n => [short(n.id), h(n.name), h(n.datacenter),
                      h(n.node_class), st(n.status),
                      h(n.scheduling_eligibility) +
                      (n.drain ? " (draining)" : "")]));
  },
  async deployments() {
    const deps = await j("/v1/deployments");
    return table(["ID", "Job", "Version", "Status", "Description"],
      deps.map(d => [short(d.id), h(d.job_id), "v" + d.job_version,
                     st(d.status), h(d.status_description)]));
  },
  async evaluations() {
    const evs = await j("/v1/evaluations");
    return table(["ID", "Job", "Triggered by", "Status"],
      evs.slice(-200).reverse().map(e => [short(e.id), h(e.job_id),
                                          h(e.triggered_by), st(e.status)]));
  },
  async volumes() {
    const vols = await j("/v1/volumes");
    return table(["ID", "Source", "Access mode", "Writers", "Readers"],
      vols.map(v => [h(v.id), h(v.source), h(v.access_mode),
                     Object.keys(v.write_claims).length,
                     Object.keys(v.read_claims).length]));
  },
  async members() {
    const out = await j("/v1/agent/members");
    return table(["Name", "Addr", "Status", "Leader"],
      out.Members.map(m => [h(m.Name), h(m.Addr || ""), st(m.Status),
                            m.Leader ? "yes" : ""]));
  },
};

function drawTabs() {
  document.getElementById("tabs").innerHTML = TABS.map(t =>
    `<button class="${t === tab ? "on" : ""}" onclick="go('${t}')">${t}</button>`
  ).join("");
}
function go(t) { tab = t; location.hash = t; drawTabs(); refresh(); }
async function refresh() {
  const err = document.getElementById("err");
  try {
    document.getElementById("main").innerHTML = await RENDER[tab]();
    err.textContent = "";
    document.getElementById("meta").textContent =
      new Date().toLocaleTimeString();
  } catch (e) { err.textContent = String(e); }
}
const tokenBox = document.getElementById("token");
tokenBox.value = localStorage.getItem("nomad_tpu_token") || "";
tokenBox.addEventListener("change", () => {
  localStorage.setItem("nomad_tpu_token", tokenBox.value);
  refresh();
});
drawTabs();
refresh();
setInterval(refresh, 3000);
</script>
</body>
</html>
"""
