"""HTTP API — the ``/v1`` surface.

Reference: ``command/agent/http.go:252-324`` route registration. JSON over
HTTP; the CLI and external tooling consume this, mirroring the reference's
api/ package contract.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..jobspec import api_to_job, parse_job
from ..structs.types import DrainStrategy, SchedulerConfiguration


def _dump(obj: Any, exclude: Tuple[str, ...] = ()) -> Any:
    if obj is None:
        return None
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        d = dataclasses.asdict(obj)
        for k in exclude:
            d.pop(k, None)
        return d
    if isinstance(obj, list):
        return [_dump(o, exclude) for o in obj]
    if isinstance(obj, dict):
        return {k: _dump(v, exclude) for k, v in obj.items()}
    return obj


class HTTPError(Exception):
    def __init__(
        self, code: int, message: str,
        headers: Optional[Dict[str, str]] = None,
    ):
        super().__init__(message)
        self.code = code
        self.message = message
        self.headers = headers or {}


@dataclasses.dataclass
class RawResponse:
    """A route() result that bypasses JSON serialization — for non-JSON
    content types (Prometheus text exposition, pre-encoded traces)."""

    body: bytes
    content_type: str = "text/plain; charset=utf-8"


class HTTPAPIServer:
    """Routes requests onto the in-process agent (server and/or client)."""

    def __init__(self, agent, host: str = "127.0.0.1", port: int = 0):
        self.agent = agent
        api = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            def _respond(
                self, code: int, payload: Any,
                headers: Optional[Dict[str, str]] = None,
            ) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _handle(self, method: str) -> None:
                try:
                    parsed = urlparse(self.path)
                    multi = parse_qs(parsed.query)
                    query = {k: v[0] for k, v in multi.items()}
                    if parsed.path == "/v1/event/stream" and method == "GET":
                        # NDJSON stream — bypasses the one-shot JSON path.
                        stream_token = self.headers.get(
                            "X-Nomad-Token", query.get("token", "")
                        )
                        api.stream_events(self, multi, token=stream_token)
                        return
                    if parsed.path == "/v1/agent/monitor" and (
                        method == "GET"
                    ):
                        mon_token = self.headers.get(
                            "X-Nomad-Token", query.get("token", "")
                        )
                        api.stream_monitor(self, query, token=mon_token)
                        return
                    if parsed.path.startswith("/v1/client/fs/") and (
                        method == "GET"
                    ):
                        # Raw-byte (possibly streaming) task-fs surface.
                        fs_token = self.headers.get(
                            "X-Nomad-Token", query.get("token", "")
                        )
                        api.serve_client_fs(
                            self, parsed.path, query, token=fs_token
                        )
                        return
                    if parsed.path in ("/", "/ui") and method == "GET":
                        # Minimal operator dashboard (api/ui.py) — the
                        # reference serves its Ember SPA the same way.
                        from .ui import UI_HTML

                        api._raw_respond(
                            self, 200, UI_HTML.encode(),
                            "text/html; charset=utf-8",
                        )
                        return
                    if parsed.path.startswith("/v1/client/exec/") and (
                        method in ("POST", "PUT")
                    ):
                        # NDJSON-framed command execution in a task's
                        # context (alloc exec).
                        ln = int(self.headers.get("Content-Length", 0) or 0)
                        raw = self.rfile.read(ln) if ln else b""
                        exec_body = json.loads(raw) if raw else {}
                        exec_token = self.headers.get(
                            "X-Nomad-Token", query.get("token", "")
                        )
                        api.serve_client_exec(
                            self, parsed.path, query, exec_body,
                            token=exec_token,
                        )
                        return
                    length = int(self.headers.get("Content-Length", 0) or 0)
                    raw = self.rfile.read(length) if length else b""
                    body = json.loads(raw) if raw else None
                    token = self.headers.get(
                        "X-Nomad-Token", query.get("token", "")
                    )
                    result = api.route(
                        method, parsed.path, query, body, token=token,
                        cluster_secret=self.headers.get(
                            "X-Nomad-Cluster-Secret", ""
                        ),
                    )
                    if isinstance(result, RawResponse):
                        api._raw_respond(
                            self, 200, result.body, result.content_type
                        )
                    else:
                        self._respond(200, result)
                except HTTPError as exc:
                    self._respond(
                        exc.code, {"error": exc.message},
                        headers=exc.headers,
                    )
                except Exception as exc:  # noqa: BLE001
                    from ..server.admission import RateLimitError
                    from ..server.replication import NotLeaderError

                    if isinstance(exc, NotLeaderError):
                        self._respond(409, {
                            "error": f"not leader; leader={exc.leader_addr}"
                        })
                    elif isinstance(exc, RateLimitError):
                        # Load-shed submission: 429 + the bucket's actual
                        # deficit as the Retry-After hint (admission.py).
                        self._respond(
                            429, {"error": str(exc)},
                            headers={
                                "Retry-After": f"{exc.retry_after:.3f}"
                            },
                        )
                    else:
                        self._respond(500, {"error": str(exc)})

            def do_GET(self):
                self._handle("GET")

            def do_PUT(self):
                self._handle("PUT")

            def do_POST(self):
                self._handle("POST")

            def do_DELETE(self):
                self._handle("DELETE")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.addr = f"http://{host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="http-api", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    # ------------------------------------------------------------------
    # Event stream (nomad/stream/ + /v1/event/stream NDJSON,
    # command/agent/event_endpoint.go)
    # ------------------------------------------------------------------

    def stream_events(self, handler, multi_query: Dict, token: str = "") -> None:
        server = self.agent.server
        if server is None:
            raise HTTPError(501, "agent is not running a server")
        if server.config.acl_enabled:
            acl = server.resolve_token(token)
            if acl is None or not acl.allow_agent("read"):
                raise HTTPError(403, "Permission denied (agent:read)")
        # topic filters: repeated topic=Topic:key params ("*" wildcards).
        topics: Dict[str, list] = {}
        for spec in multi_query.get("topic", ["*:*"]):
            topic, _, key = spec.partition(":")
            topics.setdefault(topic or "*", []).append(key or "*")
        from_index = int(multi_query.get("index", ["0"])[0] or 0)

        sub = server.store.events.subscribe(topics, from_index=from_index)
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "application/x-ndjson")
            handler.send_header("Connection", "close")
            handler.end_headers()
            while True:
                events = sub.next(timeout=10.0)
                if sub.closed:
                    return
                if not events:
                    # Heartbeat keeps intermediaries from timing the
                    # connection out (the reference sends empty objects).
                    handler.wfile.write(b"{}\n")
                    handler.wfile.flush()
                    continue
                for ev in events:
                    handler.wfile.write(
                        (json.dumps(ev.to_wire()) + "\n").encode()
                    )
                handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away
        finally:
            sub.close()

    # ------------------------------------------------------------------
    # ACL enforcement (reference: per-endpoint ResolveToken + capability
    # checks across nomad/*_endpoint.go; trimmed to a route→capability
    # map here)
    # ------------------------------------------------------------------

    def _require_ns_cap(
        self, server, token: str, namespace: str, cap: str
    ) -> None:
        """Capability check against the namespace of the RESOURCE being
        touched (the route gate can only see the query namespace; bodies
        and looked-up objects carry their own)."""
        if not server.config.acl_enabled:
            return
        acl = server.resolve_token(token)
        if acl is None or not acl.allow_namespace(namespace, cap):
            raise HTTPError(
                403, f"Permission denied ({cap} on {namespace!r})"
            )

    def _require_management(self, server, token: str) -> None:
        """Cluster-wide mutations (namespaces) need a management token
        (namespace_endpoint.go requires one for upsert/delete)."""
        if not server.config.acl_enabled:
            return
        acl = server.resolve_token(token)
        if acl is None or not acl.management:
            raise HTTPError(403, "Permission denied (management only)")

    def _check_acl(
        self, server, method: str, path: str, query: Dict, token: str
    ) -> None:
        from ..acl import CAP_READ_JOB, CAP_SUBMIT_JOB

        acl = server.resolve_token(token)
        if acl is None:
            raise HTTPError(403, "ACL token not found")
        read = method == "GET"
        if path == "/v1/jobs/parse":
            return  # pure function of its input
        if path == "/v1/search":
            return  # per-context checks in the handler (needs the body)
        if path.startswith("/v1/acl"):
            if path == "/v1/acl/token/self":
                return  # any valid token may read itself
            if not acl.management:
                raise HTTPError(403, "Permission denied (management only)")
            return
        if path.startswith("/v1/internal/node") or path == "/v1/nodes" or (
            path.startswith("/v1/node")
        ):
            want = "read" if read else "write"
            if not acl.allow_node(want):
                raise HTTPError(403, f"Permission denied (node:{want})")
            return
        if path.startswith("/v1/operator") or path.startswith("/v1/system"):
            want = "read" if read else "write"
            if not acl.allow_operator(want):
                raise HTTPError(403, f"Permission denied (operator:{want})")
            return
        if path == "/v1/jobs" or path.startswith("/v1/job") or (
            path == "/v1/validate/job"
        ):
            # The query namespace gates list/lookups (store keys are
            # (namespace, id), so the queried ns IS the resource's); write
            # bodies that carry their own Namespace are re-checked against
            # it by the route handlers (_require_ns_cap).
            from ..acl import CAP_DISPATCH_JOB, CAP_SCALE_JOB

            ns = query.get("namespace", "default")
            cap = CAP_READ_JOB if read else CAP_SUBMIT_JOB
            # Anchored on the suffix AFTER a job id (a job literally
            # named "dispatch"/"scale" must not trip these).
            if re.match(r"^/v1/job/.+/dispatch$", path):
                cap = CAP_DISPATCH_JOB
            elif re.match(r"^/v1/job/.+/scale$", path) and not read:
                cap = CAP_SCALE_JOB
            if not acl.allow_namespace(ns, cap):
                raise HTTPError(403, f"Permission denied ({cap})")
            return
        if path.startswith("/v1/allocation") or path.startswith(
            "/v1/evaluation"
        ) or path == "/v1/deployments" or path.startswith(
            "/v1/deployment"
        ) or path.startswith("/v1/scaling") or path.startswith(
            "/v1/volume"
        ):
            if not read and path.startswith("/v1/volume"):
                # register/deregister: handler enforces submit-job on the
                # volume's own namespace.
                return
            if not read and path.startswith("/v1/deployment"):
                # promote/fail/pause: the handler enforces submit-job on
                # the DEPLOYMENT's namespace (the query ns can't see it).
                return
            ns = query.get("namespace", "default")
            if not acl.allow_namespace(ns, CAP_READ_JOB):
                raise HTTPError(403, "Permission denied (read-job)")
            return
        # Agent-level surface (members, metrics, event stream).
        want = "read" if read else "write"
        if not acl.allow_agent(want):
            raise HTTPError(403, f"Permission denied (agent:{want})")

    def _route_acl(
        self, server, method: str, path: str, query: Dict, body: Any,
        token: str,
    ) -> Any:
        from ..structs import serde
        from ..structs.types import ACLPolicy, ACLToken

        if path == "/v1/acl/bootstrap" and method in ("PUT", "POST"):
            try:
                t = server.bootstrap_acl()
            except PermissionError as exc:
                raise HTTPError(400, str(exc))
            return _dump(t)
        if path == "/v1/acl/policies" and method == "GET":
            return [
                {"Name": p.name, "Description": p.description}
                for p in server.store.acl_policies.values()
            ]
        m = re.match(r"^/v1/acl/policy/([^/]+)$", path)
        if m:
            if method == "GET":
                p = server.store.acl_policies.get(m.group(1))
                if p is None:
                    raise HTTPError(404, "policy not found")
                return _dump(p)
            if method in ("PUT", "POST"):
                from ..acl import parse_policy

                rules = (body or {}).get("Rules", "")
                parse_policy(rules)  # validate before committing
                server.store.upsert_acl_policy(
                    server.next_index(),
                    ACLPolicy(
                        name=m.group(1),
                        description=(body or {}).get("Description", ""),
                        rules=rules,
                    ),
                )
                return {}
            if method == "DELETE":
                server.store.delete_acl_policy(
                    server.next_index(), m.group(1)
                )
                return {}
        if path == "/v1/acl/tokens" and method == "GET":
            return [
                _dump(t, exclude=("secret_id",))
                for t in server.store.acl_tokens.values()
            ]
        if path == "/v1/acl/token" and method in ("PUT", "POST"):
            t = ACLToken(
                name=(body or {}).get("Name", ""),
                type=(body or {}).get("Type", "client"),
                policies=list((body or {}).get("Policies", [])),
                create_time=time.time(),
            )
            server.store.upsert_acl_tokens(server.next_index(), [t])
            return _dump(t)
        m = re.match(r"^/v1/acl/token/([^/]+)$", path)
        if m and method == "DELETE":
            server.store.delete_acl_token(server.next_index(), m.group(1))
            return {}
        if path == "/v1/acl/token/self" and method == "GET":
            t = server.store.acl_token_by_secret(token)
            if t is None:
                raise HTTPError(404, "token not found")
            return _dump(t)
        raise HTTPError(404, f"unknown ACL route {path}")

    # ------------------------------------------------------------------
    # Live log monitor (reference: /v1/agent/monitor, command/agent/
    # monitor/monitor.go — streams the agent's own logs at a level)
    # ------------------------------------------------------------------

    def stream_monitor(self, handler, query: Dict, token: str = "") -> None:
        import logging
        import queue as _queue

        server = self.agent.server
        if server is not None:
            if server.config.acl_enabled:
                acl = server.resolve_token(token)
                if acl is None or not acl.allow_agent("read"):
                    raise HTTPError(403, "Permission denied (agent:read)")
        elif self.agent.client is not None:
            # Client-only agent: forward the check to the server — direct
            # node access must not bypass ACLs (same invariant as the fs
            # surface below).
            try:
                allowed = self.agent.client.server.check_acl_capability(
                    token, "agent", "read"
                )
            except Exception as exc:  # noqa: BLE001 — fail closed
                raise HTTPError(502, f"ACL check unavailable: {exc}")
            if not allowed:
                raise HTTPError(403, "Permission denied (agent:read)")

        level = getattr(
            logging, query.get("log_level", "info").upper(), logging.INFO
        )
        q: "_queue.Queue" = _queue.Queue(maxsize=512)

        class _Tap(logging.Handler):
            def emit(self, record):
                try:
                    q.put_nowait({
                        "Time": record.created,
                        "Level": record.levelname,
                        "Name": record.name,
                        "Message": record.getMessage(),
                    })
                except _queue.Full:
                    pass  # slow consumer: drop, never block the logger

        tap = _Tap(level=level)
        root = logging.getLogger()
        root.addHandler(tap)
        # The handler level alone can't see records the root logger drops:
        # with no logging config, the effective level is WARNING and an
        # info/debug monitor would stream nothing.  Lower the root level
        # for the stream's lifetime (the reference's monitor sink does the
        # same); restored below.  Concurrent monitors at different levels
        # keep the lowest until the last one exits — benign over-logging.
        prev_level = root.level
        if level < (root.level or logging.WARNING):
            root.setLevel(level)
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "application/x-ndjson")
            handler.send_header("Connection", "close")
            handler.end_headers()
            while True:
                try:
                    rec = q.get(timeout=10.0)
                    handler.wfile.write(json.dumps(rec).encode() + b"\n")
                except _queue.Empty:
                    handler.wfile.write(b"{}\n")  # keepalive
                handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            root.removeHandler(tap)
            root.setLevel(prev_level)

    # ------------------------------------------------------------------
    # Task filesystem + logs (reference: command/agent/fs_endpoint.go
    # /v1/client/fs/* — served by the agent holding the alloc, forwarded
    # by servers to the node's advertised agent address; the reference
    # forwards over the reverse yamux session, nomad/client_rpc.go)
    # ------------------------------------------------------------------

    def _authorize_alloc_ns(self, alloc_id: str, cap: str, token: str) -> None:
        """Resolve the ALLOCATION's namespace (a query parameter would let
        a token authorized in one namespace touch another's tasks) and
        enforce ``cap`` on it — via local token resolution on server
        agents, or a forwarded capability check on client-only agents
        (the reference's clients resolve ACLs via server RPC too).
        Shared by the fs/logs and exec surfaces."""
        client = self.agent.client
        server = self.agent.server
        ns = None
        if client is not None and alloc_id in client.allocs:
            ns = client.allocs[alloc_id].alloc.namespace
        elif server is not None:
            found = server.store.alloc_by_id(alloc_id)
            if found is not None:
                ns = found.namespace
        if ns is None:
            raise HTTPError(404, f"unknown allocation {alloc_id}")
        if server is not None:
            if server.config.acl_enabled:
                acl = server.resolve_token(token)
                if acl is None or not acl.allow_namespace(ns, cap):
                    raise HTTPError(403, f"Permission denied ({cap})")
        elif client is not None:
            # Reaching the node agent directly must not bypass the ACLs
            # the server enforces; fail closed when the check is down.
            try:
                allowed = client.server.check_acl_capability(
                    token, "namespace", cap, ns
                )
            except Exception as exc:  # noqa: BLE001
                raise HTTPError(502, f"ACL check unavailable: {exc}")
            if not allowed:
                raise HTTPError(403, f"Permission denied ({cap})")

    def serve_client_fs(
        self, handler, path: str, query: Dict, token: str = ""
    ) -> None:
        from ..acl import CAP_READ_FS, CAP_READ_LOGS

        cap = CAP_READ_LOGS if "/logs/" in path else CAP_READ_FS

        m = re.match(r"^/v1/client/fs/(ls|cat|logs)/([^/?]+)$", path)
        if not m:
            raise HTTPError(404, f"unknown fs route {path}")
        op, alloc_id = m.group(1), m.group(2)
        self._authorize_alloc_ns(alloc_id, cap, token)
        client = self.agent.client

        if client is None or alloc_id not in client.allocs:
            self._forward_client_fs(handler, path, query, alloc_id, token)
            return

        from ..client.client import AllocFSError

        try:
            if op == "ls":
                body = json.dumps(
                    client.list_files(alloc_id, query.get("path", ""))
                ).encode()
                self._raw_respond(handler, 200, body, "application/json")
                return
            if op == "cat":
                data = client.read_file(
                    alloc_id,
                    query.get("path", ""),
                    offset=int(query.get("offset", "0")),
                    limit=int(query.get("limit", str(1 << 20))),
                )
                self._raw_respond(
                    handler, 200, data, "application/octet-stream"
                )
                return
            # logs: tail + optional follow stream.  Positions are tracked
            # absolutely so bytes appended between the initial read and
            # the follow loop are never dropped.
            import os as _os

            rel = client.task_log_path(
                query.get("task", ""), query.get("type", "stdout")
            )
            offset = int(query.get("offset", "-65536"))
            follow = query.get("follow", "") in ("true", "1")
            target = client._resolve_fs_path(alloc_id, rel)
            size = _os.path.getsize(target)
            pos = max(0, size + offset) if offset < 0 else min(offset, size)
            data = client.read_file(
                alloc_id, rel, offset=pos, limit=max(0, size - pos)
            )
            pos += len(data)
        except AllocFSError as exc:
            raise HTTPError(exc.code, str(exc))
        except OSError as exc:
            raise HTTPError(404, str(exc))

        if not follow:
            self._raw_respond(handler, 200, data, "text/plain")
            return
        # Follow mode: chunked growth polling until the reader hangs up
        # (the reference's StreamFile frames; plain byte chunks here).
        handler.send_response(200)
        handler.send_header("Content-Type", "text/plain")
        handler.send_header("Connection", "close")
        handler.end_headers()
        try:
            handler.wfile.write(data)
            handler.wfile.flush()
            while True:
                size = _os.path.getsize(target)
                if size > pos:
                    chunk = client.read_file(
                        alloc_id, rel, offset=pos, limit=size - pos
                    )
                    handler.wfile.write(chunk)
                    handler.wfile.flush()
                    pos += len(chunk)
                time.sleep(0.25)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # reader went away / alloc dir removed
        except Exception:  # noqa: BLE001 — alloc GC'd mid-follow
            pass

    def serve_client_exec(
        self, handler, path: str, query: Dict, body: Dict, token: str = ""
    ) -> None:
        """Run a command in a task's context and stream NDJSON frames
        ({"stdout": b64} / {"stderr": b64} / {"exit": code}) — the
        alloc-exec surface (plugins/drivers/execstreaming.go; the
        reference's live pty bidi is trimmed to stdin-upfront over plain
        HTTP, which covers piped stdin and one-shot commands)."""
        import base64
        import subprocess

        from ..acl import CAP_ALLOC_EXEC

        m = re.match(r"^/v1/client/exec/([^/?]+)$", path)
        if not m:
            raise HTTPError(404, f"unknown exec route {path}")
        alloc_id = m.group(1)
        client = self.agent.client
        self._authorize_alloc_ns(alloc_id, CAP_ALLOC_EXEC, token)

        if client is None or alloc_id not in client.allocs:
            self._forward_client_exec(handler, path, body, alloc_id, token)
            return

        task = body.get("Task", "")
        argv = [str(a) for a in body.get("Cmd") or []]
        if not argv:
            raise HTTPError(400, "missing Cmd")
        ar = client.allocs[alloc_id]
        if not task and len(ar.runners) == 1:
            task = next(iter(ar.runners))
        runner = ar.runners.get(task)
        if runner is None:
            raise HTTPError(404, f"unknown task {task!r}")
        task_dir = runner.task_dir
        env = dict(os.environ)
        env.update({
            k: str(v) for k, v in (runner.task.env or {}).items()
        })
        stdin = base64.b64decode(body.get("Stdin", "") or "")

        handler.send_response(200)
        handler.send_header("Content-Type", "application/x-ndjson")
        handler.send_header("Connection", "close")
        handler.end_headers()

        def frame(obj) -> None:
            handler.wfile.write((json.dumps(obj) + "\n").encode())
            handler.wfile.flush()

        try:
            proc = subprocess.Popen(
                argv, cwd=task_dir, env=env,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
        except OSError as exc:
            frame({"error": str(exc)})
            return
        try:
            out, err = proc.communicate(stdin, timeout=float(
                body.get("Timeout", 300.0)
            ))
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
            frame({"error": "command timed out"})
        try:
            for chunk_name, data in (("stdout", out), ("stderr", err)):
                for i in range(0, len(data), 65536):
                    frame({
                        chunk_name: base64.b64encode(
                            data[i:i + 65536]
                        ).decode()
                    })
            frame({"exit": proc.returncode})
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    def _forward_client_exec(
        self, handler, path: str, body: Dict, alloc_id: str, token: str
    ) -> None:
        """Server leg: forward the exec request to the node agent holding
        the alloc and stream its NDJSON response through."""
        import urllib.error
        import urllib.request

        addr = self._node_agent_addr(alloc_id)
        headers = {"Content-Type": "application/json"}
        if token:
            headers["X-Nomad-Token"] = token
        req = urllib.request.Request(
            f"{addr}{path}", data=json.dumps(body).encode(),
            method="POST", headers=headers,
        )
        try:
            upstream = urllib.request.urlopen(req, timeout=330)
        except urllib.error.HTTPError as exc:
            raise HTTPError(exc.code, exc.read().decode(errors="replace"))
        with upstream:
            handler.send_response(upstream.status)
            handler.send_header("Content-Type", "application/x-ndjson")
            handler.send_header("Connection", "close")
            handler.end_headers()
            try:
                while True:
                    chunk = upstream.read1(65536)
                    if not chunk:
                        break
                    handler.wfile.write(chunk)
                    handler.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass

    def _node_agent_addr(self, alloc_id: str) -> str:
        """Resolve the HTTP address of the node agent holding an alloc —
        the shared first leg of every server→client forward (fs/logs,
        exec, restart/signal; fs_endpoint.go forwarding)."""
        server = self.agent.server
        if server is None:
            raise HTTPError(404, f"allocation {alloc_id} not on this agent")
        alloc = server.store.alloc_by_id(alloc_id)
        if alloc is None:
            raise HTTPError(404, f"unknown allocation {alloc_id}")
        from ..state.matrix import node_attributes

        node = server.store.node_by_id(alloc.node_id)
        addr = (
            node_attributes(node).get("nomad.advertise.address", "")
            if node is not None else ""
        )
        if not addr or addr == self.addr:
            raise HTTPError(
                404, f"allocation {alloc_id} has no reachable node agent"
            )
        return addr

    def _forward_client_alloc_op(self, path: str, body, token: str):
        """Server leg of restart/signal: POST through to the node agent."""
        import urllib.error
        import urllib.request

        m = re.match(r"^/v1/client/allocation/([^/]+)/", path)
        alloc_id = m.group(1) if m else ""
        addr = self._node_agent_addr(alloc_id)
        headers = {"Content-Type": "application/json"}
        if token:
            headers["X-Nomad-Token"] = token
        req = urllib.request.Request(
            f"{addr}{path}", data=json.dumps(body or {}).encode(),
            method="POST", headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read() or b"null")
        except urllib.error.HTTPError as exc:
            try:
                msg = json.loads(exc.read()).get("error", str(exc))
            except Exception:  # noqa: BLE001
                msg = str(exc)
            raise HTTPError(exc.code, msg)

    def _forward_client_fs(
        self, handler, path: str, query: Dict, alloc_id: str, token: str
    ) -> None:
        """Server-side forwarding: stream the node agent's response
        through (fs_endpoint.go forwarding leg)."""
        import urllib.error
        import urllib.parse
        import urllib.request

        addr = self._node_agent_addr(alloc_id)
        qs = urllib.parse.urlencode(query)
        req = urllib.request.Request(
            f"{addr}{path}?{qs}",
            headers={"X-Nomad-Token": token} if token else {},
        )
        try:
            # Generous timeout: follow-mode streams are idle between chunks.
            upstream = urllib.request.urlopen(req, timeout=300)
        except urllib.error.HTTPError as exc:
            raise HTTPError(exc.code, exc.read().decode(errors="replace"))
        with upstream:
            handler.send_response(upstream.status)
            handler.send_header(
                "Content-Type",
                upstream.headers.get("Content-Type", "text/plain"),
            )
            handler.send_header("Connection", "close")
            handler.end_headers()
            try:
                while True:
                    # read1: pass chunks through as they arrive (read(n)
                    # would stall a live follow stream until n bytes).
                    chunk = upstream.read1(65536)
                    if not chunk:
                        break
                    handler.wfile.write(chunk)
                    handler.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass

    @staticmethod
    def _raw_respond(handler, code: int, body: bytes, ctype: str) -> None:
        handler.send_response(code)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    # ------------------------------------------------------------------
    # Routing (http.go:252-324)
    # ------------------------------------------------------------------

    def route(
        self, method: str, path: str, query: Dict, body: Any,
        token: str = "", cluster_secret: str = "",
    ) -> Any:
        server = self.agent.server
        # Alloc lifecycle ops (`alloc restart` / `alloc signal`;
        # nomad/client_rpc.go forwarding → client Allocations.Restart/
        # Signal): served by the node agent holding the alloc, forwarded
        # by servers like the fs/exec surfaces.
        m = re.match(r"^/v1/client/allocation/([^/]+)/(restart|signal)$",
                     path)
        if m and method in ("PUT", "POST"):
            from ..acl import CAP_ALLOC_LIFECYCLE

            alloc_id, verb = m.group(1), m.group(2)
            self._authorize_alloc_ns(alloc_id, CAP_ALLOC_LIFECYCLE, token)
            client = self.agent.client
            if client is not None and alloc_id in client.allocs:
                ar = client.allocs[alloc_id]
                task = (body or {}).get("Task", "")
                if verb == "restart":
                    return {"Restarted": ar.restart_tasks(task)}
                import signal as _signal

                sig = (body or {}).get("Signal", "SIGTERM")
                try:
                    signum = (
                        int(sig) if str(sig).isdigit()
                        else int(_signal.Signals[str(sig).upper()])
                    )
                except KeyError:
                    raise HTTPError(400, f"unknown signal {sig!r}")
                out = ar.signal_tasks(signum, task)
                return {"Signalled": out["signalled"],
                        "Errors": out["errors"]}
            return self._forward_client_alloc_op(path, body, token)
        # Client-local surface: served by any agent running a client,
        # including client-only agents with no server to route through.
        if path == "/v1/client/stats" and method == "GET":
            if self.agent.client is None:
                raise HTTPError(501, "agent is not running a client")
            if server is not None and server.config.acl_enabled:
                acl = server.resolve_token(token)
                if acl is None or not acl.allow_node("read"):
                    raise HTTPError(403, "Permission denied (node:read)")
            elif self.agent.client is not None and server is None:
                try:
                    if not self.agent.client.server.check_acl_capability(
                        token, "node", "read"
                    ):
                        raise HTTPError(403, "Permission denied (node:read)")
                except HTTPError:
                    raise
                except Exception as exc:  # noqa: BLE001 — fail closed
                    raise HTTPError(502, f"ACL check unavailable: {exc}")
            return self.agent.client.host_stats()
        if server is None:
            raise HTTPError(501, "agent is not running a server")
        store = server.store

        # ---- consensus stream (server↔server; replication.py) ----
        if path.startswith("/v1/internal/raft/"):
            rep = store.replicator
            if rep is None:
                raise HTTPError(501, "server is not running replication")
            # Peer authentication: an unauthenticated snapshot-install
            # would let any caller replace the whole cluster state.  A
            # configured cluster_secret must match; with ACLs on and no
            # secret, a management token is accepted instead.
            want = server.config.cluster_secret
            if want:
                import hmac

                if not hmac.compare_digest(cluster_secret, want):
                    raise HTTPError(403, "bad or missing cluster secret")
            elif server.config.acl_enabled:
                acl = server.resolve_token(token)
                if acl is None or not acl.management:
                    raise HTTPError(
                        403,
                        "raft RPCs require a cluster_secret or a "
                        "management token",
                    )
            if path == "/v1/internal/raft/append":
                return rep.handle_append(body or {})
            if path == "/v1/internal/raft/vote":
                return rep.handle_vote(body or {})
            if path == "/v1/internal/raft/snapshot":
                return rep.handle_snapshot_install(body or {})
            if path == "/v1/internal/raft/stats":
                return rep.stats()
            raise HTTPError(404, f"unknown raft RPC {path}")

        # ---- leader gate: writes (and node RPCs) only serve on the leader
        # (the reference forwards to the leader, nomad/rpc.go forward; we
        # redirect — FailoverRPC/CLI follow the hint) ----
        # Any server can answer capability checks (ACL tables replicate).
        if path == "/v1/internal/acl/check":
            return {"Allowed": server.check_acl_capability(
                (body or {}).get("Token", ""),
                (body or {}).get("Kind", "agent"),
                (body or {}).get("Capability", "read"),
                (body or {}).get("Namespace", "default"),
            )}

        rep = store.replicator
        if rep is not None and not rep.is_leader:
            is_write = method in ("PUT", "POST", "DELETE") and path not in (
                "/v1/jobs/parse",
            )
            if is_write or path.startswith("/v1/internal/"):
                raise HTTPError(
                    409, f"not leader; leader={rep.leader_addr}"
                )

        # ---- ACL enforcement (nomad/acl.go resolution + per-endpoint
        # capability checks; anonymous policy when no token) ----
        if server.config.acl_enabled and path != "/v1/acl/bootstrap":
            self._check_acl(server, method, path, query, token)

        # ---- ACL endpoints (nomad/acl_endpoint.go) ----
        if path.startswith("/v1/acl"):
            return self._route_acl(server, method, path, query, body, token)

        # ---- internal node RPCs (client↔server wire; api/rpc.py peer) ----
        if path.startswith("/v1/internal/"):
            from ..structs import serde

            if path == "/v1/internal/node/register":
                node = serde.from_wire(body["Node"])
                return {"TTL": server.register_node(node)}
            if path == "/v1/internal/node/heartbeat":
                return {"TTL": server.heartbeat_node(body["NodeID"])}
            if path == "/v1/internal/node/status":
                server.update_node_status(body["NodeID"], body["Status"])
                return {}
            if path == "/v1/internal/node/client-allocs":
                wait = min(float(body.get("Wait", 30.0)), 60.0)
                allocs, index = server.get_client_allocs(
                    body["NodeID"],
                    min_index=int(body.get("MinIndex", 0)),
                    timeout=wait,
                )
                return {
                    "Allocs": [serde.to_wire(a) for a in allocs],
                    "Index": index,
                }
            if path == "/v1/internal/node/update-allocs":
                updates = [serde.from_wire(w) for w in body["Allocs"]]
                server.update_allocs_from_client(updates)
                return {}
            if path == "/v1/internal/node/volume-source":
                return {"Source": server.get_volume_source(
                    body.get("Namespace", "default"), body["VolumeID"]
                )}
            if path == "/v1/internal/node/alloc-fs-origin":
                return server.get_alloc_fs_origin(body["AllocID"])
            raise HTTPError(404, f"unknown internal RPC {path}")

        if path == "/v1/jobs" and method == "GET":
            prefix = query.get("prefix", "")
            ns = query.get("namespace", "default")
            return [
                self._job_stub(j)
                for j in store.all_jobs()
                if j.id.startswith(prefix) and j.namespace == ns
            ]
        if path == "/v1/jobs" and method in ("PUT", "POST"):
            payload = (body or {}).get("Job", body)
            if payload is None:
                raise HTTPError(400, "missing job")
            job = api_to_job(payload)
            # The body carries its own namespace — re-check against IT.
            from ..acl import CAP_SUBMIT_JOB

            self._require_ns_cap(server, token, job.namespace, CAP_SUBMIT_JOB)
            try:
                ev = server.submit_job(job)
            except ValueError as exc:
                raise HTTPError(400, str(exc))
            return {"EvalID": ev.id if ev else "", "JobModifyIndex":
                    store.job_by_id(job.namespace, job.id).modify_index}
        if path == "/v1/validate/job" and method in ("PUT", "POST"):
            # Admission dry run (nomad/job_endpoint.go Validate): mutate +
            # validate without registering.
            from ..server.admission import admit

            payload = (body or {}).get("Job", body)
            if payload is None:
                raise HTTPError(400, "missing job")
            try:
                job = api_to_job(payload)
                admit(job)
            except ValueError as exc:
                return {
                    "Valid": False,
                    "ValidationErrors": str(exc).split("; "),
                }
            except (TypeError, AttributeError, KeyError) as exc:
                # Type-malformed payloads (a string where a list belongs)
                # are invalid input, not server errors.
                return {
                    "Valid": False,
                    "ValidationErrors": [f"malformed job payload: {exc}"],
                }
            return {"Valid": True, "ValidationErrors": []}
        if path == "/v1/jobs/parse" and method == "POST":
            hcl = (body or {}).get("JobHCL", "")
            if not hcl:
                raise HTTPError(400, "missing JobHCL")
            return _dump(parse_job(hcl))

        m = re.match(r"^/v1/job/(.+)/plan$", path)
        if m and method in ("PUT", "POST"):
            payload = (body or {}).get("Job", body)
            if payload is None:
                raise HTTPError(400, "missing job")
            job = api_to_job(payload)
            if job.id != m.group(1):
                raise HTTPError(400, "job id does not match URL")
            from ..acl import CAP_SUBMIT_JOB

            self._require_ns_cap(server, token, job.namespace, CAP_SUBMIT_JOB)
            return server.plan_job(
                job, diff=bool((body or {}).get("Diff", False))
            )
        m = re.match(r"^/v1/job/(.+)/allocations$", path)
        if m and method == "GET":
            ns = query.get("namespace", "default")
            return _dump(store.allocs_by_job(ns, m.group(1)), exclude=("job",))
        m = re.match(r"^/v1/job/(.+)/evaluations$", path)
        if m and method == "GET":
            ns = query.get("namespace", "default")
            return _dump(store.evals_by_job(ns, m.group(1)))
        m = re.match(r"^/v1/job/(.+)/dispatch$", path)
        if m and method in ("PUT", "POST"):
            import base64

            ns = query.get("namespace", "default")
            from ..acl import CAP_DISPATCH_JOB

            self._require_ns_cap(server, token, ns, CAP_DISPATCH_JOB)
            try:
                # binascii.Error (bad base64) subclasses ValueError.
                payload = base64.b64decode(
                    (body or {}).get("Payload", "") or ""
                )
                child, ev = server.dispatch_job(
                    ns, m.group(1), payload, (body or {}).get("Meta") or {}
                )
            except ValueError as exc:
                raise HTTPError(400, str(exc))
            return {
                "DispatchedJobID": child.id,
                "EvalID": ev.id if ev else "",
                "Index": store.latest_index,
            }
        m = re.match(r"^/v1/job/(.+)/versions$", path)
        if m and method == "GET":
            ns = query.get("namespace", "default")
            versions = store.job_versions.get((ns, m.group(1)))
            if not versions:
                raise HTTPError(404, "job not found")
            return {
                "Versions": [_dump(v) for v in reversed(versions)],
            }
        m = re.match(r"^/v1/job/(.+)/revert$", path)
        if m and method in ("PUT", "POST"):
            ns = (body or {}).get("Namespace", query.get("namespace", "default"))
            from ..acl import CAP_SUBMIT_JOB

            self._require_ns_cap(server, token, ns, CAP_SUBMIT_JOB)
            to_version = (body or {}).get("JobVersion")
            ev = server.revert_job(
                ns, m.group(1),
                int(to_version) if to_version is not None else None,
            )
            if ev is None:
                raise HTTPError(404, "job or target version not found")
            return {"EvalID": ev.id, "JobModifyIndex": store.latest_index}
        m = re.match(r"^/v1/job/(.+)/scale$", path)
        if m:
            ns = query.get("namespace", "default")
            if method == "GET":
                # Job.ScaleStatus: per-group counts + events.
                job = store.job_by_id(ns, m.group(1))
                if job is None:
                    raise HTTPError(404, "job not found")
                groups = {}
                job_allocs = store.allocs_by_job(ns, job.id)
                for tg in job.task_groups:
                    running = sum(
                        1 for a in job_allocs
                        if a.task_group == tg.name
                        and not a.terminal_status()
                    )
                    groups[tg.name] = {
                        "Desired": tg.count,
                        "Running": running,
                        "Events": [
                            _dump(e) for e in reversed(
                                store.scaling_events.get(
                                    (ns, job.id, tg.name), []
                                )
                            )
                        ],
                    }
                return {"JobID": job.id, "JobStopped": job.stop,
                        "TaskGroups": groups}
            if method in ("PUT", "POST"):
                ns = (body or {}).get("Namespace", ns)
                from ..acl import CAP_SCALE_JOB

                self._require_ns_cap(server, token, ns, CAP_SCALE_JOB)
                target = (body or {}).get("Target") or {}
                group = target.get("Group", "")
                count = (body or {}).get("Count")
                try:
                    ev = server.scale_job(
                        ns, m.group(1), group,
                        int(count) if count is not None else None,
                        message=(body or {}).get("Message", ""),
                        error=bool((body or {}).get("Error", False)),
                        meta=(body or {}).get("Meta") or {},
                    )
                except ValueError as exc:
                    raise HTTPError(400, str(exc))
                return {"EvalID": ev.id if ev else "",
                        "Index": store.latest_index}
        m = re.match(r"^/v1/job/(.+)/deployments$", path)
        if m and method == "GET":
            ns = query.get("namespace", "default")
            deps = [
                d for d in store.deployments.values()
                if d.namespace == ns and d.job_id == m.group(1)
            ]
            deps.sort(key=lambda d: d.create_index, reverse=True)
            return _dump(deps)
        m = re.match(r"^/v1/job/(.+)/deployment$", path)
        if m and method == "GET":
            ns = query.get("namespace", "default")
            dep = store.latest_deployment_by_job(ns, m.group(1))
            return _dump(dep)
        m = re.match(r"^/v1/job/(.+)/summary$", path)
        if m and method == "GET":
            ns = query.get("namespace", "default")
            summary = store.job_summaries.get((ns, m.group(1)))
            if summary is None:
                raise HTTPError(404, "job not found")
            return {
                "JobID": summary.job_id,
                "Namespace": summary.namespace,
                "Summary": summary.summary,
            }
        # Bare job lookup LAST: the greedy id capture would otherwise
        # swallow the suffixed routes above.
        m = re.match(r"^/v1/job/(.+)$", path)
        if m:
            ns = query.get("namespace", "default")
            job = store.job_by_id(ns, m.group(1))
            if method == "GET":
                if job is None:
                    raise HTTPError(404, "job not found")
                return _dump(job)
            if method == "DELETE":
                purge = query.get("purge", "") in ("true", "1")
                ev = server.deregister_job(ns, m.group(1), purge=purge)
                if ev is None:
                    raise HTTPError(404, "job not found")
                return {"EvalID": ev.id}

        if path == "/v1/nodes" and method == "GET":
            return [
                self._node_stub(n) for n in store.nodes.values()
            ]
        m = re.match(r"^/v1/node/([^/]+)$", path)
        if m and method == "GET":
            node = store.node_by_id(m.group(1))
            if node is None:
                raise HTTPError(404, "node not found")
            return _dump(node)
        m = re.match(r"^/v1/node/([^/]+)/allocations$", path)
        if m and method == "GET":
            return _dump(store.allocs_by_node(m.group(1)), exclude=("job",))
        m = re.match(r"^/v1/node/([^/]+)/drain$", path)
        if m and method in ("PUT", "POST"):
            spec = (body or {}).get("DrainSpec")
            strategy = None
            if spec is not None:
                strategy = DrainStrategy(
                    deadline=float(spec.get("Deadline", 3600.0)),
                    ignore_system_jobs=bool(
                        spec.get("IgnoreSystemJobs", False)
                    ),
                )
            server.update_node_drain(
                m.group(1), strategy,
                mark_eligible=bool((body or {}).get("MarkEligible", False)),
            )
            return {"NodeModifyIndex": store.latest_index}
        m = re.match(r"^/v1/node/([^/]+)/eligibility$", path)
        if m and method in ("PUT", "POST"):
            elig = (body or {}).get("Eligibility", "eligible")
            server.update_node_eligibility(m.group(1), elig)
            return {"NodeModifyIndex": store.latest_index}

        if path == "/v1/evaluations" and method == "GET":
            ns = query.get("namespace", "default")
            return _dump([
                e for e in store.evals.values() if e.namespace == ns
            ])
        m = re.match(r"^/v1/evaluation/([^/]+)$", path)
        if m and method == "GET":
            ev = store.eval_by_id(m.group(1))
            if ev is None:
                raise HTTPError(404, "eval not found")
            from ..acl import CAP_READ_JOB

            self._require_ns_cap(server, token, ev.namespace, CAP_READ_JOB)
            return _dump(ev)
        m = re.match(r"^/v1/evaluation/([^/]+)/allocations$", path)
        if m and method == "GET":
            ev = store.eval_by_id(m.group(1))
            if ev is None:
                raise HTTPError(404, "eval not found")
            from ..acl import CAP_READ_JOB

            self._require_ns_cap(server, token, ev.namespace, CAP_READ_JOB)
            return _dump(store.allocs_by_eval(m.group(1)), exclude=("job",))

        if path == "/v1/allocations" and method == "GET":
            ns = query.get("namespace", "default")
            return _dump([
                a for a in store.allocs.values() if a.namespace == ns
            ], exclude=("job",))
        m = re.match(r"^/v1/allocation/([^/]+)$", path)
        if m and method == "GET":
            alloc = store.alloc_by_id(m.group(1))
            if alloc is None:
                raise HTTPError(404, "alloc not found")
            from ..acl import CAP_READ_JOB

            self._require_ns_cap(
                server, token, alloc.namespace, CAP_READ_JOB
            )
            return _dump(alloc, exclude=("job",))
        m = re.match(r"^/v1/allocation/([^/]+)/stop$", path)
        if m and method in ("PUT", "POST"):
            ev = server.stop_alloc(m.group(1))
            if ev is None:
                raise HTTPError(404, "alloc not found")
            return {"EvalID": ev.id}

        # ---- deployments (nomad/deployment_endpoint.go: List :446,
        # Promote :118, Fail, Pause) ----
        if path == "/v1/deployments" and method == "GET":
            ns = query.get("namespace", "default")
            prefix = query.get("prefix", "")
            deps = [
                d for d in store.deployments.values()
                if d.namespace == ns and d.id.startswith(prefix)
            ]
            deps.sort(key=lambda d: d.create_index, reverse=True)
            return _dump(deps)
        m = re.match(r"^/v1/deployment/([^/]+)$", path)
        if m and method == "GET":
            dep = store.deployment_by_id(m.group(1))
            if dep is None:
                raise HTTPError(404, "deployment not found")
            from ..acl import CAP_READ_JOB

            self._require_ns_cap(server, token, dep.namespace, CAP_READ_JOB)
            return _dump(dep)
        m = re.match(r"^/v1/deployment/([^/]+)/allocations$", path)
        if m and method == "GET":
            dep = store.deployment_by_id(m.group(1))
            if dep is None:
                raise HTTPError(404, "deployment not found")
            from ..acl import CAP_READ_JOB

            self._require_ns_cap(server, token, dep.namespace, CAP_READ_JOB)
            return _dump([
                a for a in store.allocs.values()
                if a.deployment_id == dep.id
            ], exclude=("job",))
        m = re.match(r"^/v1/deployment/([^/]+)/(promote|fail|pause)$", path)
        if m and method in ("PUT", "POST"):
            dep = store.deployment_by_id(m.group(1))
            if dep is None:
                raise HTTPError(404, "deployment not found")
            from ..acl import CAP_SUBMIT_JOB

            self._require_ns_cap(server, token, dep.namespace, CAP_SUBMIT_JOB)
            verb = m.group(2)
            if not dep.active():
                raise HTTPError(
                    400, f"cannot {verb} a terminal deployment "
                    f"({dep.status})"
                )
            if verb == "promote":
                groups = (body or {}).get("Groups")
                if (body or {}).get("All") or not groups:
                    groups = None  # promote every canary group
                if not dep.requires_promotion():
                    raise HTTPError(400, "deployment has no canaries to promote")
                server.promote_deployment(dep.id, groups)
            elif verb == "fail":
                server.fail_deployment(
                    dep.id, "Deployment marked as failed by operator"
                )
            else:
                server.pause_deployment(
                    dep.id, bool((body or {}).get("Pause", True))
                )
            return {"DeploymentModifyIndex": store.latest_index,
                    "Index": store.latest_index}

        # ---- volumes (nomad/csi_endpoint.go trimmed to the plugin-less
        # registered-volume analog) ----
        if path == "/v1/volumes":
            ns = query.get("namespace", "default")
            if method == "GET":
                return _dump(sorted(
                    (v for (vns, _), v in store.volumes.items()
                     if vns == ns),
                    key=lambda v: v.id,
                ))
            if method in ("PUT", "POST"):
                from ..structs.types import Volume

                spec = (body or {}).get("Volume", body) or {}
                vol = Volume(
                    id=spec.get("ID", spec.get("id", "")),
                    name=spec.get("Name", spec.get("name", "")),
                    namespace=spec.get(
                        "Namespace", spec.get("namespace", ns)
                    ),
                    source=spec.get("Source", spec.get("source", "")),
                    access_mode=spec.get(
                        "AccessMode",
                        spec.get("access_mode", "single-node-writer"),
                    ),
                    attachment_mode=spec.get(
                        "AttachmentMode",
                        spec.get("attachment_mode", "file-system"),
                    ),
                    capacity_mb=int(spec.get(
                        "CapacityMB", spec.get("capacity_mb", 0)
                    )),
                )
                from ..acl import CAP_SUBMIT_JOB

                self._require_ns_cap(
                    server, token, vol.namespace, CAP_SUBMIT_JOB
                )
                store.upsert_volume(server.next_index(), vol)
                return {"ID": vol.id, "Index": store.latest_index}
        m = re.match(r"^/v1/volume/([^/]+)$", path)
        if m:
            ns = query.get("namespace", "default")
            vol = store.volume_by_id(ns, m.group(1))
            if vol is None:
                raise HTTPError(404, "volume not found")
            if method == "GET":
                return _dump(vol)
            if method == "DELETE":
                from ..acl import CAP_SUBMIT_JOB

                self._require_ns_cap(
                    server, token, vol.namespace, CAP_SUBMIT_JOB
                )
                try:
                    store.delete_volume(server.next_index(), ns, m.group(1))
                except ValueError as exc:
                    raise HTTPError(409, str(exc))
                return {}

        # ---- scaling policies (nomad/scaling_endpoint.go) ----
        if path == "/v1/scaling/policies" and method == "GET":
            ns = query.get("namespace", "default")
            return [
                {
                    "Namespace": pns, "JobID": jid, "Group": group,
                    "Policy": _dump(pol),
                }
                for (pns, jid, group), pol in sorted(
                    store.scaling_policies.items()
                )
                if pns == ns
            ]

        # ---- system (nomad/system_endpoint.go) ----
        if path == "/v1/system/gc" and method in ("PUT", "POST"):
            server.system_gc()
            return {}

        # ---- membership (nomad/serf.go join; operator_endpoint.go
        # RaftRemovePeer) ----
        if path == "/v1/operator/raft/join" and method in ("PUT", "POST"):
            addr = (body or {}).get("Addr", "")
            if not addr:
                raise HTTPError(400, "missing Addr")
            try:
                return {"Members": server.join_peer(addr)}
            except ValueError as exc:
                raise HTTPError(501, str(exc))
        if path == "/v1/operator/raft/remove-peer" and method in (
            "PUT", "POST"
        ):
            addr = (body or {}).get("Addr", "")
            if not addr:
                raise HTTPError(400, "missing Addr")
            try:
                return {"Members": server.remove_peer(addr)}
            except ValueError as exc:
                raise HTTPError(501, str(exc))

        if path == "/v1/status/leader" and method == "GET":
            rep = store.replicator
            return rep.leader_addr if rep is not None else self.agent.rpc_addr
        if path == "/v1/agent/members" and method == "GET":
            members = [self.agent.member_info()]
            rep = store.replicator
            if rep is not None:
                st = rep.stats()
                members[0]["Leader"] = rep.is_leader
                members[0]["RaftTerm"] = st["Term"]
                for addr, pst in st["Peers"].items():
                    members.append({
                        "Name": addr,
                        "Addr": addr,
                        "Server": True,
                        "Status": "alive" if pst["Healthy"] else "failed",
                        "Leader": addr == st["LeaderAddr"],
                        "LastError": pst["LastError"],
                    })
                return {"Members": members, "Leader": st["LeaderAddr"]}
            return {"Members": members}
        if path == "/v1/agent/self" and method == "GET":
            return self.agent.member_info()
        if path == "/v1/agent/profile" and method == "GET":
            # Thread stack dump — the pprof-goroutine analog
            # (command/agent/pprof/pprof.go) for a Python runtime.
            import traceback as _tb

            frames = sys._current_frames()
            out = {}
            for t in threading.enumerate():
                frame = frames.get(t.ident)
                out[t.name] = (
                    _tb.format_stack(frame) if frame is not None else []
                )
            return {"Threads": out, "Count": len(out)}

        # ---- search (nomad/search_endpoint.go: prefix matches across
        # contexts, truncated at 20 per context) ----
        if path == "/v1/search" and method in ("PUT", "POST"):
            prefix = (body or {}).get("Prefix", "")
            context = (body or {}).get("Context", "all")
            ns = (body or {}).get("Namespace", "default")
            # Per-context capability gating (search_endpoint.go
            # sufficientSearchPerms): namespace contexts need read-job on
            # the searched namespace, nodes need node:read; a token with
            # neither gets 403 rather than an empty sweep.
            ns_ok = node_ok = True
            if server.config.acl_enabled:
                acl = server.resolve_token(token)
                if acl is None:
                    raise HTTPError(403, "ACL token not found")
                from ..acl import CAP_READ_JOB

                ns_ok = acl.allow_namespace(ns, CAP_READ_JOB)
                node_ok = acl.allow_node("read")
                if not ns_ok and not node_ok:
                    raise HTTPError(403, "Permission denied (search)")
            matches: Dict[str, List[str]] = {}
            truncations: Dict[str, bool] = {}

            def collect(name: str, ids):
                hits = [i for i in ids if i.startswith(prefix)]
                matches[name] = sorted(hits)[:20]
                truncations[name] = len(hits) > 20

            if not ns_ok:
                context = "nodes"
            elif not node_ok and context == "all":
                pass  # nodes skipped below
            if context in ("all", "jobs"):
                collect("jobs", [
                    jid for (jns, jid) in store.jobs if jns == ns
                ])
            if context in ("all", "nodes") and node_ok:
                collect("nodes", list(store.nodes))
            if context in ("all", "allocs"):
                collect("allocs", [
                    a.id for a in store.allocs.values()
                    if a.namespace == ns
                ])
            if context in ("all", "evals"):
                collect("evals", [
                    e.id for e in store.evals.values()
                    if e.namespace == ns
                ])
            if context in ("all", "deployment"):
                collect("deployment", [
                    d.id for d in store.deployments.values()
                    if d.namespace == ns
                ])
            return {"Matches": matches, "Truncations": truncations}

        # ---- namespaces (nomad/namespace_endpoint.go) ----
        if path == "/v1/namespaces" and method == "GET":
            return sorted(store.namespaces.values(), key=lambda n: n["Name"])
        m = re.match(r"^/v1/namespace/([^/]+)$", path)
        if m:
            if method == "GET":
                ns_obj = store.namespaces.get(m.group(1))
                if ns_obj is None:
                    raise HTTPError(404, "namespace not found")
                return ns_obj
            if method in ("PUT", "POST"):
                self._require_management(server, token)
                store.upsert_namespace(
                    server.next_index(), m.group(1),
                    (body or {}).get("Description", ""),
                )
                return {}
            if method == "DELETE":
                self._require_management(server, token)
                try:
                    store.delete_namespace(server.next_index(), m.group(1))
                except ValueError as exc:
                    raise HTTPError(400, str(exc))
                return {}

        if path == "/v1/operator/scheduler/configuration":
            if method == "GET":
                return _dump(store.scheduler_config)
            if method in ("PUT", "POST"):
                cfg = store.scheduler_config
                new = SchedulerConfiguration(
                    scheduler_algorithm=(body or {}).get(
                        "scheduler_algorithm", cfg.scheduler_algorithm
                    ),
                    preemption_config=cfg.preemption_config,
                    memory_oversubscription_enabled=(body or {}).get(
                        "memory_oversubscription_enabled",
                        cfg.memory_oversubscription_enabled,
                    ),
                )
                pc = (body or {}).get("preemption_config")
                if pc:
                    new.preemption_config = dataclasses.replace(
                        cfg.preemption_config, **pc
                    )
                store.set_scheduler_config(server.next_index(), new)
                return {"Updated": True}

        if path == "/v1/slo" and method == "GET":
            server = self.agent.server
            if server is None:
                raise HTTPError(501, "agent is not running a server")
            return server.observatory.slo_report()

        if path == "/v1/health" and method == "GET":
            # Liveness + overload surface: status/score/pressure inputs
            # plus currently breached SLOs (obs/health.py).  Always 200 —
            # the status field is the verdict, so a degraded cluster
            # still serves its own diagnosis.
            server = self.agent.server
            if server is None:
                raise HTTPError(501, "agent is not running a server")
            return server.observatory.health_report()

        if path == "/v1/overload" and method == "GET":
            # The control loop's full decision surface: state machine,
            # pressure windows, hysteresis budget, and per-actuator
            # stats (obs/controller.py).
            server = self.agent.server
            if server is None:
                raise HTTPError(501, "agent is not running a server")
            return server.overload_controller.report()

        if path == "/v1/metrics" and method == "GET":
            snap = self.agent.metrics()
            if query.get("format") == "prometheus":
                from ..metrics import to_prometheus

                return RawResponse(
                    to_prometheus(snap).encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            return snap

        if path == "/v1/trace" and method == "GET":
            from .. import trace as _trace

            limit = None
            if query.get("limit"):
                try:
                    limit = int(query["limit"])
                except ValueError:
                    raise HTTPError(400, "limit must be an integer")
            records = _trace.dump(limit=limit)
            if query.get("clear") in ("1", "true"):
                _trace.clear()
            if query.get("format") == "chrome":
                # Perfetto-loadable body, ready to save to a file
                # (`nomad trace dump` fetches this).
                return RawResponse(
                    json.dumps(_trace.chrome_trace(records)).encode(),
                    "application/json",
                )
            return {
                "records": records,
                "count": len(records),
                "config": _trace.config(),
            }

        if path == "/v1/trace/config":
            from .. import trace as _trace

            if method == "GET":
                return _trace.config()
            if method in ("PUT", "POST"):
                b = body or {}
                return _trace.configure(
                    enabled=b.get("enabled"),
                    sample=b.get("sample"),
                    ring=b.get("ring"),
                )

        raise HTTPError(404, f"no handler for {method} {path}")

    @staticmethod
    def _job_stub(job) -> Dict[str, Any]:
        return {
            "id": job.id,
            "name": job.name,
            "namespace": job.namespace,
            "type": job.type,
            "priority": job.priority,
            "status": job.status,
            "stop": job.stop,
            "version": job.version,
            "modify_index": job.modify_index,
        }

    @staticmethod
    def _node_stub(node) -> Dict[str, Any]:
        return {
            "id": node.id,
            "name": node.name,
            "datacenter": node.datacenter,
            "node_class": node.node_class,
            "status": node.status,
            "drain": node.drain,
            "scheduling_eligibility": node.scheduling_eligibility,
        }
