"""HTTP API — the ``/v1`` surface.

Reference: ``command/agent/http.go:252-324`` route registration. JSON over
HTTP; the CLI and external tooling consume this, mirroring the reference's
api/ package contract.
"""

from __future__ import annotations

import dataclasses
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..jobspec import api_to_job, parse_job
from ..structs.types import DrainStrategy, SchedulerConfiguration


def _dump(obj: Any, exclude: Tuple[str, ...] = ()) -> Any:
    if obj is None:
        return None
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        d = dataclasses.asdict(obj)
        for k in exclude:
            d.pop(k, None)
        return d
    if isinstance(obj, list):
        return [_dump(o, exclude) for o in obj]
    if isinstance(obj, dict):
        return {k: _dump(v, exclude) for k, v in obj.items()}
    return obj


class HTTPError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class HTTPAPIServer:
    """Routes requests onto the in-process agent (server and/or client)."""

    def __init__(self, agent, host: str = "127.0.0.1", port: int = 0):
        self.agent = agent
        api = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            def _respond(self, code: int, payload: Any) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _handle(self, method: str) -> None:
                try:
                    parsed = urlparse(self.path)
                    multi = parse_qs(parsed.query)
                    query = {k: v[0] for k, v in multi.items()}
                    if parsed.path == "/v1/event/stream" and method == "GET":
                        # NDJSON stream — bypasses the one-shot JSON path.
                        api.stream_events(self, multi)
                        return
                    length = int(self.headers.get("Content-Length", 0) or 0)
                    raw = self.rfile.read(length) if length else b""
                    body = json.loads(raw) if raw else None
                    result = api.route(method, parsed.path, query, body)
                    self._respond(200, result)
                except HTTPError as exc:
                    self._respond(exc.code, {"error": exc.message})
                except Exception as exc:  # noqa: BLE001
                    from ..server.replication import NotLeaderError

                    if isinstance(exc, NotLeaderError):
                        self._respond(409, {
                            "error": f"not leader; leader={exc.leader_addr}"
                        })
                    else:
                        self._respond(500, {"error": str(exc)})

            def do_GET(self):
                self._handle("GET")

            def do_PUT(self):
                self._handle("PUT")

            def do_POST(self):
                self._handle("POST")

            def do_DELETE(self):
                self._handle("DELETE")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.addr = f"http://{host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="http-api", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    # ------------------------------------------------------------------
    # Event stream (nomad/stream/ + /v1/event/stream NDJSON,
    # command/agent/event_endpoint.go)
    # ------------------------------------------------------------------

    def stream_events(self, handler, multi_query: Dict) -> None:
        server = self.agent.server
        if server is None:
            raise HTTPError(501, "agent is not running a server")
        # topic filters: repeated topic=Topic:key params ("*" wildcards).
        topics: Dict[str, list] = {}
        for spec in multi_query.get("topic", ["*:*"]):
            topic, _, key = spec.partition(":")
            topics.setdefault(topic or "*", []).append(key or "*")
        from_index = int(multi_query.get("index", ["0"])[0] or 0)

        sub = server.store.events.subscribe(topics, from_index=from_index)
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "application/x-ndjson")
            handler.send_header("Connection", "close")
            handler.end_headers()
            while True:
                events = sub.next(timeout=10.0)
                if sub.closed:
                    return
                if not events:
                    # Heartbeat keeps intermediaries from timing the
                    # connection out (the reference sends empty objects).
                    handler.wfile.write(b"{}\n")
                    handler.wfile.flush()
                    continue
                for ev in events:
                    handler.wfile.write(
                        (json.dumps(ev.to_wire()) + "\n").encode()
                    )
                handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away
        finally:
            sub.close()

    # ------------------------------------------------------------------
    # Routing (http.go:252-324)
    # ------------------------------------------------------------------

    def route(self, method: str, path: str, query: Dict, body: Any) -> Any:
        server = self.agent.server
        if server is None:
            raise HTTPError(501, "agent is not running a server")
        store = server.store

        # ---- consensus stream (server↔server; replication.py) ----
        if path.startswith("/v1/internal/raft/"):
            rep = store.replicator
            if rep is None:
                raise HTTPError(501, "server is not running replication")
            if path == "/v1/internal/raft/append":
                return rep.handle_append(body or {})
            if path == "/v1/internal/raft/vote":
                return rep.handle_vote(body or {})
            if path == "/v1/internal/raft/snapshot":
                return rep.handle_snapshot_install(body or {})
            if path == "/v1/internal/raft/stats":
                return rep.stats()
            raise HTTPError(404, f"unknown raft RPC {path}")

        # ---- leader gate: writes (and node RPCs) only serve on the leader
        # (the reference forwards to the leader, nomad/rpc.go forward; we
        # redirect — FailoverRPC/CLI follow the hint) ----
        rep = store.replicator
        if rep is not None and not rep.is_leader:
            is_write = method in ("PUT", "POST", "DELETE") and path not in (
                "/v1/jobs/parse",
            )
            if is_write or path.startswith("/v1/internal/"):
                raise HTTPError(
                    409, f"not leader; leader={rep.leader_addr}"
                )

        # ---- internal node RPCs (client↔server wire; api/rpc.py peer) ----
        if path.startswith("/v1/internal/"):
            from ..structs import serde

            if path == "/v1/internal/node/register":
                node = serde.from_wire(body["Node"])
                return {"TTL": server.register_node(node)}
            if path == "/v1/internal/node/heartbeat":
                return {"TTL": server.heartbeat_node(body["NodeID"])}
            if path == "/v1/internal/node/status":
                server.update_node_status(body["NodeID"], body["Status"])
                return {}
            if path == "/v1/internal/node/client-allocs":
                wait = min(float(body.get("Wait", 30.0)), 60.0)
                allocs, index = server.get_client_allocs(
                    body["NodeID"],
                    min_index=int(body.get("MinIndex", 0)),
                    timeout=wait,
                )
                return {
                    "Allocs": [serde.to_wire(a) for a in allocs],
                    "Index": index,
                }
            if path == "/v1/internal/node/update-allocs":
                updates = [serde.from_wire(w) for w in body["Allocs"]]
                server.update_allocs_from_client(updates)
                return {}
            raise HTTPError(404, f"unknown internal RPC {path}")

        if path == "/v1/jobs" and method == "GET":
            prefix = query.get("prefix", "")
            return [
                self._job_stub(j)
                for j in store.all_jobs()
                if j.id.startswith(prefix)
            ]
        if path == "/v1/jobs" and method in ("PUT", "POST"):
            payload = (body or {}).get("Job", body)
            if payload is None:
                raise HTTPError(400, "missing job")
            job = api_to_job(payload)
            ev = server.submit_job(job)
            return {"EvalID": ev.id if ev else "", "JobModifyIndex":
                    store.job_by_id(job.namespace, job.id).modify_index}
        if path == "/v1/jobs/parse" and method == "POST":
            hcl = (body or {}).get("JobHCL", "")
            if not hcl:
                raise HTTPError(400, "missing JobHCL")
            return _dump(parse_job(hcl))

        m = re.match(r"^/v1/job/([^/]+)$", path)
        if m:
            ns = query.get("namespace", "default")
            job = store.job_by_id(ns, m.group(1))
            if method == "GET":
                if job is None:
                    raise HTTPError(404, "job not found")
                return _dump(job)
            if method == "DELETE":
                purge = query.get("purge", "") in ("true", "1")
                ev = server.deregister_job(ns, m.group(1), purge=purge)
                if ev is None:
                    raise HTTPError(404, "job not found")
                return {"EvalID": ev.id}
        m = re.match(r"^/v1/job/([^/]+)/allocations$", path)
        if m and method == "GET":
            ns = query.get("namespace", "default")
            return _dump(store.allocs_by_job(ns, m.group(1)), exclude=("job",))
        m = re.match(r"^/v1/job/([^/]+)/evaluations$", path)
        if m and method == "GET":
            ns = query.get("namespace", "default")
            return _dump(store.evals_by_job(ns, m.group(1)))
        m = re.match(r"^/v1/job/([^/]+)/summary$", path)
        if m and method == "GET":
            ns = query.get("namespace", "default")
            summary = store.job_summaries.get((ns, m.group(1)))
            if summary is None:
                raise HTTPError(404, "job not found")
            return {
                "JobID": summary.job_id,
                "Namespace": summary.namespace,
                "Summary": summary.summary,
            }

        if path == "/v1/nodes" and method == "GET":
            return [
                self._node_stub(n) for n in store.nodes.values()
            ]
        m = re.match(r"^/v1/node/([^/]+)$", path)
        if m and method == "GET":
            node = store.node_by_id(m.group(1))
            if node is None:
                raise HTTPError(404, "node not found")
            return _dump(node)
        m = re.match(r"^/v1/node/([^/]+)/allocations$", path)
        if m and method == "GET":
            return _dump(store.allocs_by_node(m.group(1)), exclude=("job",))
        m = re.match(r"^/v1/node/([^/]+)/drain$", path)
        if m and method in ("PUT", "POST"):
            spec = (body or {}).get("DrainSpec")
            strategy = None
            if spec is not None:
                strategy = DrainStrategy(
                    deadline=float(spec.get("Deadline", 3600.0)),
                    ignore_system_jobs=bool(
                        spec.get("IgnoreSystemJobs", False)
                    ),
                )
            server.update_node_drain(
                m.group(1), strategy,
                mark_eligible=bool((body or {}).get("MarkEligible", False)),
            )
            return {"NodeModifyIndex": store.latest_index}
        m = re.match(r"^/v1/node/([^/]+)/eligibility$", path)
        if m and method in ("PUT", "POST"):
            elig = (body or {}).get("Eligibility", "eligible")
            server.update_node_eligibility(m.group(1), elig)
            return {"NodeModifyIndex": store.latest_index}

        if path == "/v1/evaluations" and method == "GET":
            return _dump(list(store.evals.values()))
        m = re.match(r"^/v1/evaluation/([^/]+)$", path)
        if m and method == "GET":
            ev = store.eval_by_id(m.group(1))
            if ev is None:
                raise HTTPError(404, "eval not found")
            return _dump(ev)
        m = re.match(r"^/v1/evaluation/([^/]+)/allocations$", path)
        if m and method == "GET":
            return _dump(store.allocs_by_eval(m.group(1)), exclude=("job",))

        if path == "/v1/allocations" and method == "GET":
            return _dump(list(store.allocs.values()), exclude=("job",))
        m = re.match(r"^/v1/allocation/([^/]+)$", path)
        if m and method == "GET":
            alloc = store.alloc_by_id(m.group(1))
            if alloc is None:
                raise HTTPError(404, "alloc not found")
            return _dump(alloc, exclude=("job",))
        m = re.match(r"^/v1/allocation/([^/]+)/stop$", path)
        if m and method in ("PUT", "POST"):
            ev = server.stop_alloc(m.group(1))
            if ev is None:
                raise HTTPError(404, "alloc not found")
            return {"EvalID": ev.id}

        if path == "/v1/status/leader" and method == "GET":
            return self.agent.rpc_addr
        if path == "/v1/agent/members" and method == "GET":
            return {"Members": [self.agent.member_info()]}
        if path == "/v1/agent/self" and method == "GET":
            return self.agent.member_info()

        if path == "/v1/operator/scheduler/configuration":
            if method == "GET":
                return _dump(store.scheduler_config)
            if method in ("PUT", "POST"):
                cfg = store.scheduler_config
                new = SchedulerConfiguration(
                    scheduler_algorithm=(body or {}).get(
                        "scheduler_algorithm", cfg.scheduler_algorithm
                    ),
                    preemption_config=cfg.preemption_config,
                    memory_oversubscription_enabled=(body or {}).get(
                        "memory_oversubscription_enabled",
                        cfg.memory_oversubscription_enabled,
                    ),
                )
                pc = (body or {}).get("preemption_config")
                if pc:
                    new.preemption_config = dataclasses.replace(
                        cfg.preemption_config, **pc
                    )
                store.set_scheduler_config(server.next_index(), new)
                return {"Updated": True}

        if path == "/v1/metrics" and method == "GET":
            return self.agent.metrics()

        raise HTTPError(404, f"no handler for {method} {path}")

    @staticmethod
    def _job_stub(job) -> Dict[str, Any]:
        return {
            "id": job.id,
            "name": job.name,
            "namespace": job.namespace,
            "type": job.type,
            "priority": job.priority,
            "status": job.status,
            "stop": job.stop,
            "version": job.version,
            "modify_index": job.modify_index,
        }

    @staticmethod
    def _node_stub(node) -> Dict[str, Any]:
        return {
            "id": node.id,
            "name": node.name,
            "datacenter": node.datacenter,
            "node_class": node.node_class,
            "status": node.status,
            "drain": node.drain,
            "scheduling_eligibility": node.scheduling_eligibility,
        }
