"""HTTP API client — what the CLI and external users consume.

Reference: the ``api/`` Go client package (api/jobs.go etc.).

The client is a well-behaved citizen under overload: a ``429 Too Many
Requests`` from the server's admission gate is retried through the
shared :mod:`..retry` backoff, honoring the ``Retry-After`` hint the
gate computed from the token bucket's actual deficit.  Waiting is
``max(backoff, Retry-After)`` — decorrelated jitter on top of the
server's floor, so a flash crowd of clients does not re-synchronize
into a retry storm.  ``NOMAD_TPU_RETRY_429_ATTEMPTS=1`` disables
retrying (callers see the 429 immediately).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from ..retry import Backoff, RetryPolicy, env_int


class APIError(Exception):
    def __init__(
        self, code: int, message: str,
        retry_after: Optional[float] = None,
    ):
        super().__init__(f"HTTP {code}: {message}")
        self.code = code
        self.retry_after = retry_after


def _rate_limit_policy() -> RetryPolicy:
    return RetryPolicy(
        base_delay=0.2,
        max_delay=10.0,
        max_attempts=env_int("NOMAD_TPU_RETRY_429_ATTEMPTS", 3),
    )


class APIClient:
    def __init__(
        self, address: str = "http://127.0.0.1:4646", token: str = "",
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self.address = address.rstrip("/")
        self.token = token  # X-Nomad-Token (SecretID) on every request
        self.retry_policy = retry_policy or _rate_limit_policy()
        self.rate_limited = 0  # 429s seen (retried or not)

    def _call(
        self, method: str, path: str, body: Optional[Any] = None
    ) -> Any:
        backoff = Backoff(self.retry_policy)
        attempts = 0
        while True:
            try:
                return self._call_once(method, path, body)
            except APIError as exc:
                if exc.code != 429:
                    raise
                self.rate_limited += 1
                attempts += 1
                cap = self.retry_policy.max_attempts or 1
                if attempts >= cap:
                    raise
                # Server's floor wins over our jittered backoff — never
                # retry before the gate says the bucket refills.
                delay = backoff.next_delay()
                if exc.retry_after is not None:
                    delay = max(delay, exc.retry_after)
                time.sleep(delay)

    def _call_once(
        self, method: str, path: str, body: Optional[Any] = None
    ) -> Any:
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["X-Nomad-Token"] = self.token
        req = urllib.request.Request(
            f"{self.address}{path}", data=data, method=method,
            headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read() or b"null")
        except urllib.error.HTTPError as exc:
            try:
                msg = json.loads(exc.read()).get("error", str(exc))
            except Exception:  # noqa: BLE001
                msg = str(exc)
            retry_after = None
            ra = exc.headers.get("Retry-After") if exc.headers else None
            if ra is not None:
                try:
                    retry_after = float(ra)
                except ValueError:
                    pass
            raise APIError(exc.code, msg, retry_after=retry_after) from exc

    # Jobs ------------------------------------------------------------

    def register_job(self, job_payload: Dict) -> Dict:
        return self._call("PUT", "/v1/jobs", {"Job": job_payload})

    def plan_job(
        self, job_id: str, job_payload: Dict, diff: bool = False,
        namespace: str = "default",
    ) -> Dict:
        return self._call(
            "PUT",
            f"/v1/job/{job_id}/plan?namespace={namespace}",
            {"Job": job_payload, "Diff": diff},
        )

    def list_jobs(self, prefix: str = "") -> List[Dict]:
        return self._call("GET", f"/v1/jobs?prefix={prefix}")

    # ACL --------------------------------------------------------------

    def acl_bootstrap(self) -> Dict:
        return self._call("POST", "/v1/acl/bootstrap")

    def acl_upsert_policy(
        self, name: str, rules: str, description: str = ""
    ) -> Dict:
        return self._call(
            "PUT", f"/v1/acl/policy/{name}",
            {"Rules": rules, "Description": description},
        )

    def acl_create_token(
        self, name: str = "", type: str = "client",
        policies: Optional[List[str]] = None,
    ) -> Dict:
        return self._call("POST", "/v1/acl/token", {
            "Name": name, "Type": type, "Policies": policies or [],
        })

    def acl_token_self(self) -> Dict:
        return self._call("GET", "/v1/acl/token/self")

    # Namespaces + search ----------------------------------------------

    def list_namespaces(self) -> List[Dict]:
        return self._call("GET", "/v1/namespaces")

    def upsert_namespace(self, name: str, description: str = "") -> Dict:
        return self._call(
            "PUT", f"/v1/namespace/{name}", {"Description": description}
        )

    def delete_namespace(self, name: str) -> Dict:
        return self._call("DELETE", f"/v1/namespace/{name}")

    def search(
        self, prefix: str, context: str = "all", namespace: str = "default"
    ) -> Dict:
        return self._call("POST", "/v1/search", {
            "Prefix": prefix, "Context": context, "Namespace": namespace,
        })

    def get_job(self, job_id: str, namespace: str = "default") -> Dict:
        return self._call("GET", f"/v1/job/{job_id}?namespace={namespace}")

    def deregister_job(
        self, job_id: str, purge: bool = False, namespace: str = "default"
    ) -> Dict:
        return self._call(
            "DELETE",
            f"/v1/job/{job_id}?namespace={namespace}"
            f"&purge={'true' if purge else 'false'}",
        )

    def job_allocations(self, job_id: str, namespace: str = "default"):
        return self._call(
            "GET", f"/v1/job/{job_id}/allocations?namespace={namespace}"
        )

    def job_evaluations(self, job_id: str, namespace: str = "default"):
        return self._call(
            "GET", f"/v1/job/{job_id}/evaluations?namespace={namespace}"
        )

    def job_summary(self, job_id: str, namespace: str = "default"):
        return self._call(
            "GET", f"/v1/job/{job_id}/summary?namespace={namespace}"
        )

    def dispatch_job(
        self, job_id: str, payload: bytes = b"", meta: Optional[Dict] = None,
        namespace: str = "default",
    ) -> Dict:
        import base64

        return self._call(
            "PUT", f"/v1/job/{job_id}/dispatch?namespace={namespace}",
            {
                "Payload": base64.b64encode(payload).decode()
                if payload else "",
                "Meta": meta or {},
            },
        )

    def job_versions(self, job_id: str, namespace: str = "default") -> Dict:
        return self._call(
            "GET", f"/v1/job/{job_id}/versions?namespace={namespace}"
        )

    def revert_job(
        self, job_id: str, version: Optional[int] = None,
        namespace: str = "default",
    ) -> Dict:
        body: Dict = {"Namespace": namespace}
        if version is not None:
            body["JobVersion"] = version
        return self._call("PUT", f"/v1/job/{job_id}/revert", body)

    def scale_job(
        self, job_id: str, group: str, count: int, message: str = "",
        namespace: str = "default",
    ) -> Dict:
        return self._call(
            "PUT", f"/v1/job/{job_id}/scale",
            {
                "Namespace": namespace, "Count": count,
                "Target": {"Group": group}, "Message": message,
            },
        )

    def job_scale_status(
        self, job_id: str, namespace: str = "default"
    ) -> Dict:
        return self._call(
            "GET", f"/v1/job/{job_id}/scale?namespace={namespace}"
        )

    def job_deployments(self, job_id: str, namespace: str = "default"):
        return self._call(
            "GET", f"/v1/job/{job_id}/deployments?namespace={namespace}"
        )

    # Deployments ------------------------------------------------------

    def list_deployments(self, namespace: str = "default") -> List[Dict]:
        return self._call("GET", f"/v1/deployments?namespace={namespace}")

    def get_deployment(self, deployment_id: str) -> Dict:
        return self._call("GET", f"/v1/deployment/{deployment_id}")

    def deployment_allocations(self, deployment_id: str) -> List[Dict]:
        return self._call(
            "GET", f"/v1/deployment/{deployment_id}/allocations"
        )

    def promote_deployment(
        self, deployment_id: str, groups: Optional[List[str]] = None
    ) -> Dict:
        body: Dict = {"All": True} if not groups else {"Groups": groups}
        return self._call(
            "PUT", f"/v1/deployment/{deployment_id}/promote", body
        )

    def fail_deployment(self, deployment_id: str) -> Dict:
        return self._call("PUT", f"/v1/deployment/{deployment_id}/fail", {})

    def pause_deployment(self, deployment_id: str, pause: bool = True) -> Dict:
        return self._call(
            "PUT", f"/v1/deployment/{deployment_id}/pause", {"Pause": pause}
        )

    # System -----------------------------------------------------------

    def system_gc(self) -> Dict:
        return self._call("PUT", "/v1/system/gc", {})

    def alloc_exec(
        self, alloc_id: str, task: str, argv: List[str],
        stdin: bytes = b"", timeout: float = 300.0,
    ):
        """Run a command in a task's context; returns (exit_code, stdout,
        stderr).  Streams NDJSON frames from /v1/client/exec/."""
        import base64
        import urllib.request

        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["X-Nomad-Token"] = self.token
        req = urllib.request.Request(
            f"{self.address}/v1/client/exec/{alloc_id}",
            data=json.dumps({
                "Task": task,
                "Cmd": list(argv),
                "Stdin": base64.b64encode(stdin).decode() if stdin else "",
                "Timeout": timeout,
            }).encode(),
            method="POST",
            headers=headers,
        )
        out, err, code = b"", b"", -1
        try:
            with urllib.request.urlopen(req, timeout=timeout + 30) as resp:
                for line in resp:
                    if not line.strip():
                        continue
                    frame = json.loads(line)
                    if "stdout" in frame:
                        out += base64.b64decode(frame["stdout"])
                    if "stderr" in frame:
                        err += base64.b64decode(frame["stderr"])
                    if "error" in frame:
                        raise APIError(500, frame["error"])
                    if "exit" in frame:
                        code = int(frame["exit"])
        except urllib.error.HTTPError as exc:
            try:
                msg = json.loads(exc.read()).get("error", str(exc))
            except Exception:  # noqa: BLE001
                msg = str(exc)
            raise APIError(exc.code, msg) from exc
        return code, out, err

    # Volumes ----------------------------------------------------------

    def list_volumes(self, namespace: str = "default") -> List[Dict]:
        return self._call("GET", f"/v1/volumes?namespace={namespace}")

    def register_volume(self, spec: Dict, namespace: str = "default") -> Dict:
        return self._call(
            "PUT", f"/v1/volumes?namespace={namespace}", {"Volume": spec}
        )

    def get_volume(self, volume_id: str, namespace: str = "default") -> Dict:
        return self._call(
            "GET", f"/v1/volume/{volume_id}?namespace={namespace}"
        )

    def deregister_volume(
        self, volume_id: str, namespace: str = "default"
    ) -> Dict:
        return self._call(
            "DELETE", f"/v1/volume/{volume_id}?namespace={namespace}"
        )

    def server_join(self, addr: str) -> Dict:
        return self._call("PUT", "/v1/operator/raft/join", {"Addr": addr})

    def server_remove_peer(self, addr: str) -> Dict:
        return self._call(
            "PUT", "/v1/operator/raft/remove-peer", {"Addr": addr}
        )

    def list_scaling_policies(self, namespace: str = "default"):
        return self._call(
            "GET", f"/v1/scaling/policies?namespace={namespace}"
        )

    def validate_job(self, job_payload: Dict) -> Dict:
        return self._call("PUT", "/v1/validate/job", {"Job": job_payload})

    def list_evaluations(self, namespace: str = "default") -> List[Dict]:
        return self._call("GET", f"/v1/evaluations?namespace={namespace}")

    def parse_job_hcl(self, hcl: str) -> Dict:
        return self._call("POST", "/v1/jobs/parse", {"JobHCL": hcl})

    # Nodes -----------------------------------------------------------

    def list_nodes(self) -> List[Dict]:
        return self._call("GET", "/v1/nodes")

    def get_node(self, node_id: str) -> Dict:
        return self._call("GET", f"/v1/node/{node_id}")

    def node_allocations(self, node_id: str):
        return self._call("GET", f"/v1/node/{node_id}/allocations")

    def drain_node(
        self, node_id: str, enable: bool = True, deadline: float = 3600.0
    ) -> Dict:
        body = {"DrainSpec": {"Deadline": deadline}} if enable else {
            "DrainSpec": None, "MarkEligible": True,
        }
        return self._call("PUT", f"/v1/node/{node_id}/drain", body)

    def set_node_eligibility(self, node_id: str, eligible: bool) -> Dict:
        return self._call(
            "PUT",
            f"/v1/node/{node_id}/eligibility",
            {"Eligibility": "eligible" if eligible else "ineligible"},
        )

    # Evals / allocs ----------------------------------------------------

    def get_evaluation(self, eval_id: str) -> Dict:
        return self._call("GET", f"/v1/evaluation/{eval_id}")

    def get_allocation(self, alloc_id: str) -> Dict:
        return self._call("GET", f"/v1/allocation/{alloc_id}")

    def restart_allocation(self, alloc_id: str, task: str = "") -> Dict:
        return self._call(
            "POST", f"/v1/client/allocation/{alloc_id}/restart",
            {"Task": task},
        )

    def signal_allocation(
        self, alloc_id: str, signal: str = "SIGTERM", task: str = ""
    ) -> Dict:
        return self._call(
            "POST", f"/v1/client/allocation/{alloc_id}/signal",
            {"Signal": signal, "Task": task},
        )

    def stop_allocation(self, alloc_id: str) -> Dict:
        return self._call("PUT", f"/v1/allocation/{alloc_id}/stop")

    # Operator / agent --------------------------------------------------

    def members(self) -> Dict:
        return self._call("GET", "/v1/agent/members")

    def leader(self) -> str:
        return self._call("GET", "/v1/status/leader")

    def scheduler_configuration(self) -> Dict:
        return self._call("GET", "/v1/operator/scheduler/configuration")

    def set_scheduler_configuration(self, config: Dict) -> Dict:
        return self._call(
            "PUT", "/v1/operator/scheduler/configuration", config
        )

    def metrics(self) -> Dict:
        return self._call("GET", "/v1/metrics")

    def metrics_prometheus(self) -> str:
        """Prometheus text exposition of the agent's metrics registry."""
        return self._call_raw("GET", "/v1/metrics?format=prometheus").decode()

    def slo(self) -> Dict:
        """SLO observatory report: per-spec value, burn rates, status."""
        return self._call("GET", "/v1/slo")

    def health(self) -> Dict:
        """Composite health: status band, score, pressure inputs."""
        return self._call("GET", "/v1/health")

    def overload(self) -> Dict:
        """Overload controller report: state machine, pressure windows,
        flip budget, per-actuator stats (obs/controller.py)."""
        return self._call("GET", "/v1/overload")

    # Tracing -----------------------------------------------------------

    def trace_records(
        self, limit: Optional[int] = None, clear: bool = False
    ) -> Dict:
        qs = []
        if limit is not None:
            qs.append(f"limit={limit}")
        if clear:
            qs.append("clear=1")
        suffix = "?" + "&".join(qs) if qs else ""
        return self._call("GET", f"/v1/trace{suffix}")

    def trace_dump(self, limit: Optional[int] = None) -> bytes:
        """Chrome trace-event JSON body (Perfetto-loadable), as bytes."""
        suffix = "&limit=%d" % limit if limit is not None else ""
        return self._call_raw("GET", f"/v1/trace?format=chrome{suffix}")

    def trace_config(self) -> Dict:
        return self._call("GET", "/v1/trace/config")

    def trace_configure(self, **kwargs) -> Dict:
        return self._call("PUT", "/v1/trace/config", kwargs)

    def _call_raw(self, method: str, path: str) -> bytes:
        headers = {}
        if self.token:
            headers["X-Nomad-Token"] = self.token
        req = urllib.request.Request(
            f"{self.address}{path}", method=method, headers=headers
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.read()
        except urllib.error.HTTPError as exc:
            try:
                msg = json.loads(exc.read()).get("error", str(exc))
            except Exception:  # noqa: BLE001
                msg = str(exc)
            raise APIError(exc.code, msg) from exc
