"""HTTP API client — what the CLI and external users consume.

Reference: the ``api/`` Go client package (api/jobs.go etc.).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional


class APIError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"HTTP {code}: {message}")
        self.code = code


class APIClient:
    def __init__(self, address: str = "http://127.0.0.1:4646", token: str = ""):
        self.address = address.rstrip("/")
        self.token = token  # X-Nomad-Token (SecretID) on every request

    def _call(
        self, method: str, path: str, body: Optional[Any] = None
    ) -> Any:
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["X-Nomad-Token"] = self.token
        req = urllib.request.Request(
            f"{self.address}{path}", data=data, method=method,
            headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read() or b"null")
        except urllib.error.HTTPError as exc:
            try:
                msg = json.loads(exc.read()).get("error", str(exc))
            except Exception:  # noqa: BLE001
                msg = str(exc)
            raise APIError(exc.code, msg) from exc

    # Jobs ------------------------------------------------------------

    def register_job(self, job_payload: Dict) -> Dict:
        return self._call("PUT", "/v1/jobs", {"Job": job_payload})

    def plan_job(
        self, job_id: str, job_payload: Dict, diff: bool = False,
        namespace: str = "default",
    ) -> Dict:
        return self._call(
            "PUT",
            f"/v1/job/{job_id}/plan?namespace={namespace}",
            {"Job": job_payload, "Diff": diff},
        )

    def list_jobs(self, prefix: str = "") -> List[Dict]:
        return self._call("GET", f"/v1/jobs?prefix={prefix}")

    # ACL --------------------------------------------------------------

    def acl_bootstrap(self) -> Dict:
        return self._call("POST", "/v1/acl/bootstrap")

    def acl_upsert_policy(
        self, name: str, rules: str, description: str = ""
    ) -> Dict:
        return self._call(
            "PUT", f"/v1/acl/policy/{name}",
            {"Rules": rules, "Description": description},
        )

    def acl_create_token(
        self, name: str = "", type: str = "client",
        policies: Optional[List[str]] = None,
    ) -> Dict:
        return self._call("POST", "/v1/acl/token", {
            "Name": name, "Type": type, "Policies": policies or [],
        })

    def acl_token_self(self) -> Dict:
        return self._call("GET", "/v1/acl/token/self")

    # Namespaces + search ----------------------------------------------

    def list_namespaces(self) -> List[Dict]:
        return self._call("GET", "/v1/namespaces")

    def upsert_namespace(self, name: str, description: str = "") -> Dict:
        return self._call(
            "PUT", f"/v1/namespace/{name}", {"Description": description}
        )

    def delete_namespace(self, name: str) -> Dict:
        return self._call("DELETE", f"/v1/namespace/{name}")

    def search(
        self, prefix: str, context: str = "all", namespace: str = "default"
    ) -> Dict:
        return self._call("POST", "/v1/search", {
            "Prefix": prefix, "Context": context, "Namespace": namespace,
        })

    def get_job(self, job_id: str, namespace: str = "default") -> Dict:
        return self._call("GET", f"/v1/job/{job_id}?namespace={namespace}")

    def deregister_job(
        self, job_id: str, purge: bool = False, namespace: str = "default"
    ) -> Dict:
        return self._call(
            "DELETE",
            f"/v1/job/{job_id}?namespace={namespace}"
            f"&purge={'true' if purge else 'false'}",
        )

    def job_allocations(self, job_id: str, namespace: str = "default"):
        return self._call(
            "GET", f"/v1/job/{job_id}/allocations?namespace={namespace}"
        )

    def job_evaluations(self, job_id: str, namespace: str = "default"):
        return self._call(
            "GET", f"/v1/job/{job_id}/evaluations?namespace={namespace}"
        )

    def job_summary(self, job_id: str, namespace: str = "default"):
        return self._call(
            "GET", f"/v1/job/{job_id}/summary?namespace={namespace}"
        )

    def parse_job_hcl(self, hcl: str) -> Dict:
        return self._call("POST", "/v1/jobs/parse", {"JobHCL": hcl})

    # Nodes -----------------------------------------------------------

    def list_nodes(self) -> List[Dict]:
        return self._call("GET", "/v1/nodes")

    def get_node(self, node_id: str) -> Dict:
        return self._call("GET", f"/v1/node/{node_id}")

    def node_allocations(self, node_id: str):
        return self._call("GET", f"/v1/node/{node_id}/allocations")

    def drain_node(
        self, node_id: str, enable: bool = True, deadline: float = 3600.0
    ) -> Dict:
        body = {"DrainSpec": {"Deadline": deadline}} if enable else {
            "DrainSpec": None, "MarkEligible": True,
        }
        return self._call("PUT", f"/v1/node/{node_id}/drain", body)

    def set_node_eligibility(self, node_id: str, eligible: bool) -> Dict:
        return self._call(
            "PUT",
            f"/v1/node/{node_id}/eligibility",
            {"Eligibility": "eligible" if eligible else "ineligible"},
        )

    # Evals / allocs ----------------------------------------------------

    def get_evaluation(self, eval_id: str) -> Dict:
        return self._call("GET", f"/v1/evaluation/{eval_id}")

    def get_allocation(self, alloc_id: str) -> Dict:
        return self._call("GET", f"/v1/allocation/{alloc_id}")

    def stop_allocation(self, alloc_id: str) -> Dict:
        return self._call("PUT", f"/v1/allocation/{alloc_id}/stop")

    # Operator / agent --------------------------------------------------

    def members(self) -> Dict:
        return self._call("GET", "/v1/agent/members")

    def leader(self) -> str:
        return self._call("GET", "/v1/status/leader")

    def scheduler_configuration(self) -> Dict:
        return self._call("GET", "/v1/operator/scheduler/configuration")

    def set_scheduler_configuration(self, config: Dict) -> Dict:
        return self._call(
            "PUT", "/v1/operator/scheduler/configuration", config
        )

    def metrics(self) -> Dict:
        return self._call("GET", "/v1/metrics")
