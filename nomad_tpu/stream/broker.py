"""Event broker — FSM-commit change events fanned out to subscribers.

Reference: ``nomad/stream/event_broker.go:30-49`` (EventBroker holding an
``eventBuffer`` ring; per-subscriber ``subscription`` cursors with topic
filtering) + ``ndjson.go`` (the `/v1/event/stream` encoding, handled by the
HTTP layer here).

Events are published by the state store as mutations commit (the same
place the reference hooks memdb txns), carrying *references* to the
store's immutable objects — serialization cost is paid per-subscriber at
stream time, not per-commit.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

TOPIC_ALL = "*"

# Topics (reference: structs/event.go TopicJob/TopicAlloc/...).
TOPIC_JOB = "Job"
TOPIC_EVAL = "Evaluation"
TOPIC_ALLOC = "Allocation"
TOPIC_NODE = "Node"
TOPIC_DEPLOYMENT = "Deployment"


@dataclass
class Event:
    topic: str
    type: str  # e.g. JobRegistered, AllocationUpdated, NodeDeregistered
    key: str  # primary id
    namespace: str = "default"
    index: int = 0
    payload: Any = None  # store object reference (immutable discipline)

    def to_wire(self) -> Dict:
        from ..structs import serde

        try:
            payload = serde.to_wire(self.payload)
        except TypeError:
            payload = repr(self.payload)
        return {
            "Topic": self.topic,
            "Type": self.type,
            "Key": self.key,
            "Namespace": self.namespace,
            "Index": self.index,
            "Payload": payload,
        }


class Subscription:
    def __init__(self, broker: "EventBroker", topics: Dict[str, List[str]]):
        self.broker = broker
        self.topics = topics  # topic -> list of keys ("*" = all)
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self.closed = False
        from ..lint.tsan import maybe_instrument

        maybe_instrument("subscription", self)

    def _matches(self, ev: Event) -> bool:
        for topic in (ev.topic, TOPIC_ALL):
            keys = self.topics.get(topic)
            if keys is None:
                continue
            if TOPIC_ALL in keys or ev.key in keys:
                return True
        return False

    def _offer(self, events: List[Event]) -> None:
        take = [e for e in events if self._matches(e)]
        if not take:
            return
        with self._cond:
            self._queue.extend(take)
            self._cond.notify_all()

    def next(self, timeout: Optional[float] = None) -> List[Event]:
        """Block for the next batch of matching events ([] on timeout or
        close)."""
        with self._cond:
            self._cond.wait_for(
                lambda: self._queue or self.closed, timeout=timeout
            )
            out = list(self._queue)
            self._queue.clear()
            return out

    def close(self) -> None:
        with self._cond:
            self.closed = True
            self._cond.notify_all()
        self.broker._unsubscribe(self)


class EventBroker:
    def __init__(self, buffer_size: int = 4096):
        self._lock = threading.Lock()
        self._buffer: deque = deque(maxlen=buffer_size)
        self._subs: List[Subscription] = []
        self.latest_index = 0
        # Highest index known to be unservable from the backlog: events
        # evicted from the ring, plus (after a restart) all pre-restore
        # history — restore does not re-publish, so a reconnecting
        # subscriber with a pre-restart cursor must see a gap marker.
        # A ``from_index`` at or below this cannot be served gaplessly.
        self._dropped_through = 0
        from ..lint.tsan import maybe_instrument

        maybe_instrument("broker", self)

    def mark_history_truncated(self, through_index: int) -> None:
        """Declare that no event with index <= ``through_index`` can be
        replayed (called by the store after a WAL/snapshot restore)."""
        with self._lock:
            if through_index > self._dropped_through:
                self._dropped_through = through_index

    def publish(self, events: List[Event]) -> None:
        if not events:
            return
        with self._lock:
            maxlen = self._buffer.maxlen
            for e in events:
                if maxlen is not None and len(self._buffer) == maxlen:
                    evicted = self._buffer[0]
                    if evicted.index > self._dropped_through:
                        self._dropped_through = evicted.index
                self._buffer.append(e)
            if events[-1].index > self.latest_index:
                self.latest_index = events[-1].index
            subs = list(self._subs)
        for sub in subs:
            sub._offer(events)

    def subscribe(
        self,
        topics: Optional[Dict[str, List[str]]] = None,
        from_index: int = 0,
    ) -> Subscription:
        """Subscribe to topics ({topic: [keys]}, default everything).
        ``from_index`` > 0 replays buffered events newer than it first.

        When events newer than ``from_index`` have already been evicted
        from the ring, the replay is *gapped*: the subscription's first
        event is a synthetic ``Framework/EventStreamGap`` control event
        (bypassing topic filters) telling the consumer the earliest index
        the backlog actually covers, so it can resync with a list call
        instead of silently consuming a history with a hole in it.
        """
        sub = Subscription(self, topics or {TOPIC_ALL: [TOPIC_ALL]})
        with self._lock:
            if from_index:
                if self._dropped_through > from_index:
                    gap = Event(
                        topic="Framework",
                        type="EventStreamGap",
                        key="",
                        index=self._dropped_through,
                        payload={
                            "requested_index": from_index,
                            "dropped_through": self._dropped_through,
                        },
                    )
                    with sub._cond:
                        sub._queue.append(gap)
                sub._offer(
                    [e for e in self._buffer if e.index > from_index]
                )
            self._subs.append(sub)
        return sub

    def _unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)
