"""Change-event stream (reference: ``nomad/stream/``)."""

from .broker import Event, EventBroker, Subscription, TOPIC_ALL

__all__ = ["Event", "EventBroker", "Subscription", "TOPIC_ALL"]
