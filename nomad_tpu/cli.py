"""CLI — the user surface (reference: ``command/`` ~100 subcommands; this
covers the core operational set: agent, job run/status/stop/plan-parse,
node status/drain/eligibility, alloc status, eval status, server members,
operator scheduler config, metrics)."""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time
from typing import List, Optional

from .api.client import APIClient, APIError
from .jobspec import job_to_api, parse_job

DEFAULT_ADDR = os.environ.get("NOMAD_TPU_ADDR", "http://127.0.0.1:4646")


def _client(args) -> APIClient:
    return APIClient(args.address, token=getattr(args, "token", ""))


def _print(obj) -> None:
    print(json.dumps(obj, indent=2, default=str))


def cmd_agent(args) -> int:
    from .api.agent import Agent, AgentConfig
    from .api.config_file import apply_config, load_config_files

    # Precedence (command/agent/config.go): defaults < config files
    # (merged in order) < explicitly passed CLI flags.  Flags default to
    # None so "explicitly passed" is distinguishable from "defaulted".
    config = AgentConfig(http_port=4646)
    if args.config:
        apply_config(load_config_files(args.config), config)
    if args.name is not None:
        config.name = args.name
    if args.dc is not None:
        config.datacenter = args.dc
    if args.client_only:
        config.server_enabled = False
    if args.server_only:
        config.client_enabled = False
    if args.servers is not None:
        config.server_addr = args.servers
    if args.bind is not None:
        config.http_host = args.bind
    if args.port is not None:
        config.http_port = args.port
    if args.workers is not None:
        config.server_config.num_workers = args.workers
    if args.raft:
        config.server_config.raft_enabled = True
    if args.peers is not None:
        config.server_config.peers = [
            a for a in args.peers.split(",") if a
        ]
    if args.data_dir:
        config.server_config.data_dir = args.data_dir
    agent = Agent(config)
    agent.start()
    print(f"agent started; HTTP API at {agent.rpc_addr}")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        print("shutting down")
        agent.shutdown()
    return 0


def cmd_job_run(args) -> int:
    src = open(args.jobfile).read()
    job = parse_job(src)
    client = _client(args)
    result = client.register_job(job_to_api(job))
    print(f"Job {job.id!r} registered; eval {result.get('EvalID', '')}")
    if args.detach:
        return 0
    eval_id = result.get("EvalID")
    if not eval_id:
        return 0
    deadline = time.time() + 30
    while time.time() < deadline:
        ev = client.get_evaluation(eval_id)
        if ev["status"] in ("complete", "failed", "cancelled"):
            print(f"Evaluation {eval_id[:8]} {ev['status']}")
            if ev.get("queued_allocations"):
                queued = {
                    k: v
                    for k, v in ev["queued_allocations"].items()
                    if v
                }
                if queued:
                    print(f"Queued (unplaced): {queued}")
            for a in client.job_allocations(job.id, job.namespace):
                print(
                    f"  alloc {a['id'][:8]} {a['name']} -> node "
                    f"{a['node_id'][:8]} [{a['client_status']}]"
                )
            return 0
        time.sleep(0.2)
    print("timed out waiting for evaluation")
    return 1


def cmd_job_plan(args) -> int:
    """Dry-run the scheduler on a jobspec: what WOULD change
    (reference: `nomad job plan`, command/job_plan.go)."""
    job = parse_job(open(args.jobfile).read())
    client = _client(args)
    result = client.plan_job(
        job.id, job_to_api(job), diff=args.diff, namespace=job.namespace
    )
    diff = result.get("Diff")
    if diff:
        fields = f" ({', '.join(diff['Fields'])})" if diff["Fields"] else ""
        print(f"Job: {diff['Type']}{fields}")
    for tg, counts in (
        result.get("Annotations", {}).get("DesiredTGUpdates", {}) or {}
    ).items():
        shown = {k: v for k, v in counts.items() if v}
        print(f"Task Group {tg!r}: {shown or 'no changes'}")
    failed = result.get("FailedTGAllocs") or {}
    for tg, metric in failed.items():
        print(
            f"WARNING: task group {tg!r} would have "
            f"{metric.get('coalesced_failures', 0) + 1} unplaced alloc(s)"
        )
    print(
        "\nJob Modify Index:", result.get("JobModifyIndex", 0),
        "\n(run with this index via -check-index semantics to guard "
        "against concurrent changes)",
    )
    return 1 if failed else 0


def cmd_job_status(args) -> int:
    client = _client(args)
    if not args.job_id:
        for stub in client.list_jobs():
            print(
                f"{stub['id']:40} {stub['type']:8} prio={stub['priority']:3} "
                f"{stub['status']}{' (stopped)' if stub['stop'] else ''}"
            )
        return 0
    job = client.get_job(args.job_id, args.namespace)
    print(f"ID       = {job['id']}")
    print(f"Name     = {job['name']}")
    print(f"Type     = {job['type']}")
    print(f"Priority = {job['priority']}")
    print(f"Status   = {job['status']}{' (stopped)' if job['stop'] else ''}")
    try:
        summary = client.job_summary(args.job_id, args.namespace)
        print("Summary:")
        for tg, counts in summary["Summary"].items():
            shown = {k: v for k, v in counts.items() if v}
            print(f"  {tg}: {shown or '{}'}")
    except APIError:
        pass
    print("Allocations:")
    for a in client.job_allocations(args.job_id, args.namespace):
        print(
            f"  {a['id'][:8]} {a['name']:32} node={a['node_id'][:8]} "
            f"desired={a['desired_status']} status={a['client_status']}"
        )
    return 0


def cmd_job_stop(args) -> int:
    client = _client(args)
    result = client.deregister_job(
        args.job_id, purge=args.purge, namespace=args.namespace
    )
    print(f"Job {args.job_id!r} stopping; eval {result.get('EvalID', '')}")
    return 0


def cmd_job_parse(args) -> int:
    job = parse_job(open(args.jobfile).read())
    _print(dataclasses.asdict(job))
    return 0


def cmd_node_status(args) -> int:
    client = _client(args)
    if not args.node_id:
        for n in client.list_nodes():
            print(
                f"{n['id'][:8]} {n['name']:24} {n['datacenter']:8} "
                f"{n['status']:12} drain={n['drain']} "
                f"{n['scheduling_eligibility']}"
            )
        return 0
    node = client.get_node(args.node_id)
    _print(node)
    print("Allocations:")
    for a in client.node_allocations(args.node_id):
        print(
            f"  {a['id'][:8]} {a['name']:32} desired={a['desired_status']} "
            f"status={a['client_status']}"
        )
    return 0


def cmd_node_drain(args) -> int:
    client = _client(args)
    client.drain_node(
        args.node_id, enable=not args.disable, deadline=args.deadline
    )
    print(
        f"Node {args.node_id[:8]} drain "
        f"{'disabled' if args.disable else 'enabled'}"
    )
    return 0


def cmd_node_eligibility(args) -> int:
    client = _client(args)
    client.set_node_eligibility(args.node_id, args.enable)
    print(
        f"Node {args.node_id[:8]} marked "
        f"{'eligible' if args.enable else 'ineligible'}"
    )
    return 0


def cmd_alloc_status(args) -> int:
    client = _client(args)
    alloc = client.get_allocation(_resolve_alloc_id(client, args.alloc_id))
    keep = (
        "id", "name", "node_id", "job_id", "task_group", "desired_status",
        "client_status", "create_time",
    )
    _print({k: alloc[k] for k in keep if k in alloc})
    if args.verbose and alloc.get("metrics"):
        _print(alloc["metrics"])
    if alloc.get("task_states"):
        print("Task states:")
        for name, ts in alloc["task_states"].items():
            print(
                f"  {name}: {ts['state']} failed={ts['failed']} "
                f"restarts={ts['restarts']}"
            )
    return 0


def cmd_alloc_logs(args) -> int:
    """Tail (optionally follow) a task's stdout/stderr
    (reference: `nomad alloc logs`, command/alloc_logs.go)."""
    import urllib.parse
    import urllib.request

    args.alloc_id = _resolve_alloc_id(_client(args), args.alloc_id)
    task = args.task
    if not task:
        alloc = _client(args).get_allocation(args.alloc_id)
        states = alloc.get("task_states") or {}
        task = next(iter(states), "main")
    qs = urllib.parse.urlencode({
        "task": task,
        "type": "stderr" if args.stderr else "stdout",
        "follow": "true" if args.follow else "false",
        "offset": str(-args.tail_bytes),
    })
    url = f"{args.address}/v1/client/fs/logs/{args.alloc_id}?{qs}"
    req = urllib.request.Request(url)
    if getattr(args, "token", ""):
        req.add_header("X-Nomad-Token", args.token)
    try:
        with urllib.request.urlopen(req, timeout=None) as resp:
            while True:
                # read1 returns available bytes — read(n) would block a
                # live follow stream until n accumulate.
                chunk = resp.read1(8192)
                if not chunk:
                    break
                sys.stdout.write(chunk.decode(errors="replace"))
                sys.stdout.flush()
    except KeyboardInterrupt:
        pass
    return 0


def cmd_alloc_fs(args) -> int:
    """List or read files in an allocation's directory
    (reference: `nomad alloc fs`, command/alloc_fs.go)."""
    import urllib.parse
    import urllib.request

    qs = urllib.parse.urlencode({"path": args.path})
    base = f"{args.address}/v1/client/fs"
    # ls first; fall back to cat when the path is a file.
    for op in ("ls", "cat"):
        req = urllib.request.Request(
            f"{base}/{op}/{args.alloc_id}?{qs}"
        )
        if getattr(args, "token", ""):
            req.add_header("X-Nomad-Token", args.token)
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                body = resp.read()
        except urllib.error.HTTPError as exc:
            if op == "ls" and exc.code == 404:
                continue
            print(exc.read().decode(errors="replace"), file=sys.stderr)
            return 1
        if op == "ls":
            for entry in json.loads(body):
                kind = "d" if entry["IsDir"] else "-"
                print(f"{kind} {entry['Size']:>10} {entry['Name']}")
        else:
            sys.stdout.write(body.decode(errors="replace"))
        return 0
    return 1


def _resolve_alloc_id(client: APIClient, prefix: str) -> str:
    """Expand a short alloc id the way the reference CLI does (prefix
    search, command/meta.go resolution)."""
    if len(prefix) >= 36:
        return prefix
    try:
        out = client.search(prefix, context="allocs")
        hits = out.get("Matches", {}).get("allocs", [])
    except APIError:
        return prefix
    if len(hits) == 1:
        return hits[0]
    if len(hits) > 1:
        print(f"alloc id prefix {prefix!r} is ambiguous: {hits}",
              file=sys.stderr)
    return prefix


def cmd_alloc_restart(args) -> int:
    client = _client(args)
    alloc_id = _resolve_alloc_id(client, args.alloc_id)
    out = client.restart_allocation(alloc_id, task=args.task)
    print(f"Restarted tasks: {out.get('Restarted', [])}")
    return 0


def cmd_alloc_signal(args) -> int:
    client = _client(args)
    alloc_id = _resolve_alloc_id(client, args.alloc_id)
    out = client.signal_allocation(
        alloc_id, signal=args.signal, task=args.task
    )
    print(f"Signalled tasks: {out.get('Signalled', [])}")
    return 0


def cmd_alloc_stop(args) -> int:
    client = _client(args)
    alloc_id = _resolve_alloc_id(client, args.alloc_id)
    out = client.stop_allocation(alloc_id)
    print(f"Alloc stopping; eval {out.get('EvalID', '')}")
    return 0


def cmd_alloc_exec(args) -> int:
    """Run a command inside a task's context (`nomad alloc exec`,
    command/alloc_exec.go; stdin is read upfront when piped)."""
    stdin = b""
    try:
        if not sys.stdin.isatty():
            stdin = sys.stdin.buffer.read()
    except (OSError, ValueError):
        pass  # no usable stdin (test harness)
    cmd = list(args.cmd or [])
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]  # only the leading separator; inner "--" is argv
    if not cmd:
        print("usage: alloc exec <alloc_id> [--task t] -- cmd args...",
              file=sys.stderr)
        return 1
    client = _client(args)
    alloc_id = _resolve_alloc_id(client, args.alloc_id)
    try:
        code, out, err = client.alloc_exec(
            alloc_id, args.task, cmd, stdin=stdin,
        )
    except APIError as exc:
        print(f"exec failed: {exc}", file=sys.stderr)
        return 1
    if out:
        sys.stdout.buffer.write(out)
        sys.stdout.flush()
    if err:
        sys.stderr.buffer.write(err)
        sys.stderr.flush()
    return code if code >= 0 else 1


def cmd_acl(args) -> int:
    """ACL admin (reference: `nomad acl bootstrap/policy/token`)."""
    client = _client(args)
    if args.acl_cmd == "bootstrap":
        t = client.acl_bootstrap()
        print(f"Accessor ID = {t['accessor_id']}")
        print(f"Secret ID   = {t['secret_id']}")
        print(f"Type        = {t['type']}")
        return 0
    if args.acl_cmd == "policy-apply":
        client.acl_upsert_policy(
            args.name, open(args.rules_file).read(),
            description=args.description,
        )
        print(f"Policy {args.name!r} applied")
        return 0
    if args.acl_cmd == "token-create":
        t = client.acl_create_token(
            name=args.name, type=args.type,
            policies=args.policy or [],
        )
        print(f"Accessor ID = {t['accessor_id']}")
        print(f"Secret ID   = {t['secret_id']}")
        print(f"Policies    = {t['policies']}")
        return 0
    return 1


def cmd_namespace(args) -> int:
    client = _client(args)
    if args.ns_cmd == "list":
        for n in client.list_namespaces():
            print(f"{n['Name']:20} {n.get('Description', '')}")
        return 0
    if args.ns_cmd == "apply":
        client.upsert_namespace(args.name, description=args.description)
        print(f"Namespace {args.name!r} applied")
        return 0
    if args.ns_cmd == "delete":
        client.delete_namespace(args.name)
        print(f"Namespace {args.name!r} deleted")
        return 0
    return 1


def cmd_search(args) -> int:
    client = _client(args)
    out = client.search(
        args.prefix, context=args.context, namespace=args.namespace
    )
    for context, ids in sorted(out.get("Matches", {}).items()):
        if not ids:
            continue
        print(f"{context}:")
        for i in ids:
            print(f"  {i}")
        if out.get("Truncations", {}).get(context):
            print("  ... (truncated)")
    return 0


def cmd_job_validate(args) -> int:
    """Server-side admission dry run (`nomad job validate`)."""
    job = parse_job(open(args.jobfile).read())
    out = _client(args).validate_job(job_to_api(job))
    if out["Valid"]:
        print("Job validation successful")
        return 0
    for e in out["ValidationErrors"]:
        print(f"  - {e}", file=sys.stderr)
    return 1


def cmd_job_inspect(args) -> int:
    """Full stored job JSON (`nomad job inspect`)."""
    _print(_client(args).get_job(args.job_id, args.namespace))
    return 0


def cmd_eval_list(args) -> int:
    for e in _client(args).list_evaluations(namespace=args.namespace):
        print(
            f"{e['id'][:8]} {e['job_id']:32} {e['triggered_by']:20} "
            f"{e['status']}"
        )
    return 0


def cmd_job_dispatch(args) -> int:
    client = _client(args)
    payload = b""
    if args.payload_file:
        with open(args.payload_file, "rb") as fh:
            payload = fh.read()
    for kv in args.meta or []:
        if "=" not in kv:
            print(f"-meta expects KEY=VALUE, got {kv!r}", file=sys.stderr)
            return 1
    meta = dict(kv.split("=", 1) for kv in args.meta or [])
    out = client.dispatch_job(
        args.job_id, payload, meta, namespace=args.namespace
    )
    print(f"Dispatched Job ID = {out['DispatchedJobID']}")
    print(f"Evaluation ID     = {out.get('EvalID', '')}")
    return 0


def cmd_job_history(args) -> int:
    client = _client(args)
    out = client.job_versions(args.job_id, namespace=args.namespace)
    for v in out["Versions"]:
        print(
            f"Version {v['version']:4}  submitted "
            f"{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(v['submit_time']))}"
            f"{'  (stopped)' if v['stop'] else ''}"
        )
    return 0


def cmd_job_revert(args) -> int:
    client = _client(args)
    out = client.revert_job(
        args.job_id, args.version, namespace=args.namespace
    )
    print(f"Reverted; eval {out.get('EvalID', '')}")
    return 0


def cmd_job_scale(args) -> int:
    client = _client(args)
    # `job scale <job> <count>` shorthand (single-group jobs): the count
    # binds to the optional group positional — reinterpret it.
    if args.count is None and args.group.lstrip("-").isdigit():
        args.count = int(args.group)
        args.group = ""
    if args.count is None:
        _print(client.job_scale_status(args.job_id, namespace=args.namespace))
        return 0
    out = client.scale_job(
        args.job_id, args.group, args.count,
        message=args.message, namespace=args.namespace,
    )
    print(f"Scaled {args.job_id}/{args.group} to {args.count}; "
          f"eval {out.get('EvalID', '')}")
    return 0


def _resolve_deployment_id(client: APIClient, prefix: str) -> str:
    if len(prefix) >= 36:
        return prefix
    try:
        out = client.search(prefix, context="deployment")
        hits = out.get("Matches", {}).get("deployment", [])
    except APIError:
        return prefix
    return hits[0] if len(hits) == 1 else prefix


def cmd_deployment(args) -> int:
    client = _client(args)
    action = args.deployment_action
    if getattr(args, "deployment_id", ""):
        args.deployment_id = _resolve_deployment_id(
            client, args.deployment_id
        )
    if action == "list":
        for d in client.list_deployments(namespace=args.namespace):
            print(
                f"{d['id'][:8]} job={d['job_id']:24} v{d['job_version']} "
                f"{d['status']:10} {d['status_description']}"
            )
        return 0
    if action == "status":
        _print(client.get_deployment(args.deployment_id))
        return 0
    if action == "promote":
        out = client.promote_deployment(
            args.deployment_id, args.group or None
        )
        print(f"Promoted; index {out.get('Index')}")
        return 0
    if action == "fail":
        client.fail_deployment(args.deployment_id)
        print("Deployment marked failed")
        return 0
    if action == "pause":
        client.pause_deployment(args.deployment_id, not args.resume)
        print("Deployment " + ("resumed" if args.resume else "paused"))
        return 0
    return 1


def cmd_volume(args) -> int:
    client = _client(args)
    action = args.volume_action
    if action == "list":
        for v in client.list_volumes(namespace=args.namespace):
            writers = len(v["write_claims"])
            readers = len(v["read_claims"])
            print(
                f"{v['id']:36} {v['access_mode']:24} "
                f"claims: {writers}w/{readers}r"
            )
        return 0
    if action == "register":
        spec = json.loads(open(args.volume_file).read())
        out = client.register_volume(spec, namespace=args.namespace)
        print(f"Registered volume {out['ID']}")
        return 0
    if action == "status":
        _print(client.get_volume(args.volume_id, namespace=args.namespace))
        return 0
    if action == "deregister":
        client.deregister_volume(args.volume_id, namespace=args.namespace)
        print("Deregistered")
        return 0
    return 1


def cmd_system_gc(args) -> int:
    _client(args).system_gc()
    print("GC triggered")
    return 0


def cmd_eval_status(args) -> int:
    client = _client(args)
    _print(client.get_evaluation(args.eval_id))
    return 0


def cmd_server_members(args) -> int:
    _print(_client(args).members())
    return 0


def cmd_server_join(args) -> int:
    out = _client(args).server_join(args.peer_addr)
    print("Members:")
    for m in out["Members"]:
        print(f"  {m}")
    return 0


def cmd_server_remove_peer(args) -> int:
    out = _client(args).server_remove_peer(args.peer_addr)
    print("Members:")
    for m in out["Members"]:
        print(f"  {m}")
    return 0


def cmd_operator_scheduler(args) -> int:
    client = _client(args)
    if args.algorithm:
        client.set_scheduler_configuration(
            {"scheduler_algorithm": args.algorithm}
        )
    _print(client.scheduler_configuration())
    return 0


def cmd_metrics(args) -> int:
    client = _client(args)
    if args.watch:
        return _watch_metrics(client, args)
    if args.format == "prometheus":
        sys.stdout.write(client.metrics_prometheus())
        return 0
    _print(client.metrics())
    return 0


def _watch_metrics(client, args) -> int:
    """Poll /v1/metrics and print per-interval deltas for counters (and
    current values for gauges) — `vmstat` for the cluster."""
    prev = None
    rounds = 0
    try:
        while args.count <= 0 or rounds < args.count:
            snap = client.metrics()
            flat = {
                k: v for k, v in snap.items()
                if isinstance(v, (int, float))
            }
            if prev is not None:
                deltas = {}
                for k, v in sorted(flat.items()):
                    d = v - prev.get(k, 0)
                    if d != 0:
                        deltas[k] = round(d, 6)
                stamp = time.strftime("%H:%M:%S")
                if deltas:
                    print(f"--- {stamp} (+{args.interval:g}s) ---")
                    for k, d in deltas.items():
                        sign = "+" if d > 0 else ""
                        print(f"  {k}: {sign}{d:g}  (now {flat[k]:g})")
                else:
                    print(f"--- {stamp} no change ---")
                rounds += 1
            prev = flat
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_top(args) -> int:
    from .obs.top import run_top

    return run_top(
        _client(args),
        interval=args.interval,
        count=args.count,
        clear=not args.no_clear,
    )


def cmd_slo(args) -> int:
    client = _client(args)
    if args.health:
        _print(client.health())
    elif args.overload:
        _print(client.overload())
    else:
        _print(client.slo())
    return 0


def cmd_trace_dump(args) -> int:
    body = _client(args).trace_dump(limit=args.limit)
    if args.output:
        with open(args.output, "wb") as fh:
            fh.write(body)
        doc = json.loads(body)
        n = len(doc.get("traceEvents", []))
        print(f"wrote {n} trace events to {args.output}")
        print("open in https://ui.perfetto.dev (drag the file in)")
    else:
        sys.stdout.write(body.decode())
    return 0


def cmd_trace_config(args) -> int:
    client = _client(args)
    updates = {}
    if args.sample is not None:
        updates["sample"] = args.sample
    if args.ring is not None:
        updates["ring"] = args.ring
    if args.enable:
        updates["enabled"] = True
    if args.disable:
        updates["enabled"] = False
    if updates:
        _print(client.trace_configure(**updates))
    else:
        _print(client.trace_config())
    return 0


def cmd_lint(args) -> int:
    from .lint.__main__ import main as lint_main

    forwarded = []
    if args.verbose:
        forwarded.append("--verbose")
    if args.baseline:
        forwarded += ["--baseline", args.baseline]
    if args.jaxpr:
        forwarded.append("--jaxpr")
    return lint_main(forwarded)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nomad-tpu", description="TPU-native workload orchestrator"
    )
    p.add_argument("--address", default=DEFAULT_ADDR)
    p.add_argument("--token", default=os.environ.get("NOMAD_TOKEN", ""),
                   help="ACL secret (or NOMAD_TOKEN)")
    sub = p.add_subparsers(dest="command", required=True)

    agent = sub.add_parser("agent", help="run an agent (server+client)")
    # Flags default to None so config files only lose to EXPLICIT flags
    # (cmd_agent precedence chain).
    agent.add_argument("--name", default=None)
    agent.add_argument("--config", action="append", default=[],
                       help="config file or dir (repeatable; merged in order)")
    agent.add_argument("--dc", default=None)
    agent.add_argument("--bind", default=None)
    agent.add_argument("--port", type=int, default=None)
    agent.add_argument("--workers", type=int, default=None)
    agent.add_argument("--raft", action="store_true", default=False,
                       help="run replication even with no peers "
                            "(single server that grows via `server join`)")
    agent.add_argument("--peers", default=None,
                       help="comma-separated peer server HTTP addrs")
    agent.add_argument("--server-only", action="store_true")
    agent.add_argument("--client-only", action="store_true")
    agent.add_argument("--servers", default=None,
                       help="server agent address for client-only agents")
    agent.add_argument("--data-dir", default="",
                       help="server durability dir (WAL + snapshots)")
    agent.set_defaults(fn=cmd_agent)

    job = sub.add_parser("job", help="job operations").add_subparsers(
        dest="job_cmd", required=True
    )
    run = job.add_parser("run")
    run.add_argument("jobfile")
    run.add_argument("-detach", action="store_true")
    run.set_defaults(fn=cmd_job_run)
    plan = job.add_parser("plan")
    plan.add_argument("jobfile")
    plan.add_argument("-diff", action="store_true", default=False)
    plan.set_defaults(fn=cmd_job_plan)

    status = job.add_parser("status")
    status.add_argument("job_id", nargs="?")
    status.add_argument("--namespace", default="default")
    status.set_defaults(fn=cmd_job_status)
    stop = job.add_parser("stop")
    stop.add_argument("job_id")
    stop.add_argument("-purge", action="store_true")
    stop.add_argument("--namespace", default="default")
    stop.set_defaults(fn=cmd_job_stop)
    parse = job.add_parser("parse")
    parse.add_argument("jobfile")
    parse.set_defaults(fn=cmd_job_parse)
    validate = job.add_parser("validate")
    validate.add_argument("jobfile")
    validate.set_defaults(fn=cmd_job_validate)
    inspect = job.add_parser("inspect")
    inspect.add_argument("job_id")
    inspect.add_argument("--namespace", default="default")
    inspect.set_defaults(fn=cmd_job_inspect)
    dispatch = job.add_parser("dispatch")
    dispatch.add_argument("job_id")
    dispatch.add_argument("payload_file", nargs="?", default="")
    dispatch.add_argument("-meta", action="append", metavar="KEY=VALUE")
    dispatch.add_argument("--namespace", default="default")
    dispatch.set_defaults(fn=cmd_job_dispatch)
    history = job.add_parser("history")
    history.add_argument("job_id")
    history.add_argument("--namespace", default="default")
    history.set_defaults(fn=cmd_job_history)
    revert = job.add_parser("revert")
    revert.add_argument("job_id")
    revert.add_argument("version", nargs="?", type=int, default=None)
    revert.add_argument("--namespace", default="default")
    revert.set_defaults(fn=cmd_job_revert)
    scale = job.add_parser("scale")
    scale.add_argument("job_id")
    scale.add_argument("group", nargs="?", default="")
    scale.add_argument("count", nargs="?", type=int, default=None)
    scale.add_argument("--message", default="")
    scale.add_argument("--namespace", default="default")
    scale.set_defaults(fn=cmd_job_scale)

    dep = sub.add_parser("deployment", help="deployment ops").add_subparsers(
        dest="deployment_action", required=True
    )
    dlist = dep.add_parser("list")
    dlist.add_argument("--namespace", default="default")
    dlist.set_defaults(fn=cmd_deployment)
    for verb in ("status", "promote", "fail", "pause"):
        dp = dep.add_parser(verb)
        dp.add_argument("deployment_id")
        if verb == "promote":
            dp.add_argument("-group", action="append", default=[])
        if verb == "pause":
            dp.add_argument("-resume", action="store_true")
        dp.set_defaults(fn=cmd_deployment)

    system = sub.add_parser("system", help="system ops").add_subparsers(
        dest="system_cmd", required=True
    )
    system.add_parser("gc").set_defaults(fn=cmd_system_gc)

    vol = sub.add_parser("volume", help="volume ops").add_subparsers(
        dest="volume_action", required=True
    )
    vlist = vol.add_parser("list")
    vlist.add_argument("--namespace", default="default")
    vlist.set_defaults(fn=cmd_volume)
    vreg = vol.add_parser("register")
    vreg.add_argument("volume_file")
    vreg.add_argument("--namespace", default="default")
    vreg.set_defaults(fn=cmd_volume)
    for verb in ("status", "deregister"):
        vp = vol.add_parser(verb)
        vp.add_argument("volume_id")
        vp.add_argument("--namespace", default="default")
        vp.set_defaults(fn=cmd_volume)

    node = sub.add_parser("node", help="node operations").add_subparsers(
        dest="node_cmd", required=True
    )
    nstatus = node.add_parser("status")
    nstatus.add_argument("node_id", nargs="?")
    nstatus.set_defaults(fn=cmd_node_status)
    drain = node.add_parser("drain")
    drain.add_argument("node_id")
    drain.add_argument("-disable", action="store_true")
    drain.add_argument("--deadline", type=float, default=3600.0)
    drain.set_defaults(fn=cmd_node_drain)
    elig = node.add_parser("eligibility")
    elig.add_argument("node_id")
    elig.add_argument("-enable", dest="enable", action="store_true")
    elig.add_argument("-disable", dest="enable", action="store_false")
    elig.set_defaults(fn=cmd_node_eligibility, enable=True)

    alloc = sub.add_parser("alloc", help="allocation ops").add_subparsers(
        dest="alloc_cmd", required=True
    )
    astatus = alloc.add_parser("status")
    astatus.add_argument("alloc_id")
    astatus.add_argument("-verbose", action="store_true")
    astatus.set_defaults(fn=cmd_alloc_status)

    alogs = alloc.add_parser("logs")
    alogs.add_argument("alloc_id")
    alogs.add_argument("task", nargs="?", default="")
    alogs.add_argument("-f", "--follow", action="store_true", dest="follow")
    alogs.add_argument("-stderr", action="store_true", dest="stderr")
    alogs.add_argument("-tail-bytes", type=int, default=65536,
                       dest="tail_bytes")
    alogs.set_defaults(fn=cmd_alloc_logs)

    arestart = alloc.add_parser("restart")
    arestart.add_argument("alloc_id")
    arestart.add_argument("--task", default="")
    arestart.set_defaults(fn=cmd_alloc_restart)
    asignal = alloc.add_parser("signal")
    asignal.add_argument("alloc_id")
    asignal.add_argument("signal", nargs="?", default="SIGTERM")
    asignal.add_argument("--task", default="")
    asignal.set_defaults(fn=cmd_alloc_signal)
    astop = alloc.add_parser("stop")
    astop.add_argument("alloc_id")
    astop.set_defaults(fn=cmd_alloc_stop)
    aexec = alloc.add_parser("exec")
    aexec.add_argument("alloc_id")
    aexec.add_argument("--task", default="")
    aexec.add_argument("cmd", nargs=argparse.REMAINDER)
    aexec.set_defaults(fn=cmd_alloc_exec)
    afs = alloc.add_parser("fs")
    afs.add_argument("alloc_id")
    afs.add_argument("path", nargs="?", default="")
    afs.set_defaults(fn=cmd_alloc_fs)

    acl = sub.add_parser("acl", help="ACL admin").add_subparsers(
        dest="acl_cmd", required=True
    )
    acl.add_parser("bootstrap").set_defaults(fn=cmd_acl)
    pol = acl.add_parser("policy-apply")
    pol.add_argument("name")
    pol.add_argument("rules_file")
    pol.add_argument("-description", default="")
    pol.set_defaults(fn=cmd_acl)
    tok = acl.add_parser("token-create")
    tok.add_argument("-name", default="")
    tok.add_argument("-type", default="client")
    tok.add_argument("-policy", action="append")
    tok.set_defaults(fn=cmd_acl)

    ns = sub.add_parser("namespace", help="namespace ops").add_subparsers(
        dest="ns_cmd", required=True
    )
    ns.add_parser("list").set_defaults(fn=cmd_namespace)
    nsap = ns.add_parser("apply")
    nsap.add_argument("name")
    nsap.add_argument("-description", default="")
    nsap.set_defaults(fn=cmd_namespace)
    nsdel = ns.add_parser("delete")
    nsdel.add_argument("name")
    nsdel.set_defaults(fn=cmd_namespace)

    search = sub.add_parser("search", help="prefix search")
    search.add_argument("prefix")
    search.add_argument(
        "-context", default="all",
        choices=["all", "jobs", "nodes", "allocs", "evals", "deployment"],
    )
    search.add_argument("-namespace", default="default")
    search.set_defaults(fn=cmd_search)

    ev = sub.add_parser("eval", help="evaluation ops").add_subparsers(
        dest="eval_cmd", required=True
    )
    elist = ev.add_parser("list")
    elist.add_argument("--namespace", default="default")
    elist.set_defaults(fn=cmd_eval_list)
    estatus = ev.add_parser("status")
    estatus.add_argument("eval_id")
    estatus.set_defaults(fn=cmd_eval_status)

    sm = sub.add_parser("server", help="server ops").add_subparsers(
        dest="server_cmd", required=True
    )
    sm.add_parser("members").set_defaults(fn=cmd_server_members)
    sjoin = sm.add_parser("join")
    sjoin.add_argument("peer_addr")
    sjoin.set_defaults(fn=cmd_server_join)
    srm = sm.add_parser("remove-peer")
    srm.add_argument("peer_addr")
    srm.set_defaults(fn=cmd_server_remove_peer)

    op = sub.add_parser("operator", help="operator ops").add_subparsers(
        dest="operator_cmd", required=True
    )
    sched = op.add_parser("scheduler")
    sched.add_argument("--algorithm", choices=["binpack", "spread"])
    sched.set_defaults(fn=cmd_operator_scheduler)

    metrics = sub.add_parser("metrics", help="agent metrics")
    metrics.add_argument("--format", choices=["json", "prometheus"],
                         default="json")
    metrics.add_argument("--watch", action="store_true",
                         help="poll and print per-interval counter deltas")
    metrics.add_argument("--interval", type=float, default=2.0)
    metrics.add_argument("--count", type=int, default=0,
                         help="stop after N delta rounds (0 = forever)")
    metrics.set_defaults(fn=cmd_metrics)

    top = sub.add_parser("top", help="live cluster dashboard (evals/s, "
                         "phase latencies, queues, SLO burn rates)")
    top.add_argument("--interval", type=float, default=2.0,
                     help="refresh period in seconds")
    top.add_argument("--count", type=int, default=0,
                     help="render N frames then exit (0 = until ^C)")
    top.add_argument("--no-clear", action="store_true",
                     help="append frames instead of clearing the screen")
    top.set_defaults(fn=cmd_top)

    slo = sub.add_parser("slo", help="SLO report (burn rates, status)")
    slo.add_argument("--health", action="store_true",
                     help="show the composite health report instead")
    slo.add_argument("--overload", action="store_true",
                     help="show the overload controller report instead")
    slo.set_defaults(fn=cmd_slo)

    tr = sub.add_parser("trace", help="eval-lifecycle tracing").add_subparsers(
        dest="trace_cmd", required=True
    )
    tdump = tr.add_parser("dump", help="fetch Chrome/Perfetto trace JSON")
    tdump.add_argument("-o", "--output", default="",
                       help="write to file instead of stdout")
    tdump.add_argument("--limit", type=int, default=None,
                       help="most-recent N spans only")
    tdump.set_defaults(fn=cmd_trace_dump)
    tcfg = tr.add_parser("config", help="show or adjust trace sampling")
    tcfg.add_argument("--sample", type=float, default=None)
    tcfg.add_argument("--ring", type=int, default=None)
    tcfg.add_argument("--enable", action="store_true")
    tcfg.add_argument("--disable", action="store_true")
    tcfg.set_defaults(fn=cmd_trace_config)

    lint = sub.add_parser(
        "lint", help="static analysis: lock discipline, JAX hot path, chaos "
        "seams; --jaxpr adds the semantic device-contract pass"
    )
    lint.add_argument("-v", "--verbose", action="store_true")
    lint.add_argument("--baseline", default=None)
    lint.add_argument(
        "--jaxpr", action="store_true",
        help="also trace the registered fused/sharded device entry points "
        "and enforce their declared contracts (J100-J105; needs JAX)",
    )
    lint.set_defaults(fn=cmd_lint)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except APIError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
