"""Fake-device backend — numpy twins of the scheduling kernels.

``NOMAD_TPU_FAKE_DEVICE=1`` swaps every device dispatch for an instant
host-side numpy evaluation with identical semantics (golden-tested against
the JAX kernels in tests/test_fake_device.py).  The point is isolation:
with the device answering in microseconds, a profile of the live server
shows ONLY the host path — broker dequeue, snapshot sync, reconcile,
encode, plan submit/apply — which is the part BENCH_r05.json showed
capping end-to-end throughput at 5 evals/s while the kernels sustained
527/s.  It also lets tier-1 CI exercise the full server loop without
paying JAX dispatch/compile cost.

Twins mirror ops/kernels.py exactly (same score semantics, same packed
result layout).  Two exact-output shortcuts keep them fast:

* feasibility, penalty, affinity and preemption state depend only on the
  matrix and the request — not on the scan carry — so they are computed
  once per request instead of once per scan step;
* once a scan step fails to place, the carry is unchanged, so every
  later step produces byte-identical output — computed once, replicated.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, NamedTuple, Optional

import numpy as np

from .encode import (
    OP_EQ,
    OP_GT,
    OP_GTE,
    OP_IS_NOT_SET,
    OP_IS_SET,
    OP_LT,
    OP_LTE,
    OP_NEQ,
    OP_VER_EQ,
    OP_VER_GT,
    OP_VER_GTE,
    OP_VER_LT,
    OP_VER_LTE,
    SchedRequest,
)
from ..retry import env_float

NEG_INF = -1e30
PREEMPTION_RATE = 0.0048
PREEMPTION_ORIGIN = 2048.0

_ENV = "NOMAD_TPU_FAKE_DEVICE"
_LATENCY_ENV = "NOMAD_TPU_FAKE_DEVICE_LATENCY_MS"


def enabled() -> bool:
    """True when the fake-device backend is active (env-gated)."""
    return os.environ.get(_ENV, "") == "1"


def latency_s() -> float:
    """Synthetic device→host fetch latency (seconds), from
    ``NOMAD_TPU_FAKE_DEVICE_LATENCY_MS``.

    Models the TPU tunnel's RTT the way JAX async dispatch exposes it:
    launching a computation is cheap, *fetching* its result blocks for the
    round-trip.  The coalescer therefore wraps fake dispatch results in a
    :class:`DeferredResult` whose clock starts at launch — overlapping
    in-flight dispatches overlap their latency windows exactly like real
    pipelined fetches, which is what makes pipeline speedup provable in CI
    without the (flaky) tunnel."""
    return max(0.0, env_float(_LATENCY_ENV, 0.0)) / 1000.0


class DeferredResult:
    """A fake in-flight dispatch: the value is already computed, but
    ``result()`` blocks until ``launched_at + latency`` — the fake twin of
    ``np.asarray`` on an async jax array."""

    __slots__ = ("value", "ready_at")

    def __init__(self, value, latency: float):
        self.value = value
        self.ready_at = time.monotonic() + latency

    def result(self):
        remaining = self.ready_at - time.monotonic()
        if remaining > 0:
            time.sleep(remaining)
        return self.value


# ---------------------------------------------------------------------------
# Feasibility
# ---------------------------------------------------------------------------


def _check_predicates(attr_hash, attr_num, attr_ver, slots, ops, want_hash,
                      want_num) -> np.ndarray:
    """(C, N) bool — every predicate against every node; inactive predicates
    (slot < 0) pass.  Twin of kernels._check_predicate (vmapped axis first)."""
    slots = np.asarray(slots, np.int64)
    ops = np.asarray(ops, np.int64)
    want_hash = np.asarray(want_hash)
    want_num = np.asarray(want_num, np.float32)
    safe = np.maximum(slots, 0)
    h = attr_hash[:, safe].T  # (C, N)
    is_ver = (ops >= OP_VER_EQ)[:, None]
    v = np.where(is_ver, attr_ver[:, safe].T, attr_num[:, safe].T)  # (C, N)
    present = h != 0
    num_ok = present & ~np.isnan(v) & ~np.isnan(want_num)[:, None]

    wh = want_hash[:, None]
    wn = want_num[:, None]
    o = ops[:, None]
    eq = present & (h == wh)
    res = np.ones_like(present)
    res = np.where(o == OP_EQ, eq, res)
    res = np.where(o == OP_NEQ, ~eq, res)
    with np.errstate(invalid="ignore"):
        res = np.where(o == OP_LT, num_ok & (v < wn), res)
        res = np.where(o == OP_LTE, num_ok & (v <= wn), res)
        res = np.where(o == OP_GT, num_ok & (v > wn), res)
        res = np.where(o == OP_GTE, num_ok & (v >= wn), res)
        res = np.where(o == OP_VER_EQ, num_ok & (v == wn), res)
        res = np.where(o == OP_VER_LT, num_ok & (v < wn), res)
        res = np.where(o == OP_VER_LTE, num_ok & (v <= wn), res)
        res = np.where(o == OP_VER_GT, num_ok & (v > wn), res)
        res = np.where(o == OP_VER_GTE, num_ok & (v >= wn), res)
    res = np.where(o == OP_IS_SET, present, res)
    res = np.where(o == OP_IS_NOT_SET, ~present, res)
    return np.where(slots[:, None] < 0, True, res)


def constraint_mask(arrays, req: SchedRequest) -> np.ndarray:
    c_slot = np.asarray(req.c_slot)
    active = c_slot >= 0
    if not active.any():
        return np.ones((arrays.attr_hash.shape[0],), bool)
    # Only active predicates pay the (C, N) gather.
    per = _check_predicates(
        arrays.attr_hash, arrays.attr_num, arrays.attr_ver,
        c_slot[active], np.asarray(req.c_op)[active],
        np.asarray(req.c_hash)[active], np.asarray(req.c_num)[active],
    )
    return np.all(per, axis=0)


def datacenter_mask(arrays, req: SchedRequest) -> np.ndarray:
    dc_hash = np.asarray(req.dc_hash)
    dc = arrays.attr_hash[:, 0]
    member = (dc[:, None] == dc_hash[None, :]) & (dc_hash[None, :] > 0)
    skip = dc_hash[0] == -1
    return np.any(member, axis=1) | skip


def device_mask(arrays, req: SchedRequest) -> np.ndarray:
    dev_ask = np.asarray(req.dev_ask)
    if not (dev_ask > 0).any():
        return np.ones((arrays.dev_total.shape[0],), bool)
    free = arrays.dev_total - arrays.dev_used
    ok = (free >= dev_ask[None, :]) | (dev_ask[None, :] == 0)
    return np.all(ok, axis=1)


def port_mask(arrays, req: SchedRequest) -> np.ndarray:
    from ..state.matrix import DYN_PORT_CAPACITY

    p = np.asarray(req.p_static)
    p_dyn = int(req.p_dyn)
    valid = p >= 0
    n = arrays.port_words.shape[0]
    if valid.any():
        word = np.maximum(p, 0) >> 5
        bit = (np.maximum(p, 0) & 31).astype(np.uint32)
        words = arrays.port_words[:, word]  # (N, P)
        taken = (words >> bit[None, :]) & np.uint32(1)
        conflict = np.any(valid[None, :] & (taken == 1), axis=1)
    else:
        conflict = np.zeros((n,), bool)
    dyn_ok = arrays.dyn_used + p_dyn <= DYN_PORT_CAPACITY
    return (~conflict) & dyn_ok


def feasibility_mask(arrays, req: SchedRequest,
                     class_elig: Optional[np.ndarray] = None,
                     host_mask: Optional[np.ndarray] = None) -> np.ndarray:
    mask = arrays.eligible.copy()
    mask &= datacenter_mask(arrays, req)
    mask &= constraint_mask(arrays, req)
    mask &= device_mask(arrays, req)
    mask &= port_mask(arrays, req)
    if class_elig is not None:
        class_elig = np.asarray(class_elig)
        cid = np.maximum(arrays.class_id, 0)
        mask &= np.where(arrays.class_id < 0, False, class_elig[cid])
    if host_mask is not None:
        mask &= np.asarray(host_mask)
    return mask


# ---------------------------------------------------------------------------
# Scoring
# ---------------------------------------------------------------------------


def fit_and_binpack(arrays, used, req: SchedRequest):
    ask = np.asarray(req.ask, np.float32)
    util = used + ask[None, :]
    fits_dim = util <= arrays.totals
    fits = np.all(fits_dim, axis=1)
    exhausted = np.argmax(~fits_dim, axis=1).astype(np.int32)
    exhausted = np.where(fits, -1, exhausted).astype(np.int32)

    denom = np.maximum(arrays.totals, np.float32(1.0))
    free = np.float32(1.0) - util / denom
    # exp2(x·log₂10) mirrors the kernel's 10**x lowering exactly (see
    # kernels.fit_and_binpack).
    log2_10 = np.float32(3.321928094887362)
    total = np.exp2(free[:, 0] * log2_10) + np.exp2(free[:, 1] * log2_10)
    binpack = np.clip(np.float32(20.0) - total, 0.0, 18.0)
    spread = np.clip(total - np.float32(2.0), 0.0, 18.0)
    score = np.where(int(req.algorithm) == 1, spread, binpack) / np.float32(18.0)
    return fits, score.astype(np.float32), exhausted


def anti_affinity_score(tg_count, req: SchedRequest):
    collisions = tg_count.astype(np.float32)
    score = -(collisions + 1.0) / np.float32(req.desired_count)
    appended = collisions > 0
    return np.where(appended, score, 0.0).astype(np.float32), appended


def penalty_score(penalty_mask):
    return np.where(penalty_mask, -1.0, 0.0).astype(np.float32), penalty_mask


def affinity_score(arrays, req: SchedRequest):
    a_slot = np.asarray(req.a_slot)
    n = arrays.attr_hash.shape[0]
    active = a_slot >= 0
    if not active.any():
        zero = np.zeros((n,), np.float32)
        return zero, np.zeros((n,), bool)
    matches = _check_predicates(
        arrays.attr_hash, arrays.attr_num, arrays.attr_ver,
        req.a_slot, req.a_op, req.a_hash, req.a_num,
    )  # (A, N)
    a_weight = np.asarray(req.a_weight, np.float32)
    matched = matches & active[:, None]
    sum_weight = np.sum(np.abs(a_weight) * active)
    total = np.sum(matched * a_weight[:, None], axis=0)
    norm = total / max(sum_weight, 1e-9)
    appended = (total != 0.0) & (sum_weight > 0)
    return np.where(appended, norm, 0.0).astype(np.float32), appended


def spread_score(arrays, req: SchedRequest, spread_counts):
    s_slot = np.asarray(req.s_slot)
    n = arrays.attr_hash.shape[0]
    if not (s_slot >= 0).any():
        return np.zeros((n,), np.float32), np.zeros((n,), bool)

    total = np.zeros((n,), np.float32)
    rel_denom = max(float(req.s_sum_weights), 1e-9)
    for s in range(s_slot.shape[0]):
        slot = int(s_slot[s])
        if slot < 0:
            continue
        weight = np.float32(req.s_weight[s])
        even = bool(req.s_even[s])
        value_hash = np.asarray(req.s_value_hash[s])
        desired = np.asarray(req.s_desired[s], np.float32)
        implicit = float(req.s_implicit[s])
        counts = np.asarray(spread_counts[s], np.float32)

        nvalue = arrays.attr_hash[:, slot]  # (N,)
        node_has = nvalue != 0
        vmatch = (nvalue[:, None] == value_hash[None, :]) & (
            value_hash[None, :] != 0
        )  # (N, V)
        count_at = np.sum(np.where(vmatch, counts[None, :], 0.0), axis=1)
        used_count = count_at + 1.0

        if even:
            valid = (value_hash != 0) & (counts > 0)
            any_use = valid.any()
            if any_use:
                mn = counts[valid].min()
                mx = counts[valid].max()
            else:
                mn = mx = 0.0
            current = count_at
            delta_boost = np.where(
                mn == 0, -1.0, (mn - current) / max(mn, 1e-9)
            )
            if mn == mx:
                at_min = -1.0
            elif mn == 0:
                at_min = 1.0
            else:
                at_min = (mx - mn) / max(mn, 1e-9)
            stanza = np.where(current != mn, delta_boost, at_min)
            if not any_use:
                stanza = np.zeros_like(stanza)
            stanza = np.where(node_has, stanza, -1.0)
        else:
            desired_ok = ~np.isnan(desired)
            has_target = np.any(vmatch & desired_ok[None, :], axis=1)
            with np.errstate(invalid="ignore"):
                desired_at = np.sum(
                    np.where(vmatch & desired_ok[None, :],
                             desired[None, :], 0.0),
                    axis=1,
                )
            desired_v = np.where(has_target, desired_at, np.nan)
            use_implicit = ~has_target & ~np.isnan(implicit)
            desired_v = np.where(use_implicit, implicit, desired_v)
            no_target = np.isnan(desired_v)
            rel_weight = float(weight) / rel_denom
            with np.errstate(invalid="ignore"):
                boost_t = (
                    (desired_v - used_count) / np.maximum(desired_v, 1e-9)
                ) * rel_weight
            stanza = np.where(no_target, -1.0, boost_t)

        total += stanza.astype(np.float32)

    appended = total != 0.0
    return np.where(appended, total, 0.0).astype(np.float32), appended


def preemption_state(arrays, req: SchedRequest):
    from ..state.matrix import PRIORITY_BUCKETS

    n = arrays.prio_used.shape[0]
    bucket = int(req.preempt_bucket)
    if bucket < 0:
        return (
            np.zeros((n, 3), np.float32),
            np.zeros((n,), np.float32),
            np.zeros((n,), bool),
        )
    k = min(max(bucket, 0), PRIORITY_BUCKETS)
    freeable = (
        np.sum(arrays.prio_used[:, :k], axis=1)
        if k > 0
        else np.zeros((n, 3), np.float32)
    )
    buckets = np.arange(PRIORITY_BUCKETS, dtype=np.float32)
    mid = (buckets + 0.5) * (101.0 / PRIORITY_BUCKETS)
    present = np.any(arrays.prio_used > 0, axis=2)  # (N, P)
    mid_masked = np.where(present, mid[None, :], 0.0)
    if k > 0:
        max_prio = np.max(mid_masked[:, :k], axis=1)
        sum_prio = np.sum(mid_masked[:, :k], axis=1)
    else:
        max_prio = np.zeros((n,), np.float32)
        sum_prio = np.zeros((n,), np.float32)
    net = np.where(
        max_prio > 0, max_prio + sum_prio / np.maximum(max_prio, 1e-9), 0.0
    )
    score = 1.0 / (1.0 + np.exp(PREEMPTION_RATE * (net - PREEMPTION_ORIGIN)))
    usable = np.any(freeable > 0, axis=1)
    return (
        freeable.astype(np.float32),
        score.astype(np.float32),
        usable,
    )


class _StaticParts(NamedTuple):
    """Per-request state that does not change across scan steps."""

    feas: np.ndarray  # (N,) bool — pre-distinct-hosts feasibility
    pen_score: np.ndarray  # (N,) f32
    pen_app: np.ndarray  # (N,) bool
    aff_score: np.ndarray  # (N,) f32
    aff_app: np.ndarray  # (N,) bool
    extra_free: np.ndarray  # (N, 3) f32
    pre_score: np.ndarray  # (N,) f32
    pre_usable: np.ndarray  # (N,) bool
    ask: np.ndarray  # (3,) f32
    distinct: bool


# Per-(arrays, inputs) memo for _static_parts.  Distinct jobs with identical
# constraint/affinity content compile to byte-identical request tensors, and
# steady-state bursts are dominated by such twins — the feasibility sweep
# over (N, A) attr tensors is the fake backend's single hottest block.  The
# key is the full input content (all req fields + the three mask vectors),
# so a hit is exact by construction; entries are dropped whenever a new
# device snapshot appears (syncs invalidate node state).
_STATIC_MEMO: Dict[bytes, _StaticParts] = {}
_STATIC_MEMO_ARRAYS: List[Any] = [None]  # strong ref; identity-checked
_STATIC_MEMO_MAX = 256


def _static_parts_key(req, penalty_mask, class_elig, host_mask) -> bytes:
    parts = [np.ascontiguousarray(f).tobytes() for f in req]
    parts.append(np.ascontiguousarray(penalty_mask).tobytes())
    parts.append(np.ascontiguousarray(class_elig).tobytes())
    parts.append(np.ascontiguousarray(host_mask).tobytes())
    return b"\x00".join(parts)


def _static_parts(arrays, req: SchedRequest, penalty_mask, class_elig,
                  host_mask) -> _StaticParts:
    if _STATIC_MEMO_ARRAYS[0] is not arrays:
        _STATIC_MEMO.clear()
        _STATIC_MEMO_ARRAYS[0] = arrays
    key = _static_parts_key(req, penalty_mask, class_elig, host_mask)
    hit = _STATIC_MEMO.get(key)
    if hit is not None:
        return hit
    sp = _compute_static_parts(arrays, req, penalty_mask, class_elig,
                               host_mask)
    if len(_STATIC_MEMO) >= _STATIC_MEMO_MAX:
        _STATIC_MEMO.pop(next(iter(_STATIC_MEMO)))
    _STATIC_MEMO[key] = sp
    return sp


def _compute_static_parts(arrays, req: SchedRequest, penalty_mask,
                          class_elig, host_mask) -> _StaticParts:
    feas = feasibility_mask(arrays, req, class_elig, host_mask)
    pen_score, pen_app = penalty_score(np.asarray(penalty_mask, bool))
    aff_score, aff_app = affinity_score(arrays, req)
    extra_free, pre_score, pre_usable = preemption_state(arrays, req)
    return _StaticParts(
        feas=feas,
        pen_score=pen_score,
        pen_app=pen_app,
        aff_score=aff_score,
        aff_app=aff_app,
        extra_free=extra_free,
        pre_score=pre_score,
        pre_usable=pre_usable,
        ask=np.asarray(req.ask, np.float32),
        distinct=bool(req.distinct_hosts),
    )


def _score_step(arrays, req: SchedRequest, sp: _StaticParts, used, tg_count,
                spread_counts):
    """One scan step's ScoreResult equivalents (final, needs_preempt,
    binpack, counters) given the current carry."""
    feas = sp.feas
    if sp.distinct:
        feas = feas & ~(tg_count > 0)
    fits, binpack, _ = fit_and_binpack(arrays, used, req)

    util = used + sp.ask[None, :]
    fits_with_preempt = np.all(util - sp.extra_free <= arrays.totals, axis=1)
    needs_preempt = ~fits & fits_with_preempt & sp.pre_usable
    fits_all = fits | needs_preempt

    aa_score, aa_app = anti_affinity_score(tg_count, req)
    spr_score, spr_app = spread_score(arrays, req, spread_counts)
    pre_component = np.where(needs_preempt, sp.pre_score, 0.0)

    total = (
        binpack + aa_score + sp.pen_score + sp.aff_score + spr_score
        + pre_component
    )
    count = (
        1.0
        + aa_app.astype(np.float32)
        + sp.pen_app.astype(np.float32)
        + sp.aff_app.astype(np.float32)
        + spr_app.astype(np.float32)
        + needs_preempt.astype(np.float32)
    )
    final = total / count
    final = np.where(feas & fits_all, final, NEG_INF).astype(np.float32)

    n_eval = int(np.sum(feas))
    n_filt = int(np.sum(~feas & arrays.eligible))
    n_exh = int(np.sum(feas & ~fits_all))
    return final, needs_preempt, binpack, n_eval, n_filt, n_exh


def _apply_spread_values(req: SchedRequest, s_hash, s_counts, nvalues):
    """In-place twin of kernels.apply_spread_values for the chosen node."""
    s_slot = np.asarray(req.s_slot)
    for s in range(s_slot.shape[0]):
        slot = int(s_slot[s])
        nv = int(nvalues[s])
        vh = s_hash[s]
        match = (vh == nv) & (nv != 0)
        have = bool(match.any())
        zeros = vh == 0
        free_slot = int(np.argmax(zeros)) if zeros.any() else 0
        idx = int(np.argmax(match)) if have else free_slot
        can = slot >= 0 and nv != 0 and (have or vh[free_slot] == 0)
        if can and not have:
            vh[idx] = nv
        if can:
            s_counts[s, idx] += 1.0


class _TotalsView(NamedTuple):
    """1-row stand-in for DeviceArrays when rescoring a single node."""

    totals: np.ndarray


def _place_scan(arrays, req: SchedRequest, used0, tg_count, spread_counts,
                penalty_mask, class_elig, host_mask,
                n_placements: int) -> np.ndarray:
    """Twin of kernels._place_scan; returns packed (n_placements, 7) f32."""
    sp = _static_parts(arrays, req, penalty_mask, class_elig, host_mask)
    used = np.array(used0, np.float32, copy=True)
    tg = np.array(tg_count, np.int32, copy=True)
    s_hash = np.array(req.s_value_hash, copy=True)
    s_counts = np.array(spread_counts, np.float32, copy=True)

    out = np.zeros((n_placements, 7), np.float32)
    if not (np.asarray(req.s_slot) >= 0).any():
        return _place_scan_incremental(arrays, req, sp, used, tg, out)

    # Spread stanzas shift every node's score when a placement bumps a value
    # count, so there is no single-row shortcut — full recompute per step.
    step = 0
    while step < n_placements:
        req_step = req._replace(s_value_hash=s_hash)
        final, needs_pre, binpack, n_eval, n_filt, n_exh = _score_step(
            arrays, req_step, sp, used, tg, s_counts
        )
        row = int(np.argmax(final))
        ok = final[row] > NEG_INF / 2
        if not ok:
            # Failed step leaves the carry unchanged — every remaining step
            # is byte-identical; replicate instead of recomputing.
            out[step:, :] = (-1.0, 0.0, 0.0, 0.0, n_eval, n_filt, n_exh)
            break
        out[step] = (
            row,
            final[row],
            binpack[row],
            1.0 if needs_pre[row] else 0.0,
            n_eval,
            n_filt,
            n_exh,
        )
        used[row] += sp.ask
        tg[row] += 1
        nvalues = arrays.attr_hash[
            row, np.maximum(np.asarray(req_step.s_slot), 0)
        ]
        _apply_spread_values(req_step, s_hash, s_counts, nvalues)
        step += 1
    return out


def _place_scan_incremental(arrays, req: SchedRequest, sp: _StaticParts,
                            used, tg, out) -> np.ndarray:
    """No-spread scan: score every node once, then rescore only the placed
    row between steps (the carry changes nowhere else).  The single-row
    rescore runs the same float32 expressions on 1-element slices, so the
    packed output is identical to the full per-step recompute."""
    f32 = np.float32
    feas = sp.feas & ~(tg > 0) if sp.distinct else sp.feas
    fits, binpack, _ = fit_and_binpack(arrays, used, req)
    util = used + sp.ask[None, :]
    fwp = np.all(util - sp.extra_free <= arrays.totals, axis=1)
    needs_pre = ~fits & fwp & sp.pre_usable
    fits_all = fits | needs_pre
    aa_score, aa_app = anti_affinity_score(tg, req)
    pre_component = np.where(needs_pre, sp.pre_score, 0.0)
    total = (
        binpack + aa_score + sp.pen_score + sp.aff_score + pre_component
    )
    count = (
        1.0
        + aa_app.astype(f32)
        + sp.pen_app.astype(f32)
        + sp.aff_app.astype(f32)
        + needs_pre.astype(f32)
    )
    final = np.where(feas & fits_all, total / count, NEG_INF).astype(f32)
    n_eval = int(np.sum(feas))
    n_filt = int(np.sum(~feas & arrays.eligible))
    n_exh = int(np.sum(feas & ~fits_all))

    n_placements = out.shape[0]
    step = 0
    while step < n_placements:
        row = int(np.argmax(final))
        if not final[row] > NEG_INF / 2:
            out[step:, :] = (-1.0, 0.0, 0.0, 0.0, n_eval, n_filt, n_exh)
            break
        out[step] = (
            row,
            final[row],
            binpack[row],
            1.0 if needs_pre[row] else 0.0,
            n_eval,
            n_filt,
            n_exh,
        )
        step += 1
        if step >= n_placements:
            break

        used[row] += sp.ask
        tg[row] += 1
        old_feas = bool(feas[row])
        old_open = old_feas and not bool(fits_all[row])
        if sp.distinct:
            feas = feas.copy() if feas is sp.feas else feas
            feas[row] = False
        r = slice(row, row + 1)
        fits_r, bin_r, _ = fit_and_binpack(_TotalsView(arrays.totals[r]),
                                           used[r], req)
        util_r = used[r] + sp.ask[None, :]
        fwp_r = np.all(util_r - sp.extra_free[r] <= arrays.totals[r], axis=1)
        np_r = ~fits_r & fwp_r & sp.pre_usable[r]
        fa_r = fits_r | np_r
        aa_r, aa_app_r = anti_affinity_score(tg[r], req)
        pre_r = np.where(np_r, sp.pre_score[r], 0.0)
        tot_r = bin_r + aa_r + sp.pen_score[r] + sp.aff_score[r] + pre_r
        cnt_r = (
            1.0
            + aa_app_r.astype(f32)
            + sp.pen_app[r].astype(f32)
            + sp.aff_app[r].astype(f32)
            + np_r.astype(f32)
        )
        fin_r = np.where(feas[r] & fa_r, tot_r / cnt_r, NEG_INF).astype(f32)
        binpack[row] = bin_r[0]
        needs_pre[row] = np_r[0]
        fits_all[row] = fa_r[0]
        final[row] = fin_r[0]

        new_feas = bool(feas[row])
        if new_feas != old_feas:
            n_eval += 1 if new_feas else -1
            if bool(arrays.eligible[row]):
                n_filt += -1 if new_feas else 1
        n_exh += int(new_feas and not bool(fits_all[row])) - int(old_open)
    return out


# ---------------------------------------------------------------------------
# Kernel-twin entry points (same shapes/semantics as ops.kernels)
# ---------------------------------------------------------------------------


class FakePlacementResult(NamedTuple):
    rows: np.ndarray
    scores: np.ndarray
    binpack: np.ndarray
    preempted: np.ndarray
    nodes_evaluated: np.ndarray
    nodes_filtered: np.ndarray
    nodes_exhausted: np.ndarray


def place_task_group(arrays, req: SchedRequest, used0, tg_count,
                     spread_counts, penalty_mask, class_elig, host_mask,
                     n_placements: int) -> FakePlacementResult:
    """Solo-path twin of kernels.place_task_group (host-side result views)."""
    packed = _place_scan(
        arrays, req, used0, tg_count, spread_counts, penalty_mask,
        class_elig, host_mask, n_placements,
    )
    return FakePlacementResult(
        rows=packed[:, 0].astype(np.int32),
        scores=packed[:, 1],
        binpack=packed[:, 2],
        preempted=packed[:, 3] != 0.0,
        nodes_evaluated=packed[:, 4].astype(np.int32),
        nodes_filtered=packed[:, 5].astype(np.int32),
        nodes_exhausted=packed[:, 6].astype(np.int32),
    )


def place_batch(arrays, used, delta_rows: List[np.ndarray],
                delta_vals: List[np.ndarray], tg_counts: List[np.ndarray],
                spread_counts: List[np.ndarray], penalties: List[np.ndarray],
                reqs: List[SchedRequest], class_eligs: List[np.ndarray],
                host_masks: List[np.ndarray],
                n_placements: int,
                live_counts: Optional[List[int]] = None) -> np.ndarray:
    """Batched twin of kernels.place_batch, taking per-request lists (no
    lane padding / stacking needed host-side).  Returns (B, P, 7) f32.

    ``live_counts[i]`` caps how many scan steps request ``i`` actually
    computes — callers (stack._select_locked) consume only ``rows[:remaining]``,
    so the steps past that are dead work under the jax kernel's static
    shapes.  The uncomputed tail rows are filled with the inert no-placement
    marker (row=-1); they are shape-filler, not kernel-exact values."""
    b = len(reqs)
    out = np.zeros((b, n_placements, 7), np.float32)
    for i in range(b):
        drows = np.asarray(delta_rows[i])
        live = drows >= 0
        used0 = used
        if live.any():
            used0 = used.copy()
            np.add.at(used0, drows[live], np.asarray(delta_vals[i])[live])
        steps = n_placements
        if live_counts is not None:
            steps = max(1, min(n_placements, int(live_counts[i])))
        out[i, :steps] = _place_scan(
            arrays, reqs[i], used0, tg_counts[i], spread_counts[i],
            penalties[i], class_eligs[i], host_masks[i], steps,
        )
        if steps < n_placements:
            out[i, steps:, 0] = -1.0
    return out


# Packed-output constants of the fused megakernel, mirrored from
# ops/kernels.py (this module stays importable without JAX).
FUSED_PACKED_VERIFIED = 7
FUSED_PACKED_WIDTH = 8


def fused_place_batch(arrays, used, delta_rows: List[np.ndarray],
                      delta_vals: List[np.ndarray],
                      tg_counts: List[np.ndarray],
                      spread_counts: List[np.ndarray],
                      penalties: List[np.ndarray],
                      reqs: List[SchedRequest],
                      class_eligs: List[np.ndarray],
                      host_masks: List[np.ndarray],
                      lane_mask,
                      n_placements: int,
                      live_counts: Optional[List[int]] = None) -> np.ndarray:
    """Twin of kernels.fused_place_batch — (B, P, FUSED_PACKED_WIDTH) f32.

    Adds the sequential cross-lane AllocsFit VERIFIED column on top of the
    staged scans: lanes commit their in-flight deltas and placements to a
    cumulative usage image in lane order, and each placement is checked
    against it (1.0 fits, 0.0 an earlier lane claimed the capacity, -1.0
    dead lane). ``lane_mask`` marks live lanes explicitly; dead lanes emit
    row=-1 / zeros and touch nothing.

    With ``live_counts`` the uncomputed tail rows are shape-filler
    (row=-1, verified=1.0) exactly like :func:`place_batch`; kernel-exact
    parity requires live_counts=None.
    """
    b = len(reqs)
    lane_mask = np.asarray(lane_mask, bool)
    out = np.zeros((b, n_placements, FUSED_PACKED_WIDTH), np.float32)
    cum_used = np.array(used, np.float32, copy=True)
    for i in range(b):
        if not lane_mask[i]:
            out[i, :, 0] = -1.0
            out[i, :, FUSED_PACKED_VERIFIED] = -1.0
            continue
        drows = np.asarray(delta_rows[i])
        dvals = np.asarray(delta_vals[i])
        live = drows >= 0
        used0 = used
        if live.any():
            used0 = used.copy()
            np.add.at(used0, drows[live], dvals[live])
        steps = n_placements
        if live_counts is not None:
            steps = max(1, min(n_placements, int(live_counts[i])))
        out[i, :steps, :7] = _place_scan(
            arrays, reqs[i], used0, tg_counts[i], spread_counts[i],
            penalties[i], class_eligs[i], host_masks[i], steps,
        )
        if steps < n_placements:
            out[i, steps:, 0] = -1.0
        # Sequential AllocsFit re-verify against the cumulative image.
        if live.any():
            np.add.at(cum_used, drows[live], dvals[live])
        ask = np.asarray(reqs[i].ask, np.float32)
        for p in range(n_placements):
            row = int(out[i, p, 0])
            if row < 0:
                out[i, p, FUSED_PACKED_VERIFIED] = 1.0
                continue
            cum_used[row] += ask
            out[i, p, FUSED_PACKED_VERIFIED] = float(
                np.all(cum_used[row] <= arrays.totals[row])
            )
    return out


def sharded_fused_place_batch(arrays, used, delta_rows, delta_vals,
                              tg_counts, spread_counts, penalties, reqs,
                              class_eligs, host_masks, lane_mask,
                              n_shards: int, n_placements: int,
                              live_counts: Optional[List[int]] = None,
                              ) -> np.ndarray:
    """Twin of parallel.sharding.sharded_fused_place_batch for host-only CI.

    The sharded kernel's hierarchical top-k election (per-shard stable
    top-k → cross-shard pmax/pmin of the (shards, k) candidate table,
    shard-major row-minor tie-break) provably reproduces the dense argmax
    row-for-row, and its owner-veto verify reproduces the sequential
    cross-lane AllocsFit scan (PARITY.md "Hierarchical top-k") — so the
    bit-compatible numpy reference IS the dense twin, run after validating
    the shard partition the mesh would impose.
    """
    n = int(np.asarray(used).shape[0])
    if n_shards < 1 or n % n_shards:
        raise ValueError(
            f"node axis of {n} rows does not split into {n_shards} shards"
        )
    return fused_place_batch(
        arrays, used, delta_rows, delta_vals, tg_counts, spread_counts,
        penalties, reqs, class_eligs, host_masks, lane_mask,
        n_placements=n_placements, live_counts=live_counts,
    )


def system_feasible(arrays, used0, req: SchedRequest, class_elig,
                    host_mask) -> np.ndarray:
    """Twin of kernels.system_feasible — stacked (2, N) [mask, fits]."""
    mask = feasibility_mask(arrays, req, class_elig, host_mask)
    fits, _, _ = fit_and_binpack(arrays, used0, req)
    return np.stack([mask, fits])


def verify_plan_fit(arrays, rows, deltas, eligible_required) -> np.ndarray:
    """Twin of kernels.verify_plan_fit — (K,) bool verdicts."""
    rows = np.asarray(rows)
    deltas = np.asarray(deltas, np.float32)
    eligible_required = np.asarray(eligible_required, bool)
    safe = np.maximum(rows, 0)
    used = arrays.used[safe] + deltas
    fits = np.all(used <= arrays.totals[safe], axis=1)
    ok = fits & (~eligible_required | arrays.eligible[safe])
    return np.where(rows < 0, True, ok)


def dense_used0(arrays, deltas) -> np.ndarray:
    """Numpy twin of stack._dense_used0 (proposed base usage)."""
    used0 = arrays.used
    if deltas:
        used0 = used0.copy()
        for row, d in deltas.items():
            used0[row] += d
    return used0
