"""Vectorized scheduling kernels — the hot path, in JAX.

Each kernel is a pure function over the device-resident node matrix
(``state.matrix.DeviceArrays``) and a compiled request
(``ops.encode.SchedRequest``). Where the reference pulls nodes one at a time
through a 14-iterator chain (scheduler/stack.go:324-417) and bounds work by
sampling log₂(n) candidates (stack.go:78-91), these kernels score **all**
nodes in one fused XLA program; placement of ``count`` allocs is a
``lax.scan`` that scatters proposed usage between steps (the reference's
in-plan "proposed allocs" cache, rank.go:41-52).

Score semantics mirror the reference exactly (see tests/test_kernels.py
golden tests against the scalar oracle in structs.funcs):
  binpack     = ScoreFitBinPack/18           (funcs.go:186, rank.go:513)
  anti-aff    = -(collisions+1)/desired      (rank.go:601-607, only if >0)
  penalty     = -1 on penalized nodes        (rank.go:646, only if penalized)
  affinity    = Σ weight·match / Σ|weight|   (rank.go:704-728, only if ≠0)
  spread      = per-stanza boosts            (spread.go:110-178, only if ≠0)
  preemption  = logistic(netPriority)        (rank.go:773-844, only if used)
  final       = mean of appended components  (rank.go:737-771)
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..state.matrix import PRIORITY_BUCKETS
from .encode import (
    MAX_AFFINITIES,
    MAX_CONSTRAINTS,
    MAX_SPREADS,
    OP_EQ,
    OP_GT,
    OP_GTE,
    OP_IS_NOT_SET,
    OP_IS_SET,
    OP_LT,
    OP_LTE,
    OP_NEQ,
    OP_VER_EQ,
    OP_VER_GT,
    OP_VER_GTE,
    OP_VER_LT,
    OP_VER_LTE,
    SchedRequest,
    pow2_bucket,
)

# Plain float (not a jnp scalar): materializing a device array at import
# time would force backend initialization on `import nomad_tpu`.
NEG_INF = -1e30

# Preemption score constants (reference: rank.go preemptionScore).
PREEMPTION_RATE = 0.0048
PREEMPTION_ORIGIN = 2048.0


# ---------------------------------------------------------------------------
# Static feature occupancy (compile-time work bounds)
# ---------------------------------------------------------------------------


class Features(NamedTuple):
    """Static per-dispatch work bounds, derived from *batch occupancy*.

    The request encoding pads every dispatch to worst-case widths
    (16 constraints, 8 affinities, 2 spreads, preemption tables, port
    bitmaps) so one compile serves every request shape — but a typical
    batch uses 1-2 constraint slots and no preemption, and the padded
    slots still execute (each inactive predicate is two table gathers
    plus the full decode over all N nodes). ``Features`` makes the
    *occupancy* static: widths are pow2-bucketed so the jit cache stays
    bounded (≤ 6·5·3·2·2 variants, in practice a handful), and a
    dispatcher that ratchets via :meth:`widen` compiles each variant at
    most once per process.

    Fields are hashable scalars — the whole tuple is a valid
    ``static_argnames`` value.
    """

    c_width: int = MAX_CONSTRAINTS  # active constraint slots (pow2, 0..16)
    a_width: int = MAX_AFFINITIES  # active affinity slots (pow2, 0..8)
    s_width: int = MAX_SPREADS  # active spread stanzas (0..2)
    preempt: bool = True  # any eval has preemption enabled
    ports: bool = True  # any eval asks for static/dynamic ports

    def widen(self, other: "Features") -> "Features":
        """Monotone union — the dispatcher's recompile ratchet."""
        return Features(
            c_width=max(self.c_width, other.c_width),
            a_width=max(self.a_width, other.a_width),
            s_width=max(self.s_width, other.s_width),
            preempt=self.preempt or other.preempt,
            ports=self.ports or other.ports,
        )


FULL_FEATURES = Features()


def _slot_width(slots, max_width: int) -> int:
    """Last active slot index + 1 over a (..., W) slot array. Spread slots
    are positional (an escaped stanza leaves a -1 hole), so occupancy is
    the last-used index, not the active count."""
    s = np.asarray(slots).reshape(-1, max_width)
    active = s >= 0
    if not active.any():
        return 0
    return int(np.max(np.where(active, np.arange(max_width)[None, :], -1))) + 1


def features_of(reqs: SchedRequest) -> Features:
    """Measure a request (or a stacked batch of requests) into a bucketed
    :class:`Features`. Pure numpy — safe to call per dispatch on the
    staging thread (a few µs on (B, 16) slot arrays)."""
    c_w = _slot_width(reqs.c_slot, MAX_CONSTRAINTS)
    a_w = _slot_width(reqs.a_slot, MAX_AFFINITIES)
    return Features(
        c_width=min(MAX_CONSTRAINTS, pow2_bucket(c_w)) if c_w else 0,
        a_width=min(MAX_AFFINITIES, pow2_bucket(a_w)) if a_w else 0,
        s_width=_slot_width(reqs.s_slot, MAX_SPREADS),
        preempt=bool(np.any(np.asarray(reqs.preempt_bucket) >= 0)),
        ports=bool(
            np.any(np.asarray(reqs.p_static) >= 0)
            or np.any(np.asarray(reqs.p_dyn) > 0)
        ),
    )


# ---------------------------------------------------------------------------
# Feasibility
# ---------------------------------------------------------------------------


def _check_predicate(hash_T, numver_T, slot, op, want_hash, want_num):
    """Evaluate one predicate for every node against *transposed* attribute
    tables: ``hash_T`` is (A, N), ``numver_T`` is (2A, N) — numeric rows
    then version-packed rows. Each predicate reads exactly two contiguous
    (N,)-rows (hash + the one numeric flavor its op needs). Row-major
    column reads of the old (N, A) layout were strided dynamic-slices that
    walked the whole table per predicate — the dominant memory traffic of a
    batched dispatch (≈3× slower, measured on 10K nodes). The transposes
    are batch-invariant, so XLA hoists them out of the vmap and builds them
    once per dispatch.

    The op decode is a three-way select over scalar op-class masks instead
    of a 13-deep ``jnp.where`` chain: at B=512×C=16×N=10K the chain alone
    was ~2G elementwise ops per dispatch.

    Returns (N,) bool; inactive predicates (slot < 0) return True.

    Missing-attribute semantics follow checkConstraint (feasible.go:793-858):
    ``=`` and ordered comparisons require the attribute to be present; ``!=``
    passes when it is absent. Version ops read the version-packed rows.
    NaN operands (unparseable numerics) fail ordered comparisons exactly as
    the old explicit ``num_ok`` mask did — IEEE NaN compares false.
    """
    nattrs = hash_T.shape[0]
    safe_slot = jnp.maximum(slot, 0)
    h = hash_T[safe_slot]  # (N,) contiguous
    is_ver = op >= OP_VER_EQ
    v = numver_T[safe_slot + jnp.where(is_ver, nattrs, 0)]  # (N,) contiguous
    present = h != 0

    # Scalar op-class selectors (broadcast against the (N,) vectors).
    is_num = ((op >= OP_LT) & (op <= OP_GTE)) | is_ver
    is_pres = (op == OP_IS_SET) | (op == OP_IS_NOT_SET)
    negate = (op == OP_NEQ) | (op == OP_IS_NOT_SET)
    want_lt = (op == OP_LT) | (op == OP_LTE) | (op == OP_VER_LT) | (op == OP_VER_LTE)
    want_gt = (op == OP_GT) | (op == OP_GTE) | (op == OP_VER_GT) | (op == OP_VER_GTE)
    want_eq = (
        (op == OP_LTE)
        | (op == OP_GTE)
        | (op == OP_VER_EQ)
        | (op == OP_VER_LTE)
        | (op == OP_VER_GTE)
    )
    cmp = (want_lt & (v < want_num)) | (want_gt & (v > want_num)) | (
        want_eq & (v == want_num)
    )
    inner = jnp.where(is_num, cmp, jnp.where(is_pres, True, h == want_hash))
    res = (present & inner) ^ negate
    return res | (slot < 0)


def _tables(arrays):
    """Transposed attribute tables ((A, N) hash, (2A, N) numeric‖version)
    for _check_predicate. Batch-invariant: identical across every lane of a
    dispatch, so XLA computes (and CSEs) them once per launch."""
    hash_T = arrays.attr_hash.T
    numver_T = jnp.concatenate([arrays.attr_num.T, arrays.attr_ver.T], axis=0)
    return hash_T, numver_T


def constraint_mask(
    arrays, req: SchedRequest, c_width: int = MAX_CONSTRAINTS
) -> jnp.ndarray:
    """(N,) bool — all hard constraints pass (ConstraintChecker equivalent).

    ``c_width`` (static) bounds the predicate loop to the batch's slot
    occupancy; padded requests are always left-packed so slicing is exact.
    """
    n = arrays.attr_hash.shape[0]
    if c_width == 0:
        return jnp.ones((n,), bool)
    hash_T, numver_T = _tables(arrays)
    check = jax.vmap(
        lambda s, o, h, n_: _check_predicate(hash_T, numver_T, s, o, h, n_)
    )
    per_constraint = check(
        req.c_slot[:c_width],
        req.c_op[:c_width],
        req.c_hash[:c_width],
        req.c_num[:c_width],
    )  # (c_width, N)
    return jnp.all(per_constraint, axis=0)


def datacenter_mask(arrays, req: SchedRequest) -> jnp.ndarray:
    """(N,) bool — node's datacenter is in the job's list (util.go
    readyNodesInDCs). Attribute slot 0 is node.datacenter by registry order."""
    dc = arrays.attr_hash[:, 0]  # (N,)
    member = (dc[:, None] == req.dc_hash[None, :]) & (req.dc_hash[None, :] > 0)
    skip = req.dc_hash[0] == -1  # escaped: host filters datacenters instead
    return jnp.any(member, axis=1) | skip


def device_mask(arrays, req: SchedRequest) -> jnp.ndarray:
    """(N,) bool — free device instances cover the ask (DeviceChecker +
    accounting, feasible.go:1173, structs DeviceAccounter)."""
    free = arrays.dev_total - arrays.dev_used  # (N, D)
    ok = (free >= req.dev_ask[None, :]) | (req.dev_ask[None, :] == 0)
    return jnp.all(ok, axis=1)


def port_mask(arrays, req: SchedRequest, enabled: bool = True) -> jnp.ndarray:
    """(N,) bool — no requested static port collides with the node's
    occupied-port bitmap, and the dynamic range has room (the vectorized
    half of NetworkIndex, structs/network.go:35; exact assignment stays
    host-side on the chosen node, re-verified at plan apply).

    ``enabled=False`` (static, from Features) short-circuits to all-True
    when no eval in the batch asks for any port."""
    from ..state.matrix import DYN_PORT_CAPACITY

    if not enabled:
        return jnp.ones((arrays.port_words.shape[0],), bool)
    p = req.p_static  # (P,)
    valid = p >= 0
    word = jnp.maximum(p, 0) >> 5  # (P,)
    bit = (jnp.maximum(p, 0) & 31).astype(jnp.uint32)
    words = arrays.port_words[:, word]  # (N, P)
    taken = (words >> bit[None, :]) & jnp.uint32(1)
    conflict = jnp.any(valid[None, :] & (taken == 1), axis=1)  # (N,)
    dyn_ok = arrays.dyn_used + req.p_dyn <= DYN_PORT_CAPACITY
    return (~conflict) & dyn_ok


def feasibility_mask(arrays, req: SchedRequest,
                     class_elig: Optional[jnp.ndarray] = None,
                     host_mask: Optional[jnp.ndarray] = None,
                     features: Features = FULL_FEATURES):
    """(N,) bool — eligible ∧ dc ∧ constraints ∧ devices ∧ escaped checks.

    ``class_elig``: (num_classes,) bool from host-side evaluation of escaped
    constraints, gathered per node via class_id (the computed-class cache,
    feasible.go:1029). ``host_mask``: optional (N,) bool for unique-attr
    escapes. ``features`` (static) bounds the work to the batch occupancy.
    """
    mask = arrays.eligible
    mask &= datacenter_mask(arrays, req)
    mask &= constraint_mask(arrays, req, features.c_width)
    mask &= device_mask(arrays, req)
    mask &= port_mask(arrays, req, features.ports)
    if class_elig is not None:
        cid = jnp.maximum(arrays.class_id, 0)
        mask &= jnp.where(arrays.class_id < 0, False, class_elig[cid])
    if host_mask is not None:
        mask &= host_mask
    return mask


@jax.jit
def system_feasible(arrays, used0, req: SchedRequest, class_elig, host_mask):
    """Fused system-scheduler pass: feasibility ∧ fit for every node in one
    compiled program (SystemStack, stack.go:183-321 — system jobs need no
    ranking, just the all-node mask).

    Returns ONE stacked (2, N) bool array [mask, fits] so the host pays a
    single device→host fetch (each separate fetch costs a full tunnel
    round-trip — see bench.py rtt_floor_ms)."""
    mask = feasibility_mask(arrays, req, class_elig, host_mask)
    fits, _, _ = fit_and_binpack(arrays, used0, req)
    return jnp.stack([mask, fits])


# ---------------------------------------------------------------------------
# Scoring
# ---------------------------------------------------------------------------


def fit_and_binpack(arrays, used, req: SchedRequest):
    """Resource fit + normalized fit score for all nodes.

    Returns (fits (N,) bool, score (N,) f32, exhausted_dim (N,) i32).
    util = current used + ask; fit requires util ≤ totals in all dims
    (AllocsFit, funcs.go:97-160); score per scheduler_algorithm
    (rank.go:166-170, funcs.go:186/213) normalized by 18 (rank.go:513-516).
    """
    util = used + req.ask[None, :]  # (N, 3)
    fits_dim = util <= arrays.totals  # (N, 3)
    fits = jnp.all(fits_dim, axis=1)
    # first exhausted dim index for metrics (0=cpu,1=mem,2=disk, -1 = fits)
    exhausted = jnp.argmax(~fits_dim, axis=1).astype(jnp.int32)
    exhausted = jnp.where(fits, -1, exhausted)

    denom = jnp.maximum(arrays.totals, 1.0)
    free = 1.0 - util / denom  # (N, 3)
    free_cpu, free_mem = free[:, 0], free[:, 1]
    # 10**x as exp2(x·log₂10): XLA CPU lowers pow() through a generic
    # expf/logf pair ~4× slower than a bare exp2; identical to ~1e-7 rel.
    log2_10 = jnp.float32(3.321928094887362)
    total = jnp.exp2(free_cpu * log2_10) + jnp.exp2(free_mem * log2_10)
    binpack = jnp.clip(20.0 - total, 0.0, 18.0)
    spread = jnp.clip(total - 2.0, 0.0, 18.0)
    score = jnp.where(req.algorithm == 1, spread, binpack) / 18.0
    return fits, score, exhausted


def anti_affinity_score(tg_count, req: SchedRequest):
    """(score (N,), appended (N,)) — JobAntiAffinityIterator (rank.go:560-607).

    ``tg_count`` (N,) i32 = proposed allocs of this job+TG per node."""
    collisions = tg_count.astype(jnp.float32)
    score = -(collisions + 1.0) / req.desired_count
    appended = collisions > 0
    return jnp.where(appended, score, 0.0), appended


def penalty_score(penalty_mask):
    """NodeReschedulingPenaltyIterator (rank.go:630-646)."""
    return jnp.where(penalty_mask, -1.0, 0.0), penalty_mask


def affinity_score(arrays, req: SchedRequest, a_width: int = MAX_AFFINITIES):
    """NodeAffinityIterator (rank.go:698-728): Σ weight·match / Σ|weight|,
    appended only when non-zero. ``a_width`` (static) bounds the stanza loop
    to the batch occupancy; 0 skips the pass entirely."""
    n = arrays.attr_hash.shape[0]
    if a_width == 0:
        zeros = jnp.zeros((n,), jnp.float32)
        return zeros, jnp.zeros((n,), bool)
    hash_T, numver_T = _tables(arrays)
    check = jax.vmap(
        lambda s, o, h, n_: _check_predicate(hash_T, numver_T, s, o, h, n_)
    )
    a_slot = req.a_slot[:a_width]
    a_weight = req.a_weight[:a_width]
    matches = check(
        a_slot, req.a_op[:a_width], req.a_hash[:a_width], req.a_num[:a_width]
    )  # (a_width, N)
    active = (a_slot >= 0)[:, None]  # (a_width, 1)
    matched = matches & active
    sum_weight = jnp.sum(jnp.abs(a_weight) * (a_slot >= 0))
    total = jnp.sum(matched * a_weight[:, None], axis=0)  # (N,)
    norm = total / jnp.maximum(sum_weight, 1e-9)
    appended = (total != 0.0) & (sum_weight > 0)
    return jnp.where(appended, norm, 0.0), appended


def spread_score(arrays, req: SchedRequest, spread_counts,
                 s_width: int = MAX_SPREADS):
    """SpreadIterator (spread.go:110-257).

    ``spread_counts`` (S, V) f32 — usage count per known attribute value
    (existing + proposed allocs of this TG), aligned with req.s_value_hash.
    ``s_width`` (static) bounds the stanza loop to the batch occupancy.
    Returns (score (N,), appended (N,)).
    """
    n = arrays.attr_hash.shape[0]
    if s_width == 0:
        return jnp.zeros((n,), jnp.float32), jnp.zeros((n,), bool)
    hash_T = arrays.attr_hash.T  # batch-invariant, CSE'd with _tables

    def one_stanza(slot, weight, even, value_hash, desired, implicit, counts):
        active = slot >= 0
        nvalue = hash_T[jnp.maximum(slot, 0)]  # (N,) contiguous
        node_has = nvalue != 0

        # match node value against the known-values table
        vmatch = (nvalue[:, None] == value_hash[None, :]) & (
            value_hash[None, :] != 0
        )  # (N, V)
        found = jnp.any(vmatch, axis=1)
        # Per-node lookups as masked reductions over the (small) V axis.
        # ``counts[vidx]``-style element gathers lower to scalarized TPU
        # gathers (slice_sizes={1,1,1}) that serialize 5M+ loads and
        # dominated the whole scoring pipeline; vmatch has at most one hit
        # per row, so a masked sum is the same value at VPU speed.
        count_at = jnp.sum(jnp.where(vmatch, counts[None, :], 0.0), axis=1)
        used_count = count_at + 1.0  # +1 = this placement

        # ---- targeted mode (spread.go:134-165)
        desired_ok = ~jnp.isnan(desired)  # (V,)
        has_target = jnp.any(vmatch & desired_ok[None, :], axis=1)
        desired_at = jnp.sum(
            jnp.where(vmatch & desired_ok[None, :], desired[None, :], 0.0),
            axis=1,
        )
        desired_v = jnp.where(has_target, desired_at, jnp.nan)
        use_implicit = ~has_target & ~jnp.isnan(implicit)
        desired_v = jnp.where(use_implicit, implicit, desired_v)
        no_target = jnp.isnan(desired_v)
        rel_weight = weight / jnp.maximum(req.s_sum_weights, 1e-9)
        boost_t = ((desired_v - used_count) / jnp.maximum(desired_v, 1e-9)) * rel_weight
        targeted = jnp.where(no_target, -1.0, boost_t)

        # ---- even mode (spread.go evenSpreadScoreBoost:178-230)
        valid = (value_hash != 0) & (counts > 0)
        any_use = jnp.any(valid)
        big = jnp.float32(1e30)
        mn = jnp.min(jnp.where(valid, counts, big))
        mx = jnp.max(jnp.where(valid, counts, -big))
        current = count_at
        delta_boost = jnp.where(mn == 0, -1.0, (mn - current) / jnp.maximum(mn, 1e-9))
        even_b = jnp.where(
            current != mn,
            delta_boost,
            jnp.where(
                mn == mx,
                -1.0,
                jnp.where(mn == 0, 1.0, (mx - mn) / jnp.maximum(mn, 1e-9)),
            ),
        )
        even_b = jnp.where(any_use, even_b, 0.0)
        even_b = jnp.where(node_has, even_b, -1.0)  # attr unset → max penalty

        score = jnp.where(even, even_b, targeted)
        return jnp.where(active, score, 0.0)

    per_stanza = jax.vmap(one_stanza)(
        req.s_slot[:s_width],
        req.s_weight[:s_width],
        req.s_even[:s_width],
        req.s_value_hash[:s_width],
        req.s_desired[:s_width],
        req.s_implicit[:s_width],
        spread_counts[:s_width],
    )  # (s_width, N)
    total = jnp.sum(per_stanza, axis=0)
    has_spread = jnp.any(req.s_slot[:s_width] >= 0)
    appended = (total != 0.0) & has_spread
    return jnp.where(appended, total, 0.0), appended


def preemption_state(arrays, req: SchedRequest):
    """Vectorized preemption candidate math.

    The reference walks per-node alloc lists greedily
    (preemption.go:198-557). Here ``prio_used`` (N, P, 3) holds usage per
    priority bucket; everything strictly below ``preempt_bucket`` is
    evictable, so freeable = Σ lower buckets. netPriority is approximated
    from bucket midpoints.

    The bucket-axis reductions are expressed as *prefix* scans that depend
    only on ``arrays`` — batch-invariant, computed once per dispatch — and
    each eval then reads a single column at its ``preempt_bucket``. The
    previous form re-reduced the full (N, P, 3) tensor per eval, which at
    B=4096 re-read ~8 GB of HBM per dispatch.

    Returns (extra_free (N,3), preempt_score (N,), usable (N,) bool).
    """
    buckets = jnp.arange(PRIORITY_BUCKETS)
    # Shared prefix tables with the bucket axis LEADING and a zero row so
    # index k = "buckets < k". Leading-axis layout makes each eval's lookup
    # a contiguous (N, ...) row read instead of a strided column walk; the
    # tables depend only on ``arrays`` so XLA hoists them out of the vmap.
    csum = jnp.cumsum(jnp.moveaxis(arrays.prio_used, 1, 0), axis=0)  # (P, N, 3)
    csum = jnp.concatenate(
        [jnp.zeros_like(csum[:1]), csum], axis=0
    )  # (P+1, N, 3)
    mid = (buckets.astype(jnp.float32) + 0.5) * (101.0 / PRIORITY_BUCKETS)
    present = jnp.any(arrays.prio_used > 0, axis=2).T  # (P, N)
    mid_masked = jnp.where(present, mid[:, None], 0.0)
    mid_max = lax.cummax(mid_masked, axis=0)
    mid_max = jnp.concatenate(
        [jnp.zeros_like(mid_max[:1]), mid_max], axis=0
    )  # (P+1, N)
    mid_sum = jnp.cumsum(mid_masked, axis=0)
    mid_sum = jnp.concatenate(
        [jnp.zeros_like(mid_sum[:1]), mid_sum], axis=0
    )  # (P+1, N)

    # Per-eval: one row each (the only batch-dependent reads).
    k = jnp.clip(req.preempt_bucket, 0, PRIORITY_BUCKETS)
    freeable = csum[k]  # (N, 3)
    max_prio = mid_max[k]  # (N,)
    sum_prio = mid_sum[k]  # (N,)
    net = jnp.where(max_prio > 0, max_prio + sum_prio / jnp.maximum(max_prio, 1e-9), 0.0)
    score = 1.0 / (1.0 + jnp.exp(PREEMPTION_RATE * (net - PREEMPTION_ORIGIN)))

    usable = (req.preempt_bucket >= 0) & jnp.any(freeable > 0, axis=1)
    return freeable, score, usable


class ScoreResult(NamedTuple):
    final: jnp.ndarray  # (N,) f32, NEG_INF where infeasible
    feasible: jnp.ndarray  # (N,) bool (constraints, pre-resource)
    fits: jnp.ndarray  # (N,) bool (resources, incl. preemption assist)
    needs_preempt: jnp.ndarray  # (N,) bool
    binpack: jnp.ndarray  # (N,) f32
    exhausted_dim: jnp.ndarray  # (N,) i32


def score_nodes(
    arrays,
    used,
    tg_count,
    spread_counts,
    penalty_mask,
    req: SchedRequest,
    class_elig,
    host_mask,
    features: Features = FULL_FEATURES,
) -> ScoreResult:
    """The full ranking pipeline as one fused program (GenericStack.Select,
    stack.go:117-179, minus the sampling the TPU design makes unnecessary).

    ``features`` (static) bounds every sub-pass to the dispatch's batch
    occupancy — padded constraint/affinity/spread slots, unused preemption
    tables and port bitmaps cost nothing when no eval in the batch uses
    them."""
    feas = feasibility_mask(arrays, req, class_elig, host_mask, features)
    # distinct_hosts: one proposed alloc of this job+TG per node, enforced
    # in-scan via tg_count so multi-placement batches can't stack a node.
    feas &= ~(req.distinct_hosts & (tg_count > 0))
    fits, binpack, exhausted = fit_and_binpack(arrays, used, req)

    if features.preempt:
        # Preemption assist: nodes that don't fit but could after evicting
        # lower-priority work (generic_sched.go:773-792 retry pass).
        extra_free, pre_score, pre_usable = preemption_state(arrays, req)
        util = used + req.ask[None, :]
        fits_with_preempt = jnp.all(util - extra_free <= arrays.totals, axis=1)
        needs_preempt = ~fits & fits_with_preempt & pre_usable
        fits_all = fits | needs_preempt
        pre_component = jnp.where(needs_preempt, pre_score, 0.0)
    else:
        needs_preempt = jnp.zeros_like(fits)
        fits_all = fits
        pre_component = jnp.zeros(fits.shape, jnp.float32)

    aa_score, aa_app = anti_affinity_score(tg_count, req)
    pen_score, pen_app = penalty_score(penalty_mask)
    aff_score, aff_app = affinity_score(arrays, req, features.a_width)
    spr_score, spr_app = spread_score(arrays, req, spread_counts,
                                      features.s_width)

    total = binpack + aa_score + pen_score + aff_score + spr_score + pre_component
    count = (
        1.0
        + aa_app.astype(jnp.float32)
        + pen_app.astype(jnp.float32)
        + aff_app.astype(jnp.float32)
        + spr_app.astype(jnp.float32)
        + needs_preempt.astype(jnp.float32)
    )
    final = total / count
    final = jnp.where(feas & fits_all, final, NEG_INF)
    return ScoreResult(
        final=final,
        feasible=feas,
        fits=fits_all,
        needs_preempt=needs_preempt,
        binpack=binpack,
        exhausted_dim=exhausted,
    )


# ---------------------------------------------------------------------------
# Batched independent evals (the throughput path)
# ---------------------------------------------------------------------------


class BatchScoreResult(NamedTuple):
    rows: jnp.ndarray  # (B,) i32 argmax node row, -1 = no fit
    scores: jnp.ndarray  # (B,) f32
    binpack: jnp.ndarray  # (B,) f32
    preempted: jnp.ndarray  # (B,) bool
    nodes_evaluated: jnp.ndarray  # (B,) i32
    nodes_filtered: jnp.ndarray  # (B,) i32
    nodes_exhausted: jnp.ndarray  # (B,) i32


def _score_and_pick(arrays, used, tg_count, spread_counts, penalty, req,
                    class_elig, host_mask,
                    features: Features = FULL_FEATURES) -> tuple:
    res = score_nodes(
        arrays, used, tg_count, spread_counts, penalty, req, class_elig,
        host_mask, features,
    )
    row = jnp.argmax(res.final).astype(jnp.int32)
    ok = res.final[row] > NEG_INF / 2
    return (
        jnp.where(ok, row, -1),
        # Failed placements report 0 score/binpack, matching the placement
        # scan's convention (place_task_group) so consumers can aggregate
        # without re-masking.
        jnp.where(ok, res.final[row], 0.0),
        jnp.where(ok, res.binpack[row], 0.0),
        res.needs_preempt[row] & ok,
        jnp.sum(res.feasible.astype(jnp.int32)),
        # Filtered counts exclude capacity-padding / ineligible rows, like
        # the placement scan's n_filtered.
        jnp.sum((~res.feasible & arrays.eligible).astype(jnp.int32)),
        jnp.sum((res.feasible & ~res.fits).astype(jnp.int32)),
    )


@functools.partial(jax.jit, static_argnames=("features",))
def score_batch(arrays, used, tg_counts, spread_counts, penalties, reqs,
                class_eligs, host_masks,
                features: Features = FULL_FEATURES) -> BatchScoreResult:
    """B independent evaluations in ONE dispatch: full ranking over every
    node for each, then per-eval argmax.

    This is where the TPU design earns its keep versus the reference: where
    Nomad bounds *per-eval* work (shuffle + log₂(n) candidates + po2c,
    stack.go:78-91) and scales via optimistic worker concurrency, we score
    all nodes for a whole *batch* of evals as one (B, N) data-parallel
    program. Conflicting picks are caught by the plan applier's re-verify —
    the same optimistic-concurrency contract the reference already relies on
    (plan_apply.go:49-69).

    Batched args lead with a B axis: tg_counts (B,N), spread_counts (B,S,V),
    penalties (B,N), reqs a stacked SchedRequest pytree, class_eligs (B,K),
    host_masks (B,N). ``arrays`` and ``used`` are shared.
    """
    outs = jax.vmap(
        lambda tg, sc, pen, req, ce, hm: _score_and_pick(
            arrays, used, tg, sc, pen, req, ce, hm, features
        )
    )(tg_counts, spread_counts, penalties, reqs, class_eligs, host_masks)
    return BatchScoreResult(*outs)


# ---------------------------------------------------------------------------
# Placement scan
# ---------------------------------------------------------------------------


class PlacementResult(NamedTuple):
    rows: jnp.ndarray  # (P,) i32 chosen node row, -1 = failed
    scores: jnp.ndarray  # (P,) f32 final score of chosen node
    binpack: jnp.ndarray  # (P,) f32 binpack component
    preempted: jnp.ndarray  # (P,) bool placement requires preemption
    nodes_evaluated: jnp.ndarray  # (P,) i32
    nodes_filtered: jnp.ndarray  # (P,) i32 failed constraints
    nodes_exhausted: jnp.ndarray  # (P,) i32 feasible but resource-exhausted
    used_after: jnp.ndarray  # (N, 3) proposed usage after placements
    tg_count_after: jnp.ndarray  # (N,)


def spread_values_at(arrays, req: SchedRequest, row):
    """Per-stanza attribute hash of node ``row`` ((S,) i32) — split out so
    the node-sharded step can compute it on the winning row's owner shard
    and broadcast (parallel/sharding.py)."""
    return arrays.attr_hash[row, jnp.maximum(req.s_slot, 0)]


def apply_spread_values(spread_counts, req: SchedRequest, nvalues):
    """Bump per-stanza counts for the placed node's attribute values
    (propertyset.go usage tracking). Claims an empty value slot on first
    sight of a new value.  ``nvalues``: (S,) i32 from spread_values_at."""

    def one(slot, value_hash, counts, nvalue):
        match = (value_hash == nvalue) & (nvalue != 0)
        have = jnp.any(match)
        free_slot = jnp.argmax(value_hash == 0)
        idx = jnp.where(have, jnp.argmax(match), free_slot)
        can = (slot >= 0) & (nvalue != 0) & (have | (value_hash[free_slot] == 0))
        new_hash = jnp.where(
            can & ~have, value_hash.at[idx].set(nvalue), value_hash
        )
        new_counts = jnp.where(can, counts.at[idx].add(1.0), counts)
        return new_hash, new_counts

    return jax.vmap(one)(
        req.s_slot, req.s_value_hash, spread_counts, nvalues
    )


def _update_spread_counts(spread_counts, req: SchedRequest, arrays, row):
    """After placing on ``row``, bump the count of that node's attribute
    value per stanza."""
    return apply_spread_values(
        spread_counts, req, spread_values_at(arrays, req, row)
    )


def _place_scan(
    arrays,
    req: SchedRequest,
    used0,
    tg_count,
    spread_counts,
    penalty_mask,
    class_elig,
    host_mask,
    n_placements: int,
    features: Features = FULL_FEATURES,
) -> PlacementResult:
    """Traceable core of the placement scan (shared by the solo
    ``place_task_group`` jit and the coalesced ``place_batch`` vmap)."""

    def step(carry, _):
        used, tg_cnt, s_hash, s_counts = carry
        req_step = req._replace(s_value_hash=s_hash)
        res = score_nodes(
            arrays, used, tg_cnt, s_counts, penalty_mask, req_step,
            class_elig, host_mask, features,
        )
        row = jnp.argmax(res.final).astype(jnp.int32)
        ok = res.final[row] > NEG_INF / 2
        row = jnp.where(ok, row, -1)

        n_eval = jnp.sum(res.feasible).astype(jnp.int32)
        n_filtered = jnp.sum(~res.feasible & arrays.eligible).astype(jnp.int32)
        n_exhausted = jnp.sum(res.feasible & ~res.fits).astype(jnp.int32)

        safe_row = jnp.maximum(row, 0)
        used2 = jnp.where(ok, used.at[safe_row].add(req.ask), used)
        tg2 = jnp.where(ok, tg_cnt.at[safe_row].add(1), tg_cnt)
        new_hash, new_counts = _update_spread_counts(s_counts, req_step, arrays, safe_row)
        s_hash2 = jnp.where(ok, new_hash, s_hash)
        s_counts2 = jnp.where(ok, new_counts, s_counts)

        out = (
            row,
            jnp.where(ok, res.final[safe_row], 0.0),
            jnp.where(ok, res.binpack[safe_row], 0.0),
            ok & res.needs_preempt[safe_row],
            n_eval,
            n_filtered,
            n_exhausted,
        )
        return (used2, tg2, s_hash2, s_counts2), out

    init = (used0, tg_count, req.s_value_hash, spread_counts)
    (used_after, tg_after, _, _), outs = lax.scan(
        step, init, None, length=n_placements
    )
    rows, scores, binpack, preempted, n_eval, n_filt, n_exh = outs
    return PlacementResult(
        rows=rows,
        scores=scores,
        binpack=binpack,
        preempted=preempted,
        nodes_evaluated=n_eval,
        nodes_filtered=n_filt,
        nodes_exhausted=n_exh,
        used_after=used_after,
        tg_count_after=tg_after,
    )


@functools.partial(jax.jit, static_argnames=("n_placements", "features"))
def place_task_group(
    arrays,
    req: SchedRequest,
    used0,
    tg_count,
    spread_counts,
    penalty_mask,
    class_elig,
    host_mask,
    n_placements: int,
    features: Features = FULL_FEATURES,
) -> PlacementResult:
    """Place ``n_placements`` allocs of one TG — the kernel behind
    computePlacements (generic_sched.go:472).

    A lax.scan over placements: each step scores all nodes, takes the argmax
    (replacing Limit/MaxScore sampling, stack.go:78-91), and scatters the
    proposed usage so subsequent placements see it (ProposedAllocs semantics,
    rank.go:41-52).

    ``used0`` (N, 3) is the proposed base usage — the authoritative matrix
    usage already adjusted by the reconciler's planned stops/evictions
    (the reference's ProposedAllocs = existing − plan.NodeUpdate + in-plan,
    scheduler/context.go ProposedAllocs).
    """
    return _place_scan(
        arrays, req, used0, tg_count, spread_counts, penalty_mask,
        class_elig, host_mask, n_placements, features,
    )


# Columns of place_batch's packed per-request output (one fetch per
# dispatch; each separate device→host fetch costs a tunnel round-trip).
PACKED_ROW = 0
PACKED_SCORE = 1
PACKED_BINPACK = 2
PACKED_PREEMPT = 3
PACKED_EVALUATED = 4
PACKED_FILTERED = 5
PACKED_EXHAUSTED = 6
PACKED_WIDTH = 7


def _place_batch_impl(
    arrays,
    used,
    delta_rows,
    delta_vals,
    tg_counts,
    spread_counts,
    penalties,
    reqs,
    class_eligs,
    host_masks,
    n_placements: int,
    features: Features = FULL_FEATURES,
) -> jnp.ndarray:
    """B independent placement scans in ONE dispatch — the device side of
    the dispatch coalescer (scheduler/coalescer.py).

    Where the reference scales scheduling by optimistic worker concurrency
    (worker.go:49-53) with each worker walking nodes alone, here concurrent
    workers' selects coalesce into one vmapped scan over the shared matrix;
    conflicting picks stay the plan applier's job (plan_apply.go:49-69).

    Per-request args lead with a B axis. ``delta_rows``/``delta_vals``
    ((B, K) i32 / (B, K, 3) f32, row -1 = padding) carry each request's
    sparse in-flight plan usage deltas — applied to the shared ``used``
    inside the kernel so the host never materializes a dense per-request
    usage matrix.

    Returns a packed (B, n_placements, PACKED_WIDTH) f32 array (row ids and
    counts are exact in f32 up to 2^24) so the host pays ONE fetch.
    """

    def one(drows, dvals, tg, sc, pen, req, ce, hm):
        safe = jnp.maximum(drows, 0)
        add = jnp.where((drows >= 0)[:, None], dvals, 0.0)
        used0 = used.at[safe].add(add)
        res = _place_scan(
            arrays, req, used0, tg, sc, pen, ce, hm, n_placements, features
        )
        return jnp.stack(
            [
                res.rows.astype(jnp.float32),
                res.scores,
                res.binpack,
                res.preempted.astype(jnp.float32),
                res.nodes_evaluated.astype(jnp.float32),
                res.nodes_filtered.astype(jnp.float32),
                res.nodes_exhausted.astype(jnp.float32),
            ],
            axis=1,
        )  # (P, 7)

    return jax.vmap(one)(
        delta_rows, delta_vals, tg_counts, spread_counts, penalties, reqs,
        class_eligs, host_masks,
    )


place_batch = functools.partial(
    jax.jit, static_argnames=("n_placements", "features")
)(_place_batch_impl)

# The coalescer's entry point: identical computation, but the per-dispatch
# lane operands (deltas, tg/spread counts, penalties, stacked requests,
# class eligibility, host masks — argnums 2..9) are DONATED, so XLA reuses
# their freshly-transferred device buffers as scratch instead of holding
# them live alongside the outputs. ``arrays``/``used`` (argnums 0-1) are
# never donated: they are matrix-resident and shared with other in-flight
# pipelined dispatches. Kept separate from ``place_batch`` because callers
# of the un-donated entry (tests, tools) legitimately reuse their input
# arrays across calls.
place_batch_live = functools.partial(
    jax.jit,
    static_argnames=("n_placements", "features"),
    donate_argnums=tuple(range(2, 10)),
)(_place_batch_impl)


# ---------------------------------------------------------------------------
# Fused megakernel (mega-batched eval pipeline + device-resident re-verify)
# ---------------------------------------------------------------------------

# Escape hatch reserved by the fusion work: if XLA ever stops fusing the
# sequential binpack/placement scan inside the megakernel (a regression
# observable as per-step launch overhead returning in the trace), the
# scan segment gets a hand-written Pallas kernel behind this flag.
# Measured on current jax (0.4.x): XLA fuses the whole pipeline into one
# program, so no Pallas implementation exists and the flag only warns —
# it must never silently change numerics.
PALLAS_FLAG = "NOMAD_TPU_PALLAS"
_pallas_warned = False


def pallas_requested() -> bool:
    """True when NOMAD_TPU_PALLAS opts into the (reserved) Pallas scan.

    Warns once: there is nothing to switch yet, the XLA fusion is the
    implementation. Callers must not branch numerics on this."""
    import os

    global _pallas_warned
    on = os.environ.get(PALLAS_FLAG, "").lower() in ("1", "on", "true", "yes")
    if on and not _pallas_warned:
        _pallas_warned = True
        import warnings

        warnings.warn(
            f"{PALLAS_FLAG} is set, but the fused scan has no Pallas "
            f"implementation (XLA fuses it; see ops/kernels.py) — "
            f"running the XLA path.",
            stacklevel=2,
        )
    return on

# Columns of the fused kernel's packed output. The first PACKED_WIDTH
# columns are identical to place_batch's; the extra VERIFIED column carries
# the device-resident AllocsFit re-verify verdict per placement:
#   1.0  placement survives the sequential cross-lane re-check
#   0.0  placement would be rejected (an earlier lane's plan claims the
#        capacity first, in resolve order — the applier will reject it)
#  -1.0  not computed (dead/padded lane)
FUSED_PACKED_VERIFIED = 7
FUSED_PACKED_WIDTH = 8


def pack_fused_lanes(
    rows, scores, binpack, preempted, n_eval, n_filt, n_exh, verified, live
):
    """Stack per-lane placement outputs into the fused (B, P, 8) layout with
    dead-lane masking: row/-1, VERIFIED/-1.0, zeros elsewhere.  Shared by the
    single-device fused kernel and the shard_map local body
    (parallel/sharding.py) so the two paths cannot drift column-wise —
    tests/test_parallel.py asserts bitwise parity across them.
    """
    lv = live[:, None]
    vcol = jnp.where(lv, verified.astype(jnp.float32), -1.0)
    return jnp.stack(
        [
            rows.astype(jnp.float32),
            jnp.where(lv, scores, 0.0),
            jnp.where(lv, binpack, 0.0),
            jnp.where(lv, preempted, False).astype(jnp.float32),
            jnp.where(lv, n_eval, 0).astype(jnp.float32),
            jnp.where(lv, n_filt, 0).astype(jnp.float32),
            jnp.where(lv, n_exh, 0).astype(jnp.float32),
            vcol,
        ],
        axis=2,
    )  # (B, P, FUSED_PACKED_WIDTH)


def _fused_place_batch_impl(
    arrays,
    used,
    delta_rows,
    delta_vals,
    tg_counts,
    spread_counts,
    penalties,
    reqs,
    class_eligs,
    host_masks,
    lane_mask,
    n_placements: int,
    features: Features = FULL_FEATURES,
) -> jnp.ndarray:
    """The mega-batched ranking megakernel: B eval pipelines — feasibility →
    binpack → spread/affinity → preemption evict-state → placement scan —
    PLUS the ``AllocsFit`` plan re-verify, in ONE launch.

    Differences from ``place_batch``:

    * ``lane_mask`` (B,) bool marks live eval slots explicitly. Dead lanes
      (batch occupancy < B) produce row=-1 / zero outputs and contribute
      nothing to the verify pass, so one compile serves every occupancy —
      no host-side request-faking, no shape-polymorphic recompiles.
    * The packed output grows a VERIFIED column: a device-resident
      sequential AllocsFit re-check of every lane's chosen placements
      against the authoritative matrix usage *plus all earlier lanes'
      deltas and placements*, in lane (= resolve) order. Within one lane a
      placement always fits its own proposed usage by construction; what
      the scan cannot see is *other* lanes of the same launch claiming the
      same capacity — exactly the conflicts the plan applier's
      optimistic-concurrency re-verify (plan_apply.py:_evaluate) rejects
      one plan-apply round-trip later. The verdicts are advisory (the
      applier against live state stays authoritative; lanes whose
      in-flight deltas overlap are re-checked conservatively), but at an
      unchanged matrix version a 0.0 verdict is a guaranteed applier
      rejection, surfaced hundreds of microseconds earlier and without a
      single extra launch.

    Returns (B, n_placements, FUSED_PACKED_WIDTH) f32 — one fetch.
    """

    def one(drows, dvals, tg, sc, pen, req, ce, hm):
        safe = jnp.maximum(drows, 0)
        add = jnp.where((drows >= 0)[:, None], dvals, 0.0)
        used0 = used.at[safe].add(add)
        return _place_scan(
            arrays, req, used0, tg, sc, pen, ce, hm, n_placements, features
        )

    res = jax.vmap(one)(
        delta_rows, delta_vals, tg_counts, spread_counts, penalties, reqs,
        class_eligs, host_masks,
    )
    live = lane_mask  # (B,)
    rows = jnp.where(live[:, None], res.rows, -1)  # (B, P)

    # Sequential cross-lane AllocsFit: a scan over lanes carrying the
    # cumulative proposed usage. Each lane first applies its own in-flight
    # deltas, then commits its placements one by one, checking
    # used ≤ totals on every touched row (funcs.go:97-160 AllocsFit, in
    # plan-apply order). Work per lane is O(P) row updates on an (N, 3)
    # carry — negligible next to the ranking itself.
    def lane_step(cum_used, lane):
        l_rows, l_ask, l_drows, l_dvals, l_live = lane
        dadd = jnp.where(((l_drows >= 0) & l_live)[:, None], l_dvals, 0.0)
        base = cum_used.at[jnp.maximum(l_drows, 0)].add(dadd)

        def p_step(u, row):
            ok_row = (row >= 0) & l_live
            safe_r = jnp.maximum(row, 0)
            u2 = u.at[safe_r].add(jnp.where(ok_row, l_ask, 0.0))
            fit = jnp.all(u2[safe_r] <= arrays.totals[safe_r]) | ~ok_row
            return u2, fit

        after, fits = lax.scan(p_step, base, l_rows)
        return jnp.where(l_live, after, cum_used), fits

    _, verified = lax.scan(
        lane_step, used, (rows, reqs.ask, delta_rows, delta_vals, live)
    )  # (B, P) bool

    return pack_fused_lanes(
        rows, res.scores, res.binpack, res.preempted, res.nodes_evaluated,
        res.nodes_filtered, res.nodes_exhausted, verified, live,
    )


fused_place_batch = functools.partial(
    jax.jit, static_argnames=("n_placements", "features")
)(_fused_place_batch_impl)

# Live entry: per-dispatch lane operands (argnums 2..10, including the lane
# mask) are donated, mirroring place_batch_live. ``arrays``/``used`` stay
# shared with in-flight pipelined dispatches and are never donated.
fused_place_batch_live = functools.partial(
    jax.jit,
    static_argnames=("n_placements", "features"),
    donate_argnums=tuple(range(2, 11)),
)(_fused_place_batch_impl)


# ---------------------------------------------------------------------------
# Plan-apply verification (AllocsFit re-check at commit time)
# ---------------------------------------------------------------------------


@jax.jit
def verify_plan_fit(arrays, rows, deltas, eligible_required):
    """Vectorized optimistic-concurrency check for the plan applier.

    The reference fans per-node AllocsFit checks out to an EvaluatePool of
    goroutines (plan_apply.go:439-682, plan_apply_pool.go:18). Here the whole
    plan verifies in one kernel against the authoritative matrix: for each
    plan row i, (used + delta ≤ totals) ∧ node still schedulable.

    rows: (K,) i32 node rows (-1 padded); deltas: (K, 3) f32 net usage the
    plan adds to that node; returns (K,) bool per-node verdicts.
    """
    safe = jnp.maximum(rows, 0)
    used = arrays.used[safe] + deltas  # (K, 3)
    fits = jnp.all(used <= arrays.totals[safe], axis=1)
    ok = fits & (~eligible_required | arrays.eligible[safe])
    return jnp.where(rows < 0, True, ok)
