"""Vectorized scheduling kernels — the hot path, in JAX.

Each kernel is a pure function over the device-resident node matrix
(``state.matrix.DeviceArrays``) and a compiled request
(``ops.encode.SchedRequest``). Where the reference pulls nodes one at a time
through a 14-iterator chain (scheduler/stack.go:324-417) and bounds work by
sampling log₂(n) candidates (stack.go:78-91), these kernels score **all**
nodes in one fused XLA program; placement of ``count`` allocs is a
``lax.scan`` that scatters proposed usage between steps (the reference's
in-plan "proposed allocs" cache, rank.go:41-52).

Score semantics mirror the reference exactly (see tests/test_kernels.py
golden tests against the scalar oracle in structs.funcs):
  binpack     = ScoreFitBinPack/18           (funcs.go:186, rank.go:513)
  anti-aff    = -(collisions+1)/desired      (rank.go:601-607, only if >0)
  penalty     = -1 on penalized nodes        (rank.go:646, only if penalized)
  affinity    = Σ weight·match / Σ|weight|   (rank.go:704-728, only if ≠0)
  spread      = per-stanza boosts            (spread.go:110-178, only if ≠0)
  preemption  = logistic(netPriority)        (rank.go:773-844, only if used)
  final       = mean of appended components  (rank.go:737-771)
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..state.matrix import PRIORITY_BUCKETS
from .encode import (
    OP_EQ,
    OP_GT,
    OP_GTE,
    OP_IS_NOT_SET,
    OP_IS_SET,
    OP_LT,
    OP_LTE,
    OP_NEQ,
    OP_VER_EQ,
    OP_VER_GT,
    OP_VER_GTE,
    OP_VER_LT,
    OP_VER_LTE,
    SchedRequest,
)

# Plain float (not a jnp scalar): materializing a device array at import
# time would force backend initialization on `import nomad_tpu`.
NEG_INF = -1e30

# Preemption score constants (reference: rank.go preemptionScore).
PREEMPTION_RATE = 0.0048
PREEMPTION_ORIGIN = 2048.0


# ---------------------------------------------------------------------------
# Feasibility
# ---------------------------------------------------------------------------


def _check_predicate(attr_hash, attr_numver, slot, op, want_hash, want_num):
    """Evaluate one predicate for every node. ``attr_hash`` is (N, A);
    ``attr_numver`` is (N, 2A) — the numeric columns then the
    version-packed columns concatenated, so each predicate needs exactly
    TWO column gathers (hash + the one numeric flavor its op reads) instead
    of three. The gathers are the dominant HBM traffic of a batched
    dispatch; the concat itself is batch-invariant and built once.
    Returns (N,) bool; inactive predicates (slot < 0) return True.

    Missing-attribute semantics follow checkConstraint (feasible.go:793-858):
    ``=`` and ordered comparisons require the attribute to be present; ``!=``
    passes when it is absent. Version ops read the version-packed column.
    """
    nattrs = attr_hash.shape[1]
    safe_slot = jnp.maximum(slot, 0)
    h = attr_hash[:, safe_slot]  # (N,)
    is_ver = op >= OP_VER_EQ
    v = attr_numver[:, safe_slot + jnp.where(is_ver, nattrs, 0)]  # (N,)
    present = h != 0
    num_ok = present & ~jnp.isnan(v) & ~jnp.isnan(want_num)

    eq = present & (h == want_hash)
    res = jnp.full(h.shape, True)
    res = jnp.where(op == OP_EQ, eq, res)
    res = jnp.where(op == OP_NEQ, ~eq, res)
    res = jnp.where(op == OP_LT, num_ok & (v < want_num), res)
    res = jnp.where(op == OP_LTE, num_ok & (v <= want_num), res)
    res = jnp.where(op == OP_GT, num_ok & (v > want_num), res)
    res = jnp.where(op == OP_GTE, num_ok & (v >= want_num), res)
    res = jnp.where(op == OP_VER_EQ, num_ok & (v == want_num), res)
    res = jnp.where(op == OP_VER_LT, num_ok & (v < want_num), res)
    res = jnp.where(op == OP_VER_LTE, num_ok & (v <= want_num), res)
    res = jnp.where(op == OP_VER_GT, num_ok & (v > want_num), res)
    res = jnp.where(op == OP_VER_GTE, num_ok & (v >= want_num), res)
    res = jnp.where(op == OP_IS_SET, present, res)
    res = jnp.where(op == OP_IS_NOT_SET, ~present, res)
    return jnp.where(slot < 0, True, res)


def _numver(arrays):
    """(N, 2A) — numeric and version-packed attribute columns side by side
    (see _check_predicate). Identical across a batch, so XLA computes it
    once per dispatch."""
    return jnp.concatenate([arrays.attr_num, arrays.attr_ver], axis=1)


def constraint_mask(arrays, req: SchedRequest) -> jnp.ndarray:
    """(N,) bool — all hard constraints pass (ConstraintChecker equivalent)."""
    numver = _numver(arrays)
    check = jax.vmap(
        lambda s, o, h, n: _check_predicate(
            arrays.attr_hash, numver, s, o, h, n
        )
    )
    per_constraint = check(req.c_slot, req.c_op, req.c_hash, req.c_num)  # (C, N)
    return jnp.all(per_constraint, axis=0)


def datacenter_mask(arrays, req: SchedRequest) -> jnp.ndarray:
    """(N,) bool — node's datacenter is in the job's list (util.go
    readyNodesInDCs). Attribute slot 0 is node.datacenter by registry order."""
    dc = arrays.attr_hash[:, 0]  # (N,)
    member = (dc[:, None] == req.dc_hash[None, :]) & (req.dc_hash[None, :] > 0)
    skip = req.dc_hash[0] == -1  # escaped: host filters datacenters instead
    return jnp.any(member, axis=1) | skip


def device_mask(arrays, req: SchedRequest) -> jnp.ndarray:
    """(N,) bool — free device instances cover the ask (DeviceChecker +
    accounting, feasible.go:1173, structs DeviceAccounter)."""
    free = arrays.dev_total - arrays.dev_used  # (N, D)
    ok = (free >= req.dev_ask[None, :]) | (req.dev_ask[None, :] == 0)
    return jnp.all(ok, axis=1)


def port_mask(arrays, req: SchedRequest) -> jnp.ndarray:
    """(N,) bool — no requested static port collides with the node's
    occupied-port bitmap, and the dynamic range has room (the vectorized
    half of NetworkIndex, structs/network.go:35; exact assignment stays
    host-side on the chosen node, re-verified at plan apply)."""
    from ..state.matrix import DYN_PORT_CAPACITY

    p = req.p_static  # (P,)
    valid = p >= 0
    word = jnp.maximum(p, 0) >> 5  # (P,)
    bit = (jnp.maximum(p, 0) & 31).astype(jnp.uint32)
    words = arrays.port_words[:, word]  # (N, P)
    taken = (words >> bit[None, :]) & jnp.uint32(1)
    conflict = jnp.any(valid[None, :] & (taken == 1), axis=1)  # (N,)
    dyn_ok = arrays.dyn_used + req.p_dyn <= DYN_PORT_CAPACITY
    return (~conflict) & dyn_ok


def feasibility_mask(arrays, req: SchedRequest, class_elig=None, host_mask=None):
    """(N,) bool — eligible ∧ dc ∧ constraints ∧ devices ∧ escaped checks.

    ``class_elig``: (num_classes,) bool from host-side evaluation of escaped
    constraints, gathered per node via class_id (the computed-class cache,
    feasible.go:1029). ``host_mask``: optional (N,) bool for unique-attr
    escapes.
    """
    mask = arrays.eligible
    mask &= datacenter_mask(arrays, req)
    mask &= constraint_mask(arrays, req)
    mask &= device_mask(arrays, req)
    mask &= port_mask(arrays, req)
    if class_elig is not None:
        cid = jnp.maximum(arrays.class_id, 0)
        mask &= jnp.where(arrays.class_id < 0, False, class_elig[cid])
    if host_mask is not None:
        mask &= host_mask
    return mask


@jax.jit
def system_feasible(arrays, used0, req: SchedRequest, class_elig, host_mask):
    """Fused system-scheduler pass: feasibility ∧ fit for every node in one
    compiled program (SystemStack, stack.go:183-321 — system jobs need no
    ranking, just the all-node mask).

    Returns ONE stacked (2, N) bool array [mask, fits] so the host pays a
    single device→host fetch (each separate fetch costs a full tunnel
    round-trip — see bench.py rtt_floor_ms)."""
    mask = feasibility_mask(arrays, req, class_elig, host_mask)
    fits, _, _ = fit_and_binpack(arrays, used0, req)
    return jnp.stack([mask, fits])


# ---------------------------------------------------------------------------
# Scoring
# ---------------------------------------------------------------------------


def fit_and_binpack(arrays, used, req: SchedRequest):
    """Resource fit + normalized fit score for all nodes.

    Returns (fits (N,) bool, score (N,) f32, exhausted_dim (N,) i32).
    util = current used + ask; fit requires util ≤ totals in all dims
    (AllocsFit, funcs.go:97-160); score per scheduler_algorithm
    (rank.go:166-170, funcs.go:186/213) normalized by 18 (rank.go:513-516).
    """
    util = used + req.ask[None, :]  # (N, 3)
    fits_dim = util <= arrays.totals  # (N, 3)
    fits = jnp.all(fits_dim, axis=1)
    # first exhausted dim index for metrics (0=cpu,1=mem,2=disk, -1 = fits)
    exhausted = jnp.argmax(~fits_dim, axis=1).astype(jnp.int32)
    exhausted = jnp.where(fits, -1, exhausted)

    denom = jnp.maximum(arrays.totals, 1.0)
    free = 1.0 - util / denom  # (N, 3)
    free_cpu, free_mem = free[:, 0], free[:, 1]
    total = jnp.power(10.0, free_cpu) + jnp.power(10.0, free_mem)
    binpack = jnp.clip(20.0 - total, 0.0, 18.0)
    spread = jnp.clip(total - 2.0, 0.0, 18.0)
    score = jnp.where(req.algorithm == 1, spread, binpack) / 18.0
    return fits, score, exhausted


def anti_affinity_score(tg_count, req: SchedRequest):
    """(score (N,), appended (N,)) — JobAntiAffinityIterator (rank.go:560-607).

    ``tg_count`` (N,) i32 = proposed allocs of this job+TG per node."""
    collisions = tg_count.astype(jnp.float32)
    score = -(collisions + 1.0) / req.desired_count
    appended = collisions > 0
    return jnp.where(appended, score, 0.0), appended


def penalty_score(penalty_mask):
    """NodeReschedulingPenaltyIterator (rank.go:630-646)."""
    return jnp.where(penalty_mask, -1.0, 0.0), penalty_mask


def affinity_score(arrays, req: SchedRequest):
    """NodeAffinityIterator (rank.go:698-728): Σ weight·match / Σ|weight|,
    appended only when non-zero."""
    numver = _numver(arrays)
    check = jax.vmap(
        lambda s, o, h, n: _check_predicate(
            arrays.attr_hash, numver, s, o, h, n
        )
    )
    matches = check(req.a_slot, req.a_op, req.a_hash, req.a_num)  # (A, N)
    active = (req.a_slot >= 0)[:, None]  # (A, 1)
    matched = matches & active
    sum_weight = jnp.sum(jnp.abs(req.a_weight) * (req.a_slot >= 0))
    total = jnp.sum(matched * req.a_weight[:, None], axis=0)  # (N,)
    norm = total / jnp.maximum(sum_weight, 1e-9)
    appended = (total != 0.0) & (sum_weight > 0)
    return jnp.where(appended, norm, 0.0), appended


def spread_score(arrays, req: SchedRequest, spread_counts):
    """SpreadIterator (spread.go:110-257).

    ``spread_counts`` (S, V) f32 — usage count per known attribute value
    (existing + proposed allocs of this TG), aligned with req.s_value_hash.
    Returns (score (N,), appended (N,)).
    """

    def one_stanza(slot, weight, even, value_hash, desired, implicit, counts):
        active = slot >= 0
        nvalue = arrays.attr_hash[:, jnp.maximum(slot, 0)]  # (N,)
        node_has = nvalue != 0

        # match node value against the known-values table
        vmatch = (nvalue[:, None] == value_hash[None, :]) & (
            value_hash[None, :] != 0
        )  # (N, V)
        found = jnp.any(vmatch, axis=1)
        # Per-node lookups as masked reductions over the (small) V axis.
        # ``counts[vidx]``-style element gathers lower to scalarized TPU
        # gathers (slice_sizes={1,1,1}) that serialize 5M+ loads and
        # dominated the whole scoring pipeline; vmatch has at most one hit
        # per row, so a masked sum is the same value at VPU speed.
        count_at = jnp.sum(jnp.where(vmatch, counts[None, :], 0.0), axis=1)
        used_count = count_at + 1.0  # +1 = this placement

        # ---- targeted mode (spread.go:134-165)
        desired_ok = ~jnp.isnan(desired)  # (V,)
        has_target = jnp.any(vmatch & desired_ok[None, :], axis=1)
        desired_at = jnp.sum(
            jnp.where(vmatch & desired_ok[None, :], desired[None, :], 0.0),
            axis=1,
        )
        desired_v = jnp.where(has_target, desired_at, jnp.nan)
        use_implicit = ~has_target & ~jnp.isnan(implicit)
        desired_v = jnp.where(use_implicit, implicit, desired_v)
        no_target = jnp.isnan(desired_v)
        rel_weight = weight / jnp.maximum(req.s_sum_weights, 1e-9)
        boost_t = ((desired_v - used_count) / jnp.maximum(desired_v, 1e-9)) * rel_weight
        targeted = jnp.where(no_target, -1.0, boost_t)

        # ---- even mode (spread.go evenSpreadScoreBoost:178-230)
        valid = (value_hash != 0) & (counts > 0)
        any_use = jnp.any(valid)
        big = jnp.float32(1e30)
        mn = jnp.min(jnp.where(valid, counts, big))
        mx = jnp.max(jnp.where(valid, counts, -big))
        current = count_at
        delta_boost = jnp.where(mn == 0, -1.0, (mn - current) / jnp.maximum(mn, 1e-9))
        even_b = jnp.where(
            current != mn,
            delta_boost,
            jnp.where(
                mn == mx,
                -1.0,
                jnp.where(mn == 0, 1.0, (mx - mn) / jnp.maximum(mn, 1e-9)),
            ),
        )
        even_b = jnp.where(any_use, even_b, 0.0)
        even_b = jnp.where(node_has, even_b, -1.0)  # attr unset → max penalty

        score = jnp.where(even, even_b, targeted)
        return jnp.where(active, score, 0.0)

    per_stanza = jax.vmap(one_stanza)(
        req.s_slot,
        req.s_weight,
        req.s_even,
        req.s_value_hash,
        req.s_desired,
        req.s_implicit,
        spread_counts,
    )  # (S, N)
    total = jnp.sum(per_stanza, axis=0)
    has_spread = jnp.any(req.s_slot >= 0)
    appended = (total != 0.0) & has_spread
    return jnp.where(appended, total, 0.0), appended


def preemption_state(arrays, req: SchedRequest):
    """Vectorized preemption candidate math.

    The reference walks per-node alloc lists greedily
    (preemption.go:198-557). Here ``prio_used`` (N, P, 3) holds usage per
    priority bucket; everything strictly below ``preempt_bucket`` is
    evictable, so freeable = Σ lower buckets. netPriority is approximated
    from bucket midpoints.

    The bucket-axis reductions are expressed as *prefix* scans that depend
    only on ``arrays`` — batch-invariant, computed once per dispatch — and
    each eval then reads a single column at its ``preempt_bucket``. The
    previous form re-reduced the full (N, P, 3) tensor per eval, which at
    B=4096 re-read ~8 GB of HBM per dispatch.

    Returns (extra_free (N,3), preempt_score (N,), usable (N,) bool).
    """
    buckets = jnp.arange(PRIORITY_BUCKETS)
    # Shared prefix tables, leading zero column so index k = "buckets < k".
    csum = jnp.cumsum(arrays.prio_used, axis=1)  # (N, P, 3)
    csum = jnp.concatenate(
        [jnp.zeros_like(csum[:, :1]), csum], axis=1
    )  # (N, P+1, 3)
    mid = (buckets.astype(jnp.float32) + 0.5) * (101.0 / PRIORITY_BUCKETS)
    present = jnp.any(arrays.prio_used > 0, axis=2)  # (N, P)
    mid_masked = jnp.where(present, mid[None, :], 0.0)
    mid_max = lax.cummax(mid_masked, axis=1)
    mid_max = jnp.concatenate(
        [jnp.zeros_like(mid_max[:, :1]), mid_max], axis=1
    )  # (N, P+1)
    mid_sum = jnp.cumsum(mid_masked, axis=1)
    mid_sum = jnp.concatenate(
        [jnp.zeros_like(mid_sum[:, :1]), mid_sum], axis=1
    )  # (N, P+1)

    # Per-eval: one column each (the only batch-dependent reads).
    k = jnp.clip(req.preempt_bucket, 0, PRIORITY_BUCKETS)
    freeable = csum[:, k]  # (N, 3)
    max_prio = mid_max[:, k]  # (N,)
    sum_prio = mid_sum[:, k]  # (N,)
    net = jnp.where(max_prio > 0, max_prio + sum_prio / jnp.maximum(max_prio, 1e-9), 0.0)
    score = 1.0 / (1.0 + jnp.exp(PREEMPTION_RATE * (net - PREEMPTION_ORIGIN)))

    usable = (req.preempt_bucket >= 0) & jnp.any(freeable > 0, axis=1)
    return freeable, score, usable


class ScoreResult(NamedTuple):
    final: jnp.ndarray  # (N,) f32, NEG_INF where infeasible
    feasible: jnp.ndarray  # (N,) bool (constraints, pre-resource)
    fits: jnp.ndarray  # (N,) bool (resources, incl. preemption assist)
    needs_preempt: jnp.ndarray  # (N,) bool
    binpack: jnp.ndarray  # (N,) f32
    exhausted_dim: jnp.ndarray  # (N,) i32


def score_nodes(
    arrays,
    used,
    tg_count,
    spread_counts,
    penalty_mask,
    req: SchedRequest,
    class_elig,
    host_mask,
) -> ScoreResult:
    """The full ranking pipeline as one fused program (GenericStack.Select,
    stack.go:117-179, minus the sampling the TPU design makes unnecessary)."""
    feas = feasibility_mask(arrays, req, class_elig, host_mask)
    # distinct_hosts: one proposed alloc of this job+TG per node, enforced
    # in-scan via tg_count so multi-placement batches can't stack a node.
    feas &= ~(req.distinct_hosts & (tg_count > 0))
    fits, binpack, exhausted = fit_and_binpack(arrays, used, req)

    # Preemption assist: nodes that don't fit but could after evicting
    # lower-priority work (generic_sched.go:773-792 retry pass).
    extra_free, pre_score, pre_usable = preemption_state(arrays, req)
    util = used + req.ask[None, :]
    fits_with_preempt = jnp.all(util - extra_free <= arrays.totals, axis=1)
    needs_preempt = ~fits & fits_with_preempt & pre_usable
    fits_all = fits | needs_preempt

    aa_score, aa_app = anti_affinity_score(tg_count, req)
    pen_score, pen_app = penalty_score(penalty_mask)
    aff_score, aff_app = affinity_score(arrays, req)
    spr_score, spr_app = spread_score(arrays, req, spread_counts)
    pre_component = jnp.where(needs_preempt, pre_score, 0.0)

    total = binpack + aa_score + pen_score + aff_score + spr_score + pre_component
    count = (
        1.0
        + aa_app.astype(jnp.float32)
        + pen_app.astype(jnp.float32)
        + aff_app.astype(jnp.float32)
        + spr_app.astype(jnp.float32)
        + needs_preempt.astype(jnp.float32)
    )
    final = total / count
    final = jnp.where(feas & fits_all, final, NEG_INF)
    return ScoreResult(
        final=final,
        feasible=feas,
        fits=fits_all,
        needs_preempt=needs_preempt,
        binpack=binpack,
        exhausted_dim=exhausted,
    )


# ---------------------------------------------------------------------------
# Batched independent evals (the throughput path)
# ---------------------------------------------------------------------------


class BatchScoreResult(NamedTuple):
    rows: jnp.ndarray  # (B,) i32 argmax node row, -1 = no fit
    scores: jnp.ndarray  # (B,) f32
    binpack: jnp.ndarray  # (B,) f32
    preempted: jnp.ndarray  # (B,) bool
    nodes_evaluated: jnp.ndarray  # (B,) i32
    nodes_filtered: jnp.ndarray  # (B,) i32
    nodes_exhausted: jnp.ndarray  # (B,) i32


def _score_and_pick(arrays, used, tg_count, spread_counts, penalty, req,
                    class_elig, host_mask) -> tuple:
    res = score_nodes(
        arrays, used, tg_count, spread_counts, penalty, req, class_elig,
        host_mask,
    )
    row = jnp.argmax(res.final).astype(jnp.int32)
    ok = res.final[row] > NEG_INF / 2
    return (
        jnp.where(ok, row, -1),
        # Failed placements report 0 score/binpack, matching the placement
        # scan's convention (place_task_group) so consumers can aggregate
        # without re-masking.
        jnp.where(ok, res.final[row], 0.0),
        jnp.where(ok, res.binpack[row], 0.0),
        res.needs_preempt[row] & ok,
        jnp.sum(res.feasible.astype(jnp.int32)),
        # Filtered counts exclude capacity-padding / ineligible rows, like
        # the placement scan's n_filtered.
        jnp.sum((~res.feasible & arrays.eligible).astype(jnp.int32)),
        jnp.sum((res.feasible & ~res.fits).astype(jnp.int32)),
    )


@jax.jit
def score_batch(arrays, used, tg_counts, spread_counts, penalties, reqs,
                class_eligs, host_masks) -> BatchScoreResult:
    """B independent evaluations in ONE dispatch: full ranking over every
    node for each, then per-eval argmax.

    This is where the TPU design earns its keep versus the reference: where
    Nomad bounds *per-eval* work (shuffle + log₂(n) candidates + po2c,
    stack.go:78-91) and scales via optimistic worker concurrency, we score
    all nodes for a whole *batch* of evals as one (B, N) data-parallel
    program. Conflicting picks are caught by the plan applier's re-verify —
    the same optimistic-concurrency contract the reference already relies on
    (plan_apply.go:49-69).

    Batched args lead with a B axis: tg_counts (B,N), spread_counts (B,S,V),
    penalties (B,N), reqs a stacked SchedRequest pytree, class_eligs (B,K),
    host_masks (B,N). ``arrays`` and ``used`` are shared.
    """
    outs = jax.vmap(
        lambda tg, sc, pen, req, ce, hm: _score_and_pick(
            arrays, used, tg, sc, pen, req, ce, hm
        )
    )(tg_counts, spread_counts, penalties, reqs, class_eligs, host_masks)
    return BatchScoreResult(*outs)


# ---------------------------------------------------------------------------
# Placement scan
# ---------------------------------------------------------------------------


class PlacementResult(NamedTuple):
    rows: jnp.ndarray  # (P,) i32 chosen node row, -1 = failed
    scores: jnp.ndarray  # (P,) f32 final score of chosen node
    binpack: jnp.ndarray  # (P,) f32 binpack component
    preempted: jnp.ndarray  # (P,) bool placement requires preemption
    nodes_evaluated: jnp.ndarray  # (P,) i32
    nodes_filtered: jnp.ndarray  # (P,) i32 failed constraints
    nodes_exhausted: jnp.ndarray  # (P,) i32 feasible but resource-exhausted
    used_after: jnp.ndarray  # (N, 3) proposed usage after placements
    tg_count_after: jnp.ndarray  # (N,)


def spread_values_at(arrays, req: SchedRequest, row):
    """Per-stanza attribute hash of node ``row`` ((S,) i32) — split out so
    the node-sharded step can compute it on the winning row's owner shard
    and broadcast (parallel/sharding.py)."""
    return arrays.attr_hash[row, jnp.maximum(req.s_slot, 0)]


def apply_spread_values(spread_counts, req: SchedRequest, nvalues):
    """Bump per-stanza counts for the placed node's attribute values
    (propertyset.go usage tracking). Claims an empty value slot on first
    sight of a new value.  ``nvalues``: (S,) i32 from spread_values_at."""

    def one(slot, value_hash, counts, nvalue):
        match = (value_hash == nvalue) & (nvalue != 0)
        have = jnp.any(match)
        free_slot = jnp.argmax(value_hash == 0)
        idx = jnp.where(have, jnp.argmax(match), free_slot)
        can = (slot >= 0) & (nvalue != 0) & (have | (value_hash[free_slot] == 0))
        new_hash = jnp.where(
            can & ~have, value_hash.at[idx].set(nvalue), value_hash
        )
        new_counts = jnp.where(can, counts.at[idx].add(1.0), counts)
        return new_hash, new_counts

    return jax.vmap(one)(
        req.s_slot, req.s_value_hash, spread_counts, nvalues
    )


def _update_spread_counts(spread_counts, req: SchedRequest, arrays, row):
    """After placing on ``row``, bump the count of that node's attribute
    value per stanza."""
    return apply_spread_values(
        spread_counts, req, spread_values_at(arrays, req, row)
    )


def _place_scan(
    arrays,
    req: SchedRequest,
    used0,
    tg_count,
    spread_counts,
    penalty_mask,
    class_elig,
    host_mask,
    n_placements: int,
) -> PlacementResult:
    """Traceable core of the placement scan (shared by the solo
    ``place_task_group`` jit and the coalesced ``place_batch`` vmap)."""

    def step(carry, _):
        used, tg_cnt, s_hash, s_counts = carry
        req_step = req._replace(s_value_hash=s_hash)
        res = score_nodes(
            arrays, used, tg_cnt, s_counts, penalty_mask, req_step,
            class_elig, host_mask,
        )
        row = jnp.argmax(res.final).astype(jnp.int32)
        ok = res.final[row] > NEG_INF / 2
        row = jnp.where(ok, row, -1)

        n_eval = jnp.sum(res.feasible).astype(jnp.int32)
        n_filtered = jnp.sum(~res.feasible & arrays.eligible).astype(jnp.int32)
        n_exhausted = jnp.sum(res.feasible & ~res.fits).astype(jnp.int32)

        safe_row = jnp.maximum(row, 0)
        used2 = jnp.where(ok, used.at[safe_row].add(req.ask), used)
        tg2 = jnp.where(ok, tg_cnt.at[safe_row].add(1), tg_cnt)
        new_hash, new_counts = _update_spread_counts(s_counts, req_step, arrays, safe_row)
        s_hash2 = jnp.where(ok, new_hash, s_hash)
        s_counts2 = jnp.where(ok, new_counts, s_counts)

        out = (
            row,
            jnp.where(ok, res.final[safe_row], 0.0),
            jnp.where(ok, res.binpack[safe_row], 0.0),
            ok & res.needs_preempt[safe_row],
            n_eval,
            n_filtered,
            n_exhausted,
        )
        return (used2, tg2, s_hash2, s_counts2), out

    init = (used0, tg_count, req.s_value_hash, spread_counts)
    (used_after, tg_after, _, _), outs = lax.scan(
        step, init, None, length=n_placements
    )
    rows, scores, binpack, preempted, n_eval, n_filt, n_exh = outs
    return PlacementResult(
        rows=rows,
        scores=scores,
        binpack=binpack,
        preempted=preempted,
        nodes_evaluated=n_eval,
        nodes_filtered=n_filt,
        nodes_exhausted=n_exh,
        used_after=used_after,
        tg_count_after=tg_after,
    )


@functools.partial(jax.jit, static_argnames=("n_placements",))
def place_task_group(
    arrays,
    req: SchedRequest,
    used0,
    tg_count,
    spread_counts,
    penalty_mask,
    class_elig,
    host_mask,
    n_placements: int,
) -> PlacementResult:
    """Place ``n_placements`` allocs of one TG — the kernel behind
    computePlacements (generic_sched.go:472).

    A lax.scan over placements: each step scores all nodes, takes the argmax
    (replacing Limit/MaxScore sampling, stack.go:78-91), and scatters the
    proposed usage so subsequent placements see it (ProposedAllocs semantics,
    rank.go:41-52).

    ``used0`` (N, 3) is the proposed base usage — the authoritative matrix
    usage already adjusted by the reconciler's planned stops/evictions
    (the reference's ProposedAllocs = existing − plan.NodeUpdate + in-plan,
    scheduler/context.go ProposedAllocs).
    """
    return _place_scan(
        arrays, req, used0, tg_count, spread_counts, penalty_mask,
        class_elig, host_mask, n_placements,
    )


# Columns of place_batch's packed per-request output (one fetch per
# dispatch; each separate device→host fetch costs a tunnel round-trip).
PACKED_ROW = 0
PACKED_SCORE = 1
PACKED_BINPACK = 2
PACKED_PREEMPT = 3
PACKED_EVALUATED = 4
PACKED_FILTERED = 5
PACKED_EXHAUSTED = 6
PACKED_WIDTH = 7


def _place_batch_impl(
    arrays,
    used,
    delta_rows,
    delta_vals,
    tg_counts,
    spread_counts,
    penalties,
    reqs,
    class_eligs,
    host_masks,
    n_placements: int,
) -> jnp.ndarray:
    """B independent placement scans in ONE dispatch — the device side of
    the dispatch coalescer (scheduler/coalescer.py).

    Where the reference scales scheduling by optimistic worker concurrency
    (worker.go:49-53) with each worker walking nodes alone, here concurrent
    workers' selects coalesce into one vmapped scan over the shared matrix;
    conflicting picks stay the plan applier's job (plan_apply.go:49-69).

    Per-request args lead with a B axis. ``delta_rows``/``delta_vals``
    ((B, K) i32 / (B, K, 3) f32, row -1 = padding) carry each request's
    sparse in-flight plan usage deltas — applied to the shared ``used``
    inside the kernel so the host never materializes a dense per-request
    usage matrix.

    Returns a packed (B, n_placements, PACKED_WIDTH) f32 array (row ids and
    counts are exact in f32 up to 2^24) so the host pays ONE fetch.
    """

    def one(drows, dvals, tg, sc, pen, req, ce, hm):
        safe = jnp.maximum(drows, 0)
        add = jnp.where((drows >= 0)[:, None], dvals, 0.0)
        used0 = used.at[safe].add(add)
        res = _place_scan(
            arrays, req, used0, tg, sc, pen, ce, hm, n_placements
        )
        return jnp.stack(
            [
                res.rows.astype(jnp.float32),
                res.scores,
                res.binpack,
                res.preempted.astype(jnp.float32),
                res.nodes_evaluated.astype(jnp.float32),
                res.nodes_filtered.astype(jnp.float32),
                res.nodes_exhausted.astype(jnp.float32),
            ],
            axis=1,
        )  # (P, 7)

    return jax.vmap(one)(
        delta_rows, delta_vals, tg_counts, spread_counts, penalties, reqs,
        class_eligs, host_masks,
    )


place_batch = functools.partial(jax.jit, static_argnames=("n_placements",))(
    _place_batch_impl
)

# The coalescer's entry point: identical computation, but the per-dispatch
# lane operands (deltas, tg/spread counts, penalties, stacked requests,
# class eligibility, host masks — argnums 2..9) are DONATED, so XLA reuses
# their freshly-transferred device buffers as scratch instead of holding
# them live alongside the outputs. ``arrays``/``used`` (argnums 0-1) are
# never donated: they are matrix-resident and shared with other in-flight
# pipelined dispatches. Kept separate from ``place_batch`` because callers
# of the un-donated entry (tests, tools) legitimately reuse their input
# arrays across calls.
place_batch_live = functools.partial(
    jax.jit,
    static_argnames=("n_placements",),
    donate_argnums=tuple(range(2, 10)),
)(_place_batch_impl)


# ---------------------------------------------------------------------------
# Plan-apply verification (AllocsFit re-check at commit time)
# ---------------------------------------------------------------------------


@jax.jit
def verify_plan_fit(arrays, rows, deltas, eligible_required):
    """Vectorized optimistic-concurrency check for the plan applier.

    The reference fans per-node AllocsFit checks out to an EvaluatePool of
    goroutines (plan_apply.go:439-682, plan_apply_pool.go:18). Here the whole
    plan verifies in one kernel against the authoritative matrix: for each
    plan row i, (used + delta ≤ totals) ∧ node still schedulable.

    rows: (K,) i32 node rows (-1 padded); deltas: (K, 3) f32 net usage the
    plan adds to that node; returns (K,) bool per-node verdicts.
    """
    safe = jnp.maximum(rows, 0)
    used = arrays.used[safe] + deltas  # (K, 3)
    fits = jnp.all(used <= arrays.totals[safe], axis=1)
    ok = fits & (~eligible_required | arrays.eligible[safe])
    return jnp.where(rows < 0, True, ok)
