"""Compile a (job, task-group) into dense tensors for the scheduling kernels.

The reference resolves constraints per node per eval via reflection and string
parsing (scheduler/feasible.go:709-1020 ConstraintChecker, resolveTarget
:748). Here, a task group is compiled *once* into fixed-shape arrays — slots
into the node matrix's attribute columns plus op codes — and the kernel
evaluates every node in one pass. Operators that cannot vectorize (regexp,
set_contains, lexical string order) escape to a host-side per-computed-class
check (mirroring the reference's class cache, feasible.go:1029).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

# Compile-time attribute recorder: maps an attribute name to its slot
# (None = unregistered).  The encoder threads one through constraint and
# affinity encoding so computed-class keys record what they depend on.
AttrRecorder = Callable[[str], Optional[int]]

import numpy as np

from ..state.matrix import (
    DEVICE_SLOTS,
    PORT_BITS,
    NodeMatrix,
    numeric_value,
    priority_bucket,
    stable_hash,
    version_value,
)
from ..structs.types import (
    Affinity,
    Constraint,
    Job,
    Op,
    Spread,
    TaskGroup,
    PREEMPTION_PRIORITY_DELTA,
)

# Fixed request widths (shape-stable for jit caching; see SURVEY.md §7
# hard-part e — p99 < 5ms requires avoiding recompilation).
MAX_CONSTRAINTS = 16
MAX_AFFINITIES = 8
MAX_DATACENTERS = 8
MAX_SPREADS = 2
MAX_SPREAD_VALUES = 16
MAX_STATIC_PORTS = 8

# Kernel op codes.
OP_EQ = 0
OP_NEQ = 1
OP_LT = 2
OP_LTE = 3
OP_GT = 4
OP_GTE = 5
OP_IS_SET = 6
OP_IS_NOT_SET = 7
# Version ops compare the attr_ver column (packed major*1e6+minor*1e3+patch),
# never the plain-numeric column — "2.0" is 2.0 as a number but 2000000 as a
# version, and both sides of a comparison must use the same encoding.
OP_VER_EQ = 8
OP_VER_LT = 9
OP_VER_LTE = 10
OP_VER_GT = 11
OP_VER_GTE = 12

_NUMERIC_OPS = {
    Op.LT.value: OP_LT,
    Op.LTE.value: OP_LTE,
    Op.GT.value: OP_GT,
    Op.GTE.value: OP_GTE,
}

_VERSION_RE = re.compile(r"^\s*(>=|<=|>|<|=)?\s*v?(\d+(?:\.\d+){0,2})\s*$")


def pow2_bucket(n: int) -> int:
    """Round a count up to a power of two. Used for every padded shape that
    feeds a jit'd kernel (placement-scan lengths, class-eligibility vectors)
    so the jit cache stays bounded (SURVEY.md §7 hard-part e). The single
    source of truth — stack and parallel batch-building must agree."""
    return 1 << max(0, (n - 1)).bit_length()


class SchedRequest(NamedTuple):
    """Device-side encoding of one task-group placement ask."""

    ask: np.ndarray  # (3,) f32 cpu/mem/disk
    c_slot: np.ndarray  # (C,) i32, -1 = inactive
    c_op: np.ndarray  # (C,) i32
    c_hash: np.ndarray  # (C,) i32
    c_num: np.ndarray  # (C,) f32
    dc_hash: np.ndarray  # (DC,) i32, 0 padded
    dev_ask: np.ndarray  # (D,) i32
    algorithm: np.ndarray  # () i32: 0 binpack, 1 spread
    desired_count: np.ndarray  # () f32 — TG count (anti-affinity denominator)
    a_slot: np.ndarray  # (A,) i32, -1 = inactive
    a_op: np.ndarray  # (A,) i32
    a_hash: np.ndarray  # (A,) i32
    a_num: np.ndarray  # (A,) f32
    a_weight: np.ndarray  # (A,) f32
    s_slot: np.ndarray  # (S,) i32, -1 = inactive
    s_weight: np.ndarray  # (S,) f32
    s_even: np.ndarray  # (S,) bool — even-spread mode
    s_value_hash: np.ndarray  # (S, V) i32 — known values (targets), 0 padded
    s_desired: np.ndarray  # (S, V) f32 — desired count per target value
    s_implicit: np.ndarray  # (S,) f32 — implicit-target desired count (NaN none)
    s_sum_weights: np.ndarray  # () f32
    preempt_bucket: np.ndarray  # () i32 — victims strictly below; -1 disabled
    # () bool — job carries a distinct_hosts constraint: nodes with any
    # proposed alloc of this job+TG (tg_count > 0) are hard-infeasible, so the
    # placement scan cannot stack allocs on one node between host-mask
    # refreshes (DistinctHostsIterator, feasible.go:505).
    distinct_hosts: np.ndarray
    # Port feasibility (NetworkIndex, structs/network.go:35): requested
    # static ports (-1 pad; only ports < PORT_BITS encoded — the rest are
    # host-verified) and the dynamic-port ask count.
    p_static: np.ndarray  # (P,) i32
    p_dyn: np.ndarray  # () i32


class RequestSlab:
    """Preallocated ``(B, …)`` operand slab for batched request encoding.

    The coalescer's old per-dispatch ``tree_map(np.stack)`` allocated ~25
    fresh arrays per launch.  The slab instead writes each lane's
    :class:`SchedRequest` into row ``i`` of persistent ``(B, …)`` buffers
    and hands the SAME request-of-buffers pytree to the kernel every
    dispatch — no per-launch allocation, stable shapes for the jit cache.

    Rows past the live count keep their previous (valid) contents — dead
    lanes are masked by ``lane_mask``/``host_mask``, never decoded into
    results — and the whole slab is broadcast-initialized from the first
    request filled so even a cold slab holds well-formed rows.  Buffers are
    rebuilt only if a field's trailing shape shifts (encoder version
    change)."""

    def __init__(self, lanes: int):
        self.lanes = int(lanes)
        self._bufs: Optional[SchedRequest] = None

    def _build(self, proto: SchedRequest) -> SchedRequest:
        fields = [np.asarray(f) for f in proto]
        bufs = SchedRequest(*[
            np.empty((self.lanes,) + f.shape, f.dtype) for f in fields
        ])
        for buf, f in zip(bufs, fields):
            buf[:] = f  # broadcast: every row starts as a valid request
        return bufs

    def fill(self, i: int, req: SchedRequest) -> None:
        """Write ``req`` into lane row ``i`` (rebuilds on shape drift)."""
        bufs = self._bufs
        if bufs is None or any(
            buf.shape[1:] != np.asarray(f).shape
            for buf, f in zip(bufs, req)
        ):
            bufs = self._bufs = self._build(req)
        for buf, f in zip(bufs, req):
            buf[i] = f

    def batch(self) -> SchedRequest:
        """The full (B, …) stacked request pytree (call after fill)."""
        assert self._bufs is not None, "fill at least one lane first"
        return self._bufs

    def live_view(self, k: int) -> SchedRequest:
        """Zero-copy views of the first ``k`` (live) rows — what occupancy
        measurement (kernels.features_of) should see, not stale tails."""
        assert self._bufs is not None, "fill at least one lane first"
        return SchedRequest(*[buf[:k] for buf in self._bufs])

    def nbytes(self) -> int:
        return sum(b.nbytes for b in self._bufs) if self._bufs else 0


@dataclass
class EscapedConstraint:
    """A constraint the kernel can't evaluate; checked host-side per class
    (or per node for unique attrs)."""

    constraint: Constraint
    unique: bool = False  # targets a node-unique attribute


@dataclass
class CompiledTaskGroup:
    request: SchedRequest
    escaped: List[EscapedConstraint] = field(default_factory=list)
    # Device asks that overflowed the DeviceRegistry — must be checked
    # host-side against node.resources.devices (no silent drop).
    escaped_devices: List[Tuple[str, int]] = field(default_factory=list)
    # True when job.datacenters overflowed MAX_DATACENTERS; the kernel then
    # skips the dc check (sentinel) and the host filters by datacenter.
    dc_escaped: bool = False
    # host-only soft metadata
    spreads: List[Spread] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    drivers: List[str] = field(default_factory=list)
    host_volumes: List[str] = field(default_factory=list)
    # Registered-volume asks (type "csi"): checked host-side against the
    # volume table's claims (stack._host_mask; HostVolumeChecker /
    # CSIVolumeChecker, feasible.go:132,209).
    csi_volumes: List["VolumeRequest"] = field(default_factory=list)
    # Every attr/device slot resolution this compilation made, including
    # failed ones (None = registry exhausted at compile time).  A cache hit
    # is valid iff each resolution still holds — so entries survive registry
    # GROWTH (new nodes registering unrelated attrs), which the old
    # len(slot_of) cache-key term treated as a full invalidation.
    attr_guard: List[Tuple[str, Optional[int]]] = field(default_factory=list)
    dev_guard: List[Tuple[str, Optional[int]]] = field(default_factory=list)


def _resolve_attr_name(target: str) -> Optional[str]:
    """``${attr.foo}`` / ``${node.class}`` / ``${meta.x}`` → attribute name
    (reference: feasible.go resolveTarget:748-790)."""
    if not target:
        return None
    name = target
    if name.startswith("${") and name.endswith("}"):
        name = name[2:-1]
    if name.startswith("attr."):
        name = name[len("attr.") :]
    return name


def _encode_version_operand(r_target: str) -> Optional[Tuple[int, float]]:
    """``>= 1.2.3`` → (op, packed numeric). Multi-clause falls to host."""
    if "," in r_target:
        return None
    m = _VERSION_RE.match(r_target)
    if not m:
        return None
    comparator = m.group(1) or "="
    packed = version_value(m.group(2))
    if math.isnan(packed):
        return None
    op = {
        ">=": OP_VER_GTE,
        "<=": OP_VER_LTE,
        ">": OP_VER_GT,
        "<": OP_VER_LT,
        "=": OP_VER_EQ,
    }[comparator]
    return op, packed


class RequestEncoder:
    """Compiles task groups against a NodeMatrix's registries.

    Compilation results are cached per (job id, version, tg name) — the
    reference re-runs constraint parsing per eval; we pay it once.  Cached
    entries carry slot guards (attr_guard/dev_guard) instead of keying on
    registry size: steady-state evals hit the cache even while node
    registrations keep growing the attr registry.
    """

    def __init__(self, matrix: NodeMatrix):
        self.matrix = matrix
        self._cache: Dict[tuple, CompiledTaskGroup] = {}
        # Cost attribution (ints under the GIL): a miss is a full
        # constraint re-parse, the per-eval host tax the cache exists to
        # avoid.  Surfaced as nomad.kernel.compile_cache{result=...}.
        self.cache_hits = 0
        self.cache_misses = 0

    def compile(
        self,
        job: Job,
        tg: TaskGroup,
        algorithm: str = "binpack",
        preemption_enabled: bool = False,
    ) -> CompiledTaskGroup:
        key = (job.id, job.version, tg.name, algorithm, preemption_enabled)
        hit = self._cache.get(key)
        if hit is not None and self._guard_valid(hit):
            self.cache_hits += 1
            return hit
        self.cache_misses += 1
        compiled = self._compile(job, tg, algorithm, preemption_enabled)
        self._cache[key] = compiled
        return compiled

    def _guard_valid(self, compiled: CompiledTaskGroup) -> bool:
        """True while every slot resolution the compile made still holds
        (registries are append-only, so in practice this only fails across
        a matrix rebuild)."""
        slot_of = self.matrix.attrs.slot_of
        for name, slot in compiled.attr_guard:
            if slot_of.get(name) != slot:
                return False
        for name, slot in compiled.dev_guard:
            if self.matrix.devices.lookup(name) != slot:
                return False
        return True

    def _compile(
        self,
        job: Job,
        tg: TaskGroup,
        algorithm: str,
        preemption_enabled: bool,
    ) -> CompiledTaskGroup:
        attrs = self.matrix.attrs
        attr_guard: List[Tuple[str, Optional[int]]] = []
        dev_guard: List[Tuple[str, Optional[int]]] = []

        def reg_attr(name: str) -> Optional[int]:
            slot = attrs.register(name)
            attr_guard.append((name, slot))
            return slot

        # Constraint set = job + tg + all tasks (reference: stack.go SetJob /
        # feasibility wrapper collects all levels).
        constraints: List[Constraint] = list(job.constraints) + list(tg.constraints)
        drivers: List[str] = []
        for task in tg.tasks:
            constraints.extend(task.constraints)
            if task.driver and task.driver not in drivers:
                drivers.append(task.driver)

        c_slot = np.full((MAX_CONSTRAINTS,), -1, np.int32)
        c_op = np.zeros((MAX_CONSTRAINTS,), np.int32)
        c_hash = np.zeros((MAX_CONSTRAINTS,), np.int32)
        c_num = np.full((MAX_CONSTRAINTS,), np.nan, np.float32)
        escaped: List[EscapedConstraint] = []
        ci = 0

        def emit(slot: int, op: int, h: int = 0, num: float = math.nan) -> bool:
            nonlocal ci
            if ci >= MAX_CONSTRAINTS:
                return False
            c_slot[ci] = slot
            c_op[ci] = op
            c_hash[ci] = h
            c_num[ci] = num
            ci += 1
            return True

        # Driver feasibility = constraint driver.<name> is set & truthy
        # (reference: DriverChecker feasible.go:433; matrix stores "1" only
        # for detected+healthy drivers).
        for drv in drivers:
            slot = reg_attr(f"driver.{drv}")
            if slot is not None:
                emit(slot, OP_EQ, stable_hash("1"))

        for con in constraints:
            if not self._encode_constraint(con, emit, escaped, reg_attr):
                escaped.append(self._escape(con))

        # Datacenter membership (reference: readyNodesInDCs, scheduler/util.go).
        # Jobs with more datacenters than the encoding holds escape to a
        # host-side dc filter; dc_hash[0] == -1 tells the kernel to skip.
        dc_hash = np.zeros((MAX_DATACENTERS,), np.int32)
        dc_escaped = len(job.datacenters) > MAX_DATACENTERS
        if dc_escaped:
            dc_hash[0] = -1
        else:
            for i, dc in enumerate(job.datacenters):
                dc_hash[i] = stable_hash(dc)

        # Devices. Registry overflow escapes to a host-side per-node check.
        dev_ask = np.zeros((DEVICE_SLOTS,), np.int32)
        escaped_devices: List[Tuple[str, int]] = []
        for name, count in tg.combined_devices().items():
            slot = self.matrix.devices.register(name)
            dev_guard.append((name, slot))
            if slot is not None:
                dev_ask[slot] += count
            else:
                escaped_devices.append((name, count))

        # Affinities: job + tg + tasks (reference: rank.go:678-696).
        affinities: List[Affinity] = (
            list(job.affinities)
            + list(tg.affinities)
            + [a for t in tg.tasks for a in t.affinities]
        )
        a_slot = np.full((MAX_AFFINITIES,), -1, np.int32)
        a_op = np.zeros((MAX_AFFINITIES,), np.int32)
        a_hash = np.zeros((MAX_AFFINITIES,), np.int32)
        a_num = np.full((MAX_AFFINITIES,), np.nan, np.float32)
        a_weight = np.zeros((MAX_AFFINITIES,), np.float32)
        ai = 0
        for aff in affinities[:MAX_AFFINITIES]:
            enc = self._encode_predicate(
                aff.l_target, aff.operand, aff.r_target, reg_attr
            )
            if enc is None:
                continue  # non-vectorizable affinity: skipped (soft signal)
            slot, op, h, num = enc
            a_slot[ai], a_op[ai], a_hash[ai], a_num[ai] = slot, op, h, num
            a_weight[ai] = float(aff.weight)
            ai += 1

        # Spreads: job + tg (reference: spread.go computeSpreadInfo).
        spreads: List[Spread] = list(tg.spreads) + list(job.spreads)
        s_slot = np.full((MAX_SPREADS,), -1, np.int32)
        s_weight = np.zeros((MAX_SPREADS,), np.float32)
        s_even = np.zeros((MAX_SPREADS,), bool)
        s_value_hash = np.zeros((MAX_SPREADS, MAX_SPREAD_VALUES), np.int32)
        s_desired = np.full((MAX_SPREADS, MAX_SPREAD_VALUES), np.nan, np.float32)
        s_implicit = np.full((MAX_SPREADS,), np.nan, np.float32)
        sum_weights = 0.0
        total_count = float(tg.count)
        for si, sp in enumerate(spreads[:MAX_SPREADS]):
            name = _resolve_attr_name(sp.attribute)
            slot = reg_attr(name) if name else None
            if slot is None:
                continue
            s_slot[si] = slot
            s_weight[si] = float(sp.weight)
            sum_weights += float(sp.weight)
            if not sp.targets:
                s_even[si] = True
                continue
            sum_desired = 0.0
            for vi, target in enumerate(sp.targets[:MAX_SPREAD_VALUES]):
                desired = (target.percent / 100.0) * total_count
                s_value_hash[si, vi] = stable_hash(target.value)
                s_desired[si, vi] = desired
                sum_desired += desired
            if 0.0 < sum_desired < total_count:
                s_implicit[si] = total_count - sum_desired

        preempt_bucket = -1
        if preemption_enabled:
            # Victims must have priority < job.priority − delta
            # (reference: preemption.go:663).
            threshold = job.priority - PREEMPTION_PRIORITY_DELTA
            if threshold > 0:
                preempt_bucket = priority_bucket(threshold)

        # Port asks across group + task networks (stack._assign_ports is the
        # host-side assignment twin; this is the kernel-side feasibility).
        p_static = np.full((MAX_STATIC_PORTS,), -1, np.int32)
        p_dyn = 0
        pi = 0
        all_nets = list(tg.networks) + [
            n for t in tg.tasks for n in t.resources.networks
        ]
        for net in all_nets:
            p_dyn += len(net.dynamic_ports)
            for port in net.reserved_ports:
                if 0 <= port < PORT_BITS and pi < MAX_STATIC_PORTS:
                    p_static[pi] = port
                    pi += 1
                # overflow / out-of-bitmap ports are verified host-side at
                # assignment and again at plan-apply

        ask = tg.combined_resources()
        req = SchedRequest(
            ask=np.array([ask.cpu, ask.memory_mb, ask.disk_mb], np.float32),
            c_slot=c_slot,
            c_op=c_op,
            c_hash=c_hash,
            c_num=c_num,
            dc_hash=dc_hash,
            dev_ask=dev_ask,
            algorithm=np.int32(1 if algorithm == "spread" else 0),
            desired_count=np.float32(max(1.0, float(tg.count))),
            a_slot=a_slot,
            a_op=a_op,
            a_hash=a_hash,
            a_num=a_num,
            a_weight=a_weight,
            s_slot=s_slot,
            s_weight=s_weight,
            s_even=s_even,
            s_value_hash=s_value_hash,
            s_desired=s_desired,
            s_implicit=s_implicit,
            s_sum_weights=np.float32(sum_weights if sum_weights else 1.0),
            preempt_bucket=np.int32(preempt_bucket),
            distinct_hosts=np.bool_(
                any(c.operand == Op.DISTINCT_HOSTS.value for c in constraints)
            ),
            p_static=p_static,
            p_dyn=np.int32(p_dyn),
        )
        return CompiledTaskGroup(
            request=req,
            escaped=escaped,
            escaped_devices=escaped_devices,
            dc_escaped=dc_escaped,
            spreads=spreads,
            affinities=affinities,
            drivers=drivers,
            host_volumes=[
                v.source or v.name
                for v in (tg.volumes or {}).values() if v.type == "host"
            ],
            csi_volumes=[
                v for v in (tg.volumes or {}).values() if v.type == "csi"
            ],
            attr_guard=attr_guard,
            dev_guard=dev_guard,
        )

    # -- predicate encoding --------------------------------------------------

    def _escape(self, con: Constraint) -> EscapedConstraint:
        name = _resolve_attr_name(con.l_target) or ""
        unique = "unique." in name
        return EscapedConstraint(constraint=con, unique=unique)

    def _encode_constraint(self, con: Constraint, emit, escaped,
                           reg_attr: Optional[AttrRecorder] = None) -> bool:
        if con.operand in (Op.DISTINCT_HOSTS.value, Op.DISTINCT_PROPERTY.value):
            # Handled by dedicated host-side iterators (feasible.go:505,604).
            escaped.append(self._escape(con))
            return True
        enc = self._encode_predicate(
            con.l_target, con.operand, con.r_target, reg_attr
        )
        if enc is None:
            return False
        slot, op, h, num = enc
        return emit(slot, op, h, num)

    def _encode_predicate(
        self, l_target: str, operand: str, r_target: str,
        reg_attr: Optional[AttrRecorder] = None,
    ) -> Optional[Tuple[int, int, int, float]]:
        """Encode one predicate as (slot, op, hash, num); None = escape.
        ``reg_attr`` (compile-time recorder) defaults to the raw registry."""
        name = _resolve_attr_name(l_target)
        if name is None:
            return None
        register = reg_attr or self.matrix.attrs.register
        slot = register(name)
        if slot is None:
            return None  # registry exhausted — host fallback

        if operand in (Op.EQ.value, "==", "is"):
            return slot, OP_EQ, stable_hash(r_target), math.nan
        if operand in (Op.NEQ.value, "not"):
            return slot, OP_NEQ, stable_hash(r_target), math.nan
        if operand == Op.IS_SET.value:
            return slot, OP_IS_SET, 0, math.nan
        if operand == Op.IS_NOT_SET.value:
            return slot, OP_IS_NOT_SET, 0, math.nan
        if operand in _NUMERIC_OPS:
            num = numeric_value(r_target)
            if math.isnan(num):
                return None  # lexical comparison — host fallback
            return slot, _NUMERIC_OPS[operand], 0, num
        if operand in (Op.VERSION.value, Op.SEMVER.value):
            enc = _encode_version_operand(r_target)
            if enc is None:
                return None
            op, packed = enc
            return slot, op, 0, packed
        # regexp / set_contains / others: host fallback
        return None
