"""Vectorized scheduling math (JAX kernels + request encoding)."""

from .encode import (  # noqa: F401
    CompiledTaskGroup,
    EscapedConstraint,
    RequestEncoder,
    SchedRequest,
    MAX_CONSTRAINTS,
    MAX_SPREADS,
    MAX_SPREAD_VALUES,
)
from .kernels import (  # noqa: F401
    FUSED_PACKED_VERIFIED,
    FUSED_PACKED_WIDTH,
    FULL_FEATURES,
    Features,
    NEG_INF,
    PlacementResult,
    ScoreResult,
    feasibility_mask,
    features_of,
    fit_and_binpack,
    fused_place_batch,
    fused_place_batch_live,
    place_batch,
    place_task_group,
    score_nodes,
    verify_plan_fit,
)
