"""Vectorized scheduling math (JAX kernels + request encoding)."""

from .encode import (  # noqa: F401
    CompiledTaskGroup,
    EscapedConstraint,
    RequestEncoder,
    SchedRequest,
    MAX_CONSTRAINTS,
    MAX_SPREADS,
    MAX_SPREAD_VALUES,
)
from .kernels import (  # noqa: F401
    NEG_INF,
    PlacementResult,
    ScoreResult,
    feasibility_mask,
    fit_and_binpack,
    place_task_group,
    score_nodes,
    verify_plan_fit,
)
