"""WAL crash-surface tools: truncate-at-every-offset restore sweeps.

A crash can stop a WAL file at *any* byte offset — not just at line
boundaries.  The durability contract (state/wal.py) is: a truncated
**final** record is discarded (torn final append), every complete prefix
restores, and corruption anywhere earlier raises instead of silently
skipping committed writes.  These helpers materialize every truncation
point of a real data dir so tests (and ``tools/chaos_repro.py``) can
drive a restore through each one.
"""

from __future__ import annotations

import os
import shutil
from typing import Iterator, List, Tuple

from ..state.wal import LOG_NAME, SNAPSHOT_NAME


def wal_size(data_dir: str) -> int:
    path = os.path.join(data_dir, LOG_NAME)
    return os.path.getsize(path) if os.path.exists(path) else 0


def truncation_offsets(data_dir: str, stride: int = 1) -> List[int]:
    """Every offset the log can be cut at (0..size), optionally strided
    for cheap tier-1 sweeps; line boundaries are always included so the
    complete-prefix cases are never skipped."""
    size = wal_size(data_dir)
    offsets = set(range(0, size + 1, max(1, stride)))
    offsets.add(size)
    path = os.path.join(data_dir, LOG_NAME)
    if os.path.exists(path):
        pos = 0
        with open(path, "rb") as fh:
            for line in fh:
                pos += len(line)
                offsets.add(pos)
    return sorted(offsets)


def truncated_copy(data_dir: str, dest_dir: str, offset: int) -> str:
    """Copy ``data_dir`` to ``dest_dir`` with the log cut at ``offset``
    bytes — the disk image a crash at that point would leave behind."""
    os.makedirs(dest_dir, exist_ok=True)
    snap = os.path.join(data_dir, SNAPSHOT_NAME)
    if os.path.exists(snap):
        shutil.copy2(snap, os.path.join(dest_dir, SNAPSHOT_NAME))
    log_src = os.path.join(data_dir, LOG_NAME)
    log_dst = os.path.join(dest_dir, LOG_NAME)
    if os.path.exists(log_src):
        with open(log_src, "rb") as src, open(log_dst, "wb") as dst:
            dst.write(src.read(offset))
    return dest_dir


def complete_entries_at(data_dir: str, offset: int) -> int:
    """How many intact journal lines survive a cut at ``offset`` (the
    oracle a sweep compares restored state against)."""
    path = os.path.join(data_dir, LOG_NAME)
    if not os.path.exists(path):
        return 0
    with open(path, "rb") as fh:
        data = fh.read(offset)
    return data.count(b"\n")


def sweep(
    data_dir: str, scratch_dir: str, stride: int = 1
) -> Iterator[Tuple[int, str]]:
    """Yield ``(offset, truncated_data_dir)`` for every truncation point;
    each yielded dir is a fresh copy the caller may restore from and
    mutate freely."""
    for i, offset in enumerate(truncation_offsets(data_dir, stride=stride)):
        dest = os.path.join(scratch_dir, f"cut-{i:06d}-{offset}")
        yield offset, truncated_copy(data_dir, dest, offset)
