"""Seeded, deterministic fault injection at named cross-component seams.

The production code calls :func:`inject` at every seam a distributed
failure can hit — RPC dispatch (``api/rpc.py``), raft peer streams
(``server/replication.py``), WAL appends (``state/wal.py``), heartbeat
TTL grants (``server/heartbeat.py``), the client heartbeat loop
(``client/client.py``), and task drivers (``client/driver.py``).  With no
injector installed (production, and every non-chaos test) the call is a
module-global ``None`` check — effectively free next to the I/O it
guards.

A chaos scenario installs a :class:`FaultInjector` built from a **seed**
and a declarative schedule of :class:`FaultSpec` entries.  Trigger
decisions are a pure function of ``(seed, seam, hit-number)`` — NOT a
shared RNG stream — so concurrent seams cannot perturb each other's
schedules and a scenario replays identically from its seed (the
discipline FoundationDB/Jepsen-style harnesses use: the fault schedule is
data, the run is a replayable function of it).

Seam catalog (ctx keys each seam passes):

- ``rpc.call``        — path, addr                 (client→server wire)
- ``raft.send``       — path, src, dst             (leader→peer stream)
- ``wal.write``       — op                         (journal append)
- ``heartbeat.ttl``   — node                       (server TTL grant)
- ``client.heartbeat``— node                       (client heartbeat loop)
- ``driver.start`` / ``driver.wait`` / ``driver.stop`` — driver, task
- ``controller.actuate`` — target                  (overload actuation:
  ``error`` = the actuation is lost; the controller stays in its old
  state and re-drives the same target next observatory tick)
- ``broker.shed``     — enabled                    (shed toggle lost)
- ``blocked.unblock`` — cls                        (capacity wakeup
  lost: blocked evals stay parked until the next capacity event)
- ``admission.gate``  — namespace                  (``error`` = spurious
  429: a submission with bucket capacity is rejected anyway —
  exercises the client's Retry-After path)
- ``device.wedge``    — lanes                      (device→host fetch
  never returns: the resolver's watchdog abandons it, the breaker
  trips, lanes fail with ``DeviceWedgedError``)
- ``device.slow``     — lanes                      (fetch returns past
  the deadline but inside the wedge bound — late but usable; feeds
  the breaker's slow-ratio trip)
- ``shard.loss``      — shards, lanes              (a whole matrix home
  shard dies mid-dispatch; ``lost`` evacuates it — survivors
  re-lay-out, in-flight tickets invalidate via the remap window)
- ``shard.partition`` — shards, lanes              (``dark`` marks one
  home shard's nodes ineligible mid-dispatch — healable partition,
  distinct from the permanent ``shard.loss`` evacuation)

Fault kinds each seam understands (others are ignored there):

- ``delay``   — handled centrally: sleep ``duration`` seconds, proceed
- ``drop``    — the seam raises its transport error (request lost;
  at ``raft.send`` this is also the partition primitive — match on
  src/dst to cut specific links, and sustained drops force elections)
- ``dup``     — the seam performs the operation twice (retry storms)
- ``error``   — the seam raises its domain error (5xx analog)
- ``torn``    — ``wal.write`` persists a prefix of the record then fails
- ``fsync_error`` — ``wal.write`` persists the record but reports failure
- ``skew``    — ``heartbeat.ttl`` scales the granted TTL by ``duration``
  (clock-skew analog: the client believes a TTL the server won't honor)
- ``skip``    — ``client.heartbeat`` silently misses a beat; at
  ``driver.stop`` the stop request is swallowed
- ``hang``    — driver seams block ``duration`` seconds (wedged syscall)
- ``wedge``   — ``driver.wait`` reports "still running" forever; at
  ``device.wedge`` the device→host fetch blocks past every watchdog
  bound (``duration`` caps the synthetic hold when > 0)
- ``slow``    — ``device.slow`` holds the fetch into the slow band
  (past the deadline, inside the wedge bound)
- ``lost``    — ``shard.loss`` kills a matrix home shard; the
  coalescer evacuates it across the survivors
- ``dark``    — ``shard.partition`` marks a home shard's nodes
  ineligible (authoritative-state partition, healable)
- ``exit127`` — ``driver.start`` runs a command that exits 127
  (missing-binary analog)
"""

from __future__ import annotations

import fnmatch
import hashlib
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional


@dataclass
class FaultSpec:
    """One declarative fault: where, what, when.

    ``seam`` is an exact name or fnmatch pattern (``raft.*``).  ``match``
    filters on seam ctx by string equality (e.g. ``{"dst": addr}``).
    Trigger: ``at_step`` fires on exactly the Nth matching hit (1-based);
    otherwise ``p`` is the per-hit probability (decided deterministically
    from the injector seed), considered only after ``after_step`` hits.
    ``count`` caps total fires; ``duration`` parameterizes delay/hang/skew.
    """

    seam: str
    kind: str
    p: float = 1.0
    at_step: Optional[int] = None
    after_step: int = 0
    duration: float = 0.0
    count: Optional[int] = None
    match: Dict[str, str] = field(default_factory=dict)


@dataclass(frozen=True)
class FiredFault:
    """One log record: enough to compare schedules across replays."""

    seam: str
    kind: str
    step: int


class FaultInjector:
    """Holds the schedule, the per-seam hit counters, and the fire log."""

    def __init__(self, seed: int, schedule: List[FaultSpec]):
        self.seed = seed
        self.schedule = list(schedule)
        self._hits: Dict[str, int] = {}
        self._fires: Dict[int, int] = {}
        self.log: List[FiredFault] = []
        self._lock = threading.Lock()

    # -- deterministic per-(seam, hit) coin ----------------------------

    def _coin(self, seam: str, hit: int) -> float:
        h = hashlib.sha256(f"{self.seed}:{seam}:{hit}".encode()).digest()
        return int.from_bytes(h[:8], "big") / 2.0**64

    # -- the hot path --------------------------------------------------

    def fire(self, seam: str, **ctx: Any) -> Optional[FaultSpec]:
        """Record a hit on ``seam``; return the first matching spec that
        triggers (or None).  First-match-wins keeps schedules readable:
        order specs most-specific first."""
        with self._lock:
            hit = self._hits.get(seam, 0) + 1
            self._hits[seam] = hit
            for spec in self.schedule:
                if not _seam_matches(spec.seam, seam):
                    continue
                if any(
                    str(ctx.get(k)) != str(v) for k, v in spec.match.items()
                ):
                    continue
                fired = self._fires.get(id(spec), 0)
                if spec.count is not None and fired >= spec.count:
                    continue
                if spec.at_step is not None:
                    if hit != spec.at_step:
                        continue
                else:
                    if hit <= spec.after_step:
                        continue
                    if spec.p < 1.0 and self._coin(seam, hit) >= spec.p:
                        continue
                self._fires[id(spec)] = fired + 1
                self.log.append(FiredFault(seam=seam, kind=spec.kind, step=hit))
                return spec
        return None

    def hits(self, seam: str) -> int:
        with self._lock:
            return self._hits.get(seam, 0)


def _seam_matches(pattern: str, seam: str) -> bool:
    return pattern == seam or fnmatch.fnmatchcase(seam, pattern)


# ----------------------------------------------------------------------
# Global installation — the production seams consult this.
# ----------------------------------------------------------------------

_ACTIVE: Optional[FaultInjector] = None


def install(injector: FaultInjector) -> None:
    global _ACTIVE
    _ACTIVE = injector


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[FaultInjector]:
    return _ACTIVE


@contextmanager
def injected(
    seed: int, schedule: List[FaultSpec]
) -> Iterator[FaultInjector]:
    """Scoped install (the only way tests should enable chaos — an
    injector leaking across tests would poison the whole suite)."""
    inj = FaultInjector(seed, schedule)
    install(inj)
    try:
        yield inj
    finally:
        uninstall()


def inject(seam: str, **ctx: Any) -> Optional[FaultSpec]:
    """The production-seam entry point.  ``delay`` faults are absorbed
    here (sleep, return None); every other kind is returned for the seam
    to interpret, so each seam only handles the kinds that make sense for
    it."""
    inj = _ACTIVE
    if inj is None:
        return None
    spec = inj.fire(seam, **ctx)
    if spec is None:
        return None
    if spec.kind == "delay":
        time.sleep(spec.duration)
        return None
    return spec
