"""Cluster invariant checker — the post-scenario safety oracle.

After any chaos scenario (or at any quiescent point), these checks scan
authoritative state for the properties the control plane promises to
hold *whatever failed*:

1. **Replacement coverage** — no live ``run`` alloc sits on a down or
   draining node without the control plane having reacted (a node-
   triggered eval at/after the transition, or a replacement alloc).
   Reference: ``createNodeEvals``, node_endpoint.go:1145.
2. **Capacity** — ``AllocsFit`` holds on every node: the non-terminal
   allocs placed there never exceed comparable resources (funcs.go:97).
3. **Volume safety** — a ``single-node-writer`` volume has at most one
   live writer claim (csi_endpoint.go claim discipline).
4. **Broker hygiene** — no leaked outstanding evals: once workers are
   idle, nothing stays checked out of the eval broker forever
   (eval_broker.go unack/nack lease discipline).
5. **Convergence** — after a heal, every live server's FSM image is
   byte-identical (the raft state-machine safety property, §5.4.3).

Each check returns human-readable violation strings; an empty list means
the invariant holds.  ``check_store`` composes 1-4 for one server;
``check_convergence`` compares a set of servers.
"""

from __future__ import annotations

import json
from typing import Iterable, List, Optional

from ..structs.funcs import allocs_fit
from ..structs.types import AllocDesiredStatus, NodeStatus


def check_replacement_coverage(store) -> List[str]:
    """Invariant 1: every live alloc on a down/drained node has a
    replacement eval (node-update/node-drain at or after the node's
    transition index) or a successor alloc pointing at it."""
    violations: List[str] = []
    with store._lock:
        allocs = list(store.allocs.values())
        successors = {
            a.previous_allocation for a in allocs if a.previous_allocation
        }
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            if alloc.desired_status != AllocDesiredStatus.RUN.value:
                continue  # already told to stop — the reaction happened
            node = store.nodes.get(alloc.node_id)
            gone = node is None
            down = not gone and node.status == NodeStatus.DOWN.value
            draining = not gone and bool(node.drain)
            if not (gone or down or draining):
                continue
            if alloc.id in successors:
                continue
            node_index = node.modify_index if node is not None else 0
            reacted = any(
                ev.triggered_by in ("node-update", "node-drain")
                and ev.modify_index >= node_index
                for ev in store.evals_by_job(alloc.namespace, alloc.job_id)
            )
            if not reacted:
                violations.append(
                    f"alloc {alloc.id[:8]} (job {alloc.job_id}) lives on "
                    f"{'missing' if gone else node.status} node "
                    f"{alloc.node_id[:8]} with no replacement eval"
                )
    return violations


def check_allocs_fit(store) -> List[str]:
    """Invariant 2: no node is over-committed."""
    violations: List[str] = []
    with store._lock:
        node_ids = list(store.nodes)
    for nid in node_ids:
        node = store.node_by_id(nid)
        if node is None:
            continue
        fit, dim, used = allocs_fit(node, store.allocs_by_node(nid))
        if not fit:
            violations.append(
                f"node {nid[:8]} over-committed on {dim} "
                f"(used cpu={used.cpu} mem={used.memory_mb} "
                f"disk={used.disk_mb})"
            )
    return violations


def check_volume_writers(store) -> List[str]:
    """Invariant 3: ≤1 live writer on every single-node-writer volume."""
    violations: List[str] = []
    with store._lock:
        volumes = list(store.volumes.values())
        for vol in volumes:
            if vol.access_mode != "single-node-writer":
                continue
            live = [
                aid for aid in vol.write_claims
                if (a := store.allocs.get(aid)) is not None
                and not a.terminal_status()
            ]
            if len(live) > 1:
                violations.append(
                    f"volume {vol.namespace}/{vol.id} "
                    f"(single-node-writer) has {len(live)} live writers: "
                    f"{[i[:8] for i in live]}"
                )
    return violations


def check_broker(server, settle: float = 5.0) -> List[str]:
    """Invariant 4: no eval STAYS checked out of the broker.  One sample
    cannot distinguish busy from wedged — background work (e.g. a node
    TTL expiring mid-sweep) hands workers legitimate leases at any
    moment.  A lease violates only if the SAME eval remains unacked for
    the whole settle window; the nack sweeper reclaims a dead worker's
    lease well inside it, so a survivor is a leak."""
    import time as _time

    broker = getattr(server, "eval_broker", None)
    if broker is None or not broker.enabled:
        return []
    stuck = set(broker.unacked_ids())
    deadline = _time.time() + settle
    while stuck and _time.time() < deadline:
        _time.sleep(0.1)
        stuck &= set(broker.unacked_ids())
    if stuck:
        ids = ", ".join(sorted(stuck)[:4])
        return [
            f"eval broker holds {len(stuck)} stuck unacked eval(s): {ids}"
        ]
    return []


def check_store(server) -> List[str]:
    """Invariants 1-4 against one server's authoritative state."""
    store = server.store
    return (
        check_replacement_coverage(store)
        + check_allocs_fit(store)
        + check_volume_writers(store)
        + check_broker(server)
    )


def _fsm_image(store) -> str:
    """Canonical JSON of the full FSM image (what a snapshot would
    persist), for cross-server comparison.  Table lists are sorted by
    their serialized form: insertion order can legitimately differ
    between a follower that replayed the log and one that installed a
    snapshot, and order is not part of the FSM contract."""
    wire = store.to_snapshot_wire()
    wire.pop("wal_seq", None)
    canon = {}
    for key, val in wire.items():
        if isinstance(val, list):
            canon[key] = sorted(
                json.dumps(item, sort_keys=True) for item in val
            )
        else:
            canon[key] = val
    return json.dumps(canon, sort_keys=True)


def check_convergence(servers: Iterable) -> List[str]:
    """Invariant 5: all live servers hold identical FSM images (compare
    after heal + quiescence — a mid-replication snapshot legitimately
    lags)."""
    servers = list(servers)
    if len(servers) < 2:
        return []
    violations: List[str] = []
    indexes = [s.store.latest_index for s in servers]
    if len(set(indexes)) > 1:
        violations.append(f"store indexes diverge: {indexes}")
    images = [_fsm_image(s.store) for s in servers]
    if len(set(images)) > 1:
        for i, img in enumerate(images[1:], start=1):
            if img != images[0]:
                violations.append(
                    f"server[{i}] FSM image differs from server[0] "
                    f"(indexes {indexes[i]} vs {indexes[0]})"
                )
    return violations


def wait_converged(
    servers: Iterable, timeout: float = 15.0, poll: float = 0.1
) -> List[str]:
    """Poll until convergence holds or the deadline passes; returns the
    final violation list (empty = converged)."""
    import time

    servers = list(servers)
    deadline = time.monotonic() + timeout
    violations = check_convergence(servers)
    while violations and time.monotonic() < deadline:
        time.sleep(poll)
        violations = check_convergence(servers)
    return violations


def check_cluster(
    servers: Iterable, leader: Optional[object] = None
) -> List[str]:
    """Full post-scenario sweep: convergence across ``servers`` plus
    invariants 1-4 on the leader (or the first server when in-process)."""
    servers = list(servers)
    violations = check_convergence(servers)
    subject = leader if leader is not None else (servers[0] if servers else None)
    if subject is not None:
        violations += check_store(subject)
    if violations:
        # Post-mortem: persist the flight recorder next to the chaos seed
        # so the violated run's span timeline survives the process.
        from .. import trace

        path = trace.auto_dump(
            "invariant", extra={"violations": violations[:20]}
        )
        if path:
            violations = violations + [f"flight record dumped: {path}"]
    return violations
