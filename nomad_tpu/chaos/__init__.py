"""Deterministic fault injection + cluster invariant checking.

See CHAOS.md for the operator/test-author guide: the seam catalog, fault
kinds, and how to write and replay a scenario from its seed.
"""

from .injector import (  # noqa: F401
    FaultInjector,
    FaultSpec,
    FiredFault,
    active,
    inject,
    injected,
    install,
    uninstall,
)
from .invariants import (  # noqa: F401
    check_allocs_fit,
    check_broker,
    check_cluster,
    check_convergence,
    check_replacement_coverage,
    check_store,
    check_volume_writers,
    wait_converged,
)
