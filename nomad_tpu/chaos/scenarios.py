"""Seeded chaos scenarios — reusable by tests and ``tools/chaos_repro.py``.

Each scenario is a function ``(seed, workdir, **knobs) -> report dict``:

```
{"name": ..., "seed": ..., "faults": [(seam, kind, step), ...],
 "violations": [...], ...extra per-scenario facts}
```

An empty ``violations`` list means every invariant
(:mod:`nomad_tpu.chaos.invariants`) held.  Scenarios never assert —
callers (tests, the repro tool) decide how to react, so a violating run
can still be inspected.

Replayability: fault *decisions* are a pure function of
``(seed, seam, hit-number)`` (see injector.py).  Scenarios built from
``at_step``/``count`` triggers reproduce the identical fired-fault
schedule run-to-run; probabilistic (``p``) triggers reproduce the same
decision table, with the fired subset following the seam's actual hit
count (thread timing can shift how many hits occur before quiescence).
"""

from __future__ import annotations

import os
import socket
import time
from typing import Callable, Dict, List

from .injector import FaultSpec, injected
from .invariants import check_store, wait_converged
from .wal_tools import complete_entries_at, sweep


def _wait(pred, timeout: float = 30.0, every: float = 0.05) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


def _free_ports(n: int) -> List[int]:
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _http_cluster(n: int = 3):
    """Spin an n-server HTTP control plane (the test_replication idiom)."""
    from ..api.agent import Agent, AgentConfig
    from ..server import ServerConfig

    ports = _free_ports(n)
    addrs = [f"http://127.0.0.1:{p}" for p in ports]
    agents = []
    for i in range(n):
        agents.append(Agent(AgentConfig(
            name=f"server-{i}",
            server_enabled=True,
            client_enabled=False,
            http_host="127.0.0.1",
            http_port=ports[i],
            server_config=ServerConfig(
                num_workers=2,
                heartbeat_min_ttl=60,
                heartbeat_max_ttl=90,
                server_id=f"server-{i}",
                peers=list(addrs),
                election_timeout=(0.15, 0.3),
                raft_heartbeat_interval=0.05,
            ),
        )))
    for a in agents:
        a.start()
    return agents, addrs


def _leader(agents):
    leaders = [
        a for a in agents
        if a.server is not None and a.server.replicator is not None
        and a.server.replicator.is_leader
    ]
    return leaders[0] if len(leaders) == 1 else None


def _small_job(i: int = 0):
    from .. import mock

    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 2
    for t in tg.tasks:
        t.resources.cpu = 20 + 5 * (i % 4)
        t.resources.memory_mb = 32
        t.config = {"run_for": 0}
    tg.ephemeral_disk.size_mb = 10
    return job


def _evals_settled(server) -> bool:
    """Quiescence: nothing ready/pending/checked-out in the broker.
    ``ready_count`` matters: right after a submit burst the evals sit
    *ready* (not yet dequeued), so pending+unacked alone reads settled
    during the window before any worker picks them up."""
    broker = server.eval_broker
    return (
        broker.ready_count() == 0
        and broker.pending_count() == 0
        and broker.unacked_count() == 0
    )


def _fault_rows(inj) -> List[tuple]:
    return [(f.seam, f.kind, f.step) for f in inj.log]


# ----------------------------------------------------------------------
# Scenario 1: leader killed while plans/entries are in flight
# ----------------------------------------------------------------------

def leader_kill_mid_apply(seed: int, workdir: str) -> Dict:
    """Delay the leader's peer streams (widening the mid-replication
    window), kill the leader while entries are in flight, and require the
    survivors to elect, finish the work, and converge byte-identically."""
    from .. import mock

    schedule = [
        FaultSpec("raft.send", "delay", p=0.4, duration=0.05),
    ]
    report: Dict = {"name": "leader_kill_mid_apply", "seed": seed}
    with injected(seed, schedule) as inj:
        agents, addrs = _http_cluster(3)
        try:
            assert _wait(lambda: _leader(agents) is not None, timeout=20)
            leader = _leader(agents)
            for i in range(2):
                leader.server.register_node(mock.node())
            evs = [leader.server.submit_job(_small_job(i)) for i in range(3)]
            # Kill the leader with the tail of those submissions still
            # streaming to peers (the injected delays hold the window
            # open) — no drain, no goodbye.
            leader.shutdown()
            survivors = [a for a in agents if a is not leader]
            assert _wait(
                lambda: _leader(survivors) is not None, timeout=30
            ), "survivors failed to elect"
            new_leader = _leader(survivors)
            # The new leader must still serve writes.
            post_ev = new_leader.server.submit_job(_small_job(9))
            assert _wait(
                lambda: _evals_settled(new_leader.server), timeout=30
            )
            report["pre_kill_evals"] = [e.id for e in evs if e is not None]
            report["post_kill_eval"] = post_ev.id if post_ev else None
            servers = [a.server for a in survivors]
            violations = wait_converged(servers, timeout=20)
            violations += check_store(new_leader.server)
            report["violations"] = violations
        finally:
            for a in agents:
                try:
                    a.shutdown()
                except Exception:  # noqa: BLE001
                    pass
        report["faults"] = _fault_rows(inj)
    return report


# ----------------------------------------------------------------------
# Scenario 2: WAL truncated at every offset, restore must hold
# ----------------------------------------------------------------------

def wal_truncation_sweep(
    seed: int, workdir: str, stride: int = 0
) -> Dict:
    """Build real server state, then restore from a copy of its data dir
    cut at every byte offset (strided).  Every cut must restore without
    error (torn final record dropped), applied entries must grow
    monotonically with the offset, and invariants must hold at each cut."""
    from .. import mock
    from ..server import Server, ServerConfig

    import shutil

    live_dir = os.path.join(workdir, "wal-live")
    srv = Server(ServerConfig(
        num_workers=1, heartbeat_min_ttl=600, heartbeat_max_ttl=900,
        data_dir=live_dir, snapshot_every=10_000,
    ))
    srv.start()
    try:
        for _ in range(2):
            srv.register_node(mock.node())
        for i in range(3):
            ev = srv.submit_job(_small_job(i))
            if ev is not None:
                srv.wait_for_eval(ev.id, timeout=60)
        # Capture the CRASH-STOP disk image now: the WAL flushes after
        # every append, and a clean shutdown would compact the whole log
        # into a snapshot, leaving no append surface to cut.
        data_dir = os.path.join(workdir, "wal-src")
        shutil.copytree(live_dir, data_dir)
    finally:
        srv.shutdown()

    # Strides are seeded so different seeds probe different offset
    # phases; stride=1 (tools/chaos_repro.py --stride 1) is exhaustive.
    if stride <= 0:
        stride = 61 + (seed % 13)
    report: Dict = {
        "name": "wal_truncation_sweep", "seed": seed, "stride": stride,
        "faults": [], "cuts": 0,
    }
    violations: List[str] = []
    prev_entries = -1
    prev_index = -1
    scratch = os.path.join(workdir, "wal-cuts")
    for offset, cut_dir in sweep(data_dir, scratch, stride=stride):
        entries = complete_entries_at(data_dir, offset)
        try:
            restored = Server(ServerConfig(
                num_workers=1, heartbeat_min_ttl=600,
                heartbeat_max_ttl=900, data_dir=cut_dir,
            ))
        except Exception as exc:  # noqa: BLE001
            violations.append(f"offset {offset}: restore raised {exc!r}")
            continue
        report["cuts"] += 1
        if entries < prev_entries:
            violations.append(
                f"offset {offset}: complete entries went backwards"
            )
        idx = restored.store.latest_index
        if entries >= prev_entries and idx < prev_index:
            violations.append(
                f"offset {offset}: latest_index regressed "
                f"{prev_index} -> {idx}"
            )
        prev_entries, prev_index = entries, idx
        for v in check_store(restored):
            violations.append(f"offset {offset}: {v}")
        if restored.store.wal is not None:
            restored.store.wal.close()
    report["violations"] = violations
    return report


# ----------------------------------------------------------------------
# Scenario 3: partition a follower, write through it, heal, converge
# ----------------------------------------------------------------------

def partition_then_heal(seed: int, workdir: str) -> Dict:
    """Cut the leader→follower link for a deterministic number of sends
    (count-based: the fired schedule is identical run-to-run), keep
    writing through the partition, then let the link heal and require all
    three FSM images to converge."""
    from .. import mock

    drops = 12 + (seed % 8)
    report: Dict = {
        "name": "partition_then_heal", "seed": seed, "drops": drops,
    }
    agents, addrs = _http_cluster(3)
    try:
        assert _wait(lambda: _leader(agents) is not None, timeout=20)
        leader = _leader(agents)
        victim = next(a for a in agents if a is not leader)
        schedule = [FaultSpec(
            "raft.send", "drop", match={"dst": victim.rpc_addr},
            count=drops,
        )]
        with injected(seed, schedule) as inj:
            leader.server.register_node(mock.node())
            for i in range(3):
                leader.server.submit_job(_small_job(i))
            # Hold the partition open until the budgeted drops are spent
            # (the heal is part of the schedule, not test timing).
            assert _wait(
                lambda: sum(
                    1 for f in inj.log if f.kind == "drop"
                ) >= drops,
                timeout=30,
            ), "partition never exhausted its drop budget"
            report["faults"] = _fault_rows(inj)
        assert _wait(lambda: _evals_settled(leader.server), timeout=30)
        violations = wait_converged(
            [a.server for a in agents], timeout=20
        )
        violations += check_store(leader.server)
        report["violations"] = violations
    finally:
        for a in agents:
            try:
                a.shutdown()
            except Exception:  # noqa: BLE001
                pass
    return report


# ----------------------------------------------------------------------
# Scenario 4: drain a node whose driver is wedged
# ----------------------------------------------------------------------

def wedged_driver_during_drain(seed: int, workdir: str) -> Dict:
    """Drain a node whose driver swallows stop requests and never reports
    task exit.  The kill path must time out past the wedge, the drain must
    complete, and the job must end up whole on the other node."""
    from .. import mock
    from ..client import Client, ClientConfig
    from ..server import Server, ServerConfig
    from ..structs.types import AllocClientStatus, DrainStrategy

    report: Dict = {"name": "wedged_driver_during_drain", "seed": seed}
    srv = Server(ServerConfig(
        num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90,
    ))
    srv.start()
    clients = []
    try:
        for name in ("c1", "c2"):
            c = Client(srv, ClientConfig(
                data_dir=os.path.join(workdir, name),
            ))
            c.start()
            clients.append(c)
        job = _small_job()
        tg = job.task_groups[0]
        tg.count = 2
        for t in tg.tasks:
            t.config = {}  # run until stopped
            t.kill_timeout = 0.3
        ev = srv.submit_job(job)
        srv.wait_for_eval(ev.id, timeout=60)

        def running():
            return [
                a for a in srv.store.allocs_by_job(job.namespace, job.id)
                if a.client_status == AllocClientStatus.RUNNING.value
                and not a.terminal_status()
            ]

        assert _wait(lambda: len(running()) == 2, timeout=30)
        target = clients[0].node.id
        schedule = [
            FaultSpec("driver.stop", "skip"),
            FaultSpec("driver.wait", "wedge", after_step=1),
        ]
        with injected(seed, schedule) as inj:
            srv.update_node_drain(
                target,
                DrainStrategy(
                    deadline=60.0, force_deadline=time.time() + 60.0
                ),
            )
            srv.drainer.notify()
            assert _wait(lambda: not [
                a for a in srv.store.allocs_by_node(target)
                if not a.terminal_status()
            ], timeout=60), "drain never finished past the wedged driver"
            assert _wait(
                lambda: len(set(a.node_id for a in running())) == 1
                and len(running()) == 2,
                timeout=60,
            ), "job did not recover at full count off the drained node"
            report["faults"] = _fault_rows(inj)
        assert _wait(lambda: _evals_settled(srv), timeout=30)
        report["violations"] = check_store(srv)
    finally:
        for c in clients:
            try:
                c.shutdown()
            except Exception:  # noqa: BLE001
                pass
        srv.shutdown()
    return report


# ----------------------------------------------------------------------
# Overload scenarios (ISSUE 16): the control loop under chaos
# ----------------------------------------------------------------------

def _overload_tuned_config():
    """Controller thresholds scaled down so a test-sized flash crowd
    (hundreds of evals, one worker) crosses them within a couple of
    observatory ticks — same state machine, compressed constants."""
    from ..obs import OverloadConfig

    return OverloadConfig(
        gate_enter=0.03, gate_exit=0.012,
        shed_enter=0.05, shed_exit=0.025,
        window_fast=0.6, window_slow=3.0,
        min_dwell=0.4, cooldown=0.2,
        max_flips=8, flip_window=20.0,
        shed_priority_floor=50, shed_delay=0.3, shed_jitter=0.5,
        retry_after=0.5,
    )


def _overload_cluster(n: int = 3):
    """3-server raft control plane tuned for overload scenarios: one
    worker (so a crowd actually builds backlog), a fast observatory
    tick, compressed controller thresholds, and a small admission
    bucket the crowd can empty."""
    from ..api.agent import Agent, AgentConfig
    from ..server import ServerConfig

    ports = _free_ports(n)
    addrs = [f"http://127.0.0.1:{p}" for p in ports]
    agents = []
    for i in range(n):
        agents.append(Agent(AgentConfig(
            name=f"server-{i}",
            server_enabled=True,
            client_enabled=False,
            http_host="127.0.0.1",
            http_port=ports[i],
            server_config=ServerConfig(
                num_workers=1,
                heartbeat_min_ttl=60,
                heartbeat_max_ttl=90,
                server_id=f"server-{i}",
                peers=list(addrs),
                # Roomier than the replication tests: overload runs keep
                # the GIL busy scheduling, and a spurious election mid-
                # crowd would make the goodput numbers lie.
                election_timeout=(0.5, 1.0),
                raft_heartbeat_interval=0.15,
                slo_interval=0.15,
                overload_config=_overload_tuned_config(),
                admission_rate=50.0,
                admission_burst=50.0,
            ),
        )))
    for a in agents:
        a.start()
    return agents, addrs


def _drain_rate(server, n_evals: int, timeout: float = 60.0):
    """Submit-side throughput: wait for the broker to drain and return
    (evals/s over the drain, drained_ok)."""
    start = time.time()
    ok = _wait(lambda: _evals_settled(server), timeout=timeout)
    elapsed = max(time.time() - start, 1e-6)
    return n_evals / elapsed, ok


def _submit_crowd(server, count: int, offset: int = 0,
                  low_priority_every: int = 2):
    """Blast ``count`` registrations as fast as the gate allows; every
    ``low_priority_every``-th job is priority-10 batch work (shed bait —
    the default floor only defers priority < 50).  Returns
    (admitted, rejected)."""
    from ..server.admission import RateLimitError

    admitted = rejected = 0
    for i in range(count):
        job = _small_job(offset + i)
        if low_priority_every and i % low_priority_every == 0:
            job.priority = 10
        try:
            server.submit_job(job)
            admitted += 1
        except RateLimitError:
            rejected += 1
    return admitted, rejected


def flash_crowd_flapping_partition(
    seed: int, workdir: str, crowd: int = 200, second_wave: int = 100
) -> Dict:
    """A flash crowd hits the leader while one leader→follower link
    flaps (probabilistic drops).  The controller must engage shedding
    within its fast pressure window, goodput must not collapse while
    shedding, state flips must stay inside the hysteresis budget, and
    the cluster must return to steady with store invariants intact.

    ``second_wave`` submissions arrive paced *after* engagement — the
    shed path only defers evals enqueued while shedding is on, so the
    continuing-arrivals wave is what exercises it (set 0 to skip)."""
    from .. import mock

    report: Dict = {"name": "flash_crowd_flapping_partition", "seed": seed}
    schedule = [
        # The flapping partition: one link drops ~35% of sends for the
        # whole run.  Leadership holds through the second follower.
        FaultSpec("raft.send", "drop", p=0.35, match={"dst": "@victim"}),
    ]
    agents = []
    try:
        agents, addrs = _overload_cluster(3)
        assert _wait(lambda: _leader(agents) is not None, timeout=20)
        leader = _leader(agents)
        victim = next(a for a in agents if a is not leader)
        schedule[0].match = {"dst": victim.rpc_addr}
        for _ in range(2):
            leader.server.register_node(mock.node())
        srv = leader.server
        ctrl = srv.overload_controller

        # -- warm-up (first-eval JIT compile must not skew rates) ------
        _submit_crowd(srv, 5, low_priority_every=0)
        assert _wait(lambda: _evals_settled(srv), timeout=60)
        # -- pre-overload baseline: a modest burst, fully drained ------
        n_pre, _ = _submit_crowd(srv, 30, offset=10, low_priority_every=0)
        pre_rate, drained = _drain_rate(srv, n_pre, timeout=60)
        assert drained, "baseline burst never drained"
        report["pre_rate"] = round(pre_rate, 1)
        _wait(lambda: ctrl.state == "steady", timeout=20)
        state_pre = ctrl.state

        with injected(seed, schedule) as inj:
            # -- the flash crowd under the flapping link --------------
            crowd_start = time.time()
            admitted, rejected = _submit_crowd(srv, crowd, offset=100)
            engaged = _wait(
                lambda: ctrl.state != "steady", timeout=10
            )
            t_engage = time.time() - crowd_start
            state_under_load = ctrl.state
            # -- continuing arrivals while engaged: paced so the gate's
            # throttled refill admits a trickle, and the low-priority
            # half of what lands gets shed-deferred.
            wave2_admitted = wave2_rejected = 0
            for i in range(second_wave):
                a2, r2 = _submit_crowd(
                    srv, 1, offset=1000 + i,
                    low_priority_every=1 if i % 2 == 0 else 0,
                )
                wave2_admitted += a2
                wave2_rejected += r2
                time.sleep(0.02)
            # Goodput over the whole overload phase: everything the
            # gate admitted, divided by crowd-start → queues-empty.
            drained = _wait(lambda: _evals_settled(srv), timeout=90)
            overload_rate = (admitted + wave2_admitted) / max(
                time.time() - crowd_start, 1e-6
            )
            shed_stats = srv.eval_broker.shed_stats()
            report["faults"] = _fault_rows(inj)

        report.update({
            "state_pre_crowd": state_pre,
            "admitted": admitted,
            "rejected": rejected,
            "wave2_admitted": wave2_admitted,
            "wave2_rejected": wave2_rejected,
            "engaged": engaged,
            "time_to_engage_s": round(t_engage, 3),
            "fast_window_s": ctrl.cfg.window_fast,
            "state_under_load": state_under_load,
            "crowd_drained": drained,
            "overload_rate": round(overload_rate, 1),
            "goodput_ratio": round(
                overload_rate / pre_rate, 3
            ) if pre_rate > 0 else None,
            "total_shed": shed_stats["total_shed"],
        })

        # -- recovery: de-escalate back to steady ----------------------
        recovered = _wait(lambda: ctrl.state == "steady", timeout=30)
        report["recovered"] = recovered
        report["flips"] = ctrl.flips_total
        report["flips_suppressed"] = ctrl.flips_suppressed
        report["flip_budget"] = ctrl.cfg.max_flips
        report["violations"] = check_store(srv)
    finally:
        for a in agents:
            try:
                a.shutdown()
            except Exception:  # noqa: BLE001
                pass
    return report


def breach_while_leader_killed(seed: int, workdir: str) -> Dict:
    """Kill the leader while its controller is actively shedding.  The
    dying leader must release its actuators on the way down, the
    survivors must elect, the new leader must keep serving writes (and
    re-judge overload from its own restored backlog), and the cluster
    must end steady with invariants intact."""
    from .. import mock

    report: Dict = {"name": "breach_while_leader_killed", "seed": seed}
    agents = []
    try:
        agents, addrs = _overload_cluster(3)
        assert _wait(lambda: _leader(agents) is not None, timeout=20)
        leader = _leader(agents)
        for _ in range(2):
            leader.server.register_node(mock.node())
        srv = leader.server
        ctrl = srv.overload_controller

        # Warm up the scheduler, then drive the controller out of
        # steady with a crowd.
        _submit_crowd(srv, 5, low_priority_every=0)
        assert _wait(lambda: _evals_settled(srv), timeout=60)
        admitted, rejected = _submit_crowd(srv, 200, offset=10)
        engaged = _wait(lambda: ctrl.state != "steady", timeout=10)
        report.update({
            "admitted": admitted,
            "rejected": rejected,
            "engaged_pre_kill": engaged,
            "state_pre_kill": ctrl.state,
            "shed_pre_kill": srv.eval_broker.shed_stats()["total_shed"],
        })

        # Kill it mid-shed — no drain, no goodbye.
        leader.shutdown()
        # shutdown() → overload_controller.reset(): the dead leader's
        # gate must not stay engaged (a zombie 429 source).
        report["old_leader_released"] = (
            ctrl.state == "steady"
            and srv.admission_gate.factor == 1.0
        )

        survivors = [a for a in agents if a is not leader]
        assert _wait(
            lambda: _leader(survivors) is not None, timeout=30
        ), "survivors failed to elect"
        new_leader = _leader(survivors)
        nsrv = new_leader.server
        # The new leader serves writes immediately (its own gate starts
        # steady — overload state is leader-local, not replicated).
        post_ev = nsrv.submit_job(_small_job(999))
        report["post_kill_eval"] = post_ev.id if post_ev else None
        report["new_leader_state_initial"] = (
            nsrv.overload_controller.state
        )

        assert _wait(lambda: _evals_settled(nsrv), timeout=60)
        recovered = _wait(
            lambda: nsrv.overload_controller.state == "steady",
            timeout=30,
        )
        report["recovered"] = recovered
        report["new_leader_flips"] = nsrv.overload_controller.flips_total
        report["flip_budget"] = nsrv.overload_controller.cfg.max_flips
        violations = wait_converged(
            [a.server for a in survivors], timeout=20
        )
        violations += check_store(nsrv)
        report["violations"] = violations
    finally:
        for a in agents:
            try:
                a.shutdown()
            except Exception:  # noqa: BLE001
                pass
    return report


# ----------------------------------------------------------------------
# Device fault domain scenarios (ISSUE 20): watchdog, breaker, evacuation
# ----------------------------------------------------------------------

class _pinned_env:
    """Set env knobs for the scenario's lifetime, restoring on exit —
    breaker config is read from the env at coalescer construction, so
    the knobs must be pinned before the Server/DeviceCoalescer exists."""

    def __init__(self, **kv):
        self._kv = {k: str(v) for k, v in kv.items()}
        self._saved: Dict[str, object] = {}

    def __enter__(self):
        for k, v in self._kv.items():
            self._saved[k] = os.environ.get(k)
            os.environ[k] = v
        return self

    def __exit__(self, *exc):
        for k, old in self._saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        return False


def _coalescer_inputs(m, job):
    """Compiled placement-request operands for one job (the
    tests/test_pipeline.py idiom)."""
    import numpy as np

    from ..ops.encode import RequestEncoder
    from ..scheduler.coalescer import MAX_DELTA_ROWS

    enc = RequestEncoder(m)
    compiled = enc.compile(job, job.task_groups[0])
    n = m.capacity
    return dict(
        request=compiled.request,
        delta_rows=np.full((MAX_DELTA_ROWS,), -1, np.int32),
        delta_vals=np.zeros((MAX_DELTA_ROWS, 3), np.float32),
        tg_count=np.zeros((n,), np.int32),
        spread_counts=np.zeros_like(compiled.request.s_desired),
        penalty=np.zeros((n,), bool),
        class_elig=np.ones((2,), bool),
        host_mask=np.ones((n,), bool),
    )


def wedged_dispatch_recovers(
    seed: int, workdir: str, crowd: int = 24
) -> Dict:
    """One device→host fetch wedges at full pipeline depth: the watchdog
    must classify and abandon it inside its bound (no future ever
    hangs), the breaker must trip, the wedged evals must redeliver
    through the worker's nack path and land via the degraded host twin,
    the breaker must re-close through its half-open canary once the
    fault schedule is spent, and live throughput must recover to ≥50%
    of the healthy baseline within the scenario window (degraded bursts
    and the post-re-close burst both count)."""
    from .. import mock
    from ..server import Server, ServerConfig

    report: Dict = {"name": "wedged_dispatch_recovers", "seed": seed}
    violations: List[str] = []
    env = _pinned_env(
        NOMAD_TPU_FAKE_DEVICE="1",
        NOMAD_TPU_DEVICE_DEADLINE_MS="150",
        NOMAD_TPU_DEVICE_COLD_SCALE="1",
        NOMAD_TPU_DEVICE_PROBATION="0.3",
        NOMAD_TPU_DEVICE_COOLDOWN="0.05",
    )
    with env:
        srv = Server(ServerConfig(
            num_workers=2,
            heartbeat_min_ttl=3600.0, heartbeat_max_ttl=7200.0,
            eval_nack_timeout=5.0, pipeline_depth=8,
            slo_enabled=False,
        ))
        srv.start()
        try:
            for _ in range(4):
                srv.register_node(mock.node())
            coal = srv.coalescer
            brk = coal.breaker

            def burst(count, offset):
                """Submit→queues-empty wall time for one burst (the
                submission loop is part of the measured phase — both
                phases pay it identically).  The drain poll is much
                tighter than elsewhere: a burst this small settles in
                single-digit milliseconds, so a 10 ms poll would *be*
                the measurement."""
                t0 = time.time()
                for i in range(count):
                    srv.submit_job(_small_job(offset + i))
                ok = _wait(
                    lambda: _evals_settled(srv), timeout=60, every=0.0005
                )
                return count / max(time.time() - t0, 1e-6), ok

            def best_burst(offsets):
                """Max rate over repeated measurement bursts.  A burst
                of `crowd` small jobs drains in single-digit
                milliseconds — one scheduler hiccup dominates the rate —
                so the measured bursts are 4× the crowd (amortize) and a
                hiccup can only *lower* a measurement, so best-of-N
                estimates capability."""
                best = 0.0
                all_ok = True
                for off in offsets:
                    rate, ok = burst(4 * crowd, off)
                    best = max(best, rate)
                    all_ok = all_ok and ok
                return best, all_ok

            # Warm-up (first-eval jit/encoder compile), then the healthy
            # baseline bursts.
            _, ok = burst(5, 0)
            if not ok:
                violations.append("warm-up burst never drained")
            pre_rate, ok = best_burst((1000, 1200, 1400))
            if not ok:
                violations.append("baseline burst never drained")
            report["pre_rate"] = round(pre_rate, 1)

            # -- the wedge: one fetch blows through the watchdog -------
            schedule = [FaultSpec("device.wedge", "wedge", count=1)]
            with injected(seed, schedule) as inj:
                for i in range(crowd):
                    srv.submit_job(_small_job(100 + i))
                tripped = _wait(
                    lambda: brk.brief()["breaker"] != "closed",
                    timeout=15,
                )
                drained = _wait(
                    lambda: _evals_settled(srv), timeout=60
                )
                report["faults"] = _fault_rows(inj)

            # -- degraded-path throughput: placements keep flowing -----
            # (the breaker re-closes through its half-open canary
            # somewhere inside this burst once probation elapses —
            # both regimes count toward the ≥50% floor).
            post_rate, ok = best_burst((2000, 2200, 2400))
            if not ok:
                violations.append("degraded burst never drained")

            brief = brk.brief()
            report.update({
                "tripped": tripped,
                "wedged_dispatches": coal.wedged_dispatches,
                "degraded_dispatches": brief["degraded_dispatches"],
                "trips": brief["trips"],
                "crowd_drained": drained,
                "post_rate": round(post_rate, 1),
            })
            if not any(k == "wedge" for _, k, _ in report["faults"]):
                violations.append("wedge fault never fired")
            if not tripped:
                violations.append("breaker never left closed")
            if coal.wedged_dispatches < 1:
                violations.append("no dispatch classified wedged")
            if brief["degraded_dispatches"] < 1:
                violations.append("no dispatch took the degraded path")
            if not drained:
                violations.append(
                    "wedged crowd never drained — a future hung past "
                    "the watchdog or redelivery stalled"
                )
            # Recovery: the half-open canary needs live dispatches to
            # carry its verdict — trickle until the breaker re-closes.
            deadline = time.time() + 15
            i = 0
            while (
                brk.brief()["breaker"] != "closed"
                and time.time() < deadline
            ):
                srv.submit_job(_small_job(500 + i))
                i += 1
                _wait(lambda: _evals_settled(srv), timeout=10)
                time.sleep(0.05)
            recovered = brk.brief()["breaker"] == "closed"
            report["recovered"] = recovered
            if not recovered:
                violations.append(
                    "breaker never re-closed once the schedule was spent"
                )
            # The recovery floor spans the whole post-wedge window: the
            # degraded bursts above AND a post-re-close burst — "live
            # throughput recovers to ≥50% of healthy within the
            # scenario window", not "the host twin matches the device".
            rec_rate, ok = best_burst((3000, 3200))
            if not ok:
                violations.append("post-recovery burst never drained")
            best_post = max(post_rate, rec_rate)
            ratio = best_post / pre_rate if pre_rate > 0 else None
            report["recovered_rate"] = round(rec_rate, 1)
            report["throughput_ratio"] = (
                round(ratio, 3) if ratio is not None else None
            )
            if ratio is not None and ratio < 0.5:
                violations.append(
                    f"throughput never recovered to ≥50% of healthy: "
                    f"best post-wedge {best_post:.1f}/s vs "
                    f"{pre_rate:.1f}/s healthy"
                )
            violations += check_store(srv)
            report["violations"] = violations
        finally:
            srv.shutdown()
    return report


def device_slow_flapping(
    seed: int, workdir: str, dispatches: int = 60
) -> Dict:
    """A flapping ``device.slow`` seam (p=0.5) drives the breaker's
    slow-ratio trip back and forth through open/half-open/closed; the
    flip budget must bound the oscillation and every placement must
    still complete."""
    from .. import mock
    from ..scheduler.coalescer import DeviceCoalescer
    from ..state.matrix import NodeMatrix

    report: Dict = {"name": "device_slow_flapping", "seed": seed}
    violations: List[str] = []
    env = _pinned_env(
        NOMAD_TPU_FAKE_DEVICE="1",
        NOMAD_TPU_DEVICE_DEADLINE_MS="40",
        NOMAD_TPU_DEVICE_COLD_SCALE="1",
        NOMAD_TPU_DEVICE_MIN_SAMPLES="4",
        NOMAD_TPU_DEVICE_WINDOW="30",
        NOMAD_TPU_DEVICE_PROBATION="0.05",
        NOMAD_TPU_DEVICE_COOLDOWN="0.02",
        NOMAD_TPU_DEVICE_MAX_FLIPS="4",
        NOMAD_TPU_DEVICE_FLIP_WINDOW="60",
    )
    with env:
        m = NodeMatrix(capacity=16)
        for _ in range(8):
            m.upsert_node(mock.node())
        coal = DeviceCoalescer(
            m, max_lanes=1, linger_s=0.0, pipeline_depth=1
        )
        coal.start()
        try:
            inputs = _coalescer_inputs(m, _small_job())
            schedule = [FaultSpec("device.slow", "slow", p=0.5)]
            placed = 0
            with injected(seed, schedule) as inj:
                for _ in range(dispatches):
                    out = coal.place(**inputs)
                    if out is not None:
                        placed += 1
                report["faults"] = _fault_rows(inj)
        finally:
            coal.stop()
        brk = coal.breaker
        brief = brk.brief()
        report.update({
            "placed": placed,
            "slow_recorded": brief["slow"],
            "trips": brief["trips"],
            "flips": brk.flips_total,
            "flips_suppressed": brk.flips_suppressed,
            "flip_budget": brk.cfg.max_flips,
            "final_state": brief["breaker"],
        })
        if placed != dispatches:
            violations.append(
                f"only {placed}/{dispatches} placements completed"
            )
        if not any(k == "slow" for _, k, _ in report["faults"]):
            violations.append("slow fault never fired")
        if brief["slow"] < 1:
            violations.append("no fetch classified slow")
        if brk.flips_total > brk.cfg.max_flips:
            violations.append(
                f"flip budget breached: {brk.flips_total} flips > "
                f"budget {brk.cfg.max_flips}"
            )
        report["violations"] = violations
    return report


def shard_loss_evacuation(seed: int, workdir: str) -> Dict:
    """Lose a whole matrix home shard mid-dispatch: the matrix must
    evacuate it (re-lay-out across the survivors), the post-evacuation
    layout must be bit-identical to inserting the same nodes in old-row
    order into a from-scratch survivor matrix (the PARITY.md proof),
    the in-flight placement must still complete against the re-homed
    layout, and ``heal`` must restore the original shard count with
    store invariants green."""
    from .. import mock
    from ..scheduler.coalescer import DeviceCoalescer
    from ..server import Server, ServerConfig
    from ..state.matrix import NodeMatrix

    report: Dict = {"name": "shard_loss_evacuation", "seed": seed}
    violations: List[str] = []
    with _pinned_env(NOMAD_TPU_FAKE_DEVICE="1"):
        srv = Server(ServerConfig(
            num_workers=2,
            heartbeat_min_ttl=3600.0, heartbeat_max_ttl=7200.0,
        ))
        srv.start()
        try:
            m = srv.store.matrix
            m.set_shard_count(4)
            nodes = [mock.node() for _ in range(12)]
            for n in nodes:
                srv.register_node(n)
            pre_counts = m.shard_row_counts()
            # Old-row insertion order: what the evacuation replay (and
            # the from-scratch parity twin below) both iterate.
            order = [m.node_of[r] for r in sorted(m.node_of)]
            by_id = {n.id: n for n in nodes}

            coal = DeviceCoalescer(
                m, max_lanes=2, linger_s=0.0, pipeline_depth=1
            )
            coal.start()
            try:
                schedule = [FaultSpec("shard.loss", "lost", count=1)]
                with injected(seed, schedule) as inj:
                    out = coal.place(**_coalescer_inputs(m, mock.job()))
                    report["faults"] = _fault_rows(inj)
                report.update({
                    "pre_shards": 4,
                    "pre_counts": pre_counts,
                    "post_shards": int(m.shard_count),
                    "post_counts": m.shard_row_counts(),
                    "evacuations": coal.shard_evacuations,
                    "placed_row": int(out.rows[0]),
                })
                if not any(
                    k == "lost" for _, k, _ in report["faults"]
                ):
                    violations.append("loss fault never fired")
                if int(m.shard_count) != 3:
                    violations.append(
                        f"expected 3 survivor shards, got {m.shard_count}"
                    )
                if coal.shard_evacuations != 1:
                    violations.append("evacuation counter did not move")
                if out.rows[0] < 0:
                    violations.append(
                        "in-flight placement failed after evacuation"
                    )
                # Parity: a from-scratch 3-shard matrix fed the same
                # nodes in old-row order must assign identical rows.
                twin = NodeMatrix(capacity=m.capacity)
                twin.set_shard_count(int(m.shard_count))
                for nid in order:
                    twin.upsert_node(by_id[nid])
                mismatches = [
                    nid for nid in order
                    if twin.row_of[nid] != m.row_of[nid]
                ]
                report["parity_mismatches"] = len(mismatches)
                if mismatches:
                    violations.append(
                        f"evacuated layout diverges from from-scratch "
                        f"survivor layout for {len(mismatches)} node(s)"
                    )
                # Heal: full re-layout back to the original partition.
                restored = coal.heal_shard_evacuations()
                report["restored_shards"] = restored
                if restored != 4 or int(m.shard_count) != 4:
                    violations.append("heal did not restore shard count")
                out2 = coal.place(**_coalescer_inputs(m, mock.job()))
                if out2.rows[0] < 0:
                    violations.append("post-heal placement failed")
            finally:
                coal.stop()
            violations += check_store(srv)
            report["violations"] = violations
        finally:
            srv.shutdown()
    return report


SCENARIOS: Dict[str, Callable[..., Dict]] = {
    "leader_kill_mid_apply": leader_kill_mid_apply,
    "wal_truncation_sweep": wal_truncation_sweep,
    "partition_then_heal": partition_then_heal,
    "wedged_driver_during_drain": wedged_driver_during_drain,
    "flash_crowd_flapping_partition": flash_crowd_flapping_partition,
    "breach_while_leader_killed": breach_while_leader_killed,
    "wedged_dispatch_recovers": wedged_dispatch_recovers,
    "device_slow_flapping": device_slow_flapping,
    "shard_loss_evacuation": shard_loss_evacuation,
}
