"""Seeded chaos scenarios — reusable by tests and ``tools/chaos_repro.py``.

Each scenario is a function ``(seed, workdir, **knobs) -> report dict``:

```
{"name": ..., "seed": ..., "faults": [(seam, kind, step), ...],
 "violations": [...], ...extra per-scenario facts}
```

An empty ``violations`` list means every invariant
(:mod:`nomad_tpu.chaos.invariants`) held.  Scenarios never assert —
callers (tests, the repro tool) decide how to react, so a violating run
can still be inspected.

Replayability: fault *decisions* are a pure function of
``(seed, seam, hit-number)`` (see injector.py).  Scenarios built from
``at_step``/``count`` triggers reproduce the identical fired-fault
schedule run-to-run; probabilistic (``p``) triggers reproduce the same
decision table, with the fired subset following the seam's actual hit
count (thread timing can shift how many hits occur before quiescence).
"""

from __future__ import annotations

import os
import socket
import time
from typing import Callable, Dict, List

from .injector import FaultSpec, injected
from .invariants import check_store, wait_converged
from .wal_tools import complete_entries_at, sweep


def _wait(pred, timeout: float = 30.0, every: float = 0.05) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


def _free_ports(n: int) -> List[int]:
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _http_cluster(n: int = 3):
    """Spin an n-server HTTP control plane (the test_replication idiom)."""
    from ..api.agent import Agent, AgentConfig
    from ..server import ServerConfig

    ports = _free_ports(n)
    addrs = [f"http://127.0.0.1:{p}" for p in ports]
    agents = []
    for i in range(n):
        agents.append(Agent(AgentConfig(
            name=f"server-{i}",
            server_enabled=True,
            client_enabled=False,
            http_host="127.0.0.1",
            http_port=ports[i],
            server_config=ServerConfig(
                num_workers=2,
                heartbeat_min_ttl=60,
                heartbeat_max_ttl=90,
                server_id=f"server-{i}",
                peers=list(addrs),
                election_timeout=(0.15, 0.3),
                raft_heartbeat_interval=0.05,
            ),
        )))
    for a in agents:
        a.start()
    return agents, addrs


def _leader(agents):
    leaders = [
        a for a in agents
        if a.server is not None and a.server.replicator is not None
        and a.server.replicator.is_leader
    ]
    return leaders[0] if len(leaders) == 1 else None


def _small_job(i: int = 0):
    from .. import mock

    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 2
    for t in tg.tasks:
        t.resources.cpu = 20 + 5 * (i % 4)
        t.resources.memory_mb = 32
        t.config = {"run_for": 0}
    tg.ephemeral_disk.size_mb = 10
    return job


def _evals_settled(server) -> bool:
    """Quiescence: nothing pending/checked-out in the broker."""
    broker = server.eval_broker
    return broker.pending_count() == 0 and broker.unacked_count() == 0


def _fault_rows(inj) -> List[tuple]:
    return [(f.seam, f.kind, f.step) for f in inj.log]


# ----------------------------------------------------------------------
# Scenario 1: leader killed while plans/entries are in flight
# ----------------------------------------------------------------------

def leader_kill_mid_apply(seed: int, workdir: str) -> Dict:
    """Delay the leader's peer streams (widening the mid-replication
    window), kill the leader while entries are in flight, and require the
    survivors to elect, finish the work, and converge byte-identically."""
    from .. import mock

    schedule = [
        FaultSpec("raft.send", "delay", p=0.4, duration=0.05),
    ]
    report: Dict = {"name": "leader_kill_mid_apply", "seed": seed}
    with injected(seed, schedule) as inj:
        agents, addrs = _http_cluster(3)
        try:
            assert _wait(lambda: _leader(agents) is not None, timeout=20)
            leader = _leader(agents)
            for i in range(2):
                leader.server.register_node(mock.node())
            evs = [leader.server.submit_job(_small_job(i)) for i in range(3)]
            # Kill the leader with the tail of those submissions still
            # streaming to peers (the injected delays hold the window
            # open) — no drain, no goodbye.
            leader.shutdown()
            survivors = [a for a in agents if a is not leader]
            assert _wait(
                lambda: _leader(survivors) is not None, timeout=30
            ), "survivors failed to elect"
            new_leader = _leader(survivors)
            # The new leader must still serve writes.
            post_ev = new_leader.server.submit_job(_small_job(9))
            assert _wait(
                lambda: _evals_settled(new_leader.server), timeout=30
            )
            report["pre_kill_evals"] = [e.id for e in evs if e is not None]
            report["post_kill_eval"] = post_ev.id if post_ev else None
            servers = [a.server for a in survivors]
            violations = wait_converged(servers, timeout=20)
            violations += check_store(new_leader.server)
            report["violations"] = violations
        finally:
            for a in agents:
                try:
                    a.shutdown()
                except Exception:  # noqa: BLE001
                    pass
        report["faults"] = _fault_rows(inj)
    return report


# ----------------------------------------------------------------------
# Scenario 2: WAL truncated at every offset, restore must hold
# ----------------------------------------------------------------------

def wal_truncation_sweep(
    seed: int, workdir: str, stride: int = 0
) -> Dict:
    """Build real server state, then restore from a copy of its data dir
    cut at every byte offset (strided).  Every cut must restore without
    error (torn final record dropped), applied entries must grow
    monotonically with the offset, and invariants must hold at each cut."""
    from .. import mock
    from ..server import Server, ServerConfig

    import shutil

    live_dir = os.path.join(workdir, "wal-live")
    srv = Server(ServerConfig(
        num_workers=1, heartbeat_min_ttl=600, heartbeat_max_ttl=900,
        data_dir=live_dir, snapshot_every=10_000,
    ))
    srv.start()
    try:
        for _ in range(2):
            srv.register_node(mock.node())
        for i in range(3):
            ev = srv.submit_job(_small_job(i))
            if ev is not None:
                srv.wait_for_eval(ev.id, timeout=60)
        # Capture the CRASH-STOP disk image now: the WAL flushes after
        # every append, and a clean shutdown would compact the whole log
        # into a snapshot, leaving no append surface to cut.
        data_dir = os.path.join(workdir, "wal-src")
        shutil.copytree(live_dir, data_dir)
    finally:
        srv.shutdown()

    # Strides are seeded so different seeds probe different offset
    # phases; stride=1 (tools/chaos_repro.py --stride 1) is exhaustive.
    if stride <= 0:
        stride = 61 + (seed % 13)
    report: Dict = {
        "name": "wal_truncation_sweep", "seed": seed, "stride": stride,
        "faults": [], "cuts": 0,
    }
    violations: List[str] = []
    prev_entries = -1
    prev_index = -1
    scratch = os.path.join(workdir, "wal-cuts")
    for offset, cut_dir in sweep(data_dir, scratch, stride=stride):
        entries = complete_entries_at(data_dir, offset)
        try:
            restored = Server(ServerConfig(
                num_workers=1, heartbeat_min_ttl=600,
                heartbeat_max_ttl=900, data_dir=cut_dir,
            ))
        except Exception as exc:  # noqa: BLE001
            violations.append(f"offset {offset}: restore raised {exc!r}")
            continue
        report["cuts"] += 1
        if entries < prev_entries:
            violations.append(
                f"offset {offset}: complete entries went backwards"
            )
        idx = restored.store.latest_index
        if entries >= prev_entries and idx < prev_index:
            violations.append(
                f"offset {offset}: latest_index regressed "
                f"{prev_index} -> {idx}"
            )
        prev_entries, prev_index = entries, idx
        for v in check_store(restored):
            violations.append(f"offset {offset}: {v}")
        if restored.store.wal is not None:
            restored.store.wal.close()
    report["violations"] = violations
    return report


# ----------------------------------------------------------------------
# Scenario 3: partition a follower, write through it, heal, converge
# ----------------------------------------------------------------------

def partition_then_heal(seed: int, workdir: str) -> Dict:
    """Cut the leader→follower link for a deterministic number of sends
    (count-based: the fired schedule is identical run-to-run), keep
    writing through the partition, then let the link heal and require all
    three FSM images to converge."""
    from .. import mock

    drops = 12 + (seed % 8)
    report: Dict = {
        "name": "partition_then_heal", "seed": seed, "drops": drops,
    }
    agents, addrs = _http_cluster(3)
    try:
        assert _wait(lambda: _leader(agents) is not None, timeout=20)
        leader = _leader(agents)
        victim = next(a for a in agents if a is not leader)
        schedule = [FaultSpec(
            "raft.send", "drop", match={"dst": victim.rpc_addr},
            count=drops,
        )]
        with injected(seed, schedule) as inj:
            leader.server.register_node(mock.node())
            for i in range(3):
                leader.server.submit_job(_small_job(i))
            # Hold the partition open until the budgeted drops are spent
            # (the heal is part of the schedule, not test timing).
            assert _wait(
                lambda: sum(
                    1 for f in inj.log if f.kind == "drop"
                ) >= drops,
                timeout=30,
            ), "partition never exhausted its drop budget"
            report["faults"] = _fault_rows(inj)
        assert _wait(lambda: _evals_settled(leader.server), timeout=30)
        violations = wait_converged(
            [a.server for a in agents], timeout=20
        )
        violations += check_store(leader.server)
        report["violations"] = violations
    finally:
        for a in agents:
            try:
                a.shutdown()
            except Exception:  # noqa: BLE001
                pass
    return report


# ----------------------------------------------------------------------
# Scenario 4: drain a node whose driver is wedged
# ----------------------------------------------------------------------

def wedged_driver_during_drain(seed: int, workdir: str) -> Dict:
    """Drain a node whose driver swallows stop requests and never reports
    task exit.  The kill path must time out past the wedge, the drain must
    complete, and the job must end up whole on the other node."""
    from .. import mock
    from ..client import Client, ClientConfig
    from ..server import Server, ServerConfig
    from ..structs.types import AllocClientStatus, DrainStrategy

    report: Dict = {"name": "wedged_driver_during_drain", "seed": seed}
    srv = Server(ServerConfig(
        num_workers=2, heartbeat_min_ttl=60, heartbeat_max_ttl=90,
    ))
    srv.start()
    clients = []
    try:
        for name in ("c1", "c2"):
            c = Client(srv, ClientConfig(
                data_dir=os.path.join(workdir, name),
            ))
            c.start()
            clients.append(c)
        job = _small_job()
        tg = job.task_groups[0]
        tg.count = 2
        for t in tg.tasks:
            t.config = {}  # run until stopped
            t.kill_timeout = 0.3
        ev = srv.submit_job(job)
        srv.wait_for_eval(ev.id, timeout=60)

        def running():
            return [
                a for a in srv.store.allocs_by_job(job.namespace, job.id)
                if a.client_status == AllocClientStatus.RUNNING.value
                and not a.terminal_status()
            ]

        assert _wait(lambda: len(running()) == 2, timeout=30)
        target = clients[0].node.id
        schedule = [
            FaultSpec("driver.stop", "skip"),
            FaultSpec("driver.wait", "wedge", after_step=1),
        ]
        with injected(seed, schedule) as inj:
            srv.update_node_drain(
                target,
                DrainStrategy(
                    deadline=60.0, force_deadline=time.time() + 60.0
                ),
            )
            srv.drainer.notify()
            assert _wait(lambda: not [
                a for a in srv.store.allocs_by_node(target)
                if not a.terminal_status()
            ], timeout=60), "drain never finished past the wedged driver"
            assert _wait(
                lambda: len(set(a.node_id for a in running())) == 1
                and len(running()) == 2,
                timeout=60,
            ), "job did not recover at full count off the drained node"
            report["faults"] = _fault_rows(inj)
        assert _wait(lambda: _evals_settled(srv), timeout=30)
        report["violations"] = check_store(srv)
    finally:
        for c in clients:
            try:
                c.shutdown()
            except Exception:  # noqa: BLE001
                pass
        srv.shutdown()
    return report


SCENARIOS: Dict[str, Callable[..., Dict]] = {
    "leader_kill_mid_apply": leader_kill_mid_apply,
    "wal_truncation_sweep": wal_truncation_sweep,
    "partition_then_heal": partition_then_heal,
    "wedged_driver_during_drain": wedged_driver_during_drain,
}
