"""Shared retry/backoff policy — the one recovery path for every seam.

Reference: the reference wraps each cross-component call in its own
retry discipline (``client/servers/manager.go`` server rotation,
``client/client.go:1550`` registerAndHeartbeat's ``retryIntv``/
``noServersErr`` backoff, raft's per-peer pipeline backoff).  This build
had the same logic hand-rolled at each seam — fixed ``time.sleep``
constants that chaos testing cannot reason about.  This module replaces
them all: a declarative :class:`RetryPolicy` (jittered exponential
backoff + hard deadline + attempt cap + per-attempt timeout), a stateful
:class:`Backoff` for long-lived loops that recover in place (heartbeat,
watch), and :func:`retry_call` for bounded call-until-success paths
(RPC failover, register, sidecar boot).

Every seam the chaos layer (``nomad_tpu/chaos``) can break routes its
recovery through here, so fault scenarios exercise one policy surface
instead of N copies of ``while True: sleep``.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type


def env_int(name: str, default: int) -> int:
    """Tolerant integer env knob: unset, empty, or unparsable → default.
    The one parser for every ``NOMAD_TPU_*``/``BENCH_*`` tuning variable,
    so tools and product code agree on the failure mode (a typo'd knob
    degrades to the default instead of crashing an agent at import)."""
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    """Tolerant float env knob — see :func:`env_int`."""
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def env_defaults(**pairs: str) -> None:
    """``os.environ.setdefault`` for several knobs at once — the shared
    rig-setup helper for tools that must pin env before jax imports
    (tools/chaos_repro.py; tests/conftest.py force-sets instead)."""
    for name, value in pairs.items():
        os.environ.setdefault(name, value)


@dataclass(frozen=True)
class RetryPolicy:
    """Declarative backoff shape.

    ``base_delay`` grows by ``multiplier`` per failed attempt, capped at
    ``max_delay``; each sleep is jittered by ±``jitter`` fraction so herds
    of retriers decorrelate (heartbeat.go:93 applies the same jitter to
    TTLs).  ``deadline`` is a hard wall-clock budget from the first
    attempt; ``max_attempts`` a hard attempt cap; ``attempt_timeout`` the
    per-attempt I/O timeout callers should pass to the underlying call
    (the policy carries it so seam code has one source of truth).
    """

    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25
    max_attempts: Optional[int] = None
    deadline: Optional[float] = None
    attempt_timeout: Optional[float] = None


class Backoff:
    """Stateful delay generator for long-lived recovery loops.

    ``next_delay()`` advances the exponential schedule; ``reset()`` snaps
    back to ``base_delay`` on success.  Thread-compatible: each loop owns
    its instance (a shared instance would interleave schedules).
    """

    def __init__(self, policy: RetryPolicy, rng: Optional[random.Random] = None):
        self.policy = policy
        self._rng = rng or random
        self._attempt = 0

    @property
    def attempt(self) -> int:
        return self._attempt

    def reset(self) -> None:
        self._attempt = 0

    def next_delay(self) -> float:
        p = self.policy
        raw = min(p.base_delay * (p.multiplier ** self._attempt), p.max_delay)
        self._attempt += 1
        if p.jitter:
            raw *= 1.0 + p.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, raw)


class RetryBudgetExceeded(Exception):
    """The policy's deadline or attempt cap ran out; ``__cause__`` carries
    the last underlying error."""


def retry_call(
    fn: Callable,
    policy: Optional[RetryPolicy] = None,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    stop: Optional[threading.Event] = None,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    description: str = "",
):
    """Call ``fn()`` until it succeeds or the policy's budget runs out.

    - retries only exceptions in ``retry_on``; anything else propagates
    - raises :class:`RetryBudgetExceeded` (chained to the last error)
      when ``max_attempts`` or ``deadline`` is exhausted
    - ``stop`` aborts the wait early (agent shutdown) — the last error
      is re-raised so callers see a real failure, not a silent None
    - ``on_retry(attempt, exc, delay)`` observes each scheduled retry
    """
    pol = policy or RetryPolicy()
    backoff = Backoff(pol)
    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except retry_on as exc:
            out_of_attempts = (
                pol.max_attempts is not None and attempt >= pol.max_attempts
            )
            delay = backoff.next_delay()
            out_of_time = (
                pol.deadline is not None
                and time.monotonic() - start + delay > pol.deadline
            )
            if out_of_attempts or out_of_time:
                raise RetryBudgetExceeded(
                    f"{description or getattr(fn, '__name__', 'call')}: "
                    f"gave up after {attempt} attempt(s) "
                    f"({'attempt cap' if out_of_attempts else 'deadline'})"
                ) from exc
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            if stop is not None:
                if stop.wait(timeout=delay):
                    raise exc
            else:
                time.sleep(delay)
