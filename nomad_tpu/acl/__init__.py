"""ACL policy engine — policy documents → capability checks.

Reference: ``acl/policy.go`` (HCL policy grammar: namespace rules with
``policy`` shorthands or explicit ``capabilities``, plus node/agent/
operator blocks) and ``acl/acl.go`` (the compiled ACL object answering
capability checks); token → ACL resolution lives in ``nomad/acl.go`` and
here in ``server.resolve_token``.

Policy documents reuse the jobspec HCL dialect:

    namespace "default" {
      policy = "write"
    }
    namespace "ops-*" {
      capabilities = ["read-job", "list-jobs"]
    }
    node    { policy = "read" }
    agent   { policy = "read" }
    operator { policy = "write" }
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..jobspec.hcl import parse_hcl

# Namespace capabilities (acl/policy.go:17-48).
CAP_DENY = "deny"
CAP_LIST_JOBS = "list-jobs"
CAP_READ_JOB = "read-job"
CAP_SUBMIT_JOB = "submit-job"
CAP_DISPATCH_JOB = "dispatch-job"
CAP_READ_LOGS = "read-logs"
CAP_READ_FS = "read-fs"
CAP_ALLOC_EXEC = "alloc-exec"
CAP_ALLOC_LIFECYCLE = "alloc-lifecycle"
CAP_SCALE_JOB = "scale-job"

# Policy shorthand expansion (acl/policy.go expandNamespacePolicy).
_NS_READ = [CAP_LIST_JOBS, CAP_READ_JOB]
_NS_WRITE = _NS_READ + [
    CAP_SUBMIT_JOB, CAP_DISPATCH_JOB, CAP_READ_LOGS, CAP_READ_FS,
    CAP_ALLOC_EXEC, CAP_ALLOC_LIFECYCLE, CAP_SCALE_JOB,
]

_COARSE = ("deny", "read", "write")


class ACLParseError(Exception):
    pass


@dataclass
class Policy:
    """One parsed policy document."""

    namespaces: Dict[str, Set[str]] = field(default_factory=dict)
    node: str = ""  # "", "deny", "read", "write"
    agent: str = ""
    operator: str = ""


def parse_policy(rules: str) -> Policy:
    """Parse a policy document (acl/policy.go Parse)."""
    try:
        doc = parse_hcl(rules) if rules.strip() else {}
    except Exception as exc:  # noqa: BLE001
        raise ACLParseError(f"invalid policy document: {exc}") from exc
    pol = Policy()
    for block in _blocks(doc, "namespace"):
        name, body = block
        caps: Set[str] = set()
        shorthand = body.get("policy")
        if shorthand is not None:
            if shorthand not in _COARSE:
                raise ACLParseError(f"bad namespace policy {shorthand!r}")
            if shorthand == "read":
                caps.update(_NS_READ)
            elif shorthand == "write":
                caps.update(_NS_WRITE)
            else:
                caps.add(CAP_DENY)
        for cap in body.get("capabilities", []) or []:
            caps.add(cap)
        pol.namespaces[name] = caps
    for kind in ("node", "agent", "operator"):
        for name, body in _blocks(doc, kind):
            shorthand = body.get("policy", "")
            if shorthand and shorthand not in _COARSE:
                raise ACLParseError(f"bad {kind} policy {shorthand!r}")
            setattr(pol, kind, shorthand)
    return pol


def _blocks(doc: dict, kind: str):
    """Yield (label, body) for each block of ``kind`` in the parsed HCL.
    Unlabeled blocks get label ''."""
    v = doc.get(kind)
    if v is None:
        return []
    out = []
    if isinstance(v, dict):
        # Either {label: body} or a direct body for unlabeled blocks.
        if v and all(isinstance(x, dict) for x in v.values()):
            out.extend(v.items())
        else:
            out.append(("", v))
    elif isinstance(v, list):
        for item in v:
            out.append(("", item))
    return out


class ACL:
    """Compiled capability checker over a set of policies (acl/acl.go)."""

    def __init__(self, policies: List[Policy], management: bool = False):
        self.management = management
        self._namespaces: Dict[str, Set[str]] = {}
        self._node = ""
        self._agent = ""
        self._operator = ""
        order = {"": 0, "deny": 3, "read": 1, "write": 2}
        for pol in policies:
            for ns, caps in pol.namespaces.items():
                self._namespaces.setdefault(ns, set()).update(caps)
            # deny dominates; otherwise the widest grant wins.
            for kind in ("node", "agent", "operator"):
                cur = getattr(self, f"_{kind}")
                new = getattr(pol, kind)
                if order.get(new, 0) > order.get(cur, 0) or new == "deny":
                    setattr(self, f"_{kind}", new)

    # -- namespace ------------------------------------------------------

    def _ns_caps(self, namespace: str) -> Set[str]:
        exact = self._namespaces.get(namespace)
        if exact is not None:
            return exact
        # Longest-glob match (acl.go findClosestMatchingGlob).
        best: Optional[Set[str]] = None
        best_len = -1
        for pattern, caps in self._namespaces.items():
            if "*" in pattern and fnmatch.fnmatchcase(namespace, pattern):
                if len(pattern) > best_len:
                    best, best_len = caps, len(pattern)
        return best or set()

    def allow_namespace(self, namespace: str, capability: str) -> bool:
        if self.management:
            return True
        caps = self._ns_caps(namespace)
        if CAP_DENY in caps:
            return False
        return capability in caps

    # -- coarse domains -------------------------------------------------

    def _allow(self, granted: str, want: str) -> bool:
        if self.management:
            return True
        if granted == "deny":
            return False
        if want == "read":
            return granted in ("read", "write")
        return granted == "write"

    def allow_node(self, want: str) -> bool:
        return self._allow(self._node, want)

    def allow_agent(self, want: str) -> bool:
        return self._allow(self._agent, want)

    def allow_operator(self, want: str) -> bool:
        return self._allow(self._operator, want)


MANAGEMENT_ACL = ACL([], management=True)
DENY_ALL_ACL = ACL([])
