"""Declarative SLOs + multi-window burn-rate evaluation.

The paper's north star is itself an SLO — ≥50K evals/s at p99 < 5 ms —
and this module turns objectives like it into continuously evaluated
signals.  An :class:`SLOSpec` names an objective metric in the
MetricsRegistry, a comparison against a target, and a pair of sliding
windows; the engine samples the objective every tick, classifies each
sample good/bad, and computes the **burn rate** per window:

    burn = (bad samples / total samples in window) / error_budget

A burn rate of 1.0 consumes exactly the allowed violation budget; the
Google-SRE multi-window rule (alert only when BOTH the short and long
window burn hot) keeps a single slow eval from paging while still
catching sustained breaches fast.  Windowed sample storage is
``metrics.RollingWindow`` — the engine holds one per spec, so burn
rates need no second pass over raw latencies.

Three objective kinds cover the registry's value shapes:

* ``timer`` — the objective names a registry Timer; the sampled value
  is a windowed percentile field (``p99_ms`` by default), so the SLO is
  over the *recent* distribution, not the lifetime reservoir.
* ``gauge`` — the objective is a plain number in the snapshot
  (a gauge_fn, counter, or hand-rolled agent key).
* ``rate`` — the objective is a monotonic counter; the sampled value is
  its rate of change over the short window (Prometheus ``rate()``),
  which is how ``eval_throughput >= floor`` is expressed.

Lint rule O002 (``nomad_tpu/lint/obspass.py``) checks every literal
``objective=`` here and in server config against the metric names the
code actually registers, so a renamed timer can't silently turn an SLO
into a constant.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..metrics import MetricsRegistry, RollingWindow

# Objective kinds.
KIND_TIMER = "timer"
KIND_GAUGE = "gauge"
KIND_RATE = "rate"

STATUS_OK = "ok"
STATUS_BREACHED = "breached"
STATUS_PENDING = "pending"  # not enough samples to judge yet


@dataclass(frozen=True)
class SLOSpec:
    """One declarative objective.

    ``objective`` is a metric name in the registry snapshot; ``kind``
    picks how it is sampled (see module docstring).  ``op`` is "<" or
    ">=" against ``target``.  ``windows`` is (short_s, long_s);
    ``budget`` is the allowed bad-sample fraction; breach requires
    burn > ``fast_burn`` on the short window AND > ``slow_burn`` on the
    long one, with at least ``min_samples`` in each (so a freshly
    started server never breaches off two noisy ticks).
    """

    name: str
    objective: str
    op: str
    target: float
    kind: str = KIND_GAUGE
    timer_field: str = "p99_ms"
    windows: Tuple[float, float] = (60.0, 300.0)
    budget: float = 0.05
    fast_burn: float = 2.0
    slow_burn: float = 1.0
    min_samples: int = 10
    description: str = ""

    def is_good(self, value: float) -> bool:
        if self.op == "<":
            return value < self.target
        if self.op == "<=":
            return value <= self.target
        if self.op == ">":
            return value > self.target
        return value >= self.target  # ">="


def default_slos() -> List[SLOSpec]:
    """The paper-derived objectives (BASELINE.json north star), sampled
    continuously by every leader.  Targets are the 10K-node goals; on
    the CPU sim they read as aspirational burn rates, and ``min_samples``
    keeps short-lived test servers from flapping into breach."""
    return [
        SLOSpec(
            name="placement_latency_p99_ms",
            objective="nomad.eval.latency",
            kind=KIND_TIMER,
            timer_field="p99_ms",
            op="<",
            target=5.0,
            description="end-to-end eval p99 under the 5 ms north star",
        ),
        SLOSpec(
            name="eval_throughput",
            objective="nomad.worker.evals_processed",
            kind=KIND_RATE,
            op=">=",
            target=50.0,
            description="sustained evals/s above the serving floor",
        ),
        SLOSpec(
            name="heartbeat_liveness",
            objective="nomad.heartbeat.missed",
            kind=KIND_RATE,
            op="<=",
            target=0.0,
            budget=0.10,
            description="no node lost to a missed heartbeat TTL",
        ),
    ]


@dataclass
class SLOState:
    """Mutable evaluation state for one spec."""

    spec: SLOSpec
    # good/bad decisions: value 1.0 = bad sample, 0.0 = good.
    samples: RollingWindow = field(default_factory=RollingWindow)
    # Level samples of the objective counter (rate kind only).
    counter_levels: RollingWindow = field(default_factory=RollingWindow)
    last_value: float = 0.0
    status: str = STATUS_PENDING
    breached_since: Optional[float] = None
    transitions: int = 0


class SLOEngine:
    """Evaluates a set of specs against successive registry snapshots.

    ``tick(snapshot)`` samples every objective once and returns the
    list of (spec, old_status, new_status) transitions — the evaluator
    loop publishes events and dumps the flight recorder off those, so
    steady states (even steadily-breached ones) stay quiet.
    """

    def __init__(self, specs: Optional[List[SLOSpec]] = None):
        self.specs = list(specs) if specs is not None else default_slos()
        self._states: Dict[str, SLOState] = {
            s.name: SLOState(spec=s) for s in self.specs
        }
        self.last_tick = 0.0

    # -- sampling ------------------------------------------------------

    def _sample_value(
        self, st: SLOState, snapshot: Dict[str, Any], now: float
    ) -> Optional[float]:
        spec = st.spec
        raw = snapshot.get(spec.objective)
        if spec.kind == KIND_TIMER:
            if not isinstance(raw, dict):
                return None
            # Windowed percentile when the caller passes the registry
            # (tick() resolves it); the snapshot only carries lifetime
            # reservoir percentiles.
            return float(raw.get(spec.timer_field, 0.0))
        if spec.kind == KIND_RATE:
            if not isinstance(raw, (int, float)):
                return None
            st.counter_levels.observe(float(raw), ts=now)
            return st.counter_levels.rate_of_change(spec.windows[0], now=now)
        if isinstance(raw, (int, float)):
            return float(raw)
        return None

    def _timer_windowed(
        self, registry: Optional[MetricsRegistry], spec: SLOSpec, now: float
    ) -> Optional[float]:
        """Prefer the live timer's sliding window over the snapshot's
        lifetime reservoir — the whole point of the rolling windows."""
        if registry is None:
            return None
        t = registry._timers.get(spec.objective)  # read-only peek
        if t is None:
            return None
        w = t.windowed(spec.windows[1])
        if not w["count"]:
            return None
        return float(w.get(spec.timer_field, 0.0))

    # -- evaluation ----------------------------------------------------

    def tick(
        self,
        snapshot: Dict[str, Any],
        registry: Optional[MetricsRegistry] = None,
        now: Optional[float] = None,
    ) -> List[Tuple[SLOSpec, str, str]]:
        now = now if now is not None else time.time()
        self.last_tick = now
        transitions: List[Tuple[SLOSpec, str, str]] = []
        for st in self._states.values():
            spec = st.spec
            value = None
            if spec.kind == KIND_TIMER:
                value = self._timer_windowed(registry, spec, now)
                if value is None:
                    value = self._sample_value(st, snapshot, now)
            else:
                value = self._sample_value(st, snapshot, now)
            if value is None:
                continue  # objective not yet registered — no sample
            st.last_value = value
            st.samples.observe(0.0 if spec.is_good(value) else 1.0, ts=now)
            old = st.status
            st.status = self._status(st, now)
            if st.status != old:
                if st.status == STATUS_BREACHED:
                    st.breached_since = now
                elif old == STATUS_BREACHED:
                    st.breached_since = None
                st.transitions += 1
                transitions.append((spec, old, st.status))
        return transitions

    def _burn(self, st: SLOState, window_s: float, now: float) -> Tuple[float, int]:
        vals = st.samples.values(window_s, now=now)
        if not vals:
            return 0.0, 0
        bad = sum(vals) / len(vals)
        return bad / max(st.spec.budget, 1e-9), len(vals)

    def _status(self, st: SLOState, now: float) -> str:
        spec = st.spec
        fast, n_fast = self._burn(st, spec.windows[0], now)
        slow, n_slow = self._burn(st, spec.windows[1], now)
        if min(n_fast, n_slow) < spec.min_samples:
            # Keep an existing verdict until the window can overturn it.
            return st.status if st.status != STATUS_PENDING else STATUS_PENDING
        if fast > spec.fast_burn and slow > spec.slow_burn:
            return STATUS_BREACHED
        return STATUS_OK

    # -- reporting -----------------------------------------------------

    def breached(self) -> List[str]:
        return [
            n for n, st in self._states.items()
            if st.status == STATUS_BREACHED
        ]

    def state(self, name: str) -> Optional[SLOState]:
        return self._states.get(name)

    def report(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        now = now if now is not None else time.time()
        out: List[Dict[str, Any]] = []
        for st in self._states.values():
            spec = st.spec
            fast, n_fast = self._burn(st, spec.windows[0], now)
            slow, n_slow = self._burn(st, spec.windows[1], now)
            out.append({
                "name": spec.name,
                "objective": spec.objective,
                "kind": spec.kind,
                "op": spec.op,
                "target": spec.target,
                "value": round(st.last_value, 4),
                "status": st.status,
                "burn_rate_fast": round(fast, 4),
                "burn_rate_slow": round(slow, 4),
                "windows_s": list(spec.windows),
                "budget": spec.budget,
                "samples": [n_fast, n_slow],
                "breached_since": st.breached_since,
                "description": spec.description,
            })
        return out
