"""Overload controller — closes the SLO control loop (ROADMAP item 3).

PR 6 built the sensors: multi-window burn rates (:mod:`.slo`), the
composite pressure score (:mod:`.health`), and the observatory loop
that evaluates both every second.  This module is the *decide* half of
the sense→decide→act→verify loop: a leader-side state machine that
consumes the composite pressure and the breached-SLO set each
observatory tick and drives three actuators:

* **admission gating** — ``server.admission_gate`` (per-namespace token
  buckets in :mod:`..server.admission`): engaging the gate scales every
  namespace's refill rate down, so excess submissions turn into HTTP
  429 + ``Retry-After`` instead of queue growth;
* **priority shedding** — ``server.eval_broker.set_shedding``: under
  sustained breach the broker defers the lowest-priority evals with
  jittered re-enqueue delays (backpressure, not backlog);
* **fair dequeue** is structural (per-namespace deficit round-robin in
  :mod:`..server.blocked_evals`) and always on — the controller only
  reports its stats.

Anti-oscillation is explicit, because a controller that flaps is worse
than no controller (each flip is a cluster-wide behavior change):

* **multi-window thresholds** — escalation is judged on the fast
  pressure window (react within one short burn period); de-escalation
  requires BOTH the fast and slow windows below the *exit* threshold,
  and every exit threshold sits below its enter threshold;
* **minimum dwell** — a new state holds for ``min_dwell`` seconds
  before any further transition is considered;
* **cooldown** — after any flip, no new flip for ``cooldown`` seconds;
* **bounded flip rate** — at most ``max_flips`` transitions per
  ``flip_window`` seconds; past the budget the controller freezes in
  its current state and counts the suppression instead of flapping.

Every actuator decision site emits a trace event and increments a
registered counter — lint rule O003 (``nomad_tpu/lint/obspass.py``)
enforces this the way O001 does for chaos seams.  The full decision
surface is served at ``GET /v1/overload`` and rendered as the
``nomad top`` actuator row.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from .. import trace
from ..chaos.injector import inject
from ..metrics import RollingWindow
from ..retry import env_float, env_int

STATE_STEADY = "steady"
STATE_GATING = "gating"
STATE_SHEDDING = "shedding"

_LEVELS = {STATE_STEADY: 0, STATE_GATING: 1, STATE_SHEDDING: 2}
_STATES = {v: k for k, v in _LEVELS.items()}


@dataclass(frozen=True)
class OverloadConfig:
    """Controller thresholds + hysteresis knobs.

    Defaults come from ``NOMAD_TPU_OVERLOAD_*`` env vars (see README);
    enter thresholds are composite-pressure values in [0,1] sized so an
    idle or lightly loaded server (pressure ≈ 0) never engages.  A
    breached SLO scales the enter thresholds by ``breach_factor`` — a
    burning error budget lowers the bar, but pure breach with zero
    queue pressure (an idle test server missing its throughput floor)
    never actuates.
    """

    gate_enter: float = 0.35
    gate_exit: float = 0.20
    shed_enter: float = 0.50
    shed_exit: float = 0.30
    breach_factor: float = 0.75
    window_fast: float = 5.0
    window_slow: float = 30.0
    min_dwell: float = 5.0
    cooldown: float = 2.0
    max_flips: int = 6
    flip_window: float = 60.0
    # Shedding actuation parameters handed to the broker.
    shed_priority_floor: int = 50
    shed_delay: float = 2.0
    shed_jitter: float = 0.5
    # Admission-gate rate scale per level (index = level).
    gate_factors: tuple = (1.0, 0.5, 0.25)
    retry_after: float = 2.0

    @classmethod
    def from_env(cls) -> "OverloadConfig":
        return cls(
            gate_enter=env_float("NOMAD_TPU_OVERLOAD_GATE_ENTER", cls.gate_enter),
            gate_exit=env_float("NOMAD_TPU_OVERLOAD_GATE_EXIT", cls.gate_exit),
            shed_enter=env_float("NOMAD_TPU_OVERLOAD_SHED_ENTER", cls.shed_enter),
            shed_exit=env_float("NOMAD_TPU_OVERLOAD_SHED_EXIT", cls.shed_exit),
            breach_factor=env_float(
                "NOMAD_TPU_OVERLOAD_BREACH_FACTOR", cls.breach_factor
            ),
            window_fast=env_float(
                "NOMAD_TPU_OVERLOAD_WINDOW_FAST", cls.window_fast
            ),
            window_slow=env_float(
                "NOMAD_TPU_OVERLOAD_WINDOW_SLOW", cls.window_slow
            ),
            min_dwell=env_float("NOMAD_TPU_OVERLOAD_DWELL", cls.min_dwell),
            cooldown=env_float("NOMAD_TPU_OVERLOAD_COOLDOWN", cls.cooldown),
            max_flips=env_int("NOMAD_TPU_OVERLOAD_MAX_FLIPS", cls.max_flips),
            flip_window=env_float(
                "NOMAD_TPU_OVERLOAD_FLIP_WINDOW", cls.flip_window
            ),
            shed_priority_floor=env_int(
                "NOMAD_TPU_OVERLOAD_SHED_PRIORITY", cls.shed_priority_floor
            ),
            shed_delay=env_float(
                "NOMAD_TPU_OVERLOAD_SHED_DELAY", cls.shed_delay
            ),
            retry_after=env_float(
                "NOMAD_TPU_OVERLOAD_RETRY_AFTER", cls.retry_after
            ),
        )


class OverloadController:
    """One per server, stepped by the leader's observatory tick.

    Pure state machine otherwise: ``step(report, breached, now)`` takes
    the health report the observatory just computed, so unit tests
    drive it with synthetic pressure without a server (``server`` is
    duck-typed — only ``admission_gate``, ``eval_broker``,
    ``blocked_evals``, ``metrics`` are touched).
    """

    def __init__(self, server, config: Optional[OverloadConfig] = None):
        self.server = server
        self.cfg = config or OverloadConfig.from_env()
        self._lock = threading.Lock()
        self.state = STATE_STEADY
        self._entered_at = 0.0
        self._last_flip = 0.0
        self._pressure = RollingWindow(maxlen=2048)
        self._flip_times = RollingWindow(maxlen=512)
        self._fast = 0.0
        self._slow = 0.0
        self._breached: List[str] = []
        self.steps = 0
        self.flips_total = 0
        self.flips_suppressed = 0
        self.actuations_lost = 0
        self.decisions: deque = deque(maxlen=32)
        self._register_gauges()

    # -- gauges ---------------------------------------------------------

    def _register_gauges(self) -> None:
        m = getattr(self.server, "metrics", None)
        if m is None:
            return
        m.gauge_fn("nomad.overload.state", lambda: _LEVELS[self.state])
        m.gauge_fn("nomad.overload.pressure_fast", lambda: round(self._fast, 4))
        m.gauge_fn("nomad.overload.pressure_slow", lambda: round(self._slow, 4))
        m.gauge_fn("nomad.overload.flips_total", lambda: self.flips_total)

    # -- the decide step ------------------------------------------------

    def step(
        self,
        report: Dict[str, Any],
        breached: Optional[List[str]] = None,
        now: Optional[float] = None,
    ) -> str:
        """One control decision off a health report; returns the state
        after the step.  Called from the observatory tick (leader-only),
        so actuations happen at most once per tick."""
        now = now if now is not None else time.time()
        with self._lock:
            self.steps += 1
            self._breached = list(breached or [])
            self._pressure.observe(float(report.get("pressure", 0.0)), ts=now)
            fast_vals = self._pressure.values(self.cfg.window_fast, now=now)
            slow_vals = self._pressure.values(self.cfg.window_slow, now=now)
            self._fast = sum(fast_vals) / len(fast_vals) if fast_vals else 0.0
            self._slow = sum(slow_vals) / len(slow_vals) if slow_vals else 0.0
            target = self._target_locked()
            if target == _LEVELS[self.state]:
                return self.state
            if not self._may_flip_locked(now):
                return self.state
            return self._transition_locked(target, now)

    def _target_locked(self) -> int:
        c = self.cfg
        cur = _LEVELS[self.state]
        factor = c.breach_factor if self._breached else 1.0
        # Escalation: the fast window alone decides, so the controller
        # reacts within one short burn-rate period (and may jump
        # straight to shedding on a hard spike).
        if self._fast >= c.shed_enter * factor:
            return 2
        if self._fast >= c.gate_enter * factor and cur < 2:
            return max(cur, 1)
        # De-escalation: one level at a time, both windows must clear
        # the exit threshold.  Breach alone does NOT hold the gate —
        # an SLO can stay breached with zero queue pressure (an idle
        # server under its throughput floor), and gating fixes nothing
        # the pressure score can't see.
        worst = max(self._fast, self._slow)
        if cur == 2 and worst <= c.shed_exit:
            return 1
        if cur == 1 and worst <= c.gate_exit:
            return 0
        return cur

    def _may_flip_locked(self, now: float) -> bool:
        c = self.cfg
        if self._entered_at and now - self._entered_at < c.min_dwell:
            return False
        if self._last_flip and now - self._last_flip < c.cooldown:
            return False
        recent = len(self._flip_times.values(c.flip_window, now=now))
        if recent >= c.max_flips:
            # Flip budget exhausted: freeze rather than oscillate.
            self.flips_suppressed += 1
            m = getattr(self.server, "metrics", None)
            if m is not None:
                m.incr("nomad.overload.flips_suppressed")
            return False
        return True

    def _transition_locked(self, target: int, now: float) -> str:
        prev = self.state
        reason = (
            f"fast={self._fast:.3f} slow={self._slow:.3f} "
            f"breached={','.join(self._breached) or '-'}"
        )
        actuate = {
            0: self._actuate_steady,
            1: self._actuate_gating,
            2: self._actuate_shedding,
        }[target]
        if not actuate(reason):
            # Actuation lost (chaos seam): state unchanged, the next
            # tick re-drives the same target — no half-applied state.
            self.actuations_lost += 1
            return self.state
        self.state = _STATES[target]
        self._entered_at = now
        self._last_flip = now
        self._flip_times.observe(1.0, ts=now)
        self.flips_total += 1
        self.decisions.append({
            "at": round(now, 3), "from": prev, "to": self.state,
            "reason": reason,
        })
        return self.state

    # -- actuator decision sites (lint rule O003 enforces the trace +
    # counter emission on every one of these) -------------------------

    def _actuate_steady(self, reason: str) -> bool:
        spec = inject("controller.actuate", target=STATE_STEADY)
        if spec is not None and spec.kind == "error":
            return False
        srv = self.server
        srv.admission_gate.set_gate_level(1.0, retry_after=self.cfg.retry_after)
        srv.eval_broker.set_shedding(False)
        trace.event("seam.controller.actuate", target=STATE_STEADY,
                    reason=reason)
        srv.metrics.incr("nomad.overload.actuations", target=STATE_STEADY)
        return True

    def _actuate_gating(self, reason: str) -> bool:
        spec = inject("controller.actuate", target=STATE_GATING)
        if spec is not None and spec.kind == "error":
            return False
        srv = self.server
        srv.admission_gate.set_gate_level(
            self.cfg.gate_factors[1], retry_after=self.cfg.retry_after
        )
        srv.eval_broker.set_shedding(False)
        trace.event("seam.controller.actuate", target=STATE_GATING,
                    reason=reason)
        srv.metrics.incr("nomad.overload.actuations", target=STATE_GATING)
        return True

    def _actuate_shedding(self, reason: str) -> bool:
        spec = inject("controller.actuate", target=STATE_SHEDDING)
        if spec is not None and spec.kind == "error":
            return False
        c = self.cfg
        srv = self.server
        srv.admission_gate.set_gate_level(
            c.gate_factors[2], retry_after=c.retry_after
        )
        srv.eval_broker.set_shedding(
            True, priority_floor=c.shed_priority_floor,
            delay=c.shed_delay, jitter=c.shed_jitter,
        )
        trace.event("seam.controller.actuate", target=STATE_SHEDDING,
                    reason=reason)
        srv.metrics.incr("nomad.overload.actuations", target=STATE_SHEDDING)
        return True

    # -- lifecycle ------------------------------------------------------

    def reset(self) -> None:
        """Release every actuator (leadership revoked / shutdown) —
        dwell and cooldown do not apply: a non-leader must not keep
        gating, and the flip budget should not count forced releases."""
        with self._lock:
            if self.state != STATE_STEADY and self._actuate_steady("reset"):
                self.state = STATE_STEADY
                self._entered_at = 0.0
            self._pressure = RollingWindow(maxlen=2048)
            self._fast = self._slow = 0.0
            self._breached = []

    # -- read surface (/v1/overload, nomad top) ------------------------

    def report(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = now if now is not None else time.time()
        srv = self.server
        with self._lock:
            out: Dict[str, Any] = {
                "state": self.state,
                "since": self._entered_at or None,
                "pressure": {
                    "fast": round(self._fast, 4),
                    "slow": round(self._slow, 4),
                },
                "breached_slos": list(self._breached),
                "thresholds": {
                    "gate_enter": self.cfg.gate_enter,
                    "gate_exit": self.cfg.gate_exit,
                    "shed_enter": self.cfg.shed_enter,
                    "shed_exit": self.cfg.shed_exit,
                    "breach_factor": self.cfg.breach_factor,
                },
                "hysteresis": {
                    "window_fast_s": self.cfg.window_fast,
                    "window_slow_s": self.cfg.window_slow,
                    "min_dwell_s": self.cfg.min_dwell,
                    "cooldown_s": self.cfg.cooldown,
                    "max_flips": self.cfg.max_flips,
                    "flip_window_s": self.cfg.flip_window,
                },
                "flips": {
                    "total": self.flips_total,
                    "suppressed": self.flips_suppressed,
                    "recent": len(
                        self._flip_times.values(self.cfg.flip_window, now=now)
                    ),
                },
                "steps": self.steps,
                "actuations_lost": self.actuations_lost,
                "decisions": list(self.decisions),
                "evaluated_at": now,
            }
        actuators: Dict[str, Any] = {}
        try:
            actuators["admission"] = srv.admission_gate.stats()
        except Exception:  # noqa: BLE001 — duck-typed server in tests
            pass
        try:
            actuators["shed"] = srv.eval_broker.shed_stats()
        except Exception:  # noqa: BLE001
            pass
        try:
            actuators["dequeue"] = srv.blocked_evals.fairness_stats()
        except Exception:  # noqa: BLE001
            pass
        out["actuators"] = actuators
        return out
