"""The SLO observatory — the server-side evaluation loop.

One background thread per leader: every ``interval`` seconds it samples
the registry, ticks the :class:`~.slo.SLOEngine`, recomputes the
composite health score, and

* publishes ``SLO`` topic events on the store's EventBroker on every
  status transition (``SLOBreached`` / ``SLORecovered``), and
  ``Health`` topic events when the status band moves — the same stream
  ``/v1/event/stream`` serves, so an operator tailing the NDJSON feed
  sees breaches inline with the cluster lifecycle events;
* auto-dumps the PR-5 flight recorder on a breach transition, with the
  breached SLO's name and burn rates in the metadata next to the chaos
  seed — the same replayable-postmortem path chaos invariant
  violations use;
* serves ``/v1/slo`` and ``/v1/health`` from its last tick (computing
  on demand before the first one), and exposes the score as registry
  gauges (``nomad.health.*``, ``nomad.slo.*``) so the admission-control
  hook (ROADMAP item 3) can read overload without a second code path.

The loop's budget is <1% of host-loop throughput: a tick is a handful
of locked counter reads plus one windowed-percentile walk per timer
SLO.  ``tests/test_slo.py`` gates the per-tick cost the same way
``tests/test_trace_overhead.py`` gates span cost.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ..metrics import RollingWindow
from ..stream.broker import Event
from . import health as health_mod
from .slo import SLOEngine, SLOSpec, STATUS_BREACHED

log = logging.getLogger(__name__)

TOPIC_SLO = "SLO"
TOPIC_HEALTH = "Health"

# SLO breach dumps get their OWN per-process budget, separate from
# trace.auto_dump's shared cap: on the CPU sim the paper-derived
# targets legitimately burn hot, and a few breach dumps must not starve
# the invariant-violation / test-failure dumps that share auto_dump.
_BREACH_DUMP_CAP = 4
_breach_dump_lock = threading.Lock()
_breach_dumps_used = 0


def _breach_dump(reason: str, extra: dict) -> Optional[str]:
    global _breach_dumps_used
    from ..trace import core
    from ..trace.export import dump_flight_record

    if core.recorder().span_count() == 0:
        return None
    with _breach_dump_lock:
        if _breach_dumps_used >= _BREACH_DUMP_CAP:
            return None
        _breach_dumps_used += 1
    try:
        return dump_flight_record(reason=reason, extra=extra)
    except Exception:  # noqa: BLE001
        return None


class SLOObservatory:
    """Owns the engine + health state for one server.

    Constructed at server init (so the HTTP surface always has a
    responder), started/stopped with leadership (only the leader's
    signals are authoritative — a follower's queues are idle by
    construction and would read as healthy noise).
    """

    def __init__(
        self,
        server,
        specs: Optional[List[SLOSpec]] = None,
        interval: float = 1.0,
    ):
        self.server = server
        self.interval = interval
        self.engine = SLOEngine(specs)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_health: Optional[Dict[str, Any]] = None
        self._last_signals: Dict[str, float] = {}
        self._hb_levels = RollingWindow(maxlen=512)
        self.ticks = 0
        self.breach_dumps: List[str] = []
        self._register_gauges()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="slo-observatory", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the observatory must
                # never take the leader down; a broken gauge is a log line
                log.exception("SLO observatory tick failed")

    # -- one evaluation round ------------------------------------------

    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = now if now is not None else time.time()
        srv = self.server
        snapshot = self._sample_snapshot(now)
        transitions = self.engine.tick(
            snapshot, registry=srv.metrics, now=now
        )
        signals = health_mod.collect_signals(srv)
        signals["heartbeat_miss_rate"] = self._hb_miss_rate(snapshot, now)
        report = health_mod.compute_health(
            signals, breached_slos=self.engine.breached(), now=now
        )
        # The device fault domain rides on the health report so
        # ``GET /v1/health`` answers "is the TPU path live or degraded"
        # in the same read as cluster health.  Guarded — a breaker bug
        # must not stop SLO evaluation.
        coal = getattr(srv, "coalescer", None)
        if coal is not None:
            try:
                report["device"] = coal.breaker.brief()
            except Exception:  # noqa: BLE001
                log.exception("device breaker brief failed")
        events: List[Event] = []
        for spec, old, new in transitions:
            events.append(self._slo_event(spec, old, new, now))
            if new == STATUS_BREACHED:
                self._dump_breach(spec, now)
        with self._lock:
            prev = self._last_health
            self._last_health = report
            self._last_signals = signals
            self.ticks += 1
        # Close the control loop: the same tick that measured pressure
        # drives the actuators (sense → decide → act share one clock, so
        # hysteresis windows in the controller line up with burn windows
        # here).  Guarded — a controller bug must not stop SLO evaluation.
        ctrl = getattr(srv, "overload_controller", None)
        if ctrl is not None and getattr(
            srv.config, "overload_enabled", False
        ):
            try:
                ctrl.step(
                    report, breached=self.engine.breached(), now=now
                )
            except Exception:  # noqa: BLE001
                log.exception("overload controller step failed")
        if prev is not None and prev["status"] != report["status"]:
            events.append(Event(
                topic=TOPIC_HEALTH,
                type="HealthChanged",
                key=report["status"],
                index=self._event_index(),
                payload={
                    "from": prev["status"],
                    "to": report["status"],
                    "score": report["score"],
                    "pressure": report["pressure"],
                    "breached_slos": report["breached_slos"],
                },
            ))
        if events:
            try:
                srv.store.events.publish(events)
            except Exception:  # noqa: BLE001
                log.exception("publishing SLO events failed")
        return report

    def _sample_snapshot(self, now: float) -> Dict[str, Any]:
        """The cheap snapshot the engine samples: the hand-rolled broker
        / worker / heartbeat signals, NOT the full registry snapshot
        (timer SLOs read their windows directly off the registry)."""
        srv = self.server
        snap: Dict[str, Any] = {}
        try:
            snap["nomad.worker.evals_processed"] = sum(
                w.evals_processed for w in srv.workers
            )
        except Exception:
            pass
        try:
            snap["nomad.heartbeat.missed"] = srv.metrics._counters.get(
                "nomad.heartbeat.missed", 0
            )
        except Exception:
            pass
        try:
            b = srv.eval_broker
            snap["nomad.broker.total_ready"] = b.ready_count()
            snap["nomad.broker.total_pending"] = b.pending_count()
            snap["nomad.blocked_evals.total_blocked"] = (
                srv.blocked_evals.blocked_count()
            )
        except Exception:
            pass
        return snap

    def _hb_miss_rate(self, snapshot: Dict[str, Any], now: float) -> float:
        level = snapshot.get("nomad.heartbeat.missed")
        if isinstance(level, (int, float)):
            self._hb_levels.observe(float(level), ts=now)
        return self._hb_levels.rate_of_change(60.0, now=now)

    # -- events + breach dumps -----------------------------------------

    def _event_index(self) -> int:
        # Observations are not FSM commits; riding the store's latest
        # index keeps the stream's per-subscriber ordering monotonic
        # without burning raft indexes on monitoring chatter.
        try:
            return self.server.store.latest_index
        except Exception:
            return 0

    def _slo_event(
        self, spec: SLOSpec, old: str, new: str, now: float
    ) -> Event:
        st = self.engine.state(spec.name)
        if st is not None:
            fast, _ = self.engine._burn(st, spec.windows[0], now)
            slow, _ = self.engine._burn(st, spec.windows[1], now)
        else:
            fast = slow = 0.0
        return Event(
            topic=TOPIC_SLO,
            type="SLOBreached" if new == STATUS_BREACHED else "SLORecovered",
            key=spec.name,
            index=self._event_index(),
            payload={
                "slo": spec.name,
                "objective": spec.objective,
                "target": spec.target,
                "op": spec.op,
                "value": round(st.last_value, 4) if st else None,
                # Burn rates at transition time — the rolling windows
                # drain fast, so a late reader of /v1/slo can't recover
                # these from a live query.
                "burn_rate_fast": round(fast, 4),
                "burn_rate_slow": round(slow, 4),
                "from": old,
                "to": new,
                "at": now,
            },
        )

    def _dump_breach(self, spec: SLOSpec, now: float) -> None:
        st = self.engine.state(spec.name)
        fast, _ = self.engine._burn(st, spec.windows[0], now)
        slow, _ = self.engine._burn(st, spec.windows[1], now)
        path = _breach_dump(
            "slo-breach-%s" % spec.name,
            extra={
                "breached_slo": spec.name,
                "objective": spec.objective,
                "target": spec.target,
                "value": round(st.last_value, 4),
                "burn_rate_fast": round(fast, 4),
                "burn_rate_slow": round(slow, 4),
            },
        )
        if path:
            self.breach_dumps.append(path)
            log.warning(
                "SLO %s breached (value=%.4g target=%s%s) — "
                "flight record dumped: %s",
                spec.name, st.last_value, spec.op, spec.target, path,
            )

    # -- read surface (/v1/slo, /v1/health, gauges) --------------------

    def slo_report(self) -> Dict[str, Any]:
        return {
            "slos": self.engine.report(),
            "interval_s": self.interval,
            "ticks": self.ticks,
            "evaluated_at": self.engine.last_tick or None,
        }

    def health_report(self) -> Dict[str, Any]:
        with self._lock:
            last = self._last_health
        if last is None:
            # Before the first tick (or on a follower) compute on demand
            # so the endpoint never 404s during startup.
            return self.tick()
        return last

    def _register_gauges(self) -> None:
        m = self.server.metrics

        def _health(field: str):
            def read():
                with self._lock:
                    h = self._last_health
                return h[field] if h else 0
            return read

        m.gauge_fn("nomad.health.score", _health("score"))
        m.gauge_fn("nomad.health.pressure", _health("pressure"))
        m.gauge_fn(
            "nomad.health.degraded",
            lambda: int(bool(
                self._last_health
                and self._last_health["status"] != health_mod.STATUS_OK
            )),
        )
        for spec in self.engine.specs:
            st = self.engine.state(spec.name)
            m.gauge_fn(
                "nomad.slo.breached",
                (lambda s: lambda: int(s.status == STATUS_BREACHED))(st),
                slo=spec.name,
            )
            m.gauge_fn(
                "nomad.slo.burn_rate",
                (lambda s: lambda: round(
                    self.engine._burn(s, s.spec.windows[0], time.time())[0], 4
                ))(st),
                slo=spec.name,
            )
