"""Cluster SLO observatory — burn rates, overload signals, `nomad top`.

The measurement layer under ROADMAP item 3's admission control: the
paper's north star (≥50K evals/s @ p99 < 5 ms) expressed as declarative
:class:`~.slo.SLOSpec` objectives, evaluated continuously by the
leader's :class:`~.evaluator.SLOObservatory`, fanned out as ``SLO`` /
``Health`` events on the store's EventBroker, and surfaced at
``GET /v1/slo`` / ``GET /v1/health`` and in the ``nomad top``
dashboard (:mod:`.top`).  See OBSERVABILITY.md.
"""

from .evaluator import SLOObservatory, TOPIC_HEALTH, TOPIC_SLO
from .health import compute_health, collect_signals
from .slo import (
    SLOEngine,
    SLOSpec,
    STATUS_BREACHED,
    STATUS_OK,
    STATUS_PENDING,
    default_slos,
)

__all__ = [
    "SLOEngine",
    "SLOObservatory",
    "SLOSpec",
    "STATUS_BREACHED",
    "STATUS_OK",
    "STATUS_PENDING",
    "TOPIC_HEALTH",
    "TOPIC_SLO",
    "collect_signals",
    "compute_health",
    "default_slos",
]
