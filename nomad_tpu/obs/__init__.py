"""Cluster SLO observatory — burn rates, overload signals, `nomad top`.

The measurement layer under ROADMAP item 3's admission control: the
paper's north star (≥50K evals/s @ p99 < 5 ms) expressed as declarative
:class:`~.slo.SLOSpec` objectives, evaluated continuously by the
leader's :class:`~.evaluator.SLOObservatory`, fanned out as ``SLO`` /
``Health`` events on the store's EventBroker, and surfaced at
``GET /v1/slo`` / ``GET /v1/health`` and in the ``nomad top``
dashboard (:mod:`.top`).  See OBSERVABILITY.md.

The loop is closed by :class:`~.controller.OverloadController`
(``GET /v1/overload``): pressure + burn rates drive admission gating,
priority shedding, and report the DRR dequeue fairness stats.

The device fault domain lives in :mod:`.breaker`: the coalescer's
fetch watchdog (:func:`~.breaker.watchdog_fetch`), the wedged-vs-slow
verdict (:func:`~.breaker.classify_stall`), and the
closed→open→half-open :class:`~.breaker.DeviceBreaker` that degrades
dispatch to the staged host path while the device is sick.  Its
``brief()`` rides on ``GET /v1/health`` as the ``device`` block.
"""

from .breaker import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    BreakerConfig,
    DeviceBreaker,
    DeviceWedgedError,
    STALL_OK,
    STALL_SLOW,
    STALL_WEDGED,
    classify_stall,
    watchdog_fetch,
)
from .controller import (
    OverloadConfig,
    OverloadController,
    STATE_GATING,
    STATE_SHEDDING,
    STATE_STEADY,
)
from .evaluator import SLOObservatory, TOPIC_HEALTH, TOPIC_SLO
from .health import compute_health, collect_signals
from .slo import (
    SLOEngine,
    SLOSpec,
    STATUS_BREACHED,
    STATUS_OK,
    STATUS_PENDING,
    default_slos,
)

__all__ = [
    "BREAKER_CLOSED",
    "BREAKER_HALF_OPEN",
    "BREAKER_OPEN",
    "BreakerConfig",
    "DeviceBreaker",
    "DeviceWedgedError",
    "OverloadConfig",
    "OverloadController",
    "SLOEngine",
    "SLOObservatory",
    "SLOSpec",
    "STATE_GATING",
    "STATE_SHEDDING",
    "STATE_STEADY",
    "STALL_OK",
    "STALL_SLOW",
    "STALL_WEDGED",
    "STATUS_BREACHED",
    "STATUS_OK",
    "STATUS_PENDING",
    "TOPIC_HEALTH",
    "TOPIC_SLO",
    "classify_stall",
    "collect_signals",
    "compute_health",
    "default_slos",
    "watchdog_fetch",
]
