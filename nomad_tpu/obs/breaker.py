"""Device fault domain — watchdog classification + circuit breaker
(ISSUE 20).

The live dispatch path's most common real failure is not a wrong answer
but a *missing* one: a wedged TPU tunnel or a pathologically slow fetch.
The coalescer's resolver thread pays exactly one blocking device→host
fetch per ticket; before this module, a wedged launch stalled the whole
pipeline and every caller's future forever.  Three pieces close that
hole:

* :func:`classify_stall` — the one shared wedged-vs-slow definition.
  A fetch that finishes inside its deadline is ``ok``; inside
  ``deadline * wedge_factor`` it is ``slow`` (late but usable); past
  that bound it is ``wedged`` (abandoned).  ``tools/bench_watch.py``
  classifies its TPU probe with the same function, so "probe_wedged"
  in the bench ledger and "wedged" in production mean the same thing.
* :func:`watchdog_fetch` — run a fetch under that deadline on a
  sacrificial daemon thread (device fetches cannot be interrupted; a
  wedged one is abandoned, never joined) and return the verdict plus
  the value.  A wedged ticket's futures complete with a typed
  :class:`DeviceWedgedError` — callers never hang.
* :class:`DeviceBreaker` — a per-path closed→open→half-open breaker
  over the stream of fetch verdicts, reusing the hysteresis machinery
  pattern of :class:`..obs.controller.OverloadController`: min-dwell
  (``probation_s`` in the open state), cooldown, and a bounded flip
  rate that freezes the breaker rather than let a flapping device make
  it oscillate.  While open, the coalescer degrades from device
  dispatch to the staged host path (the ``NOMAD_TPU_FAKE_DEVICE``
  twin) so placements keep flowing; after probation, half-open admits
  exactly one canary launch before re-closing.

Every breaker state transition emits a trace event AND increments a
registered counter — lint rule O004 (``nomad_tpu/lint/obspass.py``)
enforces this the way O003 does for overload actuators.  The breaker
surface rides ``GET /v1/health`` (the ``device`` field) and the
``nomad top`` breaker row; knobs are ``NOMAD_TPU_DEVICE_*`` (README).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

from .. import trace
from ..metrics import RollingWindow
from ..retry import env_float, env_int

BREAKER_CLOSED = "closed"
BREAKER_HALF_OPEN = "half_open"
BREAKER_OPEN = "open"

_LEVELS = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1, BREAKER_OPEN: 2}
_STATES = {v: k for k, v in _LEVELS.items()}

STALL_OK = "ok"
STALL_SLOW = "slow"
STALL_WEDGED = "wedged"


class DeviceWedgedError(RuntimeError):
    """A device fetch blew through its watchdog bound and was abandoned.

    Raised out of ``DeviceCoalescer.place`` for every lane of a wedged
    ticket; propagates scheduler → worker, where the existing exception
    path nacks the eval back to the broker via its delivery token, so a
    wedged launch costs one redelivery instead of a hung worker.
    """

    def __init__(
        self, message: str, elapsed_s: float = 0.0, deadline_s: float = 0.0
    ):
        super().__init__(message)
        self.elapsed_s = elapsed_s
        self.deadline_s = deadline_s


def classify_stall(
    elapsed_s: float, deadline_s: float, wedge_factor: float = 1.5
) -> str:
    """The shared wedged-vs-slow verdict for an elapsed device wait.

    ``deadline_s <= 0`` disables the watchdog (always ``ok``).  The
    slow band is ``(deadline, deadline * wedge_factor]`` — late enough
    to count against the breaker, alive enough to use the result.
    """
    if deadline_s <= 0 or elapsed_s <= deadline_s:
        return STALL_OK
    if elapsed_s <= deadline_s * wedge_factor:
        return STALL_SLOW
    return STALL_WEDGED


def watchdog_fetch(
    fetch: Callable[[], Any],
    deadline_s: float,
    wedge_factor: float = 1.5,
) -> Tuple[str, Any, float]:
    """Run ``fetch()`` under the watchdog; returns ``(verdict, value,
    elapsed_s)``.

    The fetch runs on a sacrificial daemon thread because a wedged
    device fetch cannot be interrupted from Python — on a ``wedged``
    verdict the thread is abandoned (its eventual result, if any, is
    discarded) and ``value`` is ``None``.  A ``slow`` verdict means the
    fetch completed inside the wedge bound: the value is real and
    usable, just late.  An exception raised by the fetch inside the
    bound re-raises here so callers' existing error paths apply.
    """
    if deadline_s <= 0:
        t0 = time.monotonic()
        return STALL_OK, fetch(), time.monotonic() - t0
    box: Dict[str, Any] = {}
    fetched = threading.Event()

    def _run() -> None:
        try:
            box["value"] = fetch()
        except BaseException as e:  # noqa: BLE001 — ferried to the caller
            box["error"] = e
        finally:
            fetched.set()

    t0 = time.monotonic()
    th = threading.Thread(target=_run, name="device-fetch", daemon=True)
    th.start()
    if not fetched.wait(deadline_s):
        # Past the deadline: grant the slow band before declaring a
        # wedge — a fetch that lands here is recorded against the
        # breaker but its result still serves the waiting lanes.
        fetched.wait(max(0.0, deadline_s * (wedge_factor - 1.0)))
    elapsed = time.monotonic() - t0
    if not fetched.is_set():
        return STALL_WEDGED, None, elapsed
    if "error" in box:
        raise box["error"]
    return classify_stall(elapsed, deadline_s, wedge_factor), box["value"], elapsed


@dataclass(frozen=True)
class BreakerConfig:
    """Watchdog deadline + breaker thresholds and hysteresis knobs.

    Defaults come from ``NOMAD_TPU_DEVICE_*`` env vars (see README).
    ``deadline_ms <= 0`` disables the watchdog entirely (and with it
    the breaker's fault signal).  The first fetch after a (re)start is
    a cold-compile launch and gets ``deadline_ms * cold_scale``.
    """

    deadline_ms: float = 60000.0
    cold_scale: float = 5.0
    wedge_factor: float = 1.5
    # Trip thresholds over the outcome window: any `trip_wedges` wedges
    # open the breaker; a slow fraction >= slow_ratio (with at least
    # min_samples outcomes) opens it too.
    trip_wedges: int = 1
    slow_ratio: float = 0.5
    min_samples: int = 4
    window_s: float = 30.0
    # Hysteresis (the OverloadController pattern): the open state dwells
    # `probation_s` before half-open admits one canary; `cooldown_s`
    # spaces flips; past `max_flips` per `flip_window_s` the breaker
    # freezes in place and counts suppressions instead of flapping.
    probation_s: float = 5.0
    cooldown_s: float = 1.0
    max_flips: int = 6
    flip_window_s: float = 60.0

    @classmethod
    def from_env(cls) -> "BreakerConfig":
        return cls(
            deadline_ms=env_float("NOMAD_TPU_DEVICE_DEADLINE_MS", cls.deadline_ms),
            cold_scale=env_float("NOMAD_TPU_DEVICE_COLD_SCALE", cls.cold_scale),
            wedge_factor=env_float(
                "NOMAD_TPU_DEVICE_WEDGE_FACTOR", cls.wedge_factor
            ),
            trip_wedges=env_int("NOMAD_TPU_DEVICE_TRIP_WEDGES", cls.trip_wedges),
            slow_ratio=env_float("NOMAD_TPU_DEVICE_SLOW_RATIO", cls.slow_ratio),
            min_samples=env_int(
                "NOMAD_TPU_DEVICE_MIN_SAMPLES", cls.min_samples
            ),
            window_s=env_float("NOMAD_TPU_DEVICE_WINDOW", cls.window_s),
            probation_s=env_float(
                "NOMAD_TPU_DEVICE_PROBATION", cls.probation_s
            ),
            cooldown_s=env_float("NOMAD_TPU_DEVICE_COOLDOWN", cls.cooldown_s),
            max_flips=env_int("NOMAD_TPU_DEVICE_MAX_FLIPS", cls.max_flips),
            flip_window_s=env_float(
                "NOMAD_TPU_DEVICE_FLIP_WINDOW", cls.flip_window_s
            ),
        )


class DeviceBreaker:
    """Closed→open→half-open breaker over device-fetch verdicts.

    One per coalescer.  The resolver thread records every fetch verdict
    (``record_ok``/``record_slow``/``record_wedge``); the dispatch
    thread consults :meth:`allow_device_dispatch` before each launch.
    All timestamps are injectable so unit tests drive the hysteresis
    with synthetic clocks.
    """

    def __init__(
        self,
        metrics=None,
        config: Optional[BreakerConfig] = None,
    ):
        self.metrics = metrics
        self.cfg = config or BreakerConfig.from_env()
        self._lock = threading.Lock()
        self.state = BREAKER_CLOSED
        self._entered_at = 0.0
        self._last_flip = 0.0
        self._seen = 0  # fetches observed; 0 → next deadline is cold-scaled
        self._wedges = RollingWindow(maxlen=512)
        self._slows = RollingWindow(maxlen=1024)
        self._oks = RollingWindow(maxlen=2048)
        self._flip_times = RollingWindow(maxlen=512)
        self._canary_inflight = False
        self.consecutive_wedges = 0
        self.wedges_total = 0
        self.slows_total = 0
        self.oks_total = 0
        self.trips_total = 0  # transitions INTO open
        self.flips_total = 0
        self.flips_suppressed = 0
        self.degraded_dispatches = 0
        self.evacuations = 0
        self.decisions: deque = deque(maxlen=32)
        self._register_gauges()

    # -- gauges ---------------------------------------------------------

    def _register_gauges(self) -> None:
        m = self.metrics
        if m is None:
            return
        m.gauge_fn("nomad.breaker.state", lambda: _LEVELS[self.state])
        m.gauge_fn("nomad.breaker.trips", lambda: self.trips_total)
        m.gauge_fn("nomad.breaker.wedged", lambda: self.wedges_total)
        m.gauge_fn("nomad.breaker.slow", lambda: self.slows_total)
        m.gauge_fn("nomad.breaker.degraded", lambda: self.degraded_dispatches)
        m.gauge_fn("nomad.breaker.evacuations", lambda: self.evacuations)

    # -- watchdog parameters -------------------------------------------

    def deadline_s(self) -> float:
        """Current fetch deadline in seconds (0 disables).  The first
        fetch is a cold-compile launch and gets ``cold_scale``."""
        base = max(0.0, self.cfg.deadline_ms) / 1000.0
        if base <= 0:
            return 0.0
        with self._lock:
            return base * (self.cfg.cold_scale if self._seen == 0 else 1.0)

    # -- verdict stream (resolver thread) ------------------------------

    def record_ok(
        self, elapsed_s: float = 0.0, canary: bool = False,
        now: Optional[float] = None,
    ) -> str:
        now = now if now is not None else time.time()
        with self._lock:
            self._seen += 1
            self.oks_total += 1
            self._oks.observe(1.0, ts=now)
            self.consecutive_wedges = 0
            if self.state == BREAKER_HALF_OPEN and canary:
                self._canary_inflight = False
                self._transition_locked(
                    0, now, f"canary ok in {elapsed_s * 1e3:.0f}ms"
                )
            return self.state

    def record_slow(
        self, elapsed_s: float = 0.0, canary: bool = False,
        now: Optional[float] = None,
    ) -> str:
        now = now if now is not None else time.time()
        with self._lock:
            self._seen += 1
            self.slows_total += 1
            self._slows.observe(1.0, ts=now)
            self.consecutive_wedges = 0
            if self.state == BREAKER_HALF_OPEN and canary:
                self._canary_inflight = False
                self._transition_locked(
                    2, now, f"canary slow ({elapsed_s * 1e3:.0f}ms)"
                )
            elif self.state == BREAKER_CLOSED and self._slow_trips_locked(now):
                self._transition_locked(
                    2, now, f"slow rate over {self.cfg.slow_ratio:.0%}"
                )
            return self.state

    def record_wedge(
        self, elapsed_s: float = 0.0, canary: bool = False,
        now: Optional[float] = None,
    ) -> str:
        now = now if now is not None else time.time()
        with self._lock:
            self._seen += 1
            self.wedges_total += 1
            self._wedges.observe(1.0, ts=now)
            self.consecutive_wedges += 1
            if canary:
                self._canary_inflight = False
            if self.state != BREAKER_OPEN:
                wedged = self._wedges.count(self.cfg.window_s, now=now)
                if wedged >= self.cfg.trip_wedges:
                    self._transition_locked(
                        2, now,
                        f"{wedged} wedge(s) in {self.cfg.window_s:.0f}s "
                        f"(last {elapsed_s * 1e3:.0f}ms)",
                    )
            return self.state

    def _slow_trips_locked(self, now: float) -> bool:
        c = self.cfg
        slow = self._slows.count(c.window_s, now=now)
        ok = self._oks.count(c.window_s, now=now)
        total = slow + ok + self._wedges.count(c.window_s, now=now)
        return total >= c.min_samples and slow / total >= c.slow_ratio

    # -- dispatch gate (dispatch thread) -------------------------------

    def allow_device_dispatch(
        self, now: Optional[float] = None
    ) -> Tuple[bool, bool]:
        """Consulted once per dispatch: ``(allowed, canary)``.

        Closed → always allowed.  Open → denied until ``probation_s``
        has elapsed, then the breaker moves to half-open and admits
        exactly one in-flight canary launch; further dispatches stay on
        the degraded path until the canary's verdict lands.
        """
        now = now if now is not None else time.time()
        with self._lock:
            if self.state == BREAKER_CLOSED:
                return True, False
            if self.state == BREAKER_OPEN:
                if now - self._entered_at < self.cfg.probation_s:
                    return False, False
                self._transition_locked(1, now, "probation expired")
                if self.state != BREAKER_HALF_OPEN:
                    return False, False
            if not self._canary_inflight:
                self._canary_inflight = True
                return True, True
            return False, False

    def cancel_canary(self) -> None:
        """The canary launch died before producing a verdict (launch
        error, shutdown) — release the slot so half-open can retry."""
        with self._lock:
            self._canary_inflight = False

    def note_degraded(self) -> None:
        """A dispatch the breaker steered onto the staged host path."""
        with self._lock:
            self.degraded_dispatches += 1

    def note_evacuation(self) -> None:
        with self._lock:
            self.evacuations += 1

    # -- transitions (lint rule O004: every _apply_transition call site
    # must emit a trace event AND increment a nomad.* counter) ---------

    def _transition_locked(self, target: int, now: float, reason: str) -> str:
        prev = self.state
        if target == _LEVELS[prev]:
            return self.state
        if not self._may_flip_locked(now):
            return self.state
        self._apply_transition(target, now)
        trace.event(
            "seam.breaker.transition", frm=prev, to=self.state, reason=reason
        )
        m = self.metrics
        if m is not None:
            m.incr("nomad.breaker.transitions", to=self.state)
        self.decisions.append({
            "at": round(now, 3), "from": prev, "to": self.state,
            "reason": reason,
        })
        return self.state

    def _may_flip_locked(self, now: float) -> bool:
        c = self.cfg
        if self._last_flip and now - self._last_flip < c.cooldown_s:
            return False
        recent = len(self._flip_times.values(c.flip_window_s, now=now))
        if recent >= c.max_flips:
            # Flip budget exhausted: freeze in place rather than
            # oscillate with a flapping device.
            self.flips_suppressed += 1
            m = self.metrics
            if m is not None:
                m.incr("nomad.breaker.flips_suppressed")
            return False
        return True

    def _apply_transition(
        self, target: int, now: float, count_flip: bool = True
    ) -> None:
        """State mutation only — the O004-checked callers own the trace
        event + counter emission."""
        self.state = _STATES[target]
        self._entered_at = now
        if count_flip:
            self._last_flip = now
            self._flip_times.observe(1.0, ts=now)
            self.flips_total += 1
        if self.state == BREAKER_OPEN:
            self.trips_total += 1
            self._canary_inflight = False
        elif self.state == BREAKER_CLOSED:
            self._canary_inflight = False

    # -- lifecycle ------------------------------------------------------

    def reset(self) -> None:
        """Force-close and clear windows (leadership revoked /
        coalescer restart).  Dwell, cooldown, and the flip budget do not
        apply — a forced release is not a flap."""
        now = time.time()
        with self._lock:
            if self.state != BREAKER_CLOSED:
                prev = self.state
                self._apply_transition(0, now, count_flip=False)
                trace.event(
                    "seam.breaker.transition", frm=prev, to=self.state,
                    reason="reset",
                )
                m = self.metrics
                if m is not None:
                    m.incr("nomad.breaker.transitions", to=self.state)
            self._entered_at = 0.0
            self._wedges = RollingWindow(maxlen=512)
            self._slows = RollingWindow(maxlen=1024)
            self._oks = RollingWindow(maxlen=2048)
            self.consecutive_wedges = 0
            self._canary_inflight = False

    # -- read surface (/v1/health "device", nomad top) -----------------

    def brief(self) -> Dict[str, Any]:
        """Compact dict for the /v1/health ``device`` field."""
        with self._lock:
            return {
                "breaker": self.state,
                "since": self._entered_at or None,
                "trips": self.trips_total,
                "wedged": self.wedges_total,
                "slow": self.slows_total,
                "consecutive_wedges": self.consecutive_wedges,
                "degraded_dispatches": self.degraded_dispatches,
                "evacuations": self.evacuations,
            }

    def report(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = now if now is not None else time.time()
        c = self.cfg
        with self._lock:
            return {
                "state": self.state,
                "since": self._entered_at or None,
                "outcomes": {
                    "ok": self.oks_total,
                    "slow": self.slows_total,
                    "wedged": self.wedges_total,
                },
                "window": {
                    "ok": self._oks.count(c.window_s, now=now),
                    "slow": self._slows.count(c.window_s, now=now),
                    "wedged": self._wedges.count(c.window_s, now=now),
                    "width_s": c.window_s,
                },
                "consecutive_wedges": self.consecutive_wedges,
                "trips": self.trips_total,
                "flips": {
                    "total": self.flips_total,
                    "suppressed": self.flips_suppressed,
                    "recent": len(
                        self._flip_times.values(c.flip_window_s, now=now)
                    ),
                },
                "degraded_dispatches": self.degraded_dispatches,
                "evacuations": self.evacuations,
                "thresholds": {
                    "deadline_ms": c.deadline_ms,
                    "cold_scale": c.cold_scale,
                    "wedge_factor": c.wedge_factor,
                    "trip_wedges": c.trip_wedges,
                    "slow_ratio": c.slow_ratio,
                    "min_samples": c.min_samples,
                },
                "hysteresis": {
                    "probation_s": c.probation_s,
                    "cooldown_s": c.cooldown_s,
                    "max_flips": c.max_flips,
                    "flip_window_s": c.flip_window_s,
                },
                "decisions": list(self.decisions),
                "evaluated_at": now,
            }
