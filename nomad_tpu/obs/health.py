"""Composite overload/health score — the admission-control hook.

ROADMAP item 3 (production serving) needs a single signal that says
"the control plane is saturating" *before* latency SLOs burn: load
shedding keyed off a breached SLO is already too late.  This module
folds the queueing signals the server exposes into one pressure score:

* eval-broker backlog (ready + pending vs the dispatch rate's reach),
* blocked-evals backlog (placements failing for capacity),
* coalescer pipeline occupancy (in-flight vs configured depth),
* plan-queue depth and recent plan queue-wait p99,
* heartbeat misses (nodes silently dropping off).

Each input normalizes to a [0,1] pressure via a soft knee (value /
(value + knee)) so no single unbounded queue saturates the score
discontinuously; the composite is the weighted mean, and the status
bands are ``ok`` / ``degraded`` / ``critical``.  Any breached SLO
forces at least ``degraded`` — a burned latency budget IS degradation
even when queues look calm.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

STATUS_OK = "ok"
STATUS_DEGRADED = "degraded"
STATUS_CRITICAL = "critical"

DEGRADED_AT = 0.5
CRITICAL_AT = 0.85

# (name, knee, weight): pressure_i = min(1, v / (v + knee)) — at v=knee
# the input contributes 0.5.  Knees are sized to the 10K-node target's
# comfortable operating point, not the sim's.
_QUEUE_INPUTS = (
    ("broker_backlog", 256.0, 2.0),
    ("blocked_evals", 128.0, 1.0),
    ("plan_queue_depth", 64.0, 2.0),
    ("plan_queue_wait_p99_ms", 100.0, 1.5),
    ("heartbeat_miss_rate", 0.5, 1.5),
)
_PIPELINE_WEIGHT = 1.0


def _soft(value: float, knee: float) -> float:
    if value <= 0:
        return 0.0
    return min(1.0, value / (value + knee))


def compute_health(
    signals: Dict[str, float],
    breached_slos: Optional[List[str]] = None,
    now: Optional[float] = None,
) -> Dict[str, Any]:
    """``signals`` carries the raw inputs (missing keys read as 0);
    returns the pressure breakdown, composite score, and status band.
    ``score`` is 0-100 where 100 is unloaded (operator-friendly);
    ``pressure`` is the raw composite in [0,1]."""
    breached = list(breached_slos or [])
    pressures: Dict[str, float] = {}
    total_w = 0.0
    acc = 0.0
    for name, knee, weight in _QUEUE_INPUTS:
        p = _soft(float(signals.get(name, 0.0)), knee)
        pressures[name] = round(p, 4)
        acc += p * weight
        total_w += weight
    # Pipeline occupancy is already a ratio; full pipeline = pressure 1.
    depth = float(signals.get("pipeline_depth", 0.0)) or 1.0
    occ = min(1.0, float(signals.get("pipeline_inflight", 0.0)) / depth)
    pressures["pipeline_occupancy"] = round(occ, 4)
    acc += occ * _PIPELINE_WEIGHT
    total_w += _PIPELINE_WEIGHT

    pressure = acc / total_w if total_w else 0.0
    if pressure >= CRITICAL_AT:
        status = STATUS_CRITICAL
    elif pressure >= DEGRADED_AT or breached:
        status = STATUS_DEGRADED
    else:
        status = STATUS_OK
    return {
        "status": status,
        "score": round(100.0 * (1.0 - pressure), 1),
        "pressure": round(pressure, 4),
        "inputs": pressures,
        "breached_slos": breached,
        "evaluated_at": now if now is not None else time.time(),
    }


def collect_signals(server) -> Dict[str, float]:
    """Pull the raw health inputs off a live Server.  Duck-typed (no
    import of server.py — obs must stay importable standalone); every
    read is a cheap counter/locked-len call, safe at tick rate."""
    signals: Dict[str, float] = {}
    try:
        b = server.eval_broker
        signals["broker_backlog"] = (
            b.ready_count() + b.pending_count() + b.unacked_count()
        )
    except Exception:
        pass
    try:
        signals["blocked_evals"] = server.blocked_evals.blocked_count()
    except Exception:
        pass
    try:
        signals["plan_queue_depth"] = server.plan_queue.depth()
    except Exception:
        pass
    try:
        c = server.coalescer
        signals["pipeline_inflight"] = c.inflight_depth()
        signals["pipeline_depth"] = c.pipeline_depth
    except Exception:
        pass
    try:
        t = server.metrics._timers.get("nomad.phase.plan.queue_wait")
        if t is not None:
            signals["plan_queue_wait_p99_ms"] = t.windowed(60.0)["p99_ms"]
    except Exception:
        pass
    # heartbeat_miss_rate is injected by the evaluator, which tracks the
    # nomad.heartbeat.missed counter's rate over its own rolling window.
    return signals
