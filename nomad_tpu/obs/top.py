"""``nomad top`` — live terminal dashboard over the observability API.

A refresh loop over ``/v1/metrics`` + ``/v1/slo`` + ``/v1/health``,
with a background tail of the ``SLO``/``Health`` topics on
``/v1/event/stream`` so breach/recovery transitions show up between
refreshes.  Rendering is a pure function of two successive metric
snapshots (rates are deltas / interval), so the screen layout is unit
testable without a server.

Layout:

    nomad top — http://…       health: ok (score 97.3)   uptime 142s
    evals/s     : 512.4        broker ready/unacked/pending: 0/3/1
    blocked     : 0            plan queue: 0   applied/s: 511.9
    pipeline    : 3/8 in flight   lane fill: 0.82   stale: 0
    actuator: steady    pressure 0.02/0.01  gate 1.00  429s 0 …
    device  : closed    trips 0  wedged 0  slow 0  degraded 0  evac 0
    phase                     count      p50 ms      p99 ms
      broker.queue_wait       51234       0.210       1.820
      …
    slo                        value   target   burn(f/s)   status
      placement_latency_p99_ms 3.91    <5       0.4/0.2     ok
    events:
      12:02:11 SLO SLOBreached placement_latency_p99_ms
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from collections import deque
from typing import Any, Dict, List, Optional

CLEAR = "\x1b[2J\x1b[H"

# Counters whose per-interval delta is a headline rate.
_RATE_KEYS = {
    "evals/s": "nomad.worker.evals_processed",
    "applied/s": "nomad.plan.applied",
}


def _num(snap: Dict[str, Any], key: str, default: float = 0.0) -> float:
    v = snap.get(key, default)
    return float(v) if isinstance(v, (int, float)) else default


def _rates(
    prev: Optional[Dict[str, Any]], cur: Dict[str, Any], interval: float
) -> Dict[str, float]:
    out = {}
    for label, key in _RATE_KEYS.items():
        if prev is None or interval <= 0:
            out[label] = 0.0
        else:
            out[label] = max(0.0, (_num(cur, key) - _num(prev, key)) / interval)
    return out


def _phase_rows(snap: Dict[str, Any], limit: int = 12) -> List[tuple]:
    rows = []
    for key, v in snap.items():
        if key.startswith("nomad.phase.") and isinstance(v, dict):
            rows.append((
                key[len("nomad.phase."):],
                int(v.get("count", 0)),
                float(v.get("p50_ms", 0.0)),
                float(v.get("p99_ms", 0.0)),
            ))
    rows.sort(key=lambda r: -(r[1] * r[3]))  # count×p99 ≈ where time goes
    return rows[:limit]


def render(
    metrics: Dict[str, Any],
    slo: Optional[Dict[str, Any]],
    health: Optional[Dict[str, Any]],
    prev_metrics: Optional[Dict[str, Any]] = None,
    interval: float = 2.0,
    address: str = "",
    events: Optional[List[str]] = None,
    overload: Optional[Dict[str, Any]] = None,
) -> str:
    lines: List[str] = []
    h = health or {}
    status = h.get("status", "?")
    lines.append(
        f"nomad top — {address}   health: {status} "
        f"(score {h.get('score', '?')})   "
        f"uptime {int(_num(metrics, 'uptime_s'))}s"
    )
    r = _rates(prev_metrics, metrics, interval)
    lines.append(
        f"evals/s : {r['evals/s']:>8.1f}    broker r/u/p: "
        f"{int(_num(metrics, 'nomad.broker.total_ready'))}/"
        f"{int(_num(metrics, 'nomad.broker.total_unacked'))}/"
        f"{int(_num(metrics, 'nomad.broker.total_pending'))}"
        f"    blocked: {int(_num(metrics, 'nomad.blocked_evals.total_blocked'))}"
    )
    lines.append(
        f"plans   : depth {int(_num(metrics, 'nomad.plan.queue_depth'))}"
        f"  applied/s {r['applied/s']:.1f}"
        f"    pipeline: "
        f"{int(_num(metrics, 'nomad.coalescer.inflight_depth'))}/"
        f"{int(_num(metrics, 'nomad.coalescer.pipeline_depth'))} in flight"
        f"  lane fill {_num(metrics, 'nomad.coalescer.lane_fill_ratio'):.2f}"
        f"  stale {int(_num(metrics, 'nomad.coalescer.stale_dispatches'))}"
    )
    shard_rows = []
    for key, v in metrics.items():
        if key.startswith("nomad.matrix.shard_rows{") and isinstance(
            v, (int, float)
        ):
            try:
                shard_rows.append(
                    (int(key.rsplit("=", 1)[1].rstrip("}")), int(v))
                )
            except ValueError:
                continue
    shard_rows.sort()
    if len(shard_rows) > 1:
        # Shard balance: claimed rows per home shard plus the max/mean
        # skew — a hot shard ranks/scores more rows per dispatch than the
        # rest of the mesh, so skew IS the sharded-path straggler gauge.
        counts = [c for _, c in shard_rows]
        mean = sum(counts) / len(counts)
        skew = (max(counts) / mean) if mean else 1.0
        lines.append(
            f"shards  : rows {'/'.join(str(c) for c in counts)}"
            f"  skew {skew:.2f}"
            f"  topk host bytes "
            f"{int(_num(metrics, 'nomad.topk.host_bytes_total'))}"
        )
    if overload:
        p = overload.get("pressure", {})
        act = overload.get("actuators", {})
        adm = act.get("admission", {})
        shed = act.get("shed", {})
        flips = overload.get("flips", {})
        lines.append(
            f"actuator: {overload.get('state', '?'):<9}"
            f" pressure {p.get('fast', 0):.2f}/{p.get('slow', 0):.2f}"
            f"  gate {adm.get('factor', 1.0):.2f}"
            f"  429s {int(adm.get('rejected', 0))}"
            f"  shed {int(shed.get('total_shed', 0))}"
            f"  flips {int(flips.get('total', 0))}"
            f" (supp {int(flips.get('suppressed', 0))})"
        )
    dev = h.get("device")
    if isinstance(dev, dict):
        lines.append(
            f"device  : {dev.get('breaker', '?'):<9}"
            f" trips {int(dev.get('trips', 0))}"
            f"  wedged {int(dev.get('wedged', 0))}"
            f"  slow {int(dev.get('slow', 0))}"
            f"  degraded {int(dev.get('degraded_dispatches', 0))}"
            f"  evac {int(dev.get('evacuations', 0))}"
        )
    phases = _phase_rows(metrics)
    if phases:
        lines.append(f"{'phase':<30}{'count':>9}{'p50 ms':>10}{'p99 ms':>10}")
        for name, count, p50, p99 in phases:
            lines.append(f"  {name:<28}{count:>9}{p50:>10.3f}{p99:>10.3f}")
    slos = (slo or {}).get("slos", [])
    if slos:
        lines.append(
            f"{'slo':<28}{'value':>10}{'target':>10}{'burn f/s':>12}"
            f"{'status':>10}"
        )
        for s in slos:
            burn = f"{s['burn_rate_fast']:.1f}/{s['burn_rate_slow']:.1f}"
            lines.append(
                f"  {s['name']:<26}{s['value']:>10.3g}"
                f"{s['op'] + str(s['target']):>10}"
                f"{burn:>12}{s['status']:>10}"
            )
    if events:
        lines.append("events:")
        for e in events:
            lines.append(f"  {e}")
    return "\n".join(lines)


class _EventTail:
    """Background NDJSON tail of the SLO/Health topics; keeps the last
    few transitions for the dashboard footer."""

    def __init__(self, address: str, token: str = "", keep: int = 6):
        self.lines: deque = deque(maxlen=keep)
        self._address = address.rstrip("/")
        self._token = token
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="top-event-tail", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        url = (
            f"{self._address}/v1/event/stream?topic=SLO:*&topic=Health:*"
        )
        if self._token:
            url += f"&token={self._token}"
        while not self._stop.is_set():
            try:
                with urllib.request.urlopen(url, timeout=30) as resp:
                    for raw in resp:
                        if self._stop.is_set():
                            return
                        try:
                            obj = json.loads(raw)
                        except ValueError:
                            continue
                        if not obj:
                            continue  # keepalive frame
                        stamp = time.strftime("%H:%M:%S")
                        self.lines.append(
                            f"{stamp} {obj.get('Topic')} {obj.get('Type')} "
                            f"{obj.get('Key')}"
                        )
            except Exception:
                if self._stop.wait(1.0):
                    return


def run_top(
    client,
    interval: float = 2.0,
    count: int = 0,
    clear: bool = True,
    out=None,
) -> int:
    """The refresh loop.  ``count`` > 0 renders that many frames then
    exits (scriptable/testable); 0 runs until interrupted."""
    import sys

    out = out or sys.stdout
    tail = _EventTail(client.address, token=getattr(client, "token", ""))
    tail.start()
    prev = None
    frames = 0
    try:
        while count <= 0 or frames < count:
            metrics = client.metrics()
            try:
                slo = client.slo()
            except Exception:
                slo = None
            try:
                health = client.health()
            except Exception:
                health = None
            try:
                overload = client.overload()
            except Exception:
                overload = None
            frame = render(
                metrics, slo, health,
                prev_metrics=prev, interval=interval,
                address=client.address, events=list(tail.lines),
                overload=overload,
            )
            if clear:
                out.write(CLEAR)
            out.write(frame + "\n")
            out.flush()
            prev = metrics
            frames += 1
            if count > 0 and frames >= count:
                break
            time.sleep(interval)
    except KeyboardInterrupt:
        pass
    finally:
        tail.stop()
    return 0
