"""HCL/JSON job structures → ``structs.Job`` (and back, for the API).

Reference: ``jobspec2/parse.go`` and the api/ job types. Durations accept
Go-style strings ("15s", "5m", "1h30m") or numbers (seconds).
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional

from ..structs.types import (
    Affinity,
    Constraint,
    EphemeralDisk,
    Job,
    MigrateStrategy,
    NetworkResource,
    PeriodicConfig,
    RequestedDevice,
    ReschedulePolicy,
    Resources,
    RestartPolicy,
    ScalingPolicy,
    Service,
    Spread,
    SpreadTarget,
    Task,
    TaskGroup,
    UpdateStrategy,
    VolumeMount,
    VolumeRequest,
)
from .hcl import parse_hcl

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ms|h|m|s)")
_DURATION_UNITS = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 0.001}


def duration(value: Any, default: float = 0.0) -> float:
    """Go-style duration ("1h30m", "15s") or bare number (seconds)."""
    if value is None:
        return default
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    total = 0.0
    matched = False
    for num, unit in _DURATION_RE.findall(s):
        total += float(num) * _DURATION_UNITS[unit]
        matched = True
    if not matched:
        try:
            return float(s)
        except ValueError:
            return default
    return total


def parse_job(src: str) -> Job:
    """Parse an HCL or JSON job spec into a Job."""
    stripped = src.lstrip()
    if stripped.startswith("{"):
        data = json.loads(src)
        if "Job" in data:
            data = data["Job"]
        if "job" in data and isinstance(data["job"], dict):
            return _job_from_hcl_tree(data["job"])
        return api_to_job(data)
    tree = parse_hcl(src)
    jobs = tree.get("job")
    if not jobs:
        raise ValueError("no job block found")
    return _job_from_hcl_tree(jobs)


def _one(block) -> Dict[str, Any]:
    """HCL trees store repeated bare blocks as lists; take the first."""
    if isinstance(block, list):
        return block[0]
    return block or {}


def _many(block) -> List[Dict[str, Any]]:
    if block is None:
        return []
    if isinstance(block, list):
        return block
    return [block]


def _labeled(block) -> List[tuple]:
    """(label, body) pairs from a labeled-block subtree, order preserved;
    a repeated label yields multiple pairs."""
    out = []
    for label, body in (block or {}).items():
        for b in _many(body):
            out.append((label, b))
    return out


def _job_from_hcl_tree(tree: Dict[str, Any]) -> Job:
    # job "name" { ... } parses to {name: body}
    if len(tree) == 1 and isinstance(next(iter(tree.values())), dict) and (
        "group" in next(iter(tree.values()))
        or "task_group" in next(iter(tree.values()))
        or "type" in next(iter(tree.values()))
        or "datacenters" in next(iter(tree.values()))
    ):
        job_id, body = next(iter(tree.items()))
    else:
        job_id, body = "", tree

    job = Job(
        id=body.get("id", job_id) or job_id,
        name=body.get("name", job_id) or job_id,
        namespace=body.get("namespace", "default"),
        type=body.get("type", "service"),
        priority=int(body.get("priority", 50)),
        datacenters=list(body.get("datacenters", ["dc1"])),
        region=body.get("region", "global"),
        all_at_once=bool(body.get("all_at_once", False)),
        meta={str(k): str(v) for k, v in _one(body.get("meta")).items()},
    )
    job.constraints = [_constraint(c) for c in _many(body.get("constraint"))]
    job.affinities = [_affinity(a) for a in _many(body.get("affinity"))]
    job.spreads = [_spread(s) for s in _many(body.get("spread"))]
    if "update" in body:
        job.update = _update(_one(body["update"]))
    if "periodic" in body:
        p = _one(body["periodic"])
        job.periodic = PeriodicConfig(
            enabled=bool(p.get("enabled", True)),
            spec=p.get("cron", p.get("spec", "")),
            prohibit_overlap=bool(p.get("prohibit_overlap", False)),
            time_zone=p.get("time_zone", "UTC"),
        )
    if "parameterized" in body:
        job.parameterized = _one(body["parameterized"])

    for name, gbody in _labeled(body.get("group")):
        job.task_groups.append(_group(name, gbody, job))
    if not job.task_groups:
        raise ValueError("job has no task groups")
    return job


def _group(name: str, body: Dict[str, Any], job: Job) -> TaskGroup:
    tg = TaskGroup(
        name=name,
        count=int(body.get("count", 1)),
    )
    tg.constraints = [_constraint(c) for c in _many(body.get("constraint"))]
    tg.affinities = [_affinity(a) for a in _many(body.get("affinity"))]
    tg.spreads = [_spread(s) for s in _many(body.get("spread"))]
    if "restart" in body:
        r = _one(body["restart"])
        tg.restart_policy = RestartPolicy(
            attempts=int(r.get("attempts", 2)),
            interval=duration(r.get("interval"), 1800.0),
            delay=duration(r.get("delay"), 15.0),
            mode=r.get("mode", "fail"),
        )
    if "reschedule" in body:
        r = _one(body["reschedule"])
        tg.reschedule_policy = ReschedulePolicy(
            attempts=int(r.get("attempts", 0)),
            interval=duration(r.get("interval"), 0.0),
            delay=duration(r.get("delay"), 30.0),
            delay_function=r.get("delay_function", "exponential"),
            max_delay=duration(r.get("max_delay"), 3600.0),
            unlimited=bool(r.get("unlimited", True)),
        )
    if "migrate" in body:
        m = _one(body["migrate"])
        tg.migrate_strategy = MigrateStrategy(
            max_parallel=int(m.get("max_parallel", 1)),
            health_check=m.get("health_check", "checks"),
            min_healthy_time=duration(m.get("min_healthy_time"), 10.0),
            healthy_deadline=duration(m.get("healthy_deadline"), 300.0),
        )
    if "update" in body:
        tg.update = _update(_one(body["update"]))
    if "ephemeral_disk" in body:
        e = _one(body["ephemeral_disk"])
        tg.ephemeral_disk = EphemeralDisk(
            sticky=bool(e.get("sticky", False)),
            size_mb=int(e.get("size", e.get("size_mb", 300))),
            migrate=bool(e.get("migrate", False)),
        )
    for nbody in _many(body.get("network")):
        tg.networks.append(_network(nbody))
    if body.get("stop_after_client_disconnect") is not None:
        tg.stop_after_client_disconnect = duration(
            body["stop_after_client_disconnect"]
        )
    if "scaling" in body:
        s = _one(body["scaling"])
        tg.scaling = ScalingPolicy(
            min=int(s.get("min", 0)),
            max=int(s.get("max", 0)),
            enabled=bool(s.get("enabled", True)),
            policy=_one(s.get("policy")),
        )
    for vname, vbody in _labeled(body.get("volume")):
        tg.volumes[vname] = VolumeRequest(
            name=vname,
            type=vbody.get("type", "host"),
            source=vbody.get("source", vname),
            read_only=bool(vbody.get("read_only", False)),
            per_alloc=bool(vbody.get("per_alloc", False)),
        )
    for tname, tbody in _labeled(body.get("task")):
        tg.tasks.append(_task(tname, tbody))
    if not tg.tasks:
        raise ValueError(f"group {name!r} has no tasks")
    return tg


def _task(name: str, body: Dict[str, Any]) -> Task:
    t = Task(
        name=name,
        driver=body.get("driver", "mock"),
        config=_one(body.get("config")),
        env={str(k): str(v) for k, v in _one(body.get("env")).items()},
        kill_timeout=duration(body.get("kill_timeout"), 5.0),
        leader=bool(body.get("leader", False)),
    )
    if "lifecycle" in body:
        lc = _one(body["lifecycle"])
        t.lifecycle_hook = lc.get("hook", "")
        t.lifecycle_sidecar = bool(lc.get("sidecar", False))
    if "resources" in body:
        r = _one(body["resources"])
        t.resources = Resources(
            cpu=int(r.get("cpu", 100)),
            memory_mb=int(r.get("memory", r.get("memory_mb", 300))),
            disk_mb=int(r.get("disk", r.get("disk_mb", 0))),
        )
        for d_label, d_body in _labeled(r.get("device")):
            t.resources.devices.append(
                RequestedDevice(
                    name=d_label,
                    count=int(d_body.get("count", 1)),
                    constraints=[
                        _constraint(c)
                        for c in _many(d_body.get("constraint"))
                    ],
                )
            )
        for nbody in _many(r.get("network")):
            t.resources.networks.append(_network(nbody))
    t.constraints = [_constraint(c) for c in _many(body.get("constraint"))]
    t.affinities = [_affinity(a) for a in _many(body.get("affinity"))]
    for s_label, s_body in _labeled(body.get("service")):
        t.services.append(
            Service(
                name=s_label,
                port_label=s_body.get("port", ""),
                tags=list(s_body.get("tags", [])),
            )
        )
    for sbody in _many(body.get("artifact")):
        t.artifacts.append(sbody)
    for sbody in _many(body.get("template")):
        t.templates.append(sbody)
    if "dispatch_payload" in body:
        dp = _one(body["dispatch_payload"])
        t.dispatch_payload = {"file": dp.get("file", "input")}
    if "logs" in body:
        lg = _one(body["logs"])
        t.logs = {
            "max_files": int(lg.get("max_files", 10)),
            "max_file_size_mb": int(lg.get("max_file_size", lg.get(
                "max_file_size_mb", 10
            ))),
        }
    for vm in _many(body.get("volume_mount")):
        t.volume_mounts.append(VolumeMount(
            volume=vm.get("volume", ""),
            destination=vm.get("destination", ""),
            read_only=bool(vm.get("read_only", False)),
        ))
    return t


def _network(body: Dict[str, Any]) -> NetworkResource:
    net = NetworkResource(
        mode=body.get("mode", "host"), mbits=int(body.get("mbits", 0))
    )
    for label, pbody in _labeled(body.get("port")):
        static = pbody.get("static")
        if static:
            net.reserved_ports.append(int(static))
        else:
            net.dynamic_ports.append(label)
    return net


def _constraint(body: Dict[str, Any]) -> Constraint:
    operand = body.get("operator", body.get("operand", "="))
    # distinct_hosts / distinct_property sugar.
    if body.get("distinct_hosts"):
        return Constraint(operand="distinct_hosts")
    if body.get("distinct_property"):
        return Constraint(
            l_target=body["distinct_property"],
            operand="distinct_property",
            r_target=str(body.get("value", "")),
        )
    return Constraint(
        l_target=body.get("attribute", ""),
        r_target=str(body.get("value", "")),
        operand=operand,
    )


def _affinity(body: Dict[str, Any]) -> Affinity:
    return Affinity(
        l_target=body.get("attribute", ""),
        r_target=str(body.get("value", "")),
        operand=body.get("operator", "="),
        weight=int(body.get("weight", 50)),
    )


def _spread(body: Dict[str, Any]) -> Spread:
    targets = [
        SpreadTarget(value=label, percent=int(t.get("percent", 0)))
        for label, t in _labeled(body.get("target"))
    ]
    return Spread(
        attribute=body.get("attribute", ""),
        weight=int(body.get("weight", 50)),
        targets=targets,
    )


def _update(body: Dict[str, Any]) -> UpdateStrategy:
    return UpdateStrategy(
        max_parallel=int(body.get("max_parallel", 1)),
        health_check=body.get("health_check", "checks"),
        min_healthy_time=duration(body.get("min_healthy_time"), 10.0),
        healthy_deadline=duration(body.get("healthy_deadline"), 300.0),
        progress_deadline=duration(body.get("progress_deadline"), 600.0),
        auto_revert=bool(body.get("auto_revert", False)),
        auto_promote=bool(body.get("auto_promote", False)),
        canary=int(body.get("canary", 0)),
        stagger=duration(body.get("stagger"), 30.0),
    )


# ---------------------------------------------------------------------------
# API JSON <-> Job
# ---------------------------------------------------------------------------


def job_to_api(job: Job) -> Dict[str, Any]:
    """Job → JSON-able dict (dataclasses asdict, enums already str)."""
    import dataclasses

    return dataclasses.asdict(job)


def api_to_job(data: Dict[str, Any]) -> Job:
    """JSON dict (snake_case asdict form) → Job."""

    def build(cls, payload, field_builders=None):
        import dataclasses as dc

        kwargs = {}
        names = {f.name: f for f in dc.fields(cls)}
        for k, v in (payload or {}).items():
            if k not in names:
                continue
            builder = (field_builders or {}).get(k)
            kwargs[k] = builder(v) if builder else v
        return cls(**kwargs)

    def tasks(items):
        return [
            build(
                Task,
                t,
                {
                    "resources": lambda r: build(
                        Resources,
                        r,
                        {
                            "networks": lambda ns: [
                                build(NetworkResource, n) for n in ns
                            ],
                            "devices": lambda ds: [
                                build(RequestedDevice, d, {
                                    "constraints": lambda cs: [
                                        build(Constraint, c) for c in cs
                                    ],
                                    "affinities": lambda as_: [
                                        build(Affinity, a) for a in as_
                                    ],
                                })
                                for d in ds
                            ],
                        },
                    ),
                    "constraints": lambda cs: [
                        build(Constraint, c) for c in cs
                    ],
                    "affinities": lambda as_: [build(Affinity, a) for a in as_],
                    "services": lambda ss: [build(Service, s) for s in ss],
                    "volume_mounts": lambda vms: [
                        build(VolumeMount, v) for v in vms
                    ],
                },
            )
            for t in (items or [])
        ]

    def groups(items):
        return [
            build(
                TaskGroup,
                g,
                {
                    "tasks": tasks,
                    "constraints": lambda cs: [
                        build(Constraint, c) for c in cs
                    ],
                    "affinities": lambda as_: [build(Affinity, a) for a in as_],
                    "spreads": lambda ss: [
                        build(Spread, s, {
                            "targets": lambda ts: [
                                build(SpreadTarget, t) for t in ts
                            ]
                        })
                        for s in ss
                    ],
                    "restart_policy": lambda r: build(RestartPolicy, r),
                    "reschedule_policy": lambda r: build(ReschedulePolicy, r)
                    if r
                    else None,
                    "migrate_strategy": lambda m: build(MigrateStrategy, m),
                    "update": lambda u: build(UpdateStrategy, u) if u else None,
                    "ephemeral_disk": lambda e: build(EphemeralDisk, e),
                    "networks": lambda ns: [
                        build(NetworkResource, n) for n in ns
                    ],
                    "scaling": lambda s: build(ScalingPolicy, s)
                    if s else None,
                    "volumes": lambda vs: {
                        k: build(VolumeRequest, v) for k, v in vs.items()
                    },
                },
            )
            for g in (items or [])
        ]

    return build(
        Job,
        data,
        {
            "task_groups": groups,
            "constraints": lambda cs: [build(Constraint, c) for c in cs],
            "affinities": lambda as_: [build(Affinity, a) for a in as_],
            "spreads": lambda ss: [
                build(Spread, s, {
                    "targets": lambda ts: [
                        build(SpreadTarget, t) for t in ts
                    ]
                })
                for s in ss
            ],
            "update": lambda u: build(UpdateStrategy, u) if u else None,
            "periodic": lambda p: build(PeriodicConfig, p) if p else None,
        },
    )
