"""Minimal HCL parser — the subset job specs use.

Supports: ``key = value`` attributes (strings, numbers, bools, lists,
maps, heredocs), labeled blocks (``job "name" { ... }``), nested blocks,
``#``/``//`` line comments and ``/* */`` block comments. Interpolation
sequences (``${...}``) are preserved verbatim inside strings — constraint
targets rely on that. Duration strings ("30s", "5m", "1h") are left as
strings; the schema layer converts them.

This is a from-scratch recursive-descent parser for OUR dialect, not a port
of HashiCorp's HCL — it covers what the reference's jobspec tests exercise.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple


class HCLParseError(ValueError):
    def __init__(self, msg: str, line: int):
        super().__init__(f"line {line}: {msg}")
        self.line = line


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>[ \t\r]+)
  | (?P<comment>\#[^\n]*|//[^\n]*)
  | (?P<block_comment>/\*.*?\*/)
  | (?P<heredoc><<-?(?P<tag>[A-Za-z_][A-Za-z0-9_]*)\n(?P<body>.*?)\n\s*(?P=tag))
  | (?P<string>"(?:\\.|\$\{[^}]*\}|[^"\\])*")
  | (?P<number>-?\d+(?:\.\d+)?(?![A-Za-z_]))
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.\-]*)
  | (?P<punct>[{}\[\],=:\n])
    """,
    re.VERBOSE | re.DOTALL,
)

_ESCAPES = {"n": "\n", "t": "\t", '"': '"', "\\": "\\"}


class _Lexer:
    def __init__(self, src: str):
        self.tokens: List[Tuple[str, Any, int]] = []
        line = 1
        pos = 0
        while pos < len(src):
            mo = _TOKEN_RE.match(src, pos)
            if mo is None:
                raise HCLParseError(f"unexpected character {src[pos]!r}", line)
            kind = mo.lastgroup
            text = mo.group(0)
            if kind == "ws":
                pass
            elif kind in ("comment", "block_comment"):
                line += text.count("\n")
            elif kind == "heredoc":
                self.tokens.append(("string", mo.group("body"), line))
                line += text.count("\n")
            elif kind == "string":
                self.tokens.append(("string", _unquote(text), line))
            elif kind == "number":
                num = float(text) if "." in text else int(text)
                self.tokens.append(("number", num, line))
            elif kind == "ident":
                self.tokens.append(("ident", text, line))
            elif kind == "punct":
                if text == "\n":
                    self.tokens.append(("newline", "\n", line))
                    line += 1
                else:
                    self.tokens.append((text, text, line))
            # `heredoc` handled above; `punct` covers the rest
            pos = mo.end()
        self.tokens.append(("eof", None, line))
        self.i = 0

    def peek(self) -> Tuple[str, Any, int]:
        return self.tokens[self.i]

    def next(self) -> Tuple[str, Any, int]:
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def skip_newlines(self) -> None:
        while self.tokens[self.i][0] == "newline":
            self.i += 1


def _unquote(text: str) -> str:
    body = text[1:-1]
    out = []
    i = 0
    while i < len(body):
        ch = body[i]
        if ch == "\\" and i + 1 < len(body):
            out.append(_ESCAPES.get(body[i + 1], body[i + 1]))
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


_BOOLS = {"true": True, "false": False, "null": None}


def parse_hcl(src: str) -> Dict[str, Any]:
    """Parse HCL into nested dicts. Blocks become
    ``{type: {label: body}}`` when labeled (repeated labels become lists),
    ``{type: body}`` (or list of bodies) when bare. Attributes map directly.
    """
    lx = _Lexer(src)
    return _parse_body(lx, top=True)


def _parse_body(lx: _Lexer, top: bool = False) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    while True:
        lx.skip_newlines()
        kind, value, line = lx.peek()
        if kind == "eof":
            if not top:
                raise HCLParseError("unexpected EOF in block", line)
            return out
        if kind == "}":
            lx.next()
            return out
        if kind not in ("ident", "string"):
            raise HCLParseError(f"expected identifier, got {value!r}", line)
        lx.next()
        name = value
        kind2, value2, line2 = lx.peek()
        if kind2 == "=":
            lx.next()
            out[name] = _parse_value(lx)
        elif kind2 in ("string", "ident") or kind2 == "{":
            # Block, possibly labeled: job "x" { } / config { }
            labels = []
            while True:
                k, v, ln = lx.peek()
                if k in ("string", "ident"):
                    labels.append(v)
                    lx.next()
                elif k == "{":
                    lx.next()
                    break
                else:
                    raise HCLParseError(
                        f"expected block label or '{{', got {v!r}", ln
                    )
            body = _parse_body(lx)
            _insert_block(out, name, labels, body, line)
        else:
            raise HCLParseError(
                f"expected '=' or block after {name!r}, got {value2!r}", line2
            )


def _insert_block(out, name, labels, body, line) -> None:
    if not labels:
        existing = out.get(name)
        if existing is None:
            out[name] = body
        elif isinstance(existing, list):
            existing.append(body)
        else:
            out[name] = [existing, body]
        return
    slot = out.setdefault(name, {})
    if not isinstance(slot, dict):
        raise HCLParseError(f"mixing labeled and bare {name!r} blocks", line)
    for label in labels[:-1]:
        slot = slot.setdefault(label, {})
    leaf = slot.get(labels[-1])
    if leaf is None:
        slot[labels[-1]] = body
    elif isinstance(leaf, list):
        leaf.append(body)
    else:
        slot[labels[-1]] = [leaf, body]


def _parse_value(lx: _Lexer) -> Any:
    lx.skip_newlines()
    kind, value, line = lx.next()
    if kind in ("string", "number"):
        return value
    if kind == "ident":
        if value in _BOOLS:
            return _BOOLS[value]
        return value  # bare identifier (e.g. enum-ish values)
    if kind == "[":
        items: List[Any] = []
        while True:
            lx.skip_newlines()
            if lx.peek()[0] == "]":
                lx.next()
                return items
            items.append(_parse_value(lx))
            lx.skip_newlines()
            if lx.peek()[0] == ",":
                lx.next()
    if kind == "{":
        obj: Dict[str, Any] = {}
        while True:
            lx.skip_newlines()
            k, v, ln = lx.next()
            if k == "}":
                return obj
            if k == ",":
                continue
            if k not in ("ident", "string"):
                raise HCLParseError(f"bad map key {v!r}", ln)
            sep, sv, sl = lx.next()
            if sep not in ("=", ":"):
                raise HCLParseError(f"expected '=' or ':', got {sv!r}", sl)
            obj[v] = _parse_value(lx)
    raise HCLParseError(f"unexpected value token {value!r}", line)
