"""Job specification parsing.

Reference: ``jobspec2/parse.go:19`` (HCL2) and ``jobspec/`` (HCL1). This
build implements an HCL-subset parser (blocks, attributes, heredocs,
lists/maps, comments, ``${var}`` interpolation left verbatim) plus the JSON
job format the HTTP API accepts, both mapping onto ``structs.Job``.
"""

from .hcl import HCLParseError, parse_hcl
from .parse import api_to_job, job_to_api, parse_job

__all__ = [
    "HCLParseError",
    "parse_hcl",
    "parse_job",
    "api_to_job",
    "job_to_api",
]
