"""Sharded scheduling step over a ``jax.sharding.Mesh``.

Mesh axes and their roles (the sharding design the scaling-book recipe
produces for this workload):

- ``node``  — the cluster matrix's node axis, sharded like sequence/tensor
  dims in an ML model. Every (N, ...) array in ``DeviceArrays`` plus the
  usage matrix splits along it. Feasibility/scoring is row-parallel, so each
  shard scores its own nodes with zero communication; only the final
  *argmax* crosses shards (one ``pmax`` pair over ICI — the analog of a
  ring-attention score reduction).
- ``batch`` — independent evaluations, sharded like data-parallel batches.
  Each batch shard picks winners locally; the resulting usage deltas are
  ``psum``-ed across the batch axis (the gradient-all-reduce analog) so every
  replica applies the same state update.

Reference behaviors preserved: the step scores all nodes per eval (replacing
stack.go:78-91's candidate sampling), applies proposed usage like
BinPackIterator's proposed-alloc accounting (rank.go:210-323), and leaves
conflict resolution to the serialized plan applier (plan_apply.go:49-69) —
batched picks are optimistic by design.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.encode import SchedRequest, pow2_bucket
from ..ops.kernels import (
    FULL_FEATURES,
    NEG_INF,
    apply_spread_values,
    pack_fused_lanes,
    score_nodes,
    spread_values_at,
)
from ..state.matrix import DeviceArrays

# Hierarchical top-k width: each node shard contributes its k best rows to
# the (shards, k) candidate table.  Any k >= 1 preserves exact argmax parity
# (the global winner is always some shard's per-shard maximum, and
# jax.lax.top_k is stable so the lowest-index occurrence of that maximum is
# always in the table); PARITY.md "Hierarchical top-k" documents the
# tie-break proof.  k = 1 is the fast path: XLA lowers top_k with k > 1
# inside the shard_map scan to a full sort of the (n_local,) scores —
# measured 2x end-to-end on the 100K-node sweep — while k = 1 stays the
# single-pass max+argmax.  Widen only for a future multi-winner selection
# that actually consumes the extra rows.
TOPK_K = 1


def make_mesh(
    n_devices: Optional[int] = None, batch: Optional[int] = None
) -> Mesh:
    """A 2-D ('batch', 'node') mesh over the first ``n_devices`` devices.

    ``batch`` defaults to 2 when the device count is even (so both axes get
    exercised), else 1.
    """
    devs = jax.devices()
    n = n_devices if n_devices is not None else len(devs)
    assert len(devs) >= n, (
        f"requested {n} devices but only {len(devs)} visible — set "
        "XLA_FLAGS=--xla_force_host_platform_device_count=N for a virtual mesh"
    )
    if batch is None:
        batch = 2 if n % 2 == 0 and n >= 2 else 1
    assert n % batch == 0, f"{n} devices not divisible by batch={batch}"
    arr = np.array(devs[:n]).reshape(batch, n // batch)
    return Mesh(arr, axis_names=("batch", "node"))


def node_shard_count(mesh: Mesh) -> int:
    """Width of the mesh's node axis — the number of home shards the
    matrix partitions rows across when this mesh is live.

    This is the number that shrinks on a shard evacuation: the
    coalescer drops its compiled entry points, rebuilds the mesh over
    the surviving devices (``make_mesh(survivors)``), and the matrix
    re-lays-out to this width (``relayout_shards``) so the sharded
    kernels' ``row_offset = shard * n_local`` arithmetic keeps every
    row owned by exactly one shard (scheduler/coalescer.py
    ``evacuate_shard`` / ``heal_shard_evacuations``).
    """
    return int(dict(zip(mesh.axis_names, mesh.devices.shape))["node"])


def stack_requests(reqs: Sequence[SchedRequest]) -> SchedRequest:
    """Stack B per-eval requests into one batched pytree (leading B axis).

    Trailing padding in the per-predicate dimensions (constraints,
    affinities, static ports, datacenters) is narrowed to the batch's
    actual maximum, pow2-bucketed so the jit cache stays bounded.  The
    per-predicate column gathers are the dominant HBM traffic of a batched
    dispatch (see kernels._check_predicate); typical jobs use 2-4 of the
    16 constraint slots, so this cuts the gather volume ~4x.
    """
    stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *reqs)

    def width(active: np.ndarray, cap: int) -> int:
        count = int(active.sum(axis=1).max()) if len(active) else 0
        return min(cap, pow2_bucket(max(1, count)))

    cw = width(stacked.c_slot >= 0, stacked.c_slot.shape[1])
    aw = width(stacked.a_slot >= 0, stacked.a_slot.shape[1])
    pw = width(stacked.p_static >= 0, stacked.p_static.shape[1])
    dw = width(stacked.dc_hash != 0, stacked.dc_hash.shape[1])
    return stacked._replace(
        c_slot=stacked.c_slot[:, :cw],
        c_op=stacked.c_op[:, :cw],
        c_hash=stacked.c_hash[:, :cw],
        c_num=stacked.c_num[:, :cw],
        a_slot=stacked.a_slot[:, :aw],
        a_op=stacked.a_op[:, :aw],
        a_hash=stacked.a_hash[:, :aw],
        a_num=stacked.a_num[:, :aw],
        a_weight=stacked.a_weight[:, :aw],
        p_static=stacked.p_static[:, :pw],
        dc_hash=stacked.dc_hash[:, :dw],
    )


def build_batch_inputs(matrix, requests: Sequence[SchedRequest]) -> dict:
    """Assemble the batched tensors ``score_batch``/``sharded_schedule_step``
    consume, for B evals with no in-flight plan state: zero TG counts and
    spread counts, no penalties, all classes eligible, no host mask.

    Shared by bench.py, __graft_entry__, and tests — the shapes (class-count
    padding in particular) must stay in sync with the kernel.
    """
    reqs = jax.tree_util.tree_map(
        jnp.asarray, stack_requests(list(requests))
    )
    b = len(requests)
    n = matrix.capacity
    pad = pow2_bucket(max(1, len(matrix.class_ids)))
    return dict(
        reqs=reqs,
        tg_counts=jnp.zeros((b, n), jnp.int32),
        spread_counts=jnp.zeros(
            (b,) + requests[0].s_value_hash.shape, jnp.float32
        ),
        penalties=jnp.zeros((b, n), bool),
        class_eligs=jnp.ones((b, pad), bool),
        host_masks=jnp.ones((b, n), bool),
    )


# PartitionSpecs for the matrix arrays: every (N, ...) leaf splits on 'node'.
_ARRAYS_SPEC = DeviceArrays(
    totals=P("node", None),
    used=P("node", None),
    eligible=P("node"),
    attr_hash=P("node", None),
    attr_num=P("node", None),
    attr_ver=P("node", None),
    class_id=P("node"),
    dev_total=P("node", None),
    dev_used=P("node", None),
    prio_used=P("node", None, None),
    port_words=P("node", None),
    dyn_used=P("node"),
)

# Batched request: every leaf has a leading B axis, replicated over 'node'.
_REQS_SPEC = SchedRequest(
    ask=P("batch", None),
    c_slot=P("batch", None),
    c_op=P("batch", None),
    c_hash=P("batch", None),
    c_num=P("batch", None),
    dc_hash=P("batch", None),
    dev_ask=P("batch", None),
    algorithm=P("batch"),
    desired_count=P("batch"),
    a_slot=P("batch", None),
    a_op=P("batch", None),
    a_hash=P("batch", None),
    a_num=P("batch", None),
    a_weight=P("batch", None),
    s_slot=P("batch", None),
    s_weight=P("batch", None),
    s_even=P("batch", None),
    s_value_hash=P("batch", None, None),
    s_desired=P("batch", None, None),
    s_implicit=P("batch", None),
    s_sum_weights=P("batch"),
    preempt_bucket=P("batch"),
    distinct_hosts=P("batch"),
    p_static=P("batch", None),
    p_dyn=P("batch"),
)


def shard_matrix_arrays(mesh: Mesh, arrays: DeviceArrays) -> DeviceArrays:
    """Lay the matrix out across the mesh's 'node' axis."""
    # zip over NamedTuple fields — PartitionSpec is itself a tuple, so
    # tree_map would wrongly recurse into it.
    return DeviceArrays(
        *(
            jax.device_put(x, NamedSharding(mesh, spec))
            for x, spec in zip(arrays, _ARRAYS_SPEC)
        )
    )


def make_sharded_row_scatter(mesh: Mesh):
    """Build the jitted dirty-row scatter into a mesh-RESIDENT matrix.

    ``scatter(device, idx, *row_data) -> DeviceArrays`` updates rows
    ``idx`` of the sharded snapshot with fresh host values; out_shardings
    pins every output leaf to the same 'node' layout, so XLA routes each
    row to the shard that owns it — the incremental alternative to
    re-laying the full matrix through ``shard_matrix_arrays`` per dispatch
    (state/matrix.py sync_sharded).  No donation: in-flight pipelined
    dispatches may still be reading the previous snapshot's buffers.
    """
    out_shardings = DeviceArrays(
        *(NamedSharding(mesh, spec) for spec in _ARRAYS_SPEC)
    )

    def scat(d, i, *vals):
        return DeviceArrays(
            **{
                f: getattr(d, f).at[i].set(v)
                for f, v in zip(DeviceArrays._fields, vals)
            }
        )

    return jax.jit(scat, out_shardings=out_shardings)


def _step_local(arrays, used, tg_counts, spread_counts, penalties, reqs,
                class_eligs, host_masks):
    """Per-shard body. Local shapes: arrays/used are (N/n, ...); batched
    inputs are (B/b, ...) with node-sized trailing dims already (N/n)."""
    n_local = used.shape[0]
    shard = jax.lax.axis_index("node")
    row_offset = shard * n_local

    def one(tg, sc, pen, req, ce, hm):
        res = score_nodes(arrays, used, tg, sc, pen, req, ce, hm)
        local_row = jnp.argmax(res.final).astype(jnp.int32)
        local_ok = res.final[local_row] > NEG_INF / 2

        # Cross-shard argmax over the node axis: one pmax for the score, one
        # to elect the owning shard's global row (ties break to highest row).
        score = jnp.where(local_ok, res.final[local_row], NEG_INF)
        best = jax.lax.pmax(score, "node")
        candidate = jnp.where(
            local_ok & (score == best), row_offset + local_row, -1
        )
        row = jax.lax.pmax(candidate, "node")
        ok = best > NEG_INF / 2
        row = jnp.where(ok, row, -1)
        win = (row >= row_offset) & (row < row_offset + n_local)
        pre = jax.lax.pmax(
            jnp.where(
                win & ok, res.needs_preempt[local_row], False
            ).astype(jnp.int32),
            "node",
        ).astype(bool)
        evaluated = jax.lax.psum(
            jnp.sum(res.feasible.astype(jnp.int32)), "node"
        )
        # Failed placements report score 0.0, matching score_batch /
        # place_task_group so consumers can aggregate without re-masking.
        return row, jnp.where(ok, best, 0.0), pre, evaluated, req.ask

    rows, scores, pre, evaluated, asks = jax.vmap(one)(
        tg_counts, spread_counts, penalties, reqs, class_eligs, host_masks
    )

    # State update (the "optimizer step"): scatter each winner's ask into
    # this shard's usage rows, then psum the deltas across the batch axis so
    # every batch replica applies every pick.
    local_rows = rows - row_offset
    mine = (local_rows >= 0) & (local_rows < n_local)
    safe = jnp.clip(local_rows, 0, n_local - 1)
    delta = jnp.zeros_like(used).at[safe].add(
        jnp.where(mine[:, None], asks, 0.0)
    )
    delta = jax.lax.psum(delta, "batch")
    return rows, scores, pre, evaluated, used + delta


def sharded_schedule_step(mesh: Mesh):
    """Build the jitted SPMD scheduling step for ``mesh``.

    Returns ``step(arrays, used, tg_counts, spread_counts, penalties, reqs,
    class_eligs, host_masks) -> (rows, scores, preempted, nodes_evaluated,
    used_after)`` — B optimistic placements plus the updated (still sharded)
    usage matrix.
    """
    fn = shard_map(
        _step_local,
        mesh=mesh,
        in_specs=(
            _ARRAYS_SPEC,
            P("node", None),  # used
            P("batch", "node"),  # tg_counts
            P("batch", None, None),  # spread_counts
            P("batch", "node"),  # penalties
            _REQS_SPEC,
            P("batch", None),  # class_eligs
            P("batch", "node"),  # host_masks
        ),
        out_specs=(
            P("batch"),
            P("batch"),
            P("batch"),
            P("batch"),
            P("node", None),
        ),
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Sharded dispatch-coalescer kernel (the LIVE multi-chip path)
# ---------------------------------------------------------------------------


def _place_batch_local(
    arrays, used, delta_rows, delta_vals, tg_counts, spread_counts,
    penalties, reqs, class_eligs, host_masks, n_placements,
):
    """Per-shard body of the coalescer's ``place_batch`` (ops/kernels.py:659)
    under a ('batch', 'node') mesh: each shard scores its own node rows, the
    per-placement argmax crosses shards over ICI (pmax score + pmin row, so
    ties break to the lowest global row exactly like the single-device
    ``jnp.argmax``), and the winning shard alone applies the usage/tg-count
    update.  Spread-count updates need the winning node's attribute values,
    which live on one shard — the owner broadcasts them with a psum.
    """
    n_local = used.shape[0]
    shard = jax.lax.axis_index("node")
    row_offset = shard * n_local
    big = jnp.int32(2 ** 30)

    def one(drows, dvals, tg, sc, pen, req, ce, hm):
        # Sparse in-flight plan deltas arrive as GLOBAL rows; each shard
        # applies the slice it owns.
        local = drows - row_offset
        mine = (drows >= 0) & (local >= 0) & (local < n_local)
        safe = jnp.clip(local, 0, n_local - 1)
        used0 = used.at[safe].add(jnp.where(mine[:, None], dvals, 0.0))

        def step(carry, _):
            u, tg_cnt, s_hash, s_counts = carry
            req_step = req._replace(s_value_hash=s_hash)
            res = score_nodes(
                arrays, u, tg_cnt, s_counts, pen, req_step, ce, hm
            )
            lrow = jnp.argmax(res.final).astype(jnp.int32)
            lok = res.final[lrow] > NEG_INF / 2
            score = jnp.where(lok, res.final[lrow], NEG_INF)
            best = jax.lax.pmax(score, "node")
            cand = jnp.where(
                lok & (score == best), row_offset + lrow, big
            )
            grow = jax.lax.pmin(cand, "node")  # lowest row wins ties
            ok = best > NEG_INF / 2
            grow = jnp.where(ok, grow, -1)
            owner = ok & (grow >= row_offset) & (grow < row_offset + n_local)
            lwin = jnp.clip(grow - row_offset, 0, n_local - 1)

            n_eval = jax.lax.psum(
                jnp.sum(res.feasible.astype(jnp.int32)), "node"
            )
            n_filt = jax.lax.psum(
                jnp.sum((~res.feasible & arrays.eligible).astype(jnp.int32)),
                "node",
            )
            n_exh = jax.lax.psum(
                jnp.sum((res.feasible & ~res.fits).astype(jnp.int32)), "node"
            )

            u2 = jnp.where(owner, u.at[lwin].add(req.ask), u)
            tg2 = jnp.where(owner, tg_cnt.at[lwin].add(1), tg_cnt)

            # Winning node's per-stanza attr values: owner computes, psum
            # broadcasts (hash 0 = "no value", so non-owners contribute 0).
            nvals = jnp.where(
                owner, spread_values_at(arrays, req_step, lwin), 0
            )
            nvals = jax.lax.psum(nvals, "node")
            new_hash, new_counts = apply_spread_values(
                s_counts, req_step, nvals
            )
            s_hash2 = jnp.where(ok, new_hash, s_hash)
            s_counts2 = jnp.where(ok, new_counts, s_counts)

            binp = jax.lax.psum(
                jnp.where(owner, res.binpack[lwin], 0.0), "node"
            )
            pre = jax.lax.pmax(
                jnp.where(
                    owner, res.needs_preempt[lwin], False
                ).astype(jnp.int32),
                "node",
            ).astype(bool)
            out = (
                grow,
                jnp.where(ok, best, 0.0),
                jnp.where(ok, binp, 0.0),
                pre & ok,
                n_eval,
                n_filt,
                n_exh,
            )
            return (u2, tg2, s_hash2, s_counts2), out

        init = (used0, tg, req.s_value_hash, sc)
        _, outs = jax.lax.scan(step, init, None, length=n_placements)
        rows, scores, binpack, pre, ne, nf, nx = outs
        return jnp.stack(
            [
                rows.astype(jnp.float32),
                scores,
                binpack,
                pre.astype(jnp.float32),
                ne.astype(jnp.float32),
                nf.astype(jnp.float32),
                nx.astype(jnp.float32),
            ],
            axis=1,
        )  # (P, 7) — kernels.PACKED_* layout

    return jax.vmap(one)(
        delta_rows, delta_vals, tg_counts, spread_counts, penalties, reqs,
        class_eligs, host_masks,
    )


def sharded_place_batch(mesh: Mesh, n_placements: int):
    """Build the jitted SPMD twin of ``kernels.place_batch`` for ``mesh``.

    Same signature and packed (B, P, PACKED_WIDTH) result as the unsharded
    kernel, so the dispatch coalescer swaps it in transparently when the
    server runs on a multi-chip slice (scheduler/coalescer.py).  Placement
    parity with the single-device kernel is exact (tie-breaks included) —
    tests/test_parallel.py asserts it.
    """
    fn = shard_map(
        functools.partial(_place_batch_local, n_placements=n_placements),
        mesh=mesh,
        in_specs=(
            _ARRAYS_SPEC,
            P("node", None),  # used
            P("batch", None),  # delta_rows (global ids, replicated on node)
            P("batch", None, None),  # delta_vals
            P("batch", "node"),  # tg_counts
            P("batch", None, None),  # spread_counts
            P("batch", "node"),  # penalties
            _REQS_SPEC,
            P("batch", None),  # class_eligs
            P("batch", "node"),  # host_masks
        ),
        out_specs=P("batch", None, None),
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Sharded FUSED megakernel (hierarchical top-k + sharded AllocsFit verify)
# ---------------------------------------------------------------------------


def _fused_place_batch_local(
    arrays, used, delta_rows, delta_vals, tg_counts, spread_counts,
    penalties, reqs, class_eligs, host_masks, lane_mask, n_placements,
    features,
):
    """Per-shard body of ``kernels.fused_place_batch`` under a
    ('batch', 'node') mesh — the full megakernel (ranking scan + cross-lane
    AllocsFit re-verify) with the node axis partitioned.

    Ranking is a hierarchical top-k: each shard scores only its local node
    slice and contributes its ``k = min(TOPK_K, n_local)`` best rows via one
    ``all_gather`` over ICI, producing a tiny (shards, k) candidate table
    replicated on every shard.  The global winner is the table's max score,
    ties broken to the LOWEST global row — ``jax.lax.top_k`` is stable
    (lower index first on ties), so the per-shard maximum's lowest local
    occurrence is always in the table and the min-over-ties selection
    reproduces the single-device ``jnp.argmax`` bit-for-bit (PARITY.md
    "Hierarchical top-k").  No (B, N) score tensor ever exists globally:
    per-shard intermediates are (n_local,) and everything crossing the
    interconnect or reaching the host is O(B · P) or (shards, k).

    The cross-lane verify gathers only winner rows + asks + in-flight
    deltas over the batch axis (all O(B · P), node-shape-free), scans all B
    lanes against the LOCAL (n_local, 3) usage slice with non-owned rows
    vacuously fitting, and combines verdicts with a single ``pmin`` over
    the node axis — each row's owner alone decides.
    """
    n_local = used.shape[0]
    shard = jax.lax.axis_index("node")
    row_offset = shard * n_local
    big = jnp.int32(2 ** 30)
    k = min(TOPK_K, n_local)

    def one(drows, dvals, tg, sc, pen, req, ce, hm):
        local = drows - row_offset
        mine = (drows >= 0) & (local >= 0) & (local < n_local)
        safe = jnp.clip(local, 0, n_local - 1)
        used0 = used.at[safe].add(jnp.where(mine[:, None], dvals, 0.0))

        def step(carry, _):
            u, tg_cnt, s_hash, s_counts = carry
            req_step = req._replace(s_value_hash=s_hash)
            res = score_nodes(
                arrays, u, tg_cnt, s_counts, pen, req_step, ce, hm,
                features=features,
            )
            # Hierarchical top-k: (n_local,) -> per-shard (k,) candidates,
            # then a cross-shard reduce of the implicit (shards, k) table —
            # pmax elects the winning score, pmin the lowest owning row.
            vals, idxs = jax.lax.top_k(res.final, k)
            best = jax.lax.pmax(vals[0], "node")
            ok = best > NEG_INF / 2
            cand = jnp.where(
                vals == best, row_offset + idxs.astype(jnp.int32), big
            )
            grow = jax.lax.pmin(jnp.min(cand), "node")  # lowest row on ties
            grow = jnp.where(ok, grow, -1)
            owner = ok & (grow >= row_offset) & (grow < row_offset + n_local)
            lwin = jnp.clip(grow - row_offset, 0, n_local - 1)

            n_eval = jax.lax.psum(
                jnp.sum(res.feasible.astype(jnp.int32)), "node"
            )
            n_filt = jax.lax.psum(
                jnp.sum((~res.feasible & arrays.eligible).astype(jnp.int32)),
                "node",
            )
            n_exh = jax.lax.psum(
                jnp.sum((res.feasible & ~res.fits).astype(jnp.int32)), "node"
            )

            u2 = jnp.where(owner, u.at[lwin].add(req.ask), u)
            tg2 = jnp.where(owner, tg_cnt.at[lwin].add(1), tg_cnt)

            nvals = jnp.where(
                owner, spread_values_at(arrays, req_step, lwin), 0
            )
            nvals = jax.lax.psum(nvals, "node")
            new_hash, new_counts = apply_spread_values(
                s_counts, req_step, nvals
            )
            s_hash2 = jnp.where(ok, new_hash, s_hash)
            s_counts2 = jnp.where(ok, new_counts, s_counts)

            binp = jax.lax.psum(
                jnp.where(owner, res.binpack[lwin], 0.0), "node"
            )
            pre = jax.lax.pmax(
                jnp.where(
                    owner, res.needs_preempt[lwin], False
                ).astype(jnp.int32),
                "node",
            ).astype(bool)
            out = (
                grow,
                jnp.where(ok, best, 0.0),
                jnp.where(ok, binp, 0.0),
                pre & ok,
                n_eval,
                n_filt,
                n_exh,
            )
            return (u2, tg2, s_hash2, s_counts2), out

        init = (used0, tg, req.s_value_hash, sc)
        _, outs = jax.lax.scan(step, init, None, length=n_placements)
        return outs  # each (P,)

    rows, scores, binpack, pre, ne, nf, nx = jax.vmap(one)(
        delta_rows, delta_vals, tg_counts, spread_counts, penalties, reqs,
        class_eligs, host_masks,
    )
    live = lane_mask  # (b_local,)
    rows = jnp.where(live[:, None], rows, -1)  # (b_local, P)

    # Cross-lane AllocsFit re-verify, sharded: every tensor gathered over
    # the batch axis is winner-row-shaped — (B, P) rows, (B, 3) asks,
    # (B, K) / (B, K, 3) in-flight deltas, (B,) liveness — never node-axis
    # shaped.  Each node shard then replays all B lanes in resolve order
    # against its local (n_local, 3) usage slice; rows it does not own fit
    # vacuously, and one pmin over 'node' lets each row's owner veto.
    g_rows = jax.lax.all_gather(rows, "batch", tiled=True)  # (B, P)
    g_ask = jax.lax.all_gather(reqs.ask, "batch", tiled=True)  # (B, 3)
    g_drows = jax.lax.all_gather(delta_rows, "batch", tiled=True)  # (B, K)
    g_dvals = jax.lax.all_gather(delta_vals, "batch", tiled=True)
    g_live = jax.lax.all_gather(live, "batch", tiled=True)  # (B,)

    def lane_step(cum_used, lane):
        l_rows, l_ask, l_drows, l_dvals, l_live = lane
        l_local = l_drows - row_offset
        l_mine = (
            (l_drows >= 0) & (l_local >= 0) & (l_local < n_local) & l_live
        )
        l_safe = jnp.clip(l_local, 0, n_local - 1)
        base = cum_used.at[l_safe].add(
            jnp.where(l_mine[:, None], l_dvals, 0.0)
        )

        def p_step(u, row):
            p_local = row - row_offset
            p_mine = (
                (row >= 0) & (p_local >= 0) & (p_local < n_local) & l_live
            )
            p_safe = jnp.clip(p_local, 0, n_local - 1)
            u2 = u.at[p_safe].add(jnp.where(p_mine, l_ask, 0.0))
            fit = jnp.all(u2[p_safe] <= arrays.totals[p_safe]) | ~p_mine
            return u2, fit

        after, fits = jax.lax.scan(p_step, base, l_rows)
        return jnp.where(l_live, after, cum_used), fits

    _, fits_all = jax.lax.scan(
        lane_step, used, (g_rows, g_ask, g_drows, g_dvals, g_live)
    )  # (B, P) bool, identical on every node shard only after the pmin:
    verified = jax.lax.pmin(fits_all.astype(jnp.int32), "node")  # (B, P)

    b_local = rows.shape[0]
    b_idx = jax.lax.axis_index("batch")
    v_local = jax.lax.dynamic_slice_in_dim(
        verified, b_idx * b_local, b_local, axis=0
    )  # (b_local, P)
    return pack_fused_lanes(
        rows, scores, binpack, pre, ne, nf, nx, v_local, live
    )


def sharded_fused_place_batch(mesh: Mesh, n_placements: int):
    """Build the jitted SPMD twin of ``kernels.fused_place_batch``.

    Same signature (``features`` keyword-static) and packed
    (B, P, FUSED_PACKED_WIDTH) result as the single-device fused kernel —
    the dispatch coalescer swaps it in when a mesh is configured and
    ``NOMAD_TPU_SHARDED_MEGABATCH`` is not disabled.  Placement AND
    verify-column parity with the unsharded kernel is exact (tie-breaks
    included) — tests/test_parallel.py asserts it across shard counts.
    """

    def entry(
        arrays, used, delta_rows, delta_vals, tg_counts, spread_counts,
        penalties, reqs, class_eligs, host_masks, lane_mask, *,
        features=FULL_FEATURES,
    ):
        fn = shard_map(
            functools.partial(
                _fused_place_batch_local,
                n_placements=n_placements,
                features=features,
            ),
            mesh=mesh,
            in_specs=(
                _ARRAYS_SPEC,
                P("node", None),  # used
                P("batch", None),  # delta_rows (global ids)
                P("batch", None, None),  # delta_vals
                P("batch", "node"),  # tg_counts
                P("batch", None, None),  # spread_counts
                P("batch", "node"),  # penalties
                _REQS_SPEC,
                P("batch", None),  # class_eligs
                P("batch", "node"),  # host_masks
                P("batch"),  # lane_mask
            ),
            out_specs=P("batch", None, None),
        )
        return fn(
            arrays, used, delta_rows, delta_vals, tg_counts, spread_counts,
            penalties, reqs, class_eligs, host_masks, lane_mask,
        )

    return jax.jit(entry, static_argnames=("features",))
