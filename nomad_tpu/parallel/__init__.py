"""Multi-chip SPMD scheduling — the node matrix sharded over a device mesh.

SURVEY.md §2.5/§5: the reference's scale axis is nodes×allocs; it *bounds*
per-eval work (shuffle + log₂(n) candidates) and scales via optimistic worker
concurrency. This package inverts that: the (nodes × resource-dims) matrix is
sharded across TPU devices with ``jax.sharding``, every eval scores ALL nodes,
and the cross-device argmax/psum reductions ride ICI.
"""

from .sharding import (
    build_batch_inputs,
    make_mesh,
    shard_matrix_arrays,
    sharded_fused_place_batch,
    sharded_place_batch,
    sharded_schedule_step,
    stack_requests,
)

__all__ = [
    "build_batch_inputs",
    "make_mesh",
    "shard_matrix_arrays",
    "sharded_fused_place_batch",
    "sharded_place_batch",
    "sharded_schedule_step",
    "stack_requests",
]
