"""Task drivers — the pluggable execution boundary.

Reference: the driver plugin protocol (``plugins/drivers/driver.go:47-65``):
Fingerprint, StartTask, WaitTask, StopTask, DestroyTask, RecoverTask,
InspectTask. The reference isolates drivers behind a gRPC process boundary
(go-plugin); here the protocol is the same Python interface, with the C++
executor slotting underneath the exec driver (SURVEY.md §2.4 mapping).

Two built-ins:

- ``MockDriver`` — fully scriptable fake (reference: ``drivers/mock/``,
  the cornerstone of client/integration testing): start errors, run_for,
  exit codes, kill_after, start_block_for.
- ``RawExecDriver`` — un-isolated subprocess execution (reference:
  ``drivers/rawexec/``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from .. import trace
from ..chaos import inject
from ..retry import RetryBudgetExceeded, RetryPolicy, retry_call
from ..structs.types import Task


@dataclass
class ExitResult:
    exit_code: int = 0
    signal: int = 0
    err: str = ""
    oom_killed: bool = False

    def successful(self) -> bool:
        return self.exit_code == 0 and self.signal == 0 and not self.err


@dataclass
class TaskHandle:
    """Opaque, re-attachable handle to a running task (reference:
    drivers.TaskHandle — persisted so RecoverTask can re-attach after an
    agent restart)."""

    id: str
    driver: str
    task_name: str
    alloc_id: str
    config: Dict[str, Any] = field(default_factory=dict)
    pid: int = 0
    started_at: float = 0.0


class DriverError(Exception):
    pass


def _chaos(point: str, driver: str, task: str):
    """Driver-seam chaos hook.  "hang" (a wedged runtime syscall) is
    absorbed here as a sleep; "error" raises; anything else — "exit127"
    at start, "wedge" at wait, "skip" at stop — is returned for the
    caller to act on, since only it can fabricate the right outcome."""
    fault = inject(point, driver=driver, task=task)
    trace.event("seam." + point, driver=driver, task=task)
    if fault is None:
        return None
    if fault.kind == "hang":
        time.sleep(fault.duration or 1.0)
        return None
    if fault.kind == "error":
        raise DriverError(f"injected {point} failure")
    return fault


class Driver:
    """Base driver interface."""

    name = "driver"

    def fingerprint(self) -> Dict[str, str]:
        """Attributes to merge into the node (driver.X detected/healthy)."""
        return {f"driver.{self.name}": "1"}

    def start_task(self, handle: TaskHandle, task: Task, task_dir: str) -> None:
        raise NotImplementedError

    def wait_task(self, handle: TaskHandle, timeout: Optional[float] = None) -> Optional[ExitResult]:
        raise NotImplementedError

    def stop_task(self, handle: TaskHandle, kill_timeout: float) -> None:
        raise NotImplementedError

    def destroy_task(self, handle: TaskHandle) -> None:
        pass

    def recover_task(self, handle: TaskHandle) -> bool:
        """Re-attach to a still-running task after agent restart
        (driver.go:54). Returns False when the task is gone."""
        return False

    def inspect_task(self, handle: TaskHandle) -> str:
        return "unknown"

    def signal_task(self, handle: TaskHandle, sig: int) -> None:
        """Deliver a signal to the running task (Driver.SignalTask,
        plugins/drivers/driver.go)."""
        raise DriverError(f"{self.name} driver does not support signals")

    def stats_task(self, handle: TaskHandle) -> Dict[str, Any]:
        """Point-in-time resource usage (TaskStats; the reference streams
        these, plugins/drivers driver.proto).  Empty dict = unsupported."""
        return {}


class _MockInstance:
    def __init__(self):
        self.done = threading.Event()
        self.result: Optional[ExitResult] = None
        self.timer: Optional[threading.Timer] = None


class MockDriver(Driver):
    """Scriptable fake driver. Task ``config`` knobs (reference:
    drivers/mock/driver.go:74-80):

    - ``start_error``: error message raised from start_task
    - ``start_error_recoverable``: marks the error recoverable
    - ``start_block_for``: seconds start_task blocks before returning
    - ``run_for``: seconds the task runs before exiting
    - ``exit_code`` / ``exit_signal`` / ``exit_err_msg``
    - ``kill_after``: seconds to keep running after a stop request
    """

    name = "mock"

    def __init__(self):
        self._instances: Dict[str, _MockInstance] = {}
        self._lock = threading.Lock()

    def start_task(self, handle: TaskHandle, task: Task, task_dir: str) -> None:
        cfg = task.config or {}
        fault = _chaos("driver.start", self.name, task.name)
        if fault is not None and fault.kind == "exit127":
            # Command-not-found at exec time: starts "successfully", then
            # the child exits 127 immediately.
            cfg = dict(cfg, run_for=0, exit_code=127)
        if cfg.get("start_error"):
            raise DriverError(str(cfg["start_error"]))
        block = float(cfg.get("start_block_for", 0))
        if block:
            time.sleep(block)
        inst = _MockInstance()
        with self._lock:
            self._instances[handle.id] = inst
        run_for = float(cfg.get("run_for", 0))
        result = ExitResult(
            exit_code=int(cfg.get("exit_code", 0)),
            signal=int(cfg.get("exit_signal", 0)),
            err=str(cfg.get("exit_err_msg", "")),
        )

        def finish():
            inst.result = result
            inst.done.set()

        if run_for > 0:
            inst.timer = threading.Timer(run_for, finish)
            inst.timer.daemon = True
            inst.timer.start()
        elif run_for == 0 and "run_for" in cfg:
            finish()  # exits immediately
        # run_for unset -> runs until stopped
        handle.pid = os.getpid()
        handle.started_at = time.time()
        handle.config = dict(cfg)

    def wait_task(self, handle: TaskHandle, timeout: Optional[float] = None):
        fault = _chaos("driver.wait", self.name, handle.task_name)
        if fault is not None and fault.kind == "wedge":
            # Wedged driver: never reports the exit, only "still running".
            if timeout:
                time.sleep(min(timeout, 0.05))
            return None
        inst = self._instances.get(handle.id)
        if inst is None:
            return ExitResult(err="unknown task")
        if not inst.done.wait(timeout=timeout):
            return None
        return inst.result

    def stop_task(self, handle: TaskHandle, kill_timeout: float) -> None:
        fault = _chaos("driver.stop", self.name, handle.task_name)
        if fault is not None and fault.kind == "skip":
            return  # stop request swallowed by a wedged runtime
        inst = self._instances.get(handle.id)
        if inst is None:
            return
        kill_after = float(handle.config.get("kill_after", 0))
        delay = min(kill_after, kill_timeout) if kill_after else 0.0

        def finish():
            inst.result = ExitResult(exit_code=0, signal=9)
            inst.done.set()

        if delay > 0:
            t = threading.Timer(delay, finish)
            t.daemon = True
            t.start()
        else:
            finish()

    def destroy_task(self, handle: TaskHandle) -> None:
        with self._lock:
            inst = self._instances.pop(handle.id, None)
        if inst and inst.timer:
            inst.timer.cancel()

    def recover_task(self, handle: TaskHandle) -> bool:
        # In-process driver: instances die with the agent, like a container
        # runtime losing its containers on host reboot.
        return handle.id in self._instances

    def inspect_task(self, handle: TaskHandle) -> str:
        inst = self._instances.get(handle.id)
        if inst is None:
            return "unknown"
        return "exited" if inst.done.is_set() else "running"

    def signal_task(self, handle: TaskHandle, sig: int) -> None:
        handle.config.setdefault("signals_received", []).append(int(sig))


class RawExecDriver(Driver):
    """Un-isolated subprocess execution (reference: drivers/rawexec/).

    Task config: ``command`` (required), ``args`` (list). The C++ executor
    supervisor (nomad_tpu native runtime) slots under this same interface.
    """

    name = "raw_exec"

    def __init__(self):
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def start_task(self, handle: TaskHandle, task: Task, task_dir: str) -> None:
        cfg = task.config or {}
        command = cfg.get("command")
        if not command:
            raise DriverError("raw_exec requires config.command")
        args = [str(command)] + [str(a) for a in cfg.get("args", [])]
        fault = _chaos("driver.start", self.name, task.name)
        if fault is not None and fault.kind == "exit127":
            args = ["/bin/sh", "-c", "exit 127"]  # command-not-found
        stdout = stderr = None
        try:
            stdout = open(os.path.join(task_dir, f"{task.name}.stdout"), "ab")
            stderr = open(os.path.join(task_dir, f"{task.name}.stderr"), "ab")
        except OSError as exc:
            # The alloc dir can vanish mid-restart (destroy racing the
            # restart loop) — a start failure, not an agent crash.
            if stdout is not None:
                stdout.close()
            raise DriverError(f"task dir unavailable: {exc}") from exc
        env = dict(os.environ)
        env.update({k: str(v) for k, v in (task.env or {}).items()})
        try:
            proc = subprocess.Popen(
                args, cwd=task_dir, stdout=stdout, stderr=stderr, env=env,
                start_new_session=True,
            )
        except OSError as exc:
            raise DriverError(str(exc)) from exc
        finally:
            stdout.close()
            stderr.close()
        with self._lock:
            self._procs[handle.id] = proc
        handle.pid = proc.pid
        handle.started_at = time.time()

    def wait_task(self, handle: TaskHandle, timeout: Optional[float] = None):
        fault = _chaos("driver.wait", self.name, handle.task_name)
        if fault is not None and fault.kind == "wedge":
            if timeout:
                time.sleep(min(timeout, 0.05))
            return None
        proc = self._procs.get(handle.id)
        if proc is None:
            return ExitResult(err="unknown task")
        try:
            code = proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None
        if code < 0:
            return ExitResult(exit_code=0, signal=-code)
        return ExitResult(exit_code=code)

    def stop_task(self, handle: TaskHandle, kill_timeout: float) -> None:
        proc = self._procs.get(handle.id)
        if proc is None or proc.poll() is not None:
            return
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return

        def hard_kill():
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass

        t = threading.Timer(kill_timeout, hard_kill)
        t.daemon = True
        t.start()

    def destroy_task(self, handle: TaskHandle) -> None:
        proc = self._procs.pop(handle.id, None)
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    def signal_task(self, handle: TaskHandle, sig: int) -> None:
        proc = self._procs.get(handle.id)
        pid = proc.pid if proc is not None else handle.pid
        if not pid:
            raise DriverError("task has no pid")
        try:
            os.killpg(pid, sig)
        except (ProcessLookupError, PermissionError):
            try:
                os.kill(pid, sig)
            except OSError as exc:
                raise DriverError(str(exc)) from exc

    def stats_task(self, handle: TaskHandle) -> Dict[str, Any]:
        from .executor import _group_usage

        proc = self._procs.get(handle.id)
        pid = proc.pid if proc is not None else handle.pid
        if not pid:
            return {}
        rss, ticks = _group_usage(pid)
        return {"rss_bytes": rss, "cpu_ticks": ticks, "pid": pid}

    def recover_task(self, handle: TaskHandle) -> bool:
        """Re-attach after an agent restart: the task process is no longer
        our child (we cannot waitpid it), so supervision resumes through a
        kill-0 polling shim — the same technique the reference's executor
        uses for its pre-0.9 recovery shims (drivers/shared/executor)."""
        if handle.id in self._procs:
            return True
        if handle.pid:
            try:
                os.kill(handle.pid, 0)
            except (ProcessLookupError, PermissionError):
                return False
            with self._lock:
                self._procs[handle.id] = _ReattachedProc(handle.pid)
            return True
        return False

    def inspect_task(self, handle: TaskHandle) -> str:
        proc = self._procs.get(handle.id)
        if proc is None:
            return "unknown"
        return "running" if proc.poll() is None else "exited"


class _ReattachedProc:
    """Popen-shaped supervision of a non-child process (recovery path).

    The exit *status* of a non-child is unobservable; disappearance is
    reported as exit 0 with a marker in ``err`` left to the caller.
    """

    def __init__(self, pid: int):
        self.pid = pid
        self._code: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self._code is not None:
            return self._code
        try:
            os.kill(self.pid, 0)
            return None
        except (ProcessLookupError, PermissionError):
            self._code = 0  # status unobservable for a non-child
            return self._code

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            code = self.poll()
            if code is not None:
                return code
            if deadline is not None and time.time() >= deadline:
                raise subprocess.TimeoutExpired(f"pid {self.pid}", timeout)
            time.sleep(0.05)


class SidecarClient:
    """Handle to one executor sidecar process (client/executor.py).

    The go-plugin analog: spawn a detached supervisor subprocess, talk
    JSON-lines over its unix socket, and — when the sidecar is found dead
    — spawn a replacement and hand it the dead one's task table to
    recover by pid (reattach-config semantics)."""

    def __init__(self, state_dir: str, binary: Optional[str] = None):
        self.state_dir = state_dir
        self.sock_path = os.path.join(state_dir, "executor.sock")
        self.state_path = os.path.join(state_dir, "executor.state.json")
        # Explicit supervisor binary (external driver plugins); None =
        # auto (native/nomad-executor when built, Python fallback).
        self.binary = binary
        self._lock = threading.Lock()
        self._proc: Optional[subprocess.Popen] = None

    # -- wire -----------------------------------------------------------

    def _call_raw(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        import socket as _socket

        with _socket.socket(_socket.AF_UNIX) as s:
            s.settimeout(30.0)
            s.connect(self.sock_path)
            s.sendall((json.dumps(payload) + "\n").encode())
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
        out = json.loads(buf)
        if out.get("error"):
            raise DriverError(out["error"])
        return out

    def call(self, op: str, **kw) -> Dict[str, Any]:
        """One sidecar op; a dead sidecar is replaced (and its tasks
        recovered) transparently — EXCEPT for ``start``, which is not
        idempotent: a lost start response retried against a respawned
        sidecar could launch the task twice (the first copy running
        unsupervised).  Start failures surface to the restart policy."""
        kw["op"] = op
        with self._lock:
            try:
                return self._call_raw(kw)
            except (OSError, ValueError) as exc:
                if op == "start":
                    raise DriverError(
                        f"sidecar start failed/indeterminate: {exc}"
                    ) from exc
                self._respawn_locked()
                return self._call_raw(kw)

    def ensure_running(self) -> None:
        with self._lock:
            try:
                self._call_raw({"op": "ping"})
            except (OSError, ValueError):
                self._respawn_locked()

    def _respawn_locked(self) -> None:
        # Read the DEAD sidecar's task table BEFORE the replacement
        # truncates the state file.
        orphans: Dict[str, Dict[str, Any]] = {}
        try:
            with open(self.state_path) as fh:
                orphans = (json.loads(fh.read()) or {}).get("tasks", {})
        except (OSError, ValueError):
            pass
        os.makedirs(self.state_dir, exist_ok=True)
        import sys

        # The native C++ supervisor (native/executor.cc) speaks the same
        # protocol and is preferred when built; the Python sidecar is the
        # always-available fallback.  NOMAD_TPU_EXECUTOR_BIN overrides
        # (empty string forces Python).
        native = (
            self.binary if self.binary is not None
            else os.environ.get("NOMAD_TPU_EXECUTOR_BIN")
        )
        if native is None:
            candidate = os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))
                )),
                "native", "nomad-executor",
            )
            native = candidate if os.access(candidate, os.X_OK) else ""
        if native:
            cmd = [native, "--socket", self.sock_path,
                   "--state-dir", self.state_dir]
        else:
            cmd = [sys.executable, "-m", "nomad_tpu.client.executor",
                   "--socket", self.sock_path,
                   "--state-dir", self.state_dir]
        self._proc = subprocess.Popen(
            cmd,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            start_new_session=True,  # survives the agent
        )
        try:
            retry_call(
                lambda: self._call_raw({"op": "ping"}),
                policy=RetryPolicy(
                    base_delay=0.05, max_delay=0.5, deadline=15.0
                ),
                retry_on=(OSError, ValueError),
                description="executor sidecar boot ping",
            )
        except RetryBudgetExceeded as exc:
            raise DriverError(
                f"executor sidecar failed to start: {exc.__cause__}"
            ) from exc
        # Recover the orphaned (setsid'd, still-running) tasks by pid.
        for tid, info in orphans.items():
            try:
                self._call_raw({
                    "op": "recover", "id": tid,
                    "pid": info["pid"], "start_ts": info.get("start_ts", 0),
                })
            except (OSError, ValueError, DriverError):
                pass

    def shutdown(self) -> None:
        try:
            with self._lock:
                self._call_raw({"op": "shutdown"})
        except (OSError, ValueError):
            pass


class ExecDriver(Driver):
    """Isolated subprocess execution through the executor sidecar
    (reference: drivers/exec/ over drivers/shared/executor/ — trimmed to
    the no-privilege isolations: setsid, rlimits, best-effort cgroup v2).

    Task config: ``command`` (required), ``args``, ``rlimits`` (map of
    cpu/nofile/as/fsize/nproc → soft+hard value), ``cgroup`` (bool).
    """

    name = "exec"
    # Subdir of the client data dir holding this driver's sidecar state;
    # None binary = auto-select (native build, Python fallback).
    sidecar_subdir = "executor"
    binary: Optional[str] = None

    def __init__(self, state_dir: str = ""):
        self._state_dir = state_dir
        self._sidecar: Optional[SidecarClient] = None
        self._lock = threading.Lock()

    def _get_sidecar(self, state_dir: str = "") -> SidecarClient:
        with self._lock:
            if self._sidecar is None:
                sd = self._state_dir or state_dir
                if not sd:
                    raise DriverError(
                        f"{self.name} driver has no state dir yet"
                    )
                self._state_dir = sd
                self._sidecar = SidecarClient(
                    os.path.join(sd, self.sidecar_subdir),
                    binary=self.binary,
                )
                self._sidecar.ensure_running()
            return self._sidecar

    def start_task(self, handle: TaskHandle, task: Task, task_dir: str) -> None:
        cfg = task.config or {}
        command = cfg.get("command")
        if not command:
            raise DriverError("exec requires config.command")
        # The sidecar outlives agent restarts; the handle carries the
        # state dir so recover_task can find it again.
        state_dir = os.path.dirname(os.path.dirname(task_dir))
        handle.config = {"state_dir": state_dir}
        sidecar = self._get_sidecar(state_dir)
        # Preflight: a dead sidecar respawns HERE (idempotent ping), so
        # the non-retryable start below runs against a live one.
        sidecar.ensure_running()
        env = dict(os.environ)
        env.update({k: str(v) for k, v in (task.env or {}).items()})
        argv = [str(command)] + [str(a) for a in cfg.get("args", [])]
        fault = _chaos("driver.start", self.name, task.name)
        if fault is not None and fault.kind == "exit127":
            argv = ["/bin/sh", "-c", "exit 127"]  # command-not-found
        try:
            out = sidecar.call(
                "start",
                id=handle.id,
                argv=argv,
                cwd=task_dir,
                env=env,
                stdout=os.path.join(task_dir, f"{task.name}.stdout"),
                stderr=os.path.join(task_dir, f"{task.name}.stderr"),
                rlimits=cfg.get("rlimits") or {},
                cgroup=bool(cfg.get("cgroup", True)),
            )
        except DriverError:
            raise
        except OSError as exc:
            raise DriverError(f"sidecar unavailable: {exc}") from exc
        handle.pid = int(out["pid"])
        handle.started_at = float(out["start_ts"])

    def wait_task(self, handle: TaskHandle, timeout: Optional[float] = None):
        fault = _chaos("driver.wait", self.name, handle.task_name)
        if fault is not None and fault.kind == "wedge":
            if timeout:
                time.sleep(min(timeout, 0.05))
            return None
        sidecar = self._get_sidecar(handle.config.get("state_dir", ""))
        deadline = None if timeout is None else time.time() + timeout
        while True:
            try:
                out = sidecar.call("wait", id=handle.id)
            except (DriverError, OSError) as exc:
                return ExitResult(err=f"sidecar lost task: {exc}")
            if not out.get("running"):
                return ExitResult(
                    exit_code=int(out.get("exit_code", 0)),
                    signal=int(out.get("signal", 0)),
                )
            if deadline is not None and time.time() >= deadline:
                return None
            time.sleep(0.05)

    def stop_task(self, handle: TaskHandle, kill_timeout: float) -> None:
        try:
            self._get_sidecar(handle.config.get("state_dir", "")).call(
                "stop", id=handle.id, grace=kill_timeout
            )
        except (DriverError, OSError):
            pass

    def destroy_task(self, handle: TaskHandle) -> None:
        try:
            self._get_sidecar(handle.config.get("state_dir", "")).call(
                "destroy", id=handle.id
            )
        except (DriverError, OSError):
            pass

    def recover_task(self, handle: TaskHandle) -> bool:
        """Agent restart: the sidecar (and the task) kept running.  If the
        sidecar still supervises the task, done; if the sidecar died too,
        the respawn path re-adopts the task by pid."""
        state_dir = handle.config.get("state_dir", "")
        if not state_dir:
            return False
        try:
            sidecar = self._get_sidecar(state_dir)
            out = sidecar.call("list")
            info = out.get("tasks", {}).get(handle.id)
            if info is not None:
                return bool(info.get("running"))
            if handle.pid and os.path.exists(f"/proc/{handle.pid}"):
                got = sidecar.call(
                    "recover", id=handle.id, pid=handle.pid,
                    start_ts=handle.started_at,
                )
                return bool(got.get("ok"))
        except (DriverError, OSError):
            return False
        return False

    def inspect_task(self, handle: TaskHandle) -> str:
        try:
            out = self._get_sidecar(
                handle.config.get("state_dir", "")
            ).call("wait", id=handle.id)
            return "running" if out.get("running") else "exited"
        except (DriverError, OSError):
            return "unknown"

    def signal_task(self, handle: TaskHandle, sig: int) -> None:
        try:
            self._get_sidecar(handle.config.get("state_dir", "")).call(
                "signal", id=handle.id, signal=int(sig)
            )
        except OSError as exc:
            raise DriverError(str(exc)) from exc

    def stats_task(self, handle: TaskHandle) -> Dict[str, Any]:
        try:
            out = self._get_sidecar(
                handle.config.get("state_dir", "")
            ).call("stats", id=handle.id)
        except (DriverError, OSError):
            return {}
        return {
            k: out[k] for k in ("rss_bytes", "cpu_ticks", "pid")
            if k in out
        }

    def shutdown(self) -> None:
        with self._lock:
            if self._sidecar is not None:
                self._sidecar.shutdown()
                self._sidecar = None


class ExternalPluginDriver(ExecDriver):
    """An operator-supplied task driver running as its OWN supervisor
    process — the go-plugin dispense analog (plugins/base/proto +
    plugins/drivers/proto): the agent spawns the configured binary and
    speaks the executor JSON-lines protocol to it (start/wait/stop/
    destroy/recover/list, plus an optional ``info`` op for
    name/version/config-schema discovery).  ``native/executor.cc`` and
    ``client/executor.py`` double as reference plugin implementations.

    Plugin config (client ``plugin "name" { binary = ... }`` blocks):
    the binary must accept ``--socket PATH --state-dir DIR``.
    """

    def __init__(self, name: str, binary: str, state_dir: str = ""):
        super().__init__(state_dir)
        self.name = name
        self.binary = binary
        self.sidecar_subdir = f"plugin-{name}"
        self._info: Optional[Dict[str, Any]] = None

    def info(self, state_dir: str = "") -> Dict[str, Any]:
        """PluginInfo + ConfigSchema (plugins/base/proto/base.proto):
        optional — a plugin without the op reports bare detection.
        Transient spawn failures are NOT cached (retried next call)."""
        if self._info is None:
            try:
                self._info = self._get_sidecar(state_dir).call("info")
            except (DriverError, OSError):
                return {}
        return self._info

    def fingerprint(self) -> Dict[str, str]:
        """Called at client boot + every re-fingerprint pass — this is
        where the plugin is dispensed and its info discovered."""
        info = self.info()
        attrs = {f"driver.{self.name}": "1"}
        version = info.get("version")
        if version:
            attrs[f"driver.{self.name}.version"] = str(version)
        return attrs

    def start_task(self, handle: TaskHandle, task: Task, task_dir: str) -> None:
        # Schema-validate the task's config {} against what the plugin
        # declared (hclspec analog, trimmed to required-key checking).
        state_dir = os.path.dirname(os.path.dirname(task_dir))
        schema = self.info(state_dir).get("config_schema") or {}
        required = schema.get("required") or []
        missing = [k for k in required if k not in (task.config or {})]
        if missing:
            raise DriverError(
                f"plugin {self.name!r} requires config keys {missing}"
            )
        super().start_task(handle, task, task_dir)


class DriverRegistry:
    """Per-client driver instances (reference: client/pluginmanager/
    drivermanager — dispense + fingerprint)."""

    def __init__(self, drivers: Optional[Dict[str, Driver]] = None):
        self.drivers: Dict[str, Driver] = drivers or {
            "mock": MockDriver(),
            "raw_exec": RawExecDriver(),
            "exec": ExecDriver(),
        }

    def register_plugin(
        self, name: str, binary: str, state_dir: str = ""
    ) -> None:
        """Dispense an external driver plugin (drivermanager dispense)."""
        self.drivers[name] = ExternalPluginDriver(
            name, binary, state_dir=state_dir
        )

    def get(self, name: str) -> Driver:
        d = self.drivers.get(name)
        if d is None:
            raise DriverError(f"unknown driver {name!r}")
        return d

    def fingerprint(self) -> Dict[str, str]:
        attrs: Dict[str, str] = {}
        for d in self.drivers.values():
            attrs.update(d.fingerprint())
        return attrs

    def shutdown(self) -> None:
        for d in self.drivers.values():
            if hasattr(d, "shutdown"):
                d.shutdown()
