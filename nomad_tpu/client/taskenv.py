"""Task environment — NOMAD_* variables + ${...} interpolation.

Reference: ``client/taskenv/`` (1361 LoC): the env builder exposes alloc/
task/node identity, resource limits, ports, and metadata to tasks as
NOMAD_* variables, and interpolates ``${attr.*}`` / ``${node.*}`` /
``${meta.*}`` / ``${env.*}`` / ``${NOMAD_*}`` references inside task env
values and driver config.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional

from ..structs.types import Allocation, Node, Task

_REF = re.compile(r"\$\{([^}]+)\}")


def build_task_env(
    alloc: Allocation,
    task: Task,
    task_dir: str,
    alloc_dir: str,
    node: Optional[Node] = None,
) -> Dict[str, str]:
    """The NOMAD_* environment for one task (taskenv.Builder.Build)."""
    job = alloc.job
    env: Dict[str, str] = {
        "NOMAD_ALLOC_ID": alloc.id,
        "NOMAD_ALLOC_NAME": alloc.name,
        "NOMAD_ALLOC_INDEX": str(_alloc_index(alloc.name)),
        "NOMAD_ALLOC_DIR": f"{alloc_dir}/alloc",
        "NOMAD_TASK_DIR": task_dir,
        "NOMAD_SECRETS_DIR": f"{task_dir}/secrets",
        "NOMAD_TASK_NAME": task.name,
        "NOMAD_GROUP_NAME": alloc.task_group,
        "NOMAD_JOB_ID": alloc.job_id,
        "NOMAD_JOB_NAME": job.name if job else alloc.job_id,
        "NOMAD_NAMESPACE": alloc.namespace,
        "NOMAD_CPU_LIMIT": str(int(task.resources.cpu)),
        "NOMAD_MEMORY_LIMIT": str(int(task.resources.memory_mb)),
    }
    if job is not None:
        env["NOMAD_DC"] = job.datacenters[0] if job.datacenters else ""
        env["NOMAD_REGION"] = job.region
        for k, v in (job.meta or {}).items():
            env[f"NOMAD_META_{_sanitize(k)}"] = str(v)
    if node is not None:
        env["NOMAD_NODE_ID"] = node.id
        env["NOMAD_NODE_NAME"] = node.name
        env["NOMAD_NODE_CLASS"] = node.node_class
    # Ports (taskenv network vars): NOMAD_PORT_<label>, NOMAD_ADDR_<label>,
    # NOMAD_HOST_PORT_<label>.
    for per_owner in (alloc.assigned_ports or {}).values():
        for label, port in per_owner.items():
            lab = _sanitize(label)
            env[f"NOMAD_PORT_{lab}"] = str(port)
            env[f"NOMAD_HOST_PORT_{lab}"] = str(port)
            env[f"NOMAD_ADDR_{lab}"] = f"127.0.0.1:{port}"
    return env


def interpolation_map(
    env: Dict[str, str], node: Optional[Node] = None
) -> Dict[str, str]:
    """Lookup table for ${...} references (taskenv.ReplaceEnv targets)."""
    out: Dict[str, str] = {}
    for k, v in env.items():
        out[k] = v
        out[f"env.{k}"] = v
    if node is not None:
        from ..state.matrix import node_attributes

        for name, value in node_attributes(node).items():
            out[f"attr.{name}"] = str(value)
        out["node.unique.id"] = node.id
        out["node.unique.name"] = node.name
        out["node.datacenter"] = node.datacenter
        out["node.class"] = node.node_class
        for k, v in (node.meta or {}).items():
            out[f"meta.{k}"] = str(v)
    return out


def interpolate(value: Any, table: Dict[str, str]) -> Any:
    """Replace ${ref} in strings (recursing through lists/dicts); unknown
    references are left intact, matching the reference's behavior."""
    if isinstance(value, str):
        return _REF.sub(
            lambda m: table.get(m.group(1).strip(), m.group(0)), value
        )
    if isinstance(value, list):
        return [interpolate(v, table) for v in value]
    if isinstance(value, dict):
        return {k: interpolate(v, table) for k, v in value.items()}
    return value


def interpolated_task(
    task: Task,
    alloc: Allocation,
    task_dir: str,
    alloc_dir: str,
    node: Optional[Node] = None,
) -> Task:
    """A COPY of the task with the full NOMAD_* env merged in and every
    ${...} reference in env/config resolved — what the driver receives."""
    import copy

    env = build_task_env(alloc, task, task_dir, alloc_dir, node)
    table = interpolation_map(env, node)
    out = copy.copy(task)
    merged = dict(env)
    for k, v in (task.env or {}).items():
        merged[k] = interpolate(str(v), table)
    out.env = merged
    out.config = interpolate(dict(task.config or {}), table)
    out.artifacts = interpolate(list(task.artifacts or []), table)
    out.templates = interpolate(list(task.templates or []), table)
    return out


def _alloc_index(name: str) -> int:
    m = re.search(r"\[(\d+)\]$", name or "")
    return int(m.group(1)) if m else 0


def _sanitize(key: str) -> str:
    return re.sub(r"[^A-Za-z0-9_]", "_", key)
