"""Logmon — size-capped task log rotation.

Reference: ``client/logmon/`` (489 LoC) + ``logging/rotator.go``: a
separate daemon pumps task output through a FIFO into ``N files × M
bytes``.  Here the writers are non-cooperating child processes that keep
their own O_APPEND file descriptors across agent AND sidecar restarts
(that fd continuity is what makes task recovery work, client/driver.py
RecoverTask) — so instead of interposing a pipe that would die with its
pump, the runner rotates by **copy-truncate**: when the live file crosses
the cap, its content shifts to ``<base>.1`` (… up to ``max_files - 1``,
oldest dropped) and the live file truncates to zero.  O_APPEND writers
continue seamlessly at the new EOF.  Bytes written during the copy window
can be lost — the documented tradeoff for surviving supervisor loss,
which the reference accepts at logmon-reattach the same way.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
from typing import List

log = logging.getLogger(__name__)

DEFAULT_MAX_FILE_BYTES = 10 * 1024 * 1024  # logs.max_file_size = 10 MB
DEFAULT_MAX_FILES = 10  # logs.max_files
CHECK_INTERVAL_S = 0.5


def rotate_once(
    path: str, max_files: int, max_bytes: int = 0
) -> None:
    """Shift ``path`` into the numbered history and truncate it.  When
    ``max_bytes`` is set, the history copy keeps only the newest
    ``max_bytes`` tail — a burst that outran a check interval must not
    smuggle an oversized file into the history."""
    # Drop the oldest, shift the rest up.
    oldest = f"{path}.{max_files - 1}"
    if max_files > 1 and os.path.exists(oldest):
        os.unlink(oldest)
    for i in range(max_files - 2, 0, -1):
        src = f"{path}.{i}"
        if os.path.exists(src):
            os.replace(src, f"{path}.{i + 1}")
    if max_files > 1:
        size = os.path.getsize(path)
        if max_bytes and size > max_bytes:
            with open(path, "rb") as src, open(f"{path}.1", "wb") as dst:
                src.seek(size - max_bytes)
                shutil.copyfileobj(src, dst)
        else:
            shutil.copyfile(path, f"{path}.1")
    # Truncate in place: the writer's O_APPEND fd continues at offset 0.
    with open(path, "r+b") as fh:
        fh.truncate(0)


class LogRotator:
    """Watches a task's stdout/stderr files and caps them in place."""

    def __init__(
        self,
        paths: List[str],
        max_file_bytes: int = DEFAULT_MAX_FILE_BYTES,
        max_files: int = DEFAULT_MAX_FILES,
        interval: float = CHECK_INTERVAL_S,
    ):
        self.paths = list(paths)
        self.max_file_bytes = max(1024, int(max_file_bytes))
        self.max_files = max(1, int(max_files))
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="logmon", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.check()  # final sweep so a burst right before exit is capped

    def check(self) -> None:
        for path in self.paths:
            try:
                if os.path.exists(path) and (
                    os.path.getsize(path) > self.max_file_bytes
                ):
                    rotate_once(path, self.max_files, self.max_file_bytes)
            except OSError as exc:
                log.debug("logmon rotate %s failed: %s", path, exc)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.check()
