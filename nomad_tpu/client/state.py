"""Client state persistence — restart recovery.

Reference: ``client/state/state_database.go`` (BoltDB): the client persists
its node identity, each alloc, its task states, and the **driver task
handles** so an agent restart re-attaches to still-running tasks via
``RecoverTask`` (``plugins/drivers/driver.go:54``) instead of killing and
rescheduling them.

Layout (JSON files under ``<data_dir>/state/``):

- ``node.json`` — the node id (a restarted agent must re-register as the
  SAME node or its allocs would be orphaned)
- ``allocs/<alloc_id>.json`` — alloc wire + task states + task handles
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from ..structs import serde
from ..structs.types import Allocation, TaskState


class ClientStateDB:
    def __init__(self, data_dir: str):
        self.dir = os.path.join(data_dir, "state")
        self.allocs_dir = os.path.join(self.dir, "allocs")
        os.makedirs(self.allocs_dir, exist_ok=True)
        self.node_path = os.path.join(self.dir, "node.json")

    # -- node identity --------------------------------------------------

    def get_node_id(self) -> Optional[str]:
        try:
            with open(self.node_path, "r", encoding="utf-8") as fh:
                return json.load(fh).get("node_id") or None
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def put_node_id(self, node_id: str) -> None:
        tmp = self.node_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"node_id": node_id}, fh)
        os.replace(tmp, self.node_path)

    # -- allocs ---------------------------------------------------------

    def _alloc_path(self, alloc_id: str) -> str:
        return os.path.join(self.allocs_dir, f"{alloc_id}.json")

    def put_alloc_state(
        self,
        alloc: Allocation,
        task_states: Dict[str, TaskState],
        handles: Dict[str, dict],
    ) -> None:
        """Persist one alloc's full client-side state (atomic replace —
        a crash mid-write must not corrupt the previous record)."""
        record = {
            "alloc": serde.to_wire(alloc),
            "task_states": {
                name: serde.to_wire(st) for name, st in task_states.items()
            },
            "handles": handles,
        }
        path = self._alloc_path(alloc.id)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(record, fh)
        os.replace(tmp, path)

    def delete_alloc(self, alloc_id: str) -> None:
        try:
            os.unlink(self._alloc_path(alloc_id))
        except FileNotFoundError:
            pass

    def load_allocs(
        self,
    ) -> List[Tuple[Allocation, Dict[str, TaskState], Dict[str, dict]]]:
        out = []
        for name in sorted(os.listdir(self.allocs_dir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.allocs_dir, name)
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    record = json.load(fh)
                alloc = serde.from_wire(record["alloc"])
                states = {
                    n: serde.from_wire(w)
                    for n, w in record.get("task_states", {}).items()
                }
                handles = record.get("handles", {})
            except (json.JSONDecodeError, KeyError, TypeError):
                continue  # torn write — drop the record
            out.append((alloc, states, handles))
        return out
